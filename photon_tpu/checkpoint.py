"""Step-level checkpoint/resume for GAME training.

Parity context: the reference has NO mid-optimization checkpointing — recovery
is Spark lineage recompute plus coarse warm starts (SURVEY.md §5.3/§5.4). JAX
has no task-level retry, so the rebuild supplies the missing piece directly:
after every coordinate-descent step the full training state (per-coordinate
models, score bookkeeping, best-model tracking, tracker records, position) is
snapshotted; a restarted driver resumes mid-sweep and reproduces the exact
final model the uninterrupted run would have produced (verified bit-identical
in tests/test_checkpoint.py).

Mechanics:
* ``save`` converts device arrays to host numpy (one sync D2H copy) and hands
  the snapshot to a background writer thread — training does not wait for
  disk (the "async save" of SURVEY.md §5.4's rebuild note).
* Writes are atomic: serialize to a manager-unique ``<dir>/tmp-<step>-<tag>``
  then ``os.replace`` to ``<dir>/step-<n>``; a torn write can never be
  mistaken for a checkpoint. The tmp name carries a per-manager tag because
  two managers can legitimately write the same directory at once: a
  preempted attempt's background writer may still be draining its queue
  when the supervisor's restarted attempt (a fresh manager on the same
  directory) re-runs the step it never saw on disk — with a shared tmp
  name, the loser of that race ``os.replace``s a path the winner already
  renamed away and poisons its manager with ``FileNotFoundError``. Both
  snapshots are consistent states of the same deterministic step, so
  last-writer-wins on ``step-<n>`` itself is benign.
* Payloads are checksummed (CRC32 in a small header): a snapshot corrupted
  in place — a bit flip that still unpickles into plausible-looking state —
  is refused explicitly (:class:`CheckpointCorrupt`) and ``load_latest``
  falls back to the previous ``step-<n>``, the same path a torn write takes.
  Pre-checksum snapshots (raw pickle) still load.
* The newest ``keep`` checkpoints are retained.
* Format: magic + CRC32 + pickled pytree of numpy leaves + JSON-able
  metadata. Checkpoints are ephemeral restart artifacts scoped to one
  training run (the durable model format is the Avro layout of
  io/model_io.py).

Determinism note: resume is bit-identical because everything else is already
deterministic — down-sampling keys derive from (seed, config, coordinate) via
``fold_in``, datasets rebuild identically from the same inputs, and the saved
state restores the exact device arrays.
"""
from __future__ import annotations

import dataclasses
import logging
import os
import pickle
import queue
import re
import struct
import threading
import time
import zlib
from typing import Any, Optional

import jax
import numpy as np

from photon_tpu.faults import fault_point

logger = logging.getLogger("photon_tpu.checkpoint")

_STEP_RE = re.compile(r"^step-(\d+)$")

# Checksummed snapshot framing: magic + little-endian CRC32 of the pickle
# payload. Files without the magic are pre-checksum snapshots (raw pickle).
_MAGIC = b"PHCKPT1\x00"


class CheckpointCorrupt(RuntimeError):
    """A snapshot file that exists but must not be trusted: checksum
    mismatch (bit rot / in-place corruption) or undecodable payload (torn
    write). ``load_latest`` refuses it explicitly and falls back to the
    previous step."""


class _Crc32Writer:
    """File-like pass-through that CRCs everything written (so the pickle
    streams to disk once, no full-blob copy in memory)."""

    __slots__ = ("_f", "crc")

    def __init__(self, f):
        self._f = f
        self.crc = 0

    def write(self, data) -> int:
        self.crc = zlib.crc32(data, self.crc) & 0xFFFFFFFF
        return self._f.write(data)


def run_fingerprint(parts: Any, length: int = 16) -> str:
    """Stable digest of a run's configuration identity (``repr``-hashed).
    Shared by every resume surface so refusal semantics cannot drift."""
    import hashlib

    return hashlib.sha256(repr(parts).encode()).hexdigest()[:length]


def _to_host(tree):
    return jax.tree.map(
        lambda x: np.asarray(jax.device_get(x)) if isinstance(x, jax.Array) else x,
        tree,
    )


@dataclasses.dataclass
class CheckpointManager:
    """Asynchronous, atomic, keep-N checkpoint writer + loader."""

    directory: str
    keep: int = 2
    # Test hook: raise after this many successful saves (simulates a crash
    # mid-training for resume tests). None = never.
    fail_after: Optional[int] = None

    def __post_init__(self):
        os.makedirs(self.directory, exist_ok=True)
        # Manager-unique tmp tag (module doc): a restarted attempt's fresh
        # manager must never collide on tmp paths with the preempted
        # attempt's still-draining writer.
        self._tmp_tag = f"{os.getpid():x}-{id(self):x}"
        # Sweep orphaned tmp files from crashed/preempted predecessors so a
        # restart loop never accumulates garbage — but only STALE ones (by
        # mtime): on a shared multi-host checkpoint directory
        # (docs/scaling.md) a peer's in-flight tmp file is seconds old, and
        # unlinking it between its open() and os.replace() would poison a
        # healthy manager. A live writer streams the pickle continuously,
        # so any tmp untouched for this long is a corpse.
        stale_s = 15 * 60.0
        for name in os.listdir(self.directory):
            if name.startswith("tmp-"):
                path = os.path.join(self.directory, name)
                try:
                    if time.time() - os.path.getmtime(path) > stale_s:
                        os.remove(path)
                except OSError:
                    pass
        self._queue: "queue.Queue" = queue.Queue()
        self._error: Optional[BaseException] = None
        self._saves = 0
        self.last_skipped: list[tuple[int, str]] = []
        self._worker = threading.Thread(target=self._drain, daemon=True)
        self._worker.start()

    # ------------------------------------------------------------------ save

    def save(self, step: int, state: Any, meta: Optional[dict] = None) -> None:
        """Snapshot (device → host) now; write to disk in the background.

        When an AOT compile store is active (runtime/compile_store.py) the
        metadata carries its manifest reference, so a checkpoint-resume
        restart — possibly a fresh process on another host sharing the
        filesystem — knows exactly which compiled artifacts to pre-warm
        before it starts solving."""
        if self._error is not None:
            raise RuntimeError("checkpoint writer failed") from self._error
        payload = {"state": _to_host(state), "meta": dict(meta or {}), "step": step}
        try:
            from photon_tpu.runtime.compile_store import manifest_ref_if_active

            ref = manifest_ref_if_active()
            if ref is not None:
                payload["meta"].setdefault("compile_store", ref)
        except Exception:  # noqa: BLE001 - the stamp is advisory metadata
            pass
        self._queue.put((step, payload))
        self._saves += 1
        if self.fail_after is not None and self._saves >= self.fail_after:
            self.wait()
            raise KeyboardInterrupt(
                f"simulated crash after {self._saves} checkpoint saves"
            )

    def _drain(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            step, payload = item
            try:
                fault_point("checkpoint.write", step=step)
                tmp = os.path.join(
                    self.directory, f"tmp-{step}-{self._tmp_tag}")
                with open(tmp, "wb") as f:
                    # STREAM the pickle through a CRC-accumulating wrapper
                    # (placeholder CRC patched afterwards): materializing
                    # the blob with pickle.dumps would double peak host
                    # memory for multi-GB snapshots.
                    f.write(_MAGIC)
                    f.write(struct.pack("<I", 0))
                    crc_writer = _Crc32Writer(f)
                    pickle.dump(payload, crc_writer,
                                protocol=pickle.HIGHEST_PROTOCOL)
                    f.flush()
                    f.seek(len(_MAGIC))
                    f.write(struct.pack("<I", crc_writer.crc))
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, os.path.join(self.directory, f"step-{step}"))
                self._gc()
            except BaseException as e:  # surfaced on the next save()
                self._error = e
            finally:
                self._queue.task_done()

    def _gc(self) -> None:
        steps = sorted(self._list_steps())
        for s in steps[: -self.keep]:
            try:
                os.remove(os.path.join(self.directory, f"step-{s}"))
            except OSError:
                pass

    def wait(self) -> None:
        """Block until all queued checkpoints are durably on disk."""
        self._queue.join()
        if self._error is not None:
            raise RuntimeError("checkpoint writer failed") from self._error

    def close(self) -> None:
        self.wait()
        self._queue.put(None)

    # ------------------------------------------------------------------ load

    def _list_steps(self) -> list[int]:
        out = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return out

    def latest_step(self) -> Optional[int]:
        steps = self._list_steps()
        return max(steps) if steps else None

    def load_file(self, path: str) -> dict:
        """Read + verify one snapshot file.

        Checksummed files (the current format) verify CRC32 before
        unpickling, so in-place corruption that would still unpickle into
        plausible garbage is refused, not resumed. Files without the magic
        are pre-checksum snapshots and load as raw pickle. Either way, an
        untrustworthy file raises :class:`CheckpointCorrupt`.
        """
        fault_point("checkpoint.load", path=path)
        with open(path, "rb") as f:
            head = f.read(len(_MAGIC))
            if head == _MAGIC:
                crc_bytes = f.read(4)
                if len(crc_bytes) < 4:
                    # Torn inside the header itself (magic landed, CRC did
                    # not) — corrupt, not a crash.
                    raise CheckpointCorrupt(
                        f"{path}: truncated checkpoint header"
                    )
                (stored,) = struct.unpack("<I", crc_bytes)
                blob = f.read()
                if zlib.crc32(blob) & 0xFFFFFFFF != stored:
                    raise CheckpointCorrupt(
                        f"{path}: checksum mismatch (stored {stored:#010x}) "
                        "— refusing corrupted snapshot"
                    )
            else:
                blob = head + f.read()  # pre-checksum snapshot
        try:
            return pickle.loads(blob)
        except Exception as e:
            raise CheckpointCorrupt(
                f"{path}: undecodable payload ({type(e).__name__}: {e})"
            ) from e

    def load_latest(self) -> Optional[dict]:
        """Newest trustworthy checkpoint payload, or None. A corrupt newest
        file — torn write from a hard kill, or a checksum-refused snapshot —
        falls back to the previous one; refusals are logged and recorded in
        ``self.last_skipped`` as ``(step, reason)``."""
        self.last_skipped: list[tuple[int, str]] = []
        for s in sorted(self._list_steps(), reverse=True):
            path = os.path.join(self.directory, f"step-{s}")
            try:
                return self.load_file(path)
            except CheckpointCorrupt as e:
                logger.warning(
                    "refusing checkpoint step-%d (%s); falling back to the "
                    "previous snapshot", s, e,
                )
                self.last_skipped.append((s, str(e)))
            except OSError as e:
                self.last_skipped.append((s, f"unreadable: {e}"))
        return None

    def load_checked(self, kind: str, fingerprint: str) -> Optional[dict]:
        """``load_latest`` guarded by run identity: a snapshot of a different
        kind or fingerprint raises instead of silently resuming incompatible
        state. Pair with ``save(..., meta={'kind': kind,
        'fingerprint': fingerprint, ...})``."""
        payload = self.load_latest()
        if payload is None:
            return None
        meta = payload.get("meta", {})
        if meta.get("kind") != kind or meta.get("fingerprint") != fingerprint:
            raise ValueError(
                "checkpoint directory holds snapshots from a run with a "
                f"different configuration (kind={meta.get('kind')!r}) — "
                "resuming would silently mix incompatible state; use a "
                "fresh --checkpoint-dir"
            )
        return payload
