"""Step-level checkpoint/resume for GAME training.

Parity context: the reference has NO mid-optimization checkpointing — recovery
is Spark lineage recompute plus coarse warm starts (SURVEY.md §5.3/§5.4). JAX
has no task-level retry, so the rebuild supplies the missing piece directly:
after every coordinate-descent step the full training state (per-coordinate
models, score bookkeeping, best-model tracking, tracker records, position) is
snapshotted; a restarted driver resumes mid-sweep and reproduces the exact
final model the uninterrupted run would have produced (verified bit-identical
in tests/test_checkpoint.py).

Mechanics:
* ``save`` converts device arrays to host numpy (one sync D2H copy) and hands
  the snapshot to a background writer thread — training does not wait for
  disk (the "async save" of SURVEY.md §5.4's rebuild note).
* Writes are atomic: serialize to ``<dir>/tmp-<step>`` then ``os.replace`` to
  ``<dir>/step-<n>``; a torn write can never be mistaken for a checkpoint.
* The newest ``keep`` checkpoints are retained.
* Format: pickled pytree of numpy leaves + JSON-able metadata. Checkpoints
  are ephemeral restart artifacts scoped to one training run (the durable
  model format is the Avro layout of io/model_io.py).

Determinism note: resume is bit-identical because everything else is already
deterministic — down-sampling keys derive from (seed, config, coordinate) via
``fold_in``, datasets rebuild identically from the same inputs, and the saved
state restores the exact device arrays.
"""
from __future__ import annotations

import dataclasses
import os
import pickle
import queue
import re
import threading
from typing import Any, Optional

import jax
import numpy as np

_STEP_RE = re.compile(r"^step-(\d+)$")


def run_fingerprint(parts: Any, length: int = 16) -> str:
    """Stable digest of a run's configuration identity (``repr``-hashed).
    Shared by every resume surface so refusal semantics cannot drift."""
    import hashlib

    return hashlib.sha256(repr(parts).encode()).hexdigest()[:length]


def _to_host(tree):
    return jax.tree.map(
        lambda x: np.asarray(jax.device_get(x)) if isinstance(x, jax.Array) else x,
        tree,
    )


@dataclasses.dataclass
class CheckpointManager:
    """Asynchronous, atomic, keep-N checkpoint writer + loader."""

    directory: str
    keep: int = 2
    # Test hook: raise after this many successful saves (simulates a crash
    # mid-training for resume tests). None = never.
    fail_after: Optional[int] = None

    def __post_init__(self):
        os.makedirs(self.directory, exist_ok=True)
        self._queue: "queue.Queue" = queue.Queue()
        self._error: Optional[BaseException] = None
        self._saves = 0
        self._worker = threading.Thread(target=self._drain, daemon=True)
        self._worker.start()

    # ------------------------------------------------------------------ save

    def save(self, step: int, state: Any, meta: Optional[dict] = None) -> None:
        """Snapshot (device → host) now; write to disk in the background."""
        if self._error is not None:
            raise RuntimeError("checkpoint writer failed") from self._error
        payload = {"state": _to_host(state), "meta": dict(meta or {}), "step": step}
        self._queue.put((step, payload))
        self._saves += 1
        if self.fail_after is not None and self._saves >= self.fail_after:
            self.wait()
            raise KeyboardInterrupt(
                f"simulated crash after {self._saves} checkpoint saves"
            )

    def _drain(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            step, payload = item
            try:
                tmp = os.path.join(self.directory, f"tmp-{step}")
                with open(tmp, "wb") as f:
                    pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, os.path.join(self.directory, f"step-{step}"))
                self._gc()
            except BaseException as e:  # surfaced on the next save()
                self._error = e
            finally:
                self._queue.task_done()

    def _gc(self) -> None:
        steps = sorted(self._list_steps())
        for s in steps[: -self.keep]:
            try:
                os.remove(os.path.join(self.directory, f"step-{s}"))
            except OSError:
                pass

    def wait(self) -> None:
        """Block until all queued checkpoints are durably on disk."""
        self._queue.join()
        if self._error is not None:
            raise RuntimeError("checkpoint writer failed") from self._error

    def close(self) -> None:
        self.wait()
        self._queue.put(None)

    # ------------------------------------------------------------------ load

    def _list_steps(self) -> list[int]:
        out = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return out

    def latest_step(self) -> Optional[int]:
        steps = self._list_steps()
        return max(steps) if steps else None

    def load_latest(self) -> Optional[dict]:
        """Newest readable checkpoint payload, or None. A corrupt newest file
        (torn write from a hard kill) falls back to the previous one."""
        for s in sorted(self._list_steps(), reverse=True):
            path = os.path.join(self.directory, f"step-{s}")
            try:
                with open(path, "rb") as f:
                    return pickle.load(f)
            except Exception:
                continue
        return None

    def load_checked(self, kind: str, fingerprint: str) -> Optional[dict]:
        """``load_latest`` guarded by run identity: a snapshot of a different
        kind or fingerprint raises instead of silently resuming incompatible
        state. Pair with ``save(..., meta={'kind': kind,
        'fingerprint': fingerprint, ...})``."""
        payload = self.load_latest()
        if payload is None:
            return None
        meta = payload.get("meta", {})
        if meta.get("kind") != kind or meta.get("fingerprint") != fingerprint:
            raise ValueError(
                "checkpoint directory holds snapshots from a run with a "
                f"different configuration (kind={meta.get('kind')!r}) — "
                "resuming would silently mix incompatible state; use a "
                "fresh --checkpoint-dir"
            )
        return payload
