"""Deterministic, seed-driven fault injection (docs/robustness.md).

The rebuild replaced Spark's inherited fault tolerance with its own
checkpoint-restart + supervisor + serving-hardening layers — machinery that
is worthless unless it is exercised under real faults. This module is the
injection side of that story: production code paths carry near-zero-cost
:func:`fault_point` hooks (one module-global ``is None`` check when no plan
is active), and a :class:`FaultPlan` schedules which hooks misbehave, how,
and when — deterministically, from a seed, so every chaos run is
reproducible bit-for-bit.

Hook sites threaded through the codebase (grep for ``fault_point(``):

==========================  ================================================
site                        where / what a fired fault simulates
==========================  ================================================
``io.block_read``           per Avro block in the streaming ingest
                            (transient/permanent read errors)
``io.prefetch``             per chunk on the prefetch producer thread
                            (``io/prefetch.py``; a fired error kills the
                            background decode stage mid-stream and must
                            surface at the consumer)
``io.record_read``          per file on the per-record fallback reader
``checkpoint.write``        background checkpoint writer, before the write
                            (disk-full / fs hiccup mid-snapshot)
``checkpoint.load``         checkpoint file open on resume
``descent.step``            top of each coordinate-descent step
                            (host preemption delivered as an exception)
``descent.device``          inside each coordinate-descent step, before
                            the solve (``error="device_lost"`` here drives
                            the IN-RUN recovery path: checkpoint →
                            executable-cache clear → resume, not an
                            attempt restart)
``optim.ooc_iteration``     top of each out-of-core optimizer iteration
                            (same in-run device-loss recovery, resuming
                            from the solver's own .npz checkpoint)
``optim.ooc_chunk``         per streamed ELL chunk on an out-of-core pass
                            (``error="device_oom"`` here drives the OOM
                            degradation ladder: the solver halves
                            ``chunk_rows`` and re-enters —
                            ``runtime/memory_guard``)
``re.solve``                random-effect bucket-solver dispatch
                            (``game/random_effect.py``;
                            ``error="device_oom"`` drives the chunk-tier
                            downshift ladder instead of a restart)
``heartbeat.beat``          heartbeat file write (stale-heartbeat peers)
``serving.store_lookup``    coefficient-store point lookup (latency
                            spikes via ``delay_s``, errors via ``error``)
``serving.batcher_batch``   micro-batcher worker, per assembled batch
                            (unexpected worker death)
``serving.kernel``          scoring-kernel invocation on the batcher
                            worker (``error="device_lost"`` exercises the
                            scorer's breaker-gated re-init + retry;
                            ``error="device_oom"`` the bounded max-batch
                            downshift)
``online.refresh``          top of each online refresh cycle's solve
                            (``online/trainer.py``; ``error="device_lost"``
                            drives the in-run recovery: cache clear +
                            bit-identical re-solve, bounded by
                            PHOTON_DEVICE_LOST_MAX_RECOVERIES)
``online.publish``          delta publication, before anything applies
                            (a fired error must leave the serving store,
                            trainer state, dirty set, journal, and cursor
                            untouched — the next cycle retries)
==========================  ================================================

A plan is a list of :class:`FaultSpec`; each spec independently counts the
hits at its site and decides — after an ``after`` warmup, at most ``count``
times, every ``every``-th eligible hit, with seeded ``probability`` — to
sleep ``delay_s`` and/or raise ``error``. Decisions and their outcomes are
recorded in ``FaultInjector.events`` so tests can assert the fault actually
fired. Plans round-trip through JSON (``to_json``/``from_file``) so the CLI
drivers can run under a plan via ``--fault-plan`` for manual chaos drills.
"""
from __future__ import annotations

import contextlib
import dataclasses
import json
import random
import threading
import time
from typing import Callable, Optional, Sequence

__all__ = [
    "PreemptionError",
    "DeviceLostError",
    "DeviceOomError",
    "FaultSpec",
    "FaultPlan",
    "FaultInjector",
    "fault_point",
    "install",
    "deactivate",
    "active_plan",
    "install_from_file",
]


class PreemptionError(RuntimeError):
    """A host preemption notice delivered as an exception mid-solve.

    Subclasses ``RuntimeError`` on purpose: the supervisor's default
    retryable set treats it as transient, exactly how a real preemption
    surfaced by the runtime should be handled (restart + checkpoint
    resume)."""


class DeviceLostError(RuntimeError):
    """A lost accelerator device surfaced as an exception mid-computation.

    Subclasses ``RuntimeError`` (like jaxlib's XlaRuntimeError) so the
    supervisor's retryable set treats it as transient. Distinct from
    :class:`PreemptionError` because it takes a DIFFERENT recovery path:
    the in-run handler (descent / out-of-core / scorer) checkpoints,
    clears the executable caches, and resumes WITHOUT killing the attempt
    (``runtime/backend_guard.recover_from_device_loss``); only repeated
    losses escalate to the supervisor restart."""


class DeviceOomError(RuntimeError):
    """A device out-of-memory failure surfaced mid-computation.

    Subclasses ``RuntimeError`` like jaxlib's XlaRuntimeError (whose real
    OOM text is ``RESOURCE_EXHAUSTED``), so the supervisor's retryable set
    admits it — but it classifies ``oom`` by TYPE
    (``runtime/backend_guard.classify_backend_error``), which routes it to
    the DEGRADATION LADDER, not a same-shapes retry: the failing site
    downshifts to a cheaper plan (``runtime/memory_guard``) because
    re-running the identical allocation deterministically re-OOMs."""


# JSON-able error names -> exception types raised by a firing spec.
_ERROR_TYPES = {
    "os": OSError,
    "io": OSError,
    "runtime": RuntimeError,
    "connection": ConnectionError,
    "preemption": PreemptionError,
    "device_lost": DeviceLostError,
    "device_oom": DeviceOomError,
    "memory": MemoryError,
}


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault at one hook site.

    ``after``: skip the first N hits (let the system warm up / make
    progress first). ``count``: fire at most this many times (None =
    unlimited). ``every``: fire only on every k-th eligible hit (None =
    every eligible hit). ``probability``: seeded Bernoulli per eligible
    hit. ``delay_s``: sleep this long when firing (latency injection);
    ``error``: also raise this error (by name, see ``_ERROR_TYPES``), or
    ``error_factory`` for programmatic plans (not JSON-serializable).
    ``match``: substring filters on the hook's context kwargs, e.g.
    ``{"path": "part-0003"}`` targets one input file.
    """

    site: str
    error: Optional[str] = None
    error_factory: Optional[Callable[[str], BaseException]] = None
    delay_s: float = 0.0
    probability: float = 1.0
    after: int = 0
    count: Optional[int] = None
    every: Optional[int] = None
    match: Optional[dict] = None

    def __post_init__(self):
        if self.error is not None and self.error not in _ERROR_TYPES:
            raise ValueError(
                f"unknown fault error {self.error!r}; "
                f"known: {sorted(_ERROR_TYPES)}"
            )

    def build_error(self, message: str) -> Optional[BaseException]:
        if self.error_factory is not None:
            return self.error_factory(message)
        if self.error is not None:
            return _ERROR_TYPES[self.error](message)
        return None

    def to_dict(self) -> dict:
        if self.error_factory is not None:
            raise ValueError("error_factory specs are not JSON-serializable")
        out = dataclasses.asdict(self)
        out.pop("error_factory")
        return {k: v for k, v in out.items() if v not in (None, {})}

    @classmethod
    def from_dict(cls, d: dict) -> "FaultSpec":
        return cls(**d)


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A seeded schedule of faults; install with :func:`install` or
    :func:`active_plan`."""

    specs: Sequence[FaultSpec] = ()
    seed: int = 0

    def __post_init__(self):
        # Normalize so plans compare equal regardless of list/tuple input
        # (JSON round-trips produce tuples).
        object.__setattr__(self, "specs", tuple(self.specs))

    def to_json(self) -> str:
        return json.dumps(
            {"seed": self.seed, "specs": [s.to_dict() for s in self.specs]},
            indent=2,
        )

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        d = json.loads(text)
        return cls(
            seed=int(d.get("seed", 0)),
            specs=tuple(FaultSpec.from_dict(s) for s in d.get("specs", ())),
        )

    @classmethod
    def from_file(cls, path: str) -> "FaultPlan":
        with open(path) as f:
            return cls.from_json(f.read())


class _SpecState:
    __slots__ = ("spec", "index", "hits", "eligible", "fired", "rng")

    def __init__(self, spec: FaultSpec, index: int, seed: int):
        self.spec = spec
        self.index = index
        self.hits = 0
        self.eligible = 0
        self.fired = 0
        # Per-spec stream: decisions do not shift when another spec's site
        # sees a different number of hits.
        self.rng = random.Random(f"{seed}:{index}")


class FaultInjector:
    """Live counters + decisions for one installed :class:`FaultPlan`.

    Thread-safe: serving hook sites fire from handler and worker threads.
    ``events`` records every fired fault (site, hit number, action) for
    test assertions and postmortems."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self.events: list[dict] = []
        self._lock = threading.Lock()
        self._by_site: dict[str, list[_SpecState]] = {}
        for i, spec in enumerate(plan.specs):
            self._by_site.setdefault(spec.site, []).append(
                _SpecState(spec, i, plan.seed)
            )

    def fired(self, site: Optional[str] = None) -> int:
        with self._lock:
            return sum(
                1 for e in self.events if site is None or e["site"] == site
            )

    def check(self, site: str, ctx: dict) -> None:
        states = self._by_site.get(site)
        if not states:
            return
        to_fire: list[tuple[_SpecState, str]] = []
        with self._lock:
            for st in states:
                spec = st.spec
                if spec.match and not all(
                    str(v) in str(ctx.get(k, ""))
                    for k, v in spec.match.items()
                ):
                    continue
                st.hits += 1
                if st.hits <= spec.after:
                    continue
                if spec.count is not None and st.fired >= spec.count:
                    continue
                st.eligible += 1
                if spec.every is not None and (
                    (st.eligible - 1) % spec.every != 0
                ):
                    continue
                if spec.probability < 1.0 and (
                    st.rng.random() >= spec.probability
                ):
                    continue
                st.fired += 1
                msg = (
                    f"injected fault at {site!r} (spec {st.index}, "
                    f"hit {st.hits})"
                )
                self.events.append({
                    "site": site,
                    "spec": st.index,
                    "hit": st.hits,
                    "error": spec.error,
                    "delay_s": spec.delay_s,
                })
                to_fire.append((st, msg))
        # Sleep/raise OUTSIDE the lock: a latency injection must not
        # serialize unrelated sites behind it. All fired delays execute
        # BEFORE any error raises, so a plan combining latency and error
        # specs on one site actually delivers both (events stay accurate).
        if to_fire:
            # Observability correlation (docs/observability.md): every fired
            # fault lands as a tagged instant event in the active trace, so
            # a chaos run replays as a timeline — the injected fault sits
            # next to the spans that absorbed it (same trace id when the
            # firing thread carries request context).
            from photon_tpu.obs.trace import instant as _trace_instant

            for st, _ in to_fire:
                _trace_instant(
                    f"fault:{site}", cat="fault",
                    site=site, spec=st.index, hit=st.hits,
                    error=st.spec.error, delay_s=st.spec.delay_s,
                )
        first_error: Optional[BaseException] = None
        for st, msg in to_fire:
            if st.spec.delay_s > 0:
                time.sleep(st.spec.delay_s)
            err = st.spec.build_error(msg)
            if err is not None and first_error is None:
                first_error = err
        if first_error is not None:
            raise first_error


_ACTIVE: Optional[FaultInjector] = None


def fault_point(site: str, **ctx) -> None:
    """Near-zero-cost hook: a no-op (one global read + None check) unless a
    plan is installed. Production code calls this at injectable sites."""
    inj = _ACTIVE
    if inj is not None:
        inj.check(site, ctx)


def install(plan: FaultPlan) -> FaultInjector:
    """Install ``plan`` process-wide; returns the live injector."""
    global _ACTIVE
    _ACTIVE = FaultInjector(plan)
    return _ACTIVE


def deactivate() -> None:
    global _ACTIVE
    _ACTIVE = None


@contextlib.contextmanager
def active_plan(plan: FaultPlan):
    """``with active_plan(plan) as injector:`` — scoped install/uninstall
    (restores whatever was active before, so plans can nest in tests)."""
    global _ACTIVE
    prev = _ACTIVE
    inj = FaultInjector(plan)
    _ACTIVE = inj
    try:
        yield inj
    finally:
        _ACTIVE = prev


def install_from_file(path: Optional[str]) -> Optional[FaultInjector]:
    """CLI support: install a JSON plan file (``--fault-plan``); no-op on
    None/empty so drivers can pass the flag straight through."""
    if not path:
        return None
    return install(FaultPlan.from_file(path))
