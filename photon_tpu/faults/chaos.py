"""On-disk corruption helpers for the chaos suite (docs/robustness.md).

Exception-type faults (:mod:`photon_tpu.faults.plan`) cover everything that
arrives through a ``raise``; these helpers cover the faults that arrive
through the filesystem instead — a checkpoint torn by a hard kill, a
bit-flipped snapshot from bad hardware — where the failure is only visible
when the file is read back. Both are deterministic (seeded) so a chaos run
reproduces exactly.
"""
from __future__ import annotations

import os
import random

__all__ = ["torn_write", "bit_flip"]


def torn_write(path: str, keep_fraction: float = 0.5) -> int:
    """Truncate ``path`` to ``keep_fraction`` of its size — the on-disk
    signature of a writer killed mid-write without the atomic tmp+rename
    dance. Returns the new size."""
    size = os.path.getsize(path)
    keep = max(0, int(size * keep_fraction))
    with open(path, "rb+") as f:
        f.truncate(keep)
    return keep


def bit_flip(
    path: str, n_flips: int = 1, seed: int = 0, min_offset: int = 0
) -> list[int]:
    """Flip ``n_flips`` seeded-random bits of ``path`` in place (at byte
    offsets >= ``min_offset``, so tests can aim past a header). The file
    keeps its size and framing — the corruption only a checksum catches.
    Returns the flipped byte offsets."""
    size = os.path.getsize(path)
    if size <= min_offset:
        raise ValueError(
            f"{path}: {size} bytes, nothing to flip past offset {min_offset}"
        )
    rng = random.Random(seed)
    offsets = []
    with open(path, "rb+") as f:
        for _ in range(n_flips):
            off = rng.randrange(min_offset, size)
            f.seek(off)
            byte = f.read(1)[0]
            f.seek(off)
            f.write(bytes([byte ^ (1 << rng.randrange(8))]))
            offsets.append(off)
    return offsets
