"""Deterministic fault-injection framework + chaos helpers
(docs/robustness.md).

``fault_point(site, **ctx)`` hooks are threaded through io, checkpoint,
supervisor, descent, and serving; a seeded :class:`FaultPlan` decides which
of them misbehave. The chaos test suite (``pytest -m chaos``) drives
training and serving under injected plans and asserts the recovery
contracts hold (bit-identical resume, no hung requests, bounded
degradation).
"""
from photon_tpu.faults.chaos import bit_flip, torn_write
from photon_tpu.faults.plan import (
    DeviceLostError,
    DeviceOomError,
    FaultInjector,
    FaultPlan,
    FaultSpec,
    PreemptionError,
    active_plan,
    deactivate,
    fault_point,
    install,
    install_from_file,
)

__all__ = [
    "DeviceLostError",
    "DeviceOomError",
    "FaultInjector",
    "FaultPlan",
    "FaultSpec",
    "PreemptionError",
    "active_plan",
    "bit_flip",
    "deactivate",
    "fault_point",
    "install",
    "install_from_file",
    "torn_write",
]
