"""L-BFGS as a single on-device XLA loop.

Parity: reference ⟦photon-lib/.../optimization/LBFGS.scala⟧ (which wraps
``breeze.optimize.LBFGS``): limited-memory quasi-Newton with the standard
two-loop recursion, line search, and dual convergence test.

TPU-first design (SURVEY.md §3.4, §7): where the reference runs the L-BFGS
iteration on the Spark *driver* — broadcasting coefficients and paying one
cluster round trip per iteration and per line-search probe — here the entire
loop (direction, line search, history update, convergence) is one
``lax.while_loop`` inside jit. Data-parallel gradients arrive via a ``psum``
baked into ``value_and_grad`` (see functions/distributed.py), so a whole
optimize() is one XLA program on the mesh with zero host round trips.

The history is a fixed-shape circular buffer ([m, D] S/Y plus [m] rho), masked
by the number of valid corrections — static shapes keep XLA happy and make the
optimizer `vmap`-able for batched per-entity random-effect solves.

Line search: backtracking Armijo with quadratic-fit shrink. Breeze uses strong
Wolfe; for batch-convex GLM objectives backtracking reaches the same optimum
(golden tests vs scipy assert optima, not trajectories) while costing one
fused value+grad pass per probe on-device.
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_tpu.optim.base import (
    FUNCTION_VALUES_CONVERGED,
    NOT_CONVERGED,
    Optimizer,
    OptimizerConfig,
    OptimizerResult,
    ValueAndGrad,
    check_convergence,
    finalize_reason,
)

Array = jax.Array


class LBFGSHistory(NamedTuple):
    """Circular-buffer curvature history."""

    s: Array      # [m, D] parameter deltas
    y: Array      # [m, D] gradient deltas
    rho: Array    # [m]    1 / (sᵀy)
    count: Array  # int32 — number of valid corrections (≤ m)
    pos: Array    # int32 — next write slot


def empty_history(m: int, d: int, dtype) -> LBFGSHistory:
    return LBFGSHistory(
        s=jnp.zeros((m, d), dtype),
        y=jnp.zeros((m, d), dtype),
        rho=jnp.zeros((m,), dtype),
        count=jnp.zeros((), jnp.int32),
        pos=jnp.zeros((), jnp.int32),
    )


def make_dot(axis_name=None):
    """Coefficient-space inner product. With ``axis_name``, vectors are
    SHARDS over that mesh axis (SURVEY.md §2.6 P3: feature-dimension-sharded
    optimizer state) and the dot completes with a ``psum`` over ICI — the
    sharded-state analog of the reference broadcasting whole vectors."""
    if axis_name is None:
        return jnp.dot
    return lambda a, b: lax.psum(jnp.dot(a, b), axis_name)


def two_loop_direction(g: Array, hist: LBFGSHistory, dot=jnp.dot) -> Array:
    """Compute −H·g via the standard two-loop recursion over the masked buffer.

    Falls back to steepest descent when the history is empty. All loops are
    ``fori_loop`` over the *static* memory size m with masking, so the
    computation has fixed shape regardless of how many corrections are valid.
    Under a sharded ``dot``, g/s/y are per-device shards and every inner
    product psums over the model axis; α/ρ/γ scalars stay replicated.
    """
    m = hist.rho.shape[0]

    def backward(j, carry):
        q, alpha = carry
        idx = jnp.mod(hist.pos - 1 - j, m)
        valid = j < hist.count
        a = hist.rho[idx] * dot(hist.s[idx], q)
        a = jnp.where(valid, a, 0.0)
        q = q - a * hist.y[idx]
        alpha = alpha.at[idx].set(a)
        return q, alpha

    q0 = g
    alpha0 = jnp.zeros((m,), g.dtype)
    q, alpha = lax.fori_loop(0, m, backward, (q0, alpha0))

    # Initial Hessian scaling γ = sᵀy / yᵀy from the newest pair.
    newest = jnp.mod(hist.pos - 1, m)
    sy = dot(hist.s[newest], hist.y[newest])
    yy = dot(hist.y[newest], hist.y[newest])
    gamma = jnp.where(hist.count > 0, sy / jnp.maximum(yy, 1e-30), 1.0)
    r = gamma * q

    def forward(j, r):
        idx = jnp.mod(hist.pos - hist.count + j, m)
        valid = j < hist.count
        b = hist.rho[idx] * dot(hist.y[idx], r)
        corr = jnp.where(valid, alpha[idx] - b, 0.0)
        return r + corr * hist.s[idx]

    r = lax.fori_loop(0, m, forward, r)
    return -r


def update_history(
    hist: LBFGSHistory, s: Array, y: Array, dot=jnp.dot
) -> LBFGSHistory:
    """Push a curvature pair, skipping it if sᵀy is not sufficiently positive."""
    sy = dot(s, y)
    ok = sy > 1e-10 * jnp.sqrt(dot(s, s)) * jnp.sqrt(dot(y, y))

    def push(h: LBFGSHistory) -> LBFGSHistory:
        return LBFGSHistory(
            s=h.s.at[h.pos].set(s),
            y=h.y.at[h.pos].set(y),
            rho=h.rho.at[h.pos].set(1.0 / sy),
            count=jnp.minimum(h.count + 1, h.s.shape[0]),
            pos=jnp.mod(h.pos + 1, h.s.shape[0]),
        )

    pushed = push(hist)
    return jax.tree.map(lambda a, b: jnp.where(ok, a, b), pushed, hist)


def armijo_backtrack(
    probe,
    f: Array,
    dg: Array,
    init_aux,
    max_iters: int,
    c1: float = 1e-4,
    shrink: float = 0.5,
):
    """Shared Armijo backtracking core. ``probe: t ↦ (f(x + t·d), aux)`` —
    the aux rides along untouched (the plain path carries the probe's
    gradient; the scored path carries nothing).

    Returns ``(t_final, ft, aux, accept, n_probes)``; ``t_final`` is 0 on a
    fully failed search (the caller's convergence logic stops on function
    values). If no step satisfies Armijo within the cap, the last (smallest)
    probe is accepted only if it still decreases f. NaN/Inf-safe: non-finite
    probe values are treated as failures.
    """

    def cond(carry):
        t, fx, _, _, it, done = carry
        return (~done) & (it < max_iters)

    def body(carry):
        t, _, _, _, it, _ = carry
        ft, aux = probe(t)
        ok = (ft <= f + c1 * t * dg) & jnp.isfinite(ft)
        return (jnp.where(ok, t, t * shrink), ft, aux, t, it + 1, ok)

    t0 = jnp.asarray(1.0, f.dtype)
    t, ft, aux, t_used, n, ok = lax.while_loop(
        cond, body,
        (t0, f, init_aux, t0, jnp.zeros((), jnp.int32), jnp.zeros((), bool)),
    )
    accept = ok | (jnp.isfinite(ft) & (ft < f))
    t_final = jnp.where(accept, t_used, 0.0)
    return t_final, ft, aux, accept, n


def backtracking_line_search(
    value_and_grad: ValueAndGrad,
    x: Array,
    f: Array,
    g: Array,
    d: Array,
    max_iters: int,
    c1: float = 1e-4,
    shrink: float = 0.5,
    dot=jnp.dot,
):
    """Armijo backtracking from t=1. Returns (x⁺, f⁺, g⁺, t, n_probes).

    Each probe is one fused value+grad evaluation (one data pass on-device).
    """
    dg = dot(d, g)
    t_final, ft, gt, accept, n = armijo_backtrack(
        lambda t: value_and_grad(x + t * d), f, dg, g, max_iters, c1, shrink
    )
    # Select (not scale by t=0): keeps x clean even if d has NaN/Inf entries.
    x_new = jnp.where(accept, x + t_final * d, x)
    f_new = jnp.where(accept, ft, f)
    g_new = jax.tree.map(lambda a, b: jnp.where(accept, a, b), gt, g)
    return x_new, f_new, g_new, t_final, n


class _LoopState(NamedTuple):
    x: Array
    f: Array
    g: Array
    extra: object          # step-strategy carry (e.g. maintained scores z)
    hist: LBFGSHistory
    it: Array
    reason: Array
    gnorm0: Array
    values: Array
    grad_norms: Array
    passes: Array          # int32 — instrumented data-pass counter


@dataclasses.dataclass(frozen=True)
class LBFGS(Optimizer):
    """Limited-memory BFGS. ``optimize`` is pure/jittable/vmappable.

    With ``axis_name`` set, ``x0``/gradients/history are SHARDS over that
    mesh axis (run inside ``shard_map``); every coefficient-space inner
    product completes with a psum, so optimizer state never materializes
    full-length vectors on any device (SURVEY.md §2.6 P3).
    """

    axis_name: str = None

    def _solve(self, x0, f0, g0, extra0, step_fn, init_passes=2) -> OptimizerResult:
        """Shared loop core: direction, step via ``step_fn``, history update,
        convergence bookkeeping. ``step_fn(st, dvec, it) →
        (x, f, g, extra, t_final, passes)``; ``t_final == 0`` marks a fully
        failed line search (no further progress possible); ``passes`` is the
        number of data passes the step made (see OptimizerResult)."""
        cfg = self.config
        max_it = cfg.max_iterations
        dtype = x0.dtype
        dot = make_dot(self.axis_name)
        norm = lambda v: jnp.sqrt(dot(v, v))

        gnorm0 = norm(g0)
        values = jnp.full((max_it + 1,), jnp.inf, dtype).at[0].set(f0)
        gnorms = jnp.full((max_it + 1,), jnp.inf, dtype).at[0].set(gnorm0)

        init = _LoopState(
            x=x0, f=f0, g=g0, extra=extra0,
            hist=empty_history(cfg.history_length, x0.shape[-1], dtype),
            it=jnp.zeros((), jnp.int32),
            reason=jnp.asarray(NOT_CONVERGED, jnp.int32),
            gnorm0=gnorm0,
            values=values, grad_norms=gnorms,
            passes=jnp.asarray(init_passes, jnp.int32),
        )

        def cond(st: _LoopState):
            return (st.reason == NOT_CONVERGED) & (st.it < max_it)

        def body(st: _LoopState) -> _LoopState:
            dvec = two_loop_direction(st.g, st.hist, dot)
            # Safeguard: if not a descent direction, restart from −g.
            descent = dot(dvec, st.g) < 0
            dvec = jnp.where(descent, dvec, -st.g)

            x_new, f_new, g_new, extra, t, step_passes = step_fn(st, dvec, st.it)
            hist = update_history(st.hist, x_new - st.x, g_new - st.g, dot)
            it = st.it + 1
            gnorm = norm(g_new)
            reason = check_convergence(it, st.f, f_new, gnorm, st.gnorm0, cfg)
            # A fully failed line search (t == 0) cannot make further progress.
            reason = jnp.where(
                (t == 0.0) & (reason == NOT_CONVERGED),
                jnp.asarray(FUNCTION_VALUES_CONVERGED, jnp.int32),
                reason,
            )
            return _LoopState(
                x=x_new, f=f_new, g=g_new, extra=extra, hist=hist, it=it,
                reason=reason, gnorm0=st.gnorm0,
                values=st.values.at[it].set(f_new),
                grad_norms=st.grad_norms.at[it].set(gnorm),
                passes=st.passes + step_passes.astype(jnp.int32),
            )

        st = lax.while_loop(cond, body, init)
        reason = finalize_reason(st.reason, st.it, max_it)
        return OptimizerResult(
            x=st.x, value=st.f, grad_norm=norm(st.g),
            iterations=st.it, converged_reason=reason,
            values=st.values, grad_norms=st.grad_norms,
            data_passes=st.passes,
        )

    def optimize(self, value_and_grad: ValueAndGrad, x0: Array) -> OptimizerResult:
        cfg = self.config
        dot = make_dot(self.axis_name)
        f0, g0 = value_and_grad(x0)

        def step(st, dvec, it):
            x_new, f_new, g_new, t, n_probes = backtracking_line_search(
                value_and_grad, st.x, st.f, st.g, dvec,
                cfg.max_line_search_iterations, dot=dot,
            )
            # Each probe is one fused value+grad = 1 matvec + 1 rmatvec.
            return x_new, f_new, g_new, st.extra, t, 2 * n_probes

        return self._solve(x0, f0, g0, jnp.zeros((), x0.dtype), step)

    def optimize_scored(self, so, x0: Array) -> OptimizerResult:
        """L-BFGS with incrementally maintained margins z = Xw + offsets.

        The reference pays a full data pass (a Spark job) per line-search
        probe (SURVEY.md §3.4). Here each iteration computes Xp ONCE for the
        chosen direction; every probe prices f(w + t·p) from z + t·Xp with
        elementwise work only, and the accepted point costs one rmatvec for
        the gradient. Net data passes per iteration: 1 matvec + 1 rmatvec,
        independent of probe count.

        ``so`` is a ``functions.objective.ScoreSpaceObjective``. Same
        optimum/convergence semantics as ``optimize`` (identical math;
        floating-point rounding of z + t·Xp vs X(w + t·p) differs at ~ulp).
        """
        cfg = self.config
        dot = make_dot(self.axis_name)
        dtype = x0.dtype

        z0 = so.score(x0)
        f0 = so.value_from_scores(z0, x0)
        g0 = so.grad_from_scores(z0, x0)

        def step(st, dvec, it):
            z = st.extra
            zp = so.score_delta(dvec)          # the ONE data pass (matvec)
            dg = dot(dvec, st.g)
            # Probes are elementwise over maintained scores — no data pass.
            t_final, ft, _, accept, _ = armijo_backtrack(
                lambda t: (
                    so.value_from_scores(z + t * zp, st.x + t * dvec),
                    jnp.zeros((), dtype),
                ),
                st.f, dg, jnp.zeros((), dtype),
                cfg.max_line_search_iterations,
            )
            x_new = jnp.where(accept, st.x + t_final * dvec, st.x)
            z_new = jnp.where(accept, z + t_final * zp, z)
            # Refresh z from x periodically: the incremental z accumulates
            # one rounding per accepted step, which can stall convergence
            # near the optimum. One extra matvec every 8 iterations.
            refresh = jnp.mod(it + 1, 8) == 0
            z_new = lax.cond(
                refresh,
                lambda: so.score(x_new),
                lambda: z_new,
            )
            f_new = jnp.where(accept, ft, st.f)
            g_new = so.grad_from_scores(z_new, x_new)   # one rmatvec
            # 1 matvec (Xp) + 1 rmatvec (grad) + the conditional z refresh.
            passes = 2 + refresh.astype(jnp.int32)
            return x_new, f_new, g_new, z_new, t_final, passes

        return self._solve(x0, f0, g0, z0, step)
