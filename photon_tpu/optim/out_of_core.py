"""Out-of-core fixed-effect training: host-resident row chunks streamed
through the accelerator per pass.

Why: a single TPU's HBM cannot hold config-5-scale data (100M rows x 32 nnz
= 25.6 GB of ELL vs 16 GB HBM), and the in-core path materializes the whole
dataset as device arrays (``io/data_reader.py:102``). The reference never
held the dataset on one box either — its distributed objective aggregates
partition-wise value+grad contributions (⟦ValueAndGradientAggregator⟧ via
Spark ``treeAggregate``, SURVEY.md §2.2 "Distributed objective"). This
module is that design re-cast for one accelerator whose bottleneck is HBM
capacity, not cluster size:

* Only the ELL arrays (``idx``/``val`` — the O(dataset) payload) stay in
  host RAM, split into fixed-shape row chunks; every optimizer pass streams
  them through jitted per-chunk kernels (one compile per chunk shape).
* Everything O(rows) or O(dim) is device-resident: labels/offsets/weights,
  the maintained margins z = Xw (+offsets), the direction margins, w, the
  gradient, and the L-BFGS history — so line-search probes are elementwise
  device math over the resident margins, never a data pass (the
  incremental-score trick of ``optim/lbfgs.py:310`` — same 2 streamed
  passes per iteration: direction matvec + gradient rmatvec).
* The L-BFGS math itself REUSES the in-core pieces (``two_loop_direction``,
  ``update_history``, ``check_convergence`` semantics, Armijo constants),
  so out-of-core and in-core solves agree to numerical noise — tested.

Scope: smooth L2 GLM objectives (all four pointwise losses) via
:class:`OutOfCoreLBFGS`, and L1/elastic-net via :class:`OutOfCoreOWLQN`
(the orthant machinery — pseudo-gradient, alignment, projection — is
elementwise in coefficient space, so it streams exactly like the smooth
solver; only the line search costs one extra pass per probe because the
orthant projection invalidates the resident direction margins). TRON,
priors, SIMPLE/FULL variance and normalization remain in-core features;
the driver auto-routes fixed-effect solves here when the dataset would
blow the device-data budget.
"""
from __future__ import annotations

import dataclasses
import functools
import math
import os
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.data.batch import SparseFeatures
from photon_tpu.faults import fault_point
from photon_tpu.obs import trace_span
from photon_tpu.optim.base import (
    FUNCTION_VALUES_CONVERGED,
    MAX_ITERATIONS,
    NOT_CONVERGED,
    OptimizerConfig,
    OptimizerResult,
    check_convergence,
)
from photon_tpu.optim.lbfgs import (
    LBFGSHistory,
    empty_history,
)
from photon_tpu.optim.lbfgs import two_loop_direction as _two_loop_eager
from photon_tpu.optim.lbfgs import update_history as _update_history_eager
from photon_tpu.optim.owlqn import orthant, pseudo_gradient

# The out-of-core loops run in HOST Python (streams + checkpoints force
# that), so unlike the in-core solvers these helpers would execute as a
# cascade of EAGER ops — on the axon tunnel backend every eager op is a
# round-trip dispatch. Jit them once (pinning the default dot, a plain
# jnp.dot, out of the traced signature): one compiled program per call
# site instead of dozens of dispatches per iteration.
two_loop_direction = jax.jit(lambda g, hist: _two_loop_eager(g, hist))
update_history = jax.jit(lambda hist, s, y: _update_history_eager(hist, s, y))


@jax.jit
def _reg_at_t(w, d, t, l2v):
    """½·Σ l2v·(w + t·d)² — the line-search probe's regularizer term, one
    compiled program instead of 3-4 eager O(dim) dispatches per probe
    (every arg traced, so neither backtracking nor a λ-sweep recompiles)."""
    wt = w + t * d
    return 0.5 * jnp.sum(l2v * wt * wt)

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class _HostChunk:
    """One fixed-shape row chunk; the streamed (host-RAM) part is idx/val."""

    idx: np.ndarray   # [C, K] int32, ghost-padded (col == dim, val == 0)
    val: np.ndarray   # [C, K] float (f32, or bf16 via value_dtype)


@dataclasses.dataclass
class ChunkedGLMData:
    """Fixed-effect dataset as host-resident ELL chunks + device row data.

    ``labels``/``offsets``/``weights`` are per-chunk DEVICE arrays (weights
    carry 0 on padding rows, so padded rows contribute nothing — same ghost
    convention as ``LabeledBatch``). ``n_rows`` is the true (unpadded) row
    count.

    Sharding contract: a MESH solve rebinds ``labels``/``offsets``/
    ``weights`` IN PLACE to mesh-sharded device arrays (deliberate: at
    config-5 scale the unsharded originals are ~1.2 GB of HBM that must not
    sit next to their own sharded copies, and a λ-sweep re-enters with
    already-sharded arrays as no-op puts). The object is therefore bound to
    that mesh afterwards: reusing it under a DIFFERENT mesh re-shards it to
    the new mesh (one extra put per array), while host-side consumers
    (``labels_np``/``scores_out_of_core``) read sharded arrays fine on a
    single process. Don't interleave two meshes' solves over one instance
    in a tight loop — put churn, not correctness, is the cost.
    """

    chunks: list
    labels: list
    offsets: list
    weights: list
    dim: int
    n_rows: int
    chunk_rows: int

    @classmethod
    def from_arrays(
        cls,
        idx: np.ndarray,
        val: np.ndarray,
        labels: np.ndarray,
        dim: int,
        offsets: Optional[np.ndarray] = None,
        weights: Optional[np.ndarray] = None,
        chunk_rows: int = 1 << 20,
        value_dtype=None,
    ) -> "ChunkedGLMData":
        n, k = idx.shape
        if offsets is None:
            offsets = np.zeros(n, np.float32)
        if weights is None:
            weights = np.ones(n, np.float32)
        n_chunks = max(1, math.ceil(n / chunk_rows))
        chunks, lab, off, wgt = [], [], [], []
        for c in range(n_chunks):
            lo, hi = c * chunk_rows, min((c + 1) * chunk_rows, n)
            m = hi - lo
            pad = chunk_rows - m
            ci = np.full((chunk_rows, k), dim, np.int32)
            cv = np.zeros((chunk_rows, k), np.float32)
            ci[:m] = idx[lo:hi]
            cv[:m] = val[lo:hi]
            if value_dtype is not None:
                cv = np.asarray(jnp.asarray(cv).astype(value_dtype))
            chunks.append(_HostChunk(idx=ci, val=cv))
            lab.append(jnp.asarray(np.pad(labels[lo:hi], (0, pad))))
            off.append(jnp.asarray(np.pad(offsets[lo:hi], (0, pad))))
            wgt.append(jnp.asarray(np.pad(weights[lo:hi], (0, pad))))
        return cls(chunks=chunks, labels=lab, offsets=off, weights=wgt,
                   dim=dim, n_rows=n, chunk_rows=chunk_rows)

    @classmethod
    def from_stream(
        cls,
        chunk_iter,
        shard: str,
        dim: int,
        chunk_rows: int = 1 << 20,
        value_dtype=None,
        on_chunk=None,
    ) -> "ChunkedGLMData":
        """Build from ``StreamingAvroReader.iter_chunks`` output WITHOUT
        ever materializing the dataset as one device array — the whole point
        of this path (streamed chunks hold host numpy ELL; see
        ``io/streaming.py`` chunk construction). Streamed chunk widths (K)
        may vary; the OOC chunks use the global max so one kernel compile
        serves every chunk.

        ``on_chunk(i, host_chunk, labels, offsets, weights)``, when given, is
        invoked the moment chunk ``i`` is assembled — streaming callers use
        it to FAIL FAST on invalid data (a NaN in the first chunk of a 100M
        row stream must raise within seconds, not after the whole dataset is
        decoded into host RAM). An exception from the callback aborts the
        stream. Note the ELL width may still grow after a chunk is handed
        out (``regrow`` ghost-pads flushed chunks in place); ghost padding
        never changes a chunk's validity."""
        # Streamed chunks are consumed ONE AT A TIME (peak extra memory:
        # one assembly buffer) — materializing the iterator first would
        # double host RAM at exactly the scale this path exists for. The
        # ELL width K may grow mid-stream; already-flushed chunks are then
        # ghost-padded out to the new width (one chunk's copy at a time).
        cur_k = 1
        idx = np.full((chunk_rows, cur_k), dim, np.int32)
        val = np.zeros((chunk_rows, cur_k), np.float32)
        lab = np.zeros(chunk_rows, np.float32)
        off = np.zeros(chunk_rows, np.float32)
        wgt = np.zeros(chunk_rows, np.float32)
        out = cls(chunks=[], labels=[], offsets=[], weights=[], dim=dim,
                  n_rows=0, chunk_rows=chunk_rows)
        fill = 0

        def regrow(new_k: int):
            nonlocal cur_k, idx, val
            for i, h in enumerate(out.chunks):
                gi = np.full((chunk_rows, new_k), dim, np.int32)
                gv = np.zeros((chunk_rows, new_k), h.val.dtype)
                gi[:, :cur_k] = h.idx
                gv[:, :cur_k] = h.val
                out.chunks[i] = _HostChunk(idx=gi, val=gv)
            gi = np.full((chunk_rows, new_k), dim, np.int32)
            gv = np.zeros((chunk_rows, new_k), np.float32)
            gi[:, :cur_k] = idx
            gv[:, :cur_k] = val
            idx, val, cur_k = gi, gv, new_k

        def flush():
            nonlocal fill
            cv = val
            if value_dtype is not None:
                cv = np.asarray(jnp.asarray(val).astype(value_dtype))
            out.chunks.append(_HostChunk(idx=idx.copy(), val=cv.copy()))
            # COPY before jnp.asarray: on CPU backends jax may zero-copy an
            # aligned numpy buffer, and these fill buffers are zeroed and
            # reused for the next chunk — aliasing would corrupt every
            # already-appended chunk.
            out.labels.append(jnp.asarray(lab.copy()))
            out.offsets.append(jnp.asarray(off.copy()))
            out.weights.append(jnp.asarray(wgt.copy()))
            if on_chunk is not None:
                on_chunk(len(out.chunks) - 1, out.chunks[-1],
                         out.labels[-1], out.offsets[-1], out.weights[-1])
            idx[:] = dim
            val[:] = 0.0
            lab[:] = 0.0
            off[:] = 0.0
            wgt[:] = 0.0
            fill = 0

        for c in chunk_iter:
            sf = c.features[shard]
            ci, cv = np.asarray(sf.idx), np.asarray(sf.val)
            if ci.shape[1] > cur_k:
                regrow(ci.shape[1])
            out.n_rows += c.n_rows
            at = 0
            while at < c.n_rows:
                take = min(chunk_rows - fill, c.n_rows - at)
                sl = slice(fill, fill + take)
                idx[sl, : ci.shape[1]] = ci[at:at + take]
                val[sl, : cv.shape[1]] = cv[at:at + take]
                lab[sl] = c.labels[at:at + take]
                off[sl] = c.offsets[at:at + take]
                wgt[sl] = c.weights[at:at + take]
                fill += take
                at += take
                if fill == chunk_rows:
                    flush()
        if fill:
            flush()
        if not out.chunks:
            raise ValueError("no rows streamed")
        return out

    def rechunk(self, factor: int = 2) -> "ChunkedGLMData":
        """The same dataset re-cut at ``chunk_rows / factor`` — the OOM
        degradation ladder's out-of-core rung (docs/robustness.md
        §"Memory pressure"): when a streamed pass OOMs, halving the chunk
        shape halves the live per-chunk device footprint, and the solve
        re-enters over smaller chunks with identical (weight-0 ghost-
        padded) row content. Raises ValueError when no smaller cut exists
        (``chunk_rows == 1``)."""
        if factor < 2:
            raise ValueError(f"rechunk factor must be >= 2, got {factor}")
        new_rows = -(-self.chunk_rows // factor)  # ceil division
        if new_rows >= self.chunk_rows:
            raise ValueError(
                f"cannot rechunk below chunk_rows={self.chunk_rows}")
        k = self.chunks[0].idx.shape[1]
        chunks, lab, off, wgt = [], [], [], []
        for i, c in enumerate(self.chunks):
            for lo in range(0, self.chunk_rows, new_rows):
                hi = min(lo + new_rows, self.chunk_rows)
                pad = new_rows - (hi - lo)
                ci = c.idx[lo:hi]
                cv = c.val[lo:hi]
                if pad:
                    ci = np.concatenate(
                        [ci, np.full((pad, k), self.dim, np.int32)])
                    cv = np.concatenate(
                        [cv, np.zeros((pad, k), c.val.dtype)])
                chunks.append(_HostChunk(idx=ci, val=cv))
                for src, dst in ((self.labels, lab), (self.offsets, off),
                                 (self.weights, wgt)):
                    piece = src[i][lo:hi]
                    if pad:
                        piece = jnp.pad(piece, (0, pad))
                    dst.append(piece)
        return ChunkedGLMData(
            chunks=chunks, labels=lab, offsets=off, weights=wgt,
            dim=self.dim, n_rows=self.n_rows, chunk_rows=new_rows)

    @property
    def n_chunks(self) -> int:
        return len(self.chunks)

    def streamed_bytes_per_pass(self) -> int:
        c = self.chunks[0]
        return self.n_chunks * (c.idx.nbytes + c.val.nbytes)

    def labels_np(self) -> np.ndarray:
        return np.concatenate(
            [np.asarray(x) for x in self.labels])[: self.n_rows]

    def weights_np(self) -> np.ndarray:
        return np.concatenate(
            [np.asarray(x) for x in self.weights])[: self.n_rows]


class StreamPrimer:
    """First optimizer pass computed per chunk AS IT STREAMS IN.

    Pass an instance as ``ChunkedGLMData.from_stream(..., on_chunk=primer)``:
    the moment chunk *i* is assembled, its ELL arrays go to device (through
    the sweep cache when given, so pass 1 of the solve reuses the upload)
    and the chunk's initial scores ``z = X·w0 + offsets`` and data
    value/gradient contribution are computed inside an
    ``optim.stream_init_pass`` span — so with a prefetched chunk iterator
    (``io/prefetch.py``) the solve's init pass overlaps block decode instead
    of running after it, and ``optimize(..., primed=primer.primed())`` skips
    its two init passes entirely. The per-chunk kernels and accumulation
    order are EXACTLY the solver's own (``_kernels_for``; f/g accumulate
    chunk 0..n−1), so a primed solve is bit-identical to an unprimed one.

    Single-device only: a mesh solve row-shards its resident vectors and
    ignores ``primed`` (documented in ``optimize``).
    """

    def __init__(self, loss, dim: int, w0=None, device_cache=None):
        self.dim = int(dim)
        self._kernels = _kernels_for(loss, dim)
        self.w0 = (jnp.zeros((dim,), jnp.float32) if w0 is None
                   else jnp.asarray(w0, jnp.float32))
        self.device_cache = device_cache
        self.z: list = []
        self.fd = jnp.zeros((), jnp.float32)
        self.gd = jnp.zeros((dim,), jnp.float32)
        self._fed_keys: list = []
        self._chunks_seen: list = []
        self._ell_width: Optional[int] = None

    def __call__(self, i, host_chunk, labels, offsets, weights) -> None:
        k_matvec, _k_probe, _k_probe_t, k_grad = self._kernels
        # from_stream REGROWS already-flushed chunks in place when the ELL
        # width widens mid-stream: every pin this primer made for the old
        # (now freed) arrays can never be hit again — discard them so the
        # budget holds live data, not orphans. The z/f/g already computed
        # stay exact (regrow only adds ghost padding).
        width = int(host_chunk.idx.shape[1])
        if (self.device_cache is not None and self._ell_width is not None
                and width != self._ell_width):
            for k in self._fed_keys:
                self.device_cache.discard(k)
            self._fed_keys.clear()
        self._ell_width = width
        # Feed FIRST, outside the compute span: the timeline analyzer's
        # overlap report must never count a same-thread H2D nested inside a
        # compute span as "ingest concurrent with compute".
        ci, cv = _feed_chunk(host_chunk, self.device_cache,
                             lambda a: jnp.asarray(a))
        if self.device_cache is not None:
            self._fed_keys.append(("ooc_ell", id(host_chunk.idx)))
        self._chunks_seen.append(host_chunk)
        with trace_span("optim.stream_init_pass", cat="optim", chunk=i,
                        rows=int(labels.shape[0])):
            z = k_matvec(self.w0, ci, cv, offsets)
            fc, gc = k_grad(z, labels, weights, ci, cv)
            self.z.append(z)
            self.fd = self.fd + fc
            self.gd = self.gd + gc

    def primed(self) -> dict:
        """State for ``optimize(..., primed=...)``: resident margins plus
        the DATA-ONLY value/gradient at ``w0`` (the solver adds its own
        regularizer terms), stamped with the chunk objects the pass ran
        over so a prime from a DIFFERENT dataset can never be trusted."""
        return {"z": self.z, "fd": self.fd, "gd": self.gd, "w0": self.w0,
                "chunks": list(self._chunks_seen)}


def _feed_chunk(c: "_HostChunk", cache, put):
    """(idx, val) of one host chunk on device — through the sweep cache when
    given (multi-sweep/multi-pass solves stop re-uploading), else a traced
    one-shot transfer. Keys by the ARRAY identity so a regrown chunk (new
    arrays) re-uploads instead of serving stale width."""
    if cache is not None and cache.enabled:
        return cache.get_or_put(
            ("ooc_ell", id(c.idx)),
            c.idx.nbytes + c.val.nbytes,
            lambda: (put(c.idx), put(c.val)),
            # Pin the keyed host array: a regrown chunk frees its original
            # arrays, and a recycled id() must never alias a NEW chunk onto
            # this (stale) device entry.
            retain=c.idx,
        )
    from photon_tpu.obs import trace_span as _span

    with _span("ingest.device_put", cat="ingest",
               bytes=int(c.idx.nbytes + c.val.nbytes), cached=False):
        return put(c.idx), put(c.val)


@functools.lru_cache(maxsize=None)
def _matvec_for(dim: int):
    @jax.jit
    def k_matvec(w, idx, val, offsets):
        sf = SparseFeatures(idx=idx, val=val, dim=dim)
        return sf.matvec(w) + offsets

    return k_matvec


@functools.lru_cache(maxsize=None)
def _kernels_for(loss, dim: int):
    """(matvec, probe, probe_at_t, grad) jitted per-chunk kernels. Cached
    on the (loss, dim) pair — `loss_for_task` returns per-task singletons,
    so a regularization sweep never recompiles (λ enters host-side only)."""

    @jax.jit
    def k_probe(z, labels, weights):
        return jnp.sum(weights * loss.loss(z, labels))

    @jax.jit
    def k_probe_at_t(z, zd, t, labels, weights):
        # Fused line-search probe over RESIDENT margins: one compiled
        # program instead of an eager z+t·zd add (a full chunk-sized
        # temporary + an extra dispatch per chunk per probe — on the axon
        # tunnel backend every eager op is a round trip). ``t`` is a
        # traced scalar so backtracking never recompiles.
        return jnp.sum(weights * loss.loss(z + t * zd, labels))

    @jax.jit
    def k_grad(z, labels, weights, idx, val):
        lv, d1 = loss.loss_and_d1(z, labels)
        sf = SparseFeatures(idx=idx, val=val, dim=dim)
        return jnp.sum(weights * lv), sf.rmatvec(weights * d1)

    return _matvec_for(dim), k_probe, k_probe_at_t, k_grad


@functools.lru_cache(maxsize=None)
def _kernels_for_spmd(loss, dim: int, mesh, axes: tuple):
    """Explicit-collective variants of :func:`_kernels_for`: every kernel is
    a ``shard_map`` body over the row axis with ONE ``lax.psum`` where the
    dense path has a row reduction — the out-of-core consumption of the
    ``parallel/spmd_objective`` pattern (treeAggregate ≙ psum, SURVEY.md
    §2.2). Same signatures, same results to fp noise; selected by
    ``OutOfCoreLBFGS(collectives="shard_map")``. Cached per
    (loss, dim, mesh, axes) so a λ-sweep never recompiles."""
    from functools import partial as _partial

    from jax import lax
    from jax.sharding import PartitionSpec as P

    from photon_tpu.parallel.mesh import shard_map

    row, ell = P(axes), P(axes, None)
    smap = _partial(shard_map, mesh=mesh)

    @jax.jit
    @_partial(smap, in_specs=(P(), ell, ell, row), out_specs=row)
    def k_matvec(w, idx, val, offsets):
        sf = SparseFeatures(idx=idx, val=val, dim=dim)
        return sf.matvec(w) + offsets

    @jax.jit
    @_partial(smap, in_specs=(row, row, row), out_specs=P())
    def k_probe(z, labels, weights):
        return lax.psum(jnp.sum(weights * loss.loss(z, labels)), axes)

    @jax.jit
    @_partial(smap, in_specs=(row, row, P(), row, row), out_specs=P())
    def k_probe_at_t(z, zd, t, labels, weights):
        return lax.psum(
            jnp.sum(weights * loss.loss(z + t * zd, labels)), axes)

    @jax.jit
    @_partial(smap, in_specs=(row, row, row, ell, ell),
              out_specs=(P(), P()))
    def k_grad(z, labels, weights, idx, val):
        lv, d1 = loss.loss_and_d1(z, labels)
        sf = SparseFeatures(idx=idx, val=val, dim=dim)
        return (lax.psum(jnp.sum(weights * lv), axes),
                lax.psum(sf.rmatvec(weights * d1), axes))

    return k_matvec, k_probe, k_probe_at_t, k_grad


def _mesh_puts(mesh, data_axis, chunk_rows: int):
    """``(put_row, put_ell, put_rep)`` placement helpers shared by every
    streamed solver: row-sharded resident vectors, row-sharded ELL chunk
    streams, replicated coefficient-space state (SURVEY.md §2.6 P1 × OOC).
    ``data_axis`` may be one mesh axis or a tuple (``("dcn", "data")`` on a
    2-level multi-slice mesh). With no mesh all three are the identity.

    The row/ELL puts are the "fan out per shard" half of the streamed data
    path: ``jax.device_put`` with a NamedSharding splits the host chunk
    into per-device shards and issues each shard's H2D directly to its
    device — wrapped in ``pipelined_puts`` by ``ell_feed`` so shard
    transfers for chunk N+1 overlap chunk N's compute."""
    if mesh is None:
        def ident(a):
            return a

        return ident, ident, ident
    from jax.sharding import NamedSharding, PartitionSpec

    from photon_tpu.parallel.mesh import axes_size, axis_tuple

    axes = axis_tuple(data_axis)
    nsh = axes_size(mesh, axes)
    if chunk_rows % nsh != 0:
        raise ValueError(
            f"chunk_rows={chunk_rows} must divide evenly over "
            f"mesh axis {data_axis!r} ({nsh} devices) for "
            "row-sharded streaming"
        )
    _row = NamedSharding(mesh, PartitionSpec(axes))
    _ell = NamedSharding(mesh, PartitionSpec(axes, None))
    _rep = NamedSharding(mesh, PartitionSpec())

    def put_row(a):
        return jax.device_put(a, _row)

    def put_ell(a):
        return jax.device_put(a, _ell)

    def put_rep(a):
        return jax.device_put(a, _rep)

    return put_row, put_ell, put_rep


@dataclasses.dataclass(frozen=True)
class OutOfCoreLBFGS:
    """Host-loop L-BFGS over a :class:`ChunkedGLMData` (see module doc)."""

    loss: object                      # PointwiseLoss
    l2_weight: float = 0.0
    reg_mask: Optional[Array] = None
    config: OptimizerConfig = OptimizerConfig()
    # Called after every iteration with (it, value, grad_norm, passes).
    # Streamed passes can take minutes each at scale; liveness signals
    # (driver logs, autopilot stall detection) hang off this.
    progress: Optional[object] = None
    # Per-iteration checkpoint/resume (.npz written atomically after an
    # accepted step). A config-5-scale solve outlives the flaky tunnel's
    # recovery windows (~minutes, 2026-07-31), so a killed solve must
    # restart at iteration k, not 0. Scores (n_rows floats) are NOT stored
    # — they rebuild from w in one streamed pass on resume. Saves throttle
    # to one per ``checkpoint_min_interval_s`` (after the first): at 10M+
    # features a save is ~0.9 GB of npz, and losing <interval of work is
    # the same accepted trade as the scores-rebuild pass.
    checkpoint_path: Optional[str] = None
    checkpoint_min_interval_s: float = 60.0
    # Data-parallel streaming (SURVEY.md §2.6 P1 × out-of-core): with a
    # Mesh, every streamed chunk is device_put ROW-SHARDED over
    # ``data_axis`` while w/direction stay replicated — GSPMD partitions
    # the per-chunk kernels and inserts the cross-device reductions
    # (value/grad all-reduce), so a pod streams each pass at aggregate
    # H2D + HBM bandwidth. This is how the config-5 shape maps to a
    # v5e-256: host-resident chunks per process, rows sharded over the
    # mesh, one collective per pass — the reference's treeAggregate
    # re-cast as GSPMD (SURVEY.md §2.2 "Distributed objective").
    mesh: Optional[object] = None
    data_axis: str = "data"
    # Collective lowering under a mesh: "gspmd" (default — sharded inputs,
    # XLA inserts the all-reduces) or "shard_map" (explicit psum kernels
    # from _kernels_for_spmd — hand-placed collectives for multi-slice
    # meshes / auditability; same results to fp noise, tested).
    collectives: str = "gspmd"
    # Device-resident sweep cache (photon_tpu/data/device_cache.py): streamed
    # ELL chunks pin on device after the first pass that touches them, so a
    # multi-iteration solve (and a multi-sweep GAME fit re-entering it) stops
    # re-uploading the dataset — budget-gated, spills back to streaming.
    device_cache: Optional[object] = None

    # -- jitted per-chunk kernels -----------------------------------------

    def _kernels(self, dim: int):
        # Module-level cache: kernels depend only on (loss, dim), NOT on
        # the reg weight, so a driver λ-sweep shares one compile across the
        # whole grid (the in-core sweep makes the same guarantee).
        return _kernels_for(self.loss, dim)

    # -- scaffolding shared with OutOfCoreOWLQN ---------------------------

    def _streams(self, data: ChunkedGLMData):
        """Shard the resident row vectors (REBINDING onto ``data`` — see
        the class doc's sharding contract) and return the streamed-pass
        closures ``(put_rep, stream_scores, data_value, data_value_at_t,
        stream_grad)``
        every out-of-core solver loop is built from."""
        if self.mesh is not None and self.collectives == "shard_map":
            from photon_tpu.parallel.mesh import axis_tuple

            k_matvec, k_probe, k_probe_at_t, k_grad = _kernels_for_spmd(
                self.loss, data.dim, self.mesh,
                tuple(axis_tuple(self.data_axis)))
        elif self.collectives not in ("gspmd", "shard_map"):
            raise ValueError(
                f"collectives must be 'gspmd' or 'shard_map', "
                f"got {self.collectives!r}")
        else:
            k_matvec, k_probe, k_probe_at_t, k_grad = self._kernels(data.dim)
        put_row, put_ell, put_rep = _mesh_puts(
            self.mesh, self.data_axis, data.chunk_rows
        )
        labels = data.labels = [put_row(x) for x in data.labels]
        offsets = data.offsets = [put_row(x) for x in data.offsets]
        weights = data.weights = [put_row(x) for x in data.weights]

        # The no-mesh put is an EXPLICIT device commit (jnp.asarray), not
        # the identity: relying on the kernel call's implicit conversion
        # would re-upload every pass even when the sweep cache "holds" the
        # chunk (it would be pinning host numpy). Mesh solves keep the
        # sharded device_put, which commits directly to the right layout.
        put_dev = put_ell if self.mesh is not None else jnp.asarray

        def feed_one(c):
            # Chaos hook: error="device_oom" per streamed chunk drives the
            # halve-chunk_rows degradation ladder in optimize() on CPU.
            fault_point("optim.ooc_chunk", chunk_rows=data.chunk_rows)
            return _feed_chunk(c, self.device_cache, put_dev)

        def ell_feed():
            """Per-pass (idx, val) device feed, DOUBLE-BUFFERED: chunk i+1's
            transfer is issued before chunk i is handed to its kernel, so an
            async backend overlaps the next H2D with the current compute.
            Chunks pinned by the sweep cache skip the transfer entirely."""
            from photon_tpu.io.prefetch import pipelined_puts

            return pipelined_puts(data.chunks, feed_one, ahead=1)

        # Per-chunk compute spans (cat "optim") cover ONLY the kernel call;
        # the feed is pulled from the generator BEFORE the span opens, so
        # the analyzer's ingest/compute overlap never credits a same-thread
        # serial H2D as concurrency. (Spans measure dispatch wall, the
        # repo-wide convention for async backends.)
        def stream_scores(wv, with_offsets=True):
            zero = jnp.zeros_like(offsets[0])
            out = []
            for i, (ci, cv) in enumerate(ell_feed()):
                with trace_span("optim.ooc_scores_chunk", cat="optim",
                                chunk=i):
                    out.append(
                        k_matvec(wv, ci, cv,
                                 offsets[i] if with_offsets else zero)
                    )
            return out

        def data_value(z_chunks):
            with trace_span("optim.ooc_probe", cat="optim",
                            chunks=len(z_chunks)):
                return sum(
                    k_probe(z, labels[i], weights[i])
                    for i, z in enumerate(z_chunks)
                )

        def data_value_at_t(z_chunks, zd_chunks, t):
            """Line-search probe f_data(z + t·zd), fused per chunk."""
            t = jnp.asarray(t, jnp.float32)
            with trace_span("optim.ooc_probe", cat="optim",
                            chunks=len(z_chunks)):
                return sum(
                    k_probe_at_t(z, zd, t, labels[i], weights[i])
                    for i, (z, zd) in enumerate(zip(z_chunks, zd_chunks))
                )

        def stream_grad(z_chunks):
            f = jnp.zeros((), jnp.float32)
            g = jnp.zeros((data.dim,), jnp.float32)
            for i, (ci, cv) in enumerate(ell_feed()):
                with trace_span("optim.ooc_grad_chunk", cat="optim",
                                chunk=i):
                    fc, gc = k_grad(z_chunks[i], labels[i], weights[i],
                                    ci, cv)
                    f, g = f + fc, g + gc
            return f, g

        return (put_rep, stream_scores, data_value, data_value_at_t,
                stream_grad)

    def _ckpt_tag(self, data: ChunkedGLMData, prefix: str,
                  extra: str = "") -> str:
        """Fingerprint guarding a checkpoint against a DIFFERENT problem or
        data resuming from it: loss (task), shape, chunking, regularization
        (weights AND mask, ``extra`` carries solver-specific terms like the
        L1 weight), iteration cap, plus cheap content probes over EVERY
        data component (labels, weights, offsets, features of the first
        chunk) so same-shaped different data never cross-resumes —
        regenerated features or reweighted rows change the tag even when
        labels don't."""
        cfg = self.config
        c0 = data.chunks[0]
        data_probe = (
            float(np.asarray(data.labels[0], np.float64).sum()),
            float(np.asarray(data.weights[0], np.float64).sum()),
            float(np.asarray(data.offsets[0], np.float64).sum()),
            int(np.asarray(c0.idx, np.int64).sum()),
            float(np.asarray(c0.val, np.float64).sum()),
        )
        mask_probe = (
            "none" if self.reg_mask is None
            else repr(float(np.asarray(self.reg_mask, np.float64).sum()))
        )
        return (
            f"{prefix}:{type(self.loss).__name__}:{data.n_rows}:{data.dim}:"
            f"{data.n_chunks}:{data.chunk_rows}:{self.l2_weight}:{extra}"
            f"{mask_probe}:{cfg.history_length}:{cfg.max_iterations}:"
            f"{data_probe!r}"
        )

    @staticmethod
    def _restore(state, put_rep):
        """Checkpointed coefficient-space state, re-placed under the SAME
        replicated sharding the fresh path gives it — resuming a mesh solve
        with default-device arrays would recompile every kernel under
        different input shardings (and fail outright on a multi-host mesh
        with non-addressable devices)."""
        hist = LBFGSHistory(
            s=put_rep(jnp.asarray(state["hist_s"])),
            y=put_rep(jnp.asarray(state["hist_y"])),
            rho=put_rep(jnp.asarray(state["hist_rho"])),
            count=put_rep(jnp.asarray(state["hist_count"])),
            pos=put_rep(jnp.asarray(state["hist_pos"])),
        )
        return (
            put_rep(jnp.asarray(state["w"])),
            put_rep(jnp.asarray(state["g"])),
            hist,
            int(state["it"]),
            int(state["passes"]),
            jnp.asarray(state["f"]),
            jnp.asarray(state["f_prev"]),
            jnp.asarray(state["gnorm0"]),
            np.asarray(state["values"]).copy(),
            np.asarray(state["grad_norms"]).copy(),
        )

    def _l2_vec(self, w: Array) -> Array:
        if self.reg_mask is None:
            return jnp.full_like(w, self.l2_weight)
        return self.l2_weight * self.reg_mask.astype(w.dtype)

    # -- checkpoint/resume -------------------------------------------------

    _STATE_KEYS = ("w", "g", "hist_s", "hist_y", "hist_rho", "hist_count",
                   "hist_pos", "it", "passes", "f", "f_prev", "gnorm0",
                   "values", "grad_norms")

    def _load_checkpoint(self, tag: str, dim: int):
        if self.checkpoint_path is None:
            return None
        try:
            state = np.load(self.checkpoint_path, allow_pickle=False)
            # Validate AND materialize every member inside the try: a
            # corrupt zip can raise lazily on member access (BadZipFile /
            # EOFError / KeyError), and a bad checkpoint must mean "start
            # fresh", never a crashed solve that dies identically every
            # retry window.
            if str(state.get("tag", "")) != tag or state["w"].shape != (dim,):
                return None  # different problem/data: never cross-resume
            return {k: np.asarray(state[k]) for k in self._STATE_KEYS}
        except FileNotFoundError:
            return None  # no checkpoint yet: the normal first-run case
        except Exception as e:  # noqa: BLE001 - any unreadable state = fresh run
            # WARN, don't raise: a corrupt checkpoint means "start fresh".
            # But silence would make a RECURRING failure (e.g. permissions
            # on checkpoint_path) look like "no checkpoint" forever — every
            # recovery window would restart at iteration 0 with no signal.
            import logging

            logging.getLogger("photon_tpu.ooc").warning(
                "checkpoint %s unreadable (%s: %s) — starting fresh; if "
                "this repeats, resume is broken, not absent",
                self.checkpoint_path, type(e).__name__, e,
            )
            return None

    def _save_checkpoint(self, tag: str, w, g, hist, it, passes, f, f_prev,
                         gnorm0, values, grad_norms) -> None:
        if self.checkpoint_path is None:
            return
        tmp = self.checkpoint_path + ".tmp"
        try:
            with open(tmp, "wb") as fh:
                np.savez(
                    fh, tag=tag,
                    w=np.asarray(w), g=np.asarray(g),
                    hist_s=np.asarray(hist.s), hist_y=np.asarray(hist.y),
                    hist_rho=np.asarray(hist.rho),
                    hist_count=np.asarray(hist.count),
                    hist_pos=np.asarray(hist.pos),
                    it=it, passes=passes,
                    f=np.asarray(f), f_prev=np.asarray(f_prev),
                    gnorm0=np.asarray(gnorm0),
                    values=values, grad_norms=grad_norms,
                )
            os.replace(tmp, self.checkpoint_path)
        except OSError:
            pass  # best-effort: a failed save must never kill the solve

    def _primed_init(self, primed, data: ChunkedGLMData, w) -> Optional[tuple]:
        """(z, fd, gd) from a :class:`StreamPrimer` when it is usable for
        THIS solve: the prime's pass ran over EXACTLY these chunk objects
        (identity-checked — a prime from a different dataset, or from
        chunks replaced by a mid-stream regrow, must never be trusted), at
        exactly this start point, no mesh (the primer's margins are
        unsharded). Unusable primes fall back to the fresh init passes —
        correctness never depends on the pipeline.
        """
        if primed is None or self.mesh is not None:
            return None
        z = primed.get("z") or []
        chunks = primed.get("chunks") or []
        if len(z) != data.n_chunks or len(chunks) != data.n_chunks or any(
                a is not b for a, b in zip(chunks, data.chunks)):
            return None
        w0 = primed.get("w0")
        if w0 is None or w0.shape != w.shape or not bool(
                jnp.all(w0 == w)):
            return None
        return z, primed["fd"], primed["gd"]

    def optimize(self, data: ChunkedGLMData, x0: Array,
                 primed: Optional[dict] = None) -> OptimizerResult:
        """``primed`` (from :class:`StreamPrimer`) carries the init pass
        computed while the data streamed in; a valid prime skips the two
        init passes (scores + gradient) bit-identically.

        In-run device-loss recovery (docs/robustness.md): a classified
        device loss mid-solve does NOT kill the attempt — the executable
        caches clear, sweep-cache pins release, and the solve re-enters
        through ``_optimize_impl``, whose checkpoint load fast-forwards to
        the last saved iteration (or restarts the deterministic loop from
        scratch without a checkpoint path) — bit-identical either way.
        Bounded by ``PHOTON_DEVICE_LOST_MAX_RECOVERIES``; past it the
        error escalates to the supervisor restart.

        An ``oom``-classified failure takes the DEGRADATION ladder instead
        (docs/robustness.md §"Memory pressure"): restarting with identical
        chunk shapes would deterministically re-OOM, so the solve halves
        ``chunk_rows`` (``ChunkedGLMData.rechunk``) and re-enters — the
        per-chunk device footprint halves while the row content (weight-0
        ghost padding) is unchanged. Bounded by
        ``PHOTON_OOM_MAX_DOWNSHIFTS``; the downshift is journaled, counted
        in ``oom_downshifts_total{site="optim.ooc_chunk"}``, and sticky
        for this solve (the re-cut data IS the new plan). Note the
        rechunked solve restarts its iteration loop from scratch: the
        checkpoint tag covers the chunking, so a cross-chunking resume is
        refused by design."""
        recoveries = 0
        while True:
            try:
                return self._optimize_impl(data, x0, primed=primed)
            except Exception as e:  # noqa: BLE001 - classified below
                import logging

                from photon_tpu.runtime import backend_guard as _bg
                from photon_tpu.runtime import memory_guard as _mg

                log = logging.getLogger("photon_tpu.ooc")
                if _mg.is_oom(e):
                    # Rechunking under a mesh must keep chunk_rows evenly
                    # divisible over the data axis (_mesh_puts contract).
                    new_rows = -(-data.chunk_rows // 2)
                    divisible = (self.mesh is None or new_rows
                                 % self.mesh.shape[self.data_axis] == 0)
                    if data.chunk_rows <= 1 or not divisible:
                        # No cheaper cut exists: journal the classified
                        # exhaustion (same contract as re.solve) so the
                        # recovery record shows WHY the OOM escalated.
                        _mg.journal_event(
                            "oom_exhausted", site="optim.ooc_chunk",
                            cause="oom",
                            plan=f"chunk_rows={data.chunk_rows}",
                            reason=("chunk_rows already 1" if divisible
                                    else "half-cut not divisible over the "
                                         "mesh data axis"))
                        raise
                    if not _mg.downshifter("optim.ooc_chunk").absorb(
                            e, before=f"chunk_rows={data.chunk_rows}",
                            after=f"chunk_rows={new_rows}"):
                        raise  # absorb journaled the spent budget
                    if self.device_cache is not None:
                        # The old cut's pins can never be hit again.
                        for c in data.chunks:
                            self.device_cache.discard(
                                ("ooc_ell", id(c.idx)))
                    data = data.rechunk(2)
                    primed = None  # margins were cut for the old shape
                    continue
                if (not _bg.is_device_lost(e)
                        or recoveries >= _bg.max_inrun_recoveries()):
                    raise
                recoveries += 1
                log.warning(
                    "device lost mid-solve (%s: %s); in-run recovery %d/%d"
                    "%s", type(e).__name__, e, recoveries,
                    _bg.max_inrun_recoveries(),
                    ", resuming from checkpoint" if self.checkpoint_path
                    else ", re-running the deterministic loop")
                _bg.recover_from_device_loss(
                    "out-of-core solve", device_cache=self.device_cache,
                )
                # The prime's resident margins died with the device; the
                # re-entry rebuilds them (checkpoint scores-rebuild pass or
                # fresh init passes).
                primed = None

    def _optimize_impl(self, data: ChunkedGLMData, x0: Array,
                       primed: Optional[dict] = None) -> OptimizerResult:
        cfg = self.config
        dim = data.dim
        (put_rep, stream_scores, data_value, data_value_at_t,
         stream_grad) = self._streams(data)

        w = put_rep(jnp.asarray(x0, jnp.float32))
        l2v = self._l2_vec(w)

        def full_fg(wv, z_chunks):
            fd, gd = stream_grad(z_chunks)
            return (fd + 0.5 * jnp.sum(l2v * wv * wv), gd + l2v * wv)

        max_it = cfg.max_iterations
        ckpt_tag = self._ckpt_tag(data, "ooc-v1")
        state = self._load_checkpoint(ckpt_tag, dim)
        if state is not None:
            (w, g, hist, it, passes, f, f_prev, gnorm0, values,
             grad_norms) = self._restore(state, put_rep)
            z = stream_scores(w)  # scores rebuild from w: one pass
            passes += 1
        else:
            prime = self._primed_init(primed, data, w)
            if prime is not None:
                # The init already ran during ingest as ONE fused pass per
                # chunk (scores + grad off the same feed) — data_passes is
                # a measured count, so the prime records 1, not the
                # unprimed path's 2.
                z, fd, gd = prime
                f = fd + 0.5 * jnp.sum(l2v * w * w)
                g = gd + l2v * w
                passes = 1
            else:
                # init: one scores pass + one grad pass
                z = stream_scores(w)
                f, g = full_fg(w, z)
                passes = 2
            gnorm0 = jnp.linalg.norm(g)
            hist = empty_history(cfg.history_length, dim, jnp.float32)
            values = np.full(max_it + 1, np.inf, np.float32)
            grad_norms = np.full(max_it + 1, np.inf, np.float32)
            values[0] = float(f)
            grad_norms[0] = float(gnorm0)
            it = 0
            f_prev = jnp.asarray(jnp.inf, jnp.float32)

        reason = NOT_CONVERGED
        last_save = float("-inf")
        while True:
            # Chaos hook: error="device_lost" here exercises the in-run
            # recovery wrapper in optimize() (checkpoint fast-forward →
            # bit-identical result).
            fault_point("optim.ooc_iteration", it=it)
            # Convergence test BEFORE the max-iteration cut (and so also
            # after the final update) — same ordering as the in-core loop,
            # so converged_reason agrees on runs that converge exactly at
            # the iteration cap.
            reason = int(check_convergence(
                jnp.asarray(it), f_prev, f, jnp.linalg.norm(g), gnorm0, cfg
            ))
            if reason != NOT_CONVERGED:
                break
            if it >= max_it:
                reason = MAX_ITERATIONS
                break
            d = two_loop_direction(g, hist)
            dg = jnp.dot(d, g)
            if float(dg) >= 0.0:  # not a descent direction: restart memory
                hist = empty_history(cfg.history_length, dim, jnp.float32)
                d, dg = -g, -jnp.dot(g, g)
            zd = stream_scores(d, with_offsets=False)
            passes += 1
            # Armijo backtracking over RESIDENT margins (no data pass per
            # probe) — same constants as optim/lbfgs.py armijo_backtrack.
            t, ft, accept = 1.0, f, False
            t_last = 0.0  # the step size the CURRENT ft was evaluated at
            c1, shrink = 1e-4, 0.5
            for _ in range(cfg.max_line_search_iterations):
                ft = data_value_at_t(z, zd, t) + _reg_at_t(
                    w, d, jnp.asarray(t, jnp.float32), l2v
                )
                if bool(jnp.isfinite(ft)) and float(ft) <= float(
                    f + c1 * t * dg
                ):
                    accept = True
                    break
                t_last = t
                t *= shrink
            if not accept and bool(jnp.isfinite(ft)) and float(ft) < float(f):
                # Smallest PROBED step still decreases f: apply that exact
                # step, not the once-more-shrunk t that was never evaluated.
                t = t_last
                accept = t > 0.0
            if not accept:
                # No further progress possible — same terminal behavior as
                # the in-core loop (next dual test fires on |Δf| = 0).
                reason = FUNCTION_VALUES_CONVERGED
                break
            s = t * d
            w = w + s
            z = [z[i] + t * zd[i] for i in range(data.n_chunks)]
            f_prev = f
            f, g_new = full_fg(w, z)
            passes += 1
            hist = update_history(hist, s, g_new - g)
            g = g_new
            it += 1
            values[it] = float(f)
            grad_norms[it] = float(jnp.linalg.norm(g))
            # Save BEFORE the progress callback: the checkpoint must bank
            # the just-finished iteration even if logging (or a supervisor
            # signal delivered inside it) kills the process. Throttled
            # after the first save (see checkpoint_min_interval_s).
            now = time.monotonic()
            if it == 1 or now - last_save >= self.checkpoint_min_interval_s:
                self._save_checkpoint(ckpt_tag, w, g, hist, it, passes, f,
                                      f_prev, gnorm0, values, grad_norms)
                last_save = now
            if self.progress is not None:
                self.progress(it, values[it], grad_norms[it], passes)

        self._save_checkpoint(ckpt_tag, w, g, hist, it, passes, f,
                              f_prev, gnorm0, values, grad_norms)
        return OptimizerResult(
            x=w,
            value=f,
            grad_norm=jnp.linalg.norm(g),
            iterations=jnp.asarray(it, jnp.int32),
            converged_reason=jnp.asarray(reason, jnp.int32),
            values=jnp.asarray(values),
            grad_norms=jnp.asarray(grad_norms),
            data_passes=jnp.asarray(passes, jnp.int32),
        )


@dataclasses.dataclass(frozen=True)
class OutOfCoreOWLQN(OutOfCoreLBFGS):
    """Host-loop OWL-QN over a :class:`ChunkedGLMData` — L1/elastic-net at
    beyond-HBM scale (BASELINE config 2; SURVEY.md §2.1 OWL-QN).

    Same Andrew & Gao (2007) semantics as the in-core ``optim/owlqn.py``
    (pseudo-gradient, smooth-gradient history, direction alignment, orthant
    projection of trial points, Armijo on the total objective via the
    projected displacement, same constants), so in-core and out-of-core
    solves agree to numerical noise — tested.

    The one structural difference from :class:`OutOfCoreLBFGS`: the orthant
    projection makes a trial point a NONLINEAR function of the step size
    (clipped coordinates pin to zero), so the resident direction margins
    ``zd`` cannot price a probe — each line-search probe streams one scores
    pass. Probes are value-only (the in-core path computes a fused
    value+grad per probe = 2 passes), so a typical accept-at-t=1 iteration
    costs probe + gradient = 2 streamed passes, identical to the smooth
    solver. Everything else (mesh row-sharding, per-iteration checkpoints,
    λ-sweep kernel reuse) is inherited.

    ``l1_weight`` scales ``reg_mask`` (ones if absent) into the
    per-coefficient L1 vector — the intercept stays unpenalized exactly as
    in-core ``GLMOptimizationProblem.run`` builds ``l1 * mask``.
    """

    l1_weight: float = 0.0

    def _l1_vec(self, w: Array) -> Array:
        if self.reg_mask is None:
            return jnp.full_like(w, self.l1_weight)
        return self.l1_weight * self.reg_mask.astype(w.dtype)

    def _optimize_impl(self, data: ChunkedGLMData, x0: Array,
                       primed: Optional[dict] = None) -> OptimizerResult:
        cfg = self.config
        dim = data.dim
        (put_rep, stream_scores, data_value, data_value_at_t,
         stream_grad) = self._streams(data)

        w = put_rep(jnp.asarray(x0, jnp.float32))
        l2v = self._l2_vec(w)
        l1v = self._l1_vec(w)

        def total_at(wv, z_chunks):
            """Total objective (data + L2 + L1) from resident margins."""
            return (
                data_value(z_chunks)
                + 0.5 * jnp.sum(l2v * wv * wv)
                + jnp.sum(l1v * jnp.abs(wv))
            )

        def smooth_fg(wv, z_chunks):
            """Fused (total objective, SMOOTH gradient) — one streamed
            pass. History and pseudo-gradient both want the smooth grad
            (data + L2), per Andrew & Gao."""
            fd, gd = stream_grad(z_chunks)
            f = (fd + 0.5 * jnp.sum(l2v * wv * wv)
                 + jnp.sum(l1v * jnp.abs(wv)))
            return f, gd + l2v * wv

        max_it = cfg.max_iterations
        ckpt_tag = self._ckpt_tag(
            data, "ooc-owlqn-v1", extra=f"{self.l1_weight}:"
        )
        state = self._load_checkpoint(ckpt_tag, dim)
        if state is not None:
            (w, g, hist, it, passes, f, f_prev, gnorm0, values,
             grad_norms) = self._restore(state, put_rep)
            z = stream_scores(w)  # scores rebuild from w: one pass
            passes += 1
        else:
            prime = self._primed_init(primed, data, w)
            if prime is not None:
                z, fd, gd = prime
                f = (fd + 0.5 * jnp.sum(l2v * w * w)
                     + jnp.sum(l1v * jnp.abs(w)))
                g = gd + l2v * w
                passes = 1  # one fused streamed pass during ingest
            else:
                z = stream_scores(w)
                f, g = smooth_fg(w, z)
                passes = 2
            gnorm0 = jnp.linalg.norm(pseudo_gradient(w, g, l1v))
            hist = empty_history(cfg.history_length, dim, jnp.float32)
            values = np.full(max_it + 1, np.inf, np.float32)
            grad_norms = np.full(max_it + 1, np.inf, np.float32)
            values[0] = float(f)
            grad_norms[0] = float(gnorm0)
            it = 0
            f_prev = jnp.asarray(jnp.inf, jnp.float32)

        reason = NOT_CONVERGED
        last_save = float("-inf")
        while True:
            # Same in-run device-loss recovery hook as the smooth solver.
            fault_point("optim.ooc_iteration", it=it)
            pg = pseudo_gradient(w, g, l1v)
            reason = int(check_convergence(
                jnp.asarray(it), f_prev, f, jnp.linalg.norm(pg), gnorm0, cfg
            ))
            if reason != NOT_CONVERGED:
                break
            if it >= max_it:
                reason = MAX_ITERATIONS
                break
            d = two_loop_direction(pg, hist)
            # Orthant alignment: zero components disagreeing with -pg;
            # steepest descent if alignment annihilated the direction.
            d = jnp.where(d * (-pg) > 0.0, d, 0.0)
            if float(jnp.dot(d, d)) == 0.0:
                d = -pg
            xi = orthant(w, pg)

            # Backtracking Armijo on the TOTAL objective with orthant
            # projection of each trial point — one streamed scores pass
            # per probe (see class doc). Same constants as in-core.
            t, accept = 1.0, False
            xt = w
            zt = z
            ft = f
            for _ in range(cfg.max_line_search_iterations):
                xt = jnp.where((w + t * d) * xi >= 0.0, w + t * d, 0.0)
                zt = stream_scores(xt)
                passes += 1
                ft = total_at(xt, zt)
                decrease = jnp.dot(pg, xt - w)
                if bool(jnp.isfinite(ft)) and float(ft) <= float(
                    f + 1e-4 * decrease
                ):
                    accept = True
                    break
                t *= 0.5
            if not accept and bool(jnp.isfinite(ft)) and float(ft) < float(f):
                accept = True  # smallest probed step still decreases f
            if not accept:
                reason = FUNCTION_VALUES_CONVERGED
                break
            s = xt - w
            w = xt
            z = zt
            f_prev = f
            f, g_new = smooth_fg(w, z)
            passes += 1
            hist = update_history(hist, s, g_new - g)
            g = g_new
            it += 1
            values[it] = float(f)
            grad_norms[it] = float(
                jnp.linalg.norm(pseudo_gradient(w, g, l1v))
            )
            now = time.monotonic()
            if it == 1 or now - last_save >= self.checkpoint_min_interval_s:
                self._save_checkpoint(ckpt_tag, w, g, hist, it, passes, f,
                                      f_prev, gnorm0, values, grad_norms)
                last_save = now
            if self.progress is not None:
                self.progress(it, values[it], grad_norms[it], passes)

        self._save_checkpoint(ckpt_tag, w, g, hist, it, passes, f,
                              f_prev, gnorm0, values, grad_norms)
        return OptimizerResult(
            x=w,
            value=f,
            grad_norm=jnp.linalg.norm(pseudo_gradient(w, g, l1v)),
            iterations=jnp.asarray(it, jnp.int32),
            converged_reason=jnp.asarray(reason, jnp.int32),
            values=jnp.asarray(values),
            grad_norms=jnp.asarray(grad_norms),
            data_passes=jnp.asarray(passes, jnp.int32),
        )


def scores_out_of_core(data: ChunkedGLMData, w) -> np.ndarray:
    """Streamed scores z = Xw + offsets for every (true) row — the chunked
    analogue of ``GeneralizedLinearModel.compute_score``. Reuses the cached
    matvec kernel, so a λ-sweep scoring after each fit never recompiles."""
    w = jnp.asarray(w, jnp.float32)
    k_matvec = _matvec_for(data.dim)
    outs = [
        np.asarray(k_matvec(w, c.idx, c.val, data.offsets[i]))
        for i, c in enumerate(data.chunks)
    ]
    return np.concatenate(outs)[: data.n_rows]


def run_out_of_core(problem, data: ChunkedGLMData, w0=None, reg_mask=None,
                    progress=None, checkpoint_path=None, mesh=None,
                    data_axis="data", device_cache=None, primed=None,
                    collectives="gspmd"):
    """Problem-level entry mirroring ``GLMOptimizationProblem.run`` for the
    out-of-core path: same task→loss mapping, regularization/reg-mask
    semantics, and ``(GLMModel, OptimizerResult)`` return. LBFGS handles
    smooth L2; OWLQN handles any L1 component (L1/ELASTIC_NET) — the same
    optimizer↔regularization pairing rules as in-core run(): an L1
    component under a smooth optimizer raises (silently training the L2
    part alone would return wrong coefficients). Variance NONE only
    (SIMPLE/FULL need in-core Hessian passes)."""
    from photon_tpu.models.coefficients import Coefficients
    from photon_tpu.models.glm import GeneralizedLinearModel
    from photon_tpu.ops.losses import loss_for_task
    from photon_tpu.optim import OptimizerType

    l1 = problem.regularization.l1_weight(float(problem.reg_weight))
    common = dict(
        loss=loss_for_task(problem.task),
        l2_weight=problem.regularization.l2_weight(float(problem.reg_weight)),
        reg_mask=reg_mask,
        config=problem.optimizer_config,
        progress=progress,
        checkpoint_path=checkpoint_path,
        mesh=mesh,
        data_axis=data_axis,
        collectives=collectives,
        device_cache=device_cache,
    )
    if problem.optimizer_type == OptimizerType.OWLQN:
        solver = OutOfCoreOWLQN(l1_weight=l1, **common)
    elif problem.optimizer_type != OptimizerType.LBFGS:
        raise NotImplementedError(
            "out-of-core training supports LBFGS (smooth L2) and OWLQN "
            f"(L1/elastic-net) only; got {problem.optimizer_type}"
        )
    elif l1 > 0.0:
        raise NotImplementedError(
            "L1 components need an orthant-wise optimizer: use "
            "OptimizerType.OWLQN out-of-core, same as the in-core rule; "
            f"got LBFGS with {problem.regularization.reg_type.name}"
        )
    else:
        solver = OutOfCoreLBFGS(**common)
    if w0 is None:
        w0 = jnp.zeros((data.dim,), jnp.float32)
    result = solver.optimize(data, w0, primed=primed)
    model = GeneralizedLinearModel(
        Coefficients(means=result.x, variances=None), problem.task
    )
    return model, result
