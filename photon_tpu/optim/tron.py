"""TRON — trust-region Newton with truncated conjugate-gradient inner solves.

Parity: reference ⟦photon-lib/.../optimization/TRON.scala⟧, itself a port of
LIBLINEAR's TRON (Lin, Weng & Keerthi 2008): an outer trust-region loop whose
step comes from a Steihaug truncated-CG solve of ``H p = −g`` using only
Hessian-vector products, with the classic η/σ radius-update constants. No line
search.

TPU-first design: the Hessian-vector product is *not* hand-coded per loss as in
the reference's ⟦HessianVectorAggregator⟧ — it is forward-over-reverse autodiff
(``jax.jvp`` of the gradient), which XLA fuses into the same data pass. Outer
loop, inner CG, and the radius logic all live in nested ``lax.while_loop``s, so
a full TRON solve is one XLA program (vs. one Spark job per CG step in the
reference, SURVEY.md §3.4).
"""
from __future__ import annotations

import dataclasses
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_tpu.optim.base import (
    FUNCTION_VALUES_CONVERGED,
    NOT_CONVERGED,
    Optimizer,
    OptimizerResult,
    ValueAndGrad,
    check_convergence,
    finalize_reason,
)
from photon_tpu.optim.lbfgs import make_dot

Array = jax.Array

# LIBLINEAR TRON constants.
_ETA0, _ETA1, _ETA2 = 1e-4, 0.25, 0.75
_SIGMA1, _SIGMA2, _SIGMA3 = 0.25, 0.5, 4.0


def _boundary_tau(p: Array, d: Array, delta: Array, dot) -> Array:
    """τ ≥ 0 with ‖p + τ·d‖ = delta (positive root of the quadratic)."""
    dd = dot(d, d)
    pd = dot(p, d)
    pp = dot(p, p)
    disc = jnp.sqrt(jnp.maximum(pd * pd + dd * (delta * delta - pp), 0.0))
    return (-pd + disc) / jnp.maximum(dd, 1e-30)


def steihaug_cg(hvp, g: Array, delta: Array, max_iters: int, tol: Array,
                dot=jnp.dot):
    """Truncated CG for H p = −g inside ‖p‖ ≤ delta.

    Returns (p, Hp, n_hvp) — Hp is maintained incrementally so the caller can
    compute the predicted reduction without another Hessian pass; n_hvp is the
    number of Hessian-vector products performed (for pass accounting).
    ``dot`` abstracts the inner product (a psum-reduced one when vectors are
    shards over a mesh axis).
    """

    class CGState(NamedTuple):
        p: Array
        r: Array      # residual = −g − Hp
        d: Array      # search direction
        hp: Array     # H @ p
        rr: Array
        it: Array
        done: Array

    r0 = -g
    init = CGState(
        p=jnp.zeros_like(g), r=r0, d=r0, hp=jnp.zeros_like(g),
        rr=dot(r0, r0), it=jnp.zeros((), jnp.int32),
        done=jnp.zeros((), bool),
    )

    def cond(st: CGState):
        return (~st.done) & (st.it < max_iters) & (jnp.sqrt(st.rr) > tol)

    def body(st: CGState) -> CGState:
        hd = hvp(st.d)
        dhd = dot(st.d, hd)
        alpha = st.rr / jnp.where(dhd > 1e-30, dhd, 1.0)
        # Negative curvature or singular direction → walk to the boundary.
        neg_curv = dhd <= 1e-30
        p_try = st.p + alpha * st.d
        outside = jnp.sqrt(dot(p_try, p_try)) >= delta
        tau = _boundary_tau(st.p, st.d, delta, dot)
        hit_boundary = neg_curv | outside
        step = jnp.where(hit_boundary, tau, alpha)
        p_new = st.p + step * st.d
        hp_new = st.hp + step * hd
        r_new = st.r - step * hd
        rr_new = dot(r_new, r_new)
        beta = rr_new / jnp.maximum(st.rr, 1e-30)
        d_new = r_new + beta * st.d
        return CGState(
            p=p_new, r=r_new, d=d_new, hp=hp_new, rr=rr_new,
            it=st.it + 1, done=hit_boundary,
        )

    st = lax.while_loop(cond, body, init)
    return st.p, st.hp, st.it


class _LoopState(NamedTuple):
    x: Array
    f: Array
    g: Array
    delta: Array
    it: Array
    reason: Array
    gnorm0: Array
    values: Array
    grad_norms: Array
    passes: Array   # int32 — instrumented data-pass counter


@dataclasses.dataclass(frozen=True)
class TRON(Optimizer):
    """Trust-region Newton. Requires an HVP factory alongside value+grad.

    ``optimize(value_and_grad, x0, hvp_at)`` where ``hvp_at(x)`` returns
    ``v ↦ H(x)·v``. The factory form lets an objective hoist work that
    depends only on x (GLM margins/curvature — see
    ``GLMObjective.bind_hvp_at``) out of the inner CG loop explicitly.
    Build one generically as
    ``lambda x: (lambda v: jax.jvp(grad_fn, (x,), (v,))[1])``.

    With ``axis_name`` set, ``x0``/gradients/CG vectors are SHARDS over that
    mesh axis (P3 feature sharding): every inner product psums across shards
    and the caller's value_and_grad/hvp must return globally-reduced values
    on shard-local vectors (see ``parallel/model_parallel.py``).
    """

    axis_name: str = None

    def optimize(  # type: ignore[override]
        self,
        value_and_grad: ValueAndGrad,
        x0: Array,
        hvp_at: "Callable[[Array], Callable[[Array], Array]]",
        hvp_passes: int = 2,
        factory_passes: int = 1,
    ) -> OptimizerResult:
        """``hvp_passes``/``factory_passes`` declare how many feature-data
        passes one H·v call / one ``hvp_at(x)`` call costs, for the
        ``data_passes`` counter. Defaults match ``GLMObjective.bind_hvp_at``
        (hoisted margin matvec at the factory, Xv matvec + rmatvec per HVP);
        callers with a different objective structure must pass their own
        costs (0/0 for objectives not backed by feature data)."""
        cfg = self.config
        max_it = cfg.max_iterations
        dtype = x0.dtype
        dot = make_dot(self.axis_name)
        norm = lambda v: jnp.sqrt(dot(v, v))

        f0, g0 = value_and_grad(x0)
        gnorm0 = norm(g0)
        values = jnp.full((max_it + 1,), jnp.inf, dtype).at[0].set(f0)
        gnorms = jnp.full((max_it + 1,), jnp.inf, dtype).at[0].set(gnorm0)

        init = _LoopState(
            x=x0, f=f0, g=g0, delta=gnorm0,
            it=jnp.zeros((), jnp.int32),
            reason=jnp.asarray(NOT_CONVERGED, jnp.int32),
            gnorm0=gnorm0, values=values, grad_norms=gnorms,
            passes=jnp.asarray(2, jnp.int32),  # init fused value+grad
        )

        def cond(st: _LoopState):
            return (st.reason == NOT_CONVERGED) & (st.it < max_it)

        def body(st: _LoopState) -> _LoopState:
            gnorm = norm(st.g)
            cg_tol = 0.1 * gnorm
            p, hp, n_hvp = steihaug_cg(
                hvp_at(st.x), st.g, st.delta,
                cfg.max_cg_iterations, cg_tol, dot=dot,
            )
            # Predicted reduction of the quadratic model: −(gᵀp + ½ pᵀHp).
            pred = -(dot(st.g, p) + 0.5 * dot(p, hp))
            x_try = st.x + p
            f_try, g_try = value_and_grad(x_try)
            actual = st.f - f_try
            rho = actual / jnp.where(jnp.abs(pred) > 1e-30, pred, 1.0)
            # A non-finite trial value must take the shrink branch.
            rho = jnp.where(jnp.isfinite(f_try), rho, -jnp.inf)

            pnorm = norm(p)
            # LIBLINEAR radius update: shrink on poor agreement, halve on
            # moderate, expand (bounded) on good.
            delta = jnp.where(
                rho < _ETA1,
                jnp.maximum(_SIGMA1 * jnp.minimum(pnorm, st.delta), 1e-12),
                jnp.where(
                    rho < _ETA2,
                    _SIGMA2 * st.delta,
                    jnp.clip(_SIGMA3 * pnorm, st.delta, _SIGMA3 * st.delta),
                ),
            )
            accept = rho > _ETA0
            x_new = jnp.where(accept, x_try, st.x)
            f_new = jnp.where(accept, f_try, st.f)
            g_new = jnp.where(accept, g_try, st.g)

            it = st.it + 1
            gnorm_new = norm(g_new)
            # The function-value test is only meaningful on accepted steps —
            # a rejected step leaves f unchanged and must not read as
            # convergence; it shrinks delta and retries instead.
            reason = jnp.where(
                accept,
                check_convergence(it, st.f, f_new, gnorm_new, st.gnorm0, cfg),
                jnp.asarray(NOT_CONVERGED, jnp.int32),
            )
            # Collapsed radius means no further progress is possible.
            reason = jnp.where(
                (delta <= 1e-12) & (reason == NOT_CONVERGED),
                jnp.asarray(FUNCTION_VALUES_CONVERGED, jnp.int32),
                reason,
            )
            return _LoopState(
                x=x_new, f=f_new, g=g_new, delta=delta, it=it, reason=reason,
                gnorm0=st.gnorm0,
                values=st.values.at[it].set(f_new),
                grad_norms=st.grad_norms.at[it].set(gnorm_new),
                # Per outer iteration: the declared factory cost (hoisted
                # margin matvec for GLMs), hvp_passes per CG HVP, and 2 for
                # the fused trial value+grad.
                passes=st.passes + factory_passes + hvp_passes * n_hvp + 2,
            )

        st = lax.while_loop(cond, body, init)
        reason = finalize_reason(st.reason, st.it, max_it)
        return OptimizerResult(
            x=st.x, value=st.f, grad_norm=norm(st.g),
            iterations=st.it, converged_reason=reason,
            values=st.values, grad_norms=st.grad_norms,
            data_passes=st.passes,
        )
