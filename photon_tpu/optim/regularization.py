"""Regularization configuration.

Parity: reference ⟦photon-lib/.../optimization/RegularizationContext.scala⟧ —
NONE / L1 / L2 / ELASTIC_NET with an elastic-net mixing weight α splitting a
single regularization weight λ into λ·α (L1) and λ·(1−α) (L2).

The L2 part is added analytically to value/gradient/Hessian by the objective
(reference ⟦L2RegularizationDiff/TwiceDiff⟧ stackable traits); the L1 part is
handled by OWL-QN's pseudo-gradient — never by smooth differentiation.

A ``reg_mask`` (1.0 for regularized coefficients, 0.0 for the intercept)
reproduces the reference convention that the intercept is never regularized.
"""
from __future__ import annotations

import dataclasses
import enum


class RegularizationType(enum.Enum):
    NONE = "NONE"
    L1 = "L1"
    L2 = "L2"
    ELASTIC_NET = "ELASTIC_NET"


@dataclasses.dataclass(frozen=True)
class RegularizationContext:
    reg_type: RegularizationType = RegularizationType.NONE
    # Elastic-net mixing: fraction of the weight that is L1.
    elastic_net_alpha: float = 0.0

    def l1_weight(self, reg_weight: float) -> float:
        if self.reg_type == RegularizationType.L1:
            return reg_weight
        if self.reg_type == RegularizationType.ELASTIC_NET:
            return reg_weight * self.elastic_net_alpha
        return 0.0

    def l2_weight(self, reg_weight: float) -> float:
        if self.reg_type == RegularizationType.L2:
            return reg_weight
        if self.reg_type == RegularizationType.ELASTIC_NET:
            return reg_weight * (1.0 - self.elastic_net_alpha)
        return 0.0


NoRegularizationContext = RegularizationContext(RegularizationType.NONE)
L1RegularizationContext = RegularizationContext(RegularizationType.L1)
L2RegularizationContext = RegularizationContext(RegularizationType.L2)


def elastic_net_context(alpha: float) -> RegularizationContext:
    return RegularizationContext(RegularizationType.ELASTIC_NET, alpha)
