"""OWL-QN (Orthant-Wise Limited-memory Quasi-Newton) for L1 regularization.

Parity: reference ⟦photon-lib/.../optimization/OWLQN.scala⟧ (which wraps
``breeze.optimize.OWLQN``), following Andrew & Gao (2007):

  * pseudo-gradient of f(x) + β‖x‖₁ choosing the steepest descent subgradient,
  * two-loop L-BFGS direction built from *smooth* gradient history,
  * direction sign-aligned with the negative pseudo-gradient,
  * line-search iterates projected onto the orthant of the starting point.

The L1 weight is a per-coefficient vector (β · l1_mask) so the intercept is
excluded, matching the reference's convention that regularization never touches
the intercept. Runs as one on-device ``lax.while_loop`` like LBFGS.
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_tpu.optim.base import (
    FUNCTION_VALUES_CONVERGED,
    NOT_CONVERGED,
    Optimizer,
    OptimizerResult,
    ValueAndGrad,
    check_convergence,
    finalize_reason,
    l2_norm,
)
from photon_tpu.optim.lbfgs import (
    LBFGSHistory,
    empty_history,
    make_dot,
    two_loop_direction,
    update_history,
)

Array = jax.Array


def pseudo_gradient(x: Array, g: Array, l1: Array) -> Array:
    """Steepest-descent subgradient of f(x) + Σ l1ᵢ|xᵢ| (Andrew & Gao eq. 4)."""
    right = g + l1
    left = g - l1
    at_zero = jnp.where(left > 0.0, left, jnp.where(right < 0.0, right, 0.0))
    return jnp.where(x > 0.0, right, jnp.where(x < 0.0, left, at_zero))


def orthant(x: Array, pg: Array) -> Array:
    """ξᵢ = sign(xᵢ), or sign(−pgᵢ) when xᵢ = 0 — the search orthant."""
    return jnp.where(x != 0.0, jnp.sign(x), jnp.sign(-pg))


class _LoopState(NamedTuple):
    x: Array
    f: Array        # total objective: smooth + L1
    g: Array        # smooth gradient
    hist: LBFGSHistory
    it: Array
    reason: Array
    gnorm0: Array
    values: Array
    grad_norms: Array
    passes: Array   # int32 — instrumented data-pass counter


@dataclasses.dataclass(frozen=True)
class OWLQN(Optimizer):
    """Orthant-wise L-BFGS for L1/elastic-net objectives.

    ``optimize(value_and_grad, x0, l1_weights)``: ``value_and_grad`` must be
    the *smooth* part (loss + any L2 term); ``l1_weights`` is the [D] vector of
    per-coefficient L1 penalties (zeros for unpenalized entries).

    With ``axis_name`` set, ``x0``/gradients/history are SHARDS over that
    mesh axis (run inside ``shard_map``; SURVEY.md §2.6 P3). The orthant
    machinery — pseudo-gradient, alignment, projection — is elementwise and
    therefore shard-local; only inner products and the L1 term psum.
    """

    axis_name: str = None

    def optimize(  # type: ignore[override]
        self, value_and_grad: ValueAndGrad, x0: Array, l1_weights: Array
    ) -> OptimizerResult:
        cfg = self.config
        m = cfg.history_length
        max_it = cfg.max_iterations
        dim = x0.shape[-1]
        dtype = x0.dtype
        l1 = jnp.asarray(l1_weights, dtype)
        dot = make_dot(self.axis_name)
        norm = lambda v: jnp.sqrt(dot(v, v))

        def total(x, fsmooth):
            return fsmooth + dot(l1, jnp.abs(x))

        f0s, g0 = value_and_grad(x0)
        f0 = total(x0, f0s)
        pg0 = pseudo_gradient(x0, g0, l1)
        gnorm0 = norm(pg0)
        values = jnp.full((max_it + 1,), jnp.inf, dtype).at[0].set(f0)
        gnorms = jnp.full((max_it + 1,), jnp.inf, dtype).at[0].set(gnorm0)

        init = _LoopState(
            x=x0, f=f0, g=g0,
            hist=empty_history(m, dim, dtype),
            it=jnp.zeros((), jnp.int32),
            reason=jnp.asarray(NOT_CONVERGED, jnp.int32),
            gnorm0=gnorm0, values=values, grad_norms=gnorms,
            passes=jnp.asarray(2, jnp.int32),  # init fused value+grad
        )

        def cond(st: _LoopState):
            return (st.reason == NOT_CONVERGED) & (st.it < max_it)

        def body(st: _LoopState) -> _LoopState:
            pg = pseudo_gradient(st.x, st.g, l1)
            d = two_loop_direction(pg, st.hist, dot)
            # Align the direction with −pg (zero out disagreeing components).
            d = jnp.where(d * (-pg) > 0.0, d, 0.0)
            # Fallback to steepest descent if alignment annihilated d
            # (a GLOBAL test under sharding: any shard non-zero keeps d).
            d = jnp.where(dot(d, d) > 0.0, d, -pg)
            xi = orthant(st.x, pg)

            def project(xt):
                return jnp.where(xt * xi >= 0.0, xt, 0.0)

            # Backtracking Armijo on the *total* objective with orthant
            # projection of each trial point (Andrew & Gao's constrained step).
            def ls_cond(carry):
                t, *_, it, done = carry
                return (~done) & (it < cfg.max_line_search_iterations)

            def ls_body(carry):
                t, _, _, _, _, it, _ = carry
                xt = project(st.x + t * d)
                fts, gt = value_and_grad(xt)
                ft = total(xt, fts)
                # Armijo via the projected displacement, per OWL-QN.
                decrease = dot(pg, xt - st.x)
                ok = jnp.isfinite(ft) & (ft <= st.f + 1e-4 * decrease)
                return (jnp.where(ok, t, 0.5 * t), ft, fts, gt, xt, it + 1, ok)

            t0 = jnp.asarray(1.0, dtype)
            _, ft, fts, gt, xt, n_probes, ok = lax.while_loop(
                ls_cond, ls_body,
                (t0, st.f, st.f, st.g, st.x, jnp.zeros((), jnp.int32),
                 jnp.zeros((), bool)),
            )
            accept = ok | (jnp.isfinite(ft) & (ft < st.f))
            x_new = jnp.where(accept, xt, st.x)
            f_new = jnp.where(accept, ft, st.f)
            g_new = jnp.where(accept, gt, st.g)

            hist = update_history(st.hist, x_new - st.x, g_new - st.g, dot)
            it = st.it + 1
            pg_new = pseudo_gradient(x_new, g_new, l1)
            gnorm = norm(pg_new)
            reason = check_convergence(it, st.f, f_new, gnorm, st.gnorm0, cfg)
            reason = jnp.where(
                (~accept) & (reason == NOT_CONVERGED),
                jnp.asarray(FUNCTION_VALUES_CONVERGED, jnp.int32),
                reason,
            )
            return _LoopState(
                x=x_new, f=f_new, g=g_new, hist=hist, it=it,
                reason=reason, gnorm0=st.gnorm0,
                values=st.values.at[it].set(f_new),
                grad_norms=st.grad_norms.at[it].set(gnorm),
                # Each probe is one fused value+grad = 2 data passes.
                passes=st.passes + 2 * n_probes,
            )

        st = lax.while_loop(cond, body, init)
        reason = finalize_reason(st.reason, st.it, max_it)
        pg_fin = pseudo_gradient(st.x, st.g, l1)
        return OptimizerResult(
            x=st.x, value=st.f, grad_norm=norm(pg_fin),
            iterations=st.it, converged_reason=reason,
            values=st.values, grad_norms=st.grad_norms,
            data_passes=st.passes,
        )
