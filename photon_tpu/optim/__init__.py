"""Core optimizers: on-device L-BFGS, OWL-QN, TRON.

Parity: reference ⟦photon-lib/.../optimization/⟧ (SURVEY.md §2.1). The
``OptimizerType`` enum matches the reference's optimizer dispatch in
⟦GLMOptimizationConfiguration⟧.
"""
from __future__ import annotations

import enum

from photon_tpu.optim.base import (
    CONVERGENCE_REASON_NAMES,
    FUNCTION_VALUES_CONVERGED,
    GRADIENT_CONVERGED,
    MAX_ITERATIONS,
    NOT_CONVERGED,
    Optimizer,
    OptimizerConfig,
    OptimizerResult,
)
from photon_tpu.optim.lbfgs import LBFGS
from photon_tpu.optim.owlqn import OWLQN
from photon_tpu.optim.regularization import (
    L1RegularizationContext,
    L2RegularizationContext,
    NoRegularizationContext,
    RegularizationContext,
    RegularizationType,
    elastic_net_context,
)
from photon_tpu.optim.tron import TRON


class OptimizerType(enum.Enum):
    LBFGS = "LBFGS"
    OWLQN = "OWLQN"
    TRON = "TRON"


def make_optimizer(opt_type: OptimizerType, config: OptimizerConfig) -> Optimizer:
    return {
        OptimizerType.LBFGS: LBFGS,
        OptimizerType.OWLQN: OWLQN,
        OptimizerType.TRON: TRON,
    }[opt_type](config)


__all__ = [
    "LBFGS", "OWLQN", "TRON", "Optimizer", "OptimizerConfig",
    "OptimizerResult", "OptimizerType", "make_optimizer",
    "RegularizationContext", "RegularizationType",
    "NoRegularizationContext", "L1RegularizationContext",
    "L2RegularizationContext", "elastic_net_context",
    "NOT_CONVERGED", "MAX_ITERATIONS", "FUNCTION_VALUES_CONVERGED",
    "GRADIENT_CONVERGED", "CONVERGENCE_REASON_NAMES",
]
