"""Optimizer base types: convergence reasons, config, result, state tracking.

Parity: reference ⟦photon-lib/.../optimization/Optimizer.scala⟧ template
(init → iterate → convergence check), ``ConvergenceReason``, ``OptimizerState``
and ⟦OptimizationStatesTracker.scala⟧.

TPU-first design: the whole optimize loop runs on-device inside one
``lax.while_loop`` under jit (SURVEY.md §3.4 — the reference's driver-side
Breeze loop with one Spark job per iteration becomes a single XLA program).
The per-iteration tracker is a pair of fixed-size arrays written by masked
dynamic-index updates, so state history survives jit. Everything here is
vmap-compatible so the same optimizer batches over thousands of random-effect
entity solves (SURVEY.md §2.6 P2).
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp

Array = jax.Array

# Convergence reason codes (int32 on device; 0 means "still running").
NOT_CONVERGED = 0
MAX_ITERATIONS = 1
FUNCTION_VALUES_CONVERGED = 2
GRADIENT_CONVERGED = 3

CONVERGENCE_REASON_NAMES = {
    NOT_CONVERGED: "NOT_CONVERGED",
    MAX_ITERATIONS: "MAX_ITERATIONS",
    FUNCTION_VALUES_CONVERGED: "FUNCTION_VALUES_CONVERGED",
    GRADIENT_CONVERGED: "GRADIENT_CONVERGED",
}

# An objective for first-order optimizers: x -> (value, gradient).
ValueAndGrad = Callable[[Array], tuple[Array, Array]]
# Hessian-vector product for second-order optimizers: (x, v) -> H(x) @ v.
Hvp = Callable[[Array, Array], Array]


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Static (compile-time) optimizer hyperparameters.

    Defaults follow the reference ⟦GLMOptimizationConfiguration⟧ conventions:
    tolerance is *relative* function-change tolerance, also applied to the
    relative gradient norm, as in the reference's dual convergence check.
    """

    max_iterations: int = 80
    tolerance: float = 1e-7
    # L-BFGS/OWL-QN history length (Breeze default m=10 ⟦LBFGS.scala⟧).
    history_length: int = 10
    # Line-search probe cap per iteration.
    max_line_search_iterations: int = 25
    # TRON inner conjugate-gradient iteration cap.
    max_cg_iterations: int = 20


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class OptimizerResult:
    """Terminal state + per-iteration history (the states tracker).

    ``values[i]`` / ``grad_norms[i]`` are valid for i < iterations; beyond that
    they hold ``inf`` padding (inf, not NaN, so ``--debug-nans`` /
    ``jax_debug_nans`` stays usable on healthy runs). ``converged_reason`` is
    a code from this module.

    ``data_passes`` is an *instrumented* on-device counter of full-data
    touches (one pass = one matvec OR one rmatvec over all N·K feature
    entries), incremented by the optimizer loop exactly where evaluations
    happen — line-search probes, gradient refreshes, CG Hessian-vector
    products — so "fewer data passes" claims are measured, not derived
    (VERDICT round-2 weak #9). HVPs count as 2 passes (Xv matvec + rmatvec)
    plus 1 per TRON outer iteration for the margin matvec that
    ``GLMObjective.bind_hvp_at`` hoists out of the CG loop explicitly; a test
    cross-checks this counter against a host-callback counter at the
    feature-op level (``ops/pass_counter.py``).
    """

    x: Array
    value: Array
    grad_norm: Array
    iterations: Array            # int32 scalar
    converged_reason: Array      # int32 scalar
    values: Array                # [max_iterations + 1] tracked objective values
    grad_norms: Array            # [max_iterations + 1] tracked gradient norms
    data_passes: Array           # int32 scalar — instrumented data-pass count

    def reason_name(self) -> str:
        return CONVERGENCE_REASON_NAMES[int(self.converged_reason)]


def l2_norm(v: Array) -> Array:
    return jnp.sqrt(jnp.sum(v * v))


def check_convergence(
    it: Array,
    f_prev: Array,
    f: Array,
    gnorm: Array,
    gnorm0: Array,
    config: OptimizerConfig,
) -> Array:
    """Reference-parity dual convergence test → reason code (0 if not done).

    Gradient test is relative to the initial gradient norm (Breeze/LIBLINEAR
    convention: ``|∇f| ≤ tol·|∇f₀|``); function test is relative change.
    """
    tol = jnp.asarray(config.tolerance, f.dtype)
    grad_ok = gnorm <= tol * jnp.maximum(gnorm0, 1e-30)
    denom = jnp.maximum(jnp.maximum(jnp.abs(f_prev), jnp.abs(f)), 1.0)
    fun_ok = (it > 0) & (jnp.abs(f_prev - f) <= tol * denom)
    reason = jnp.where(
        grad_ok,
        GRADIENT_CONVERGED,
        jnp.where(fun_ok, FUNCTION_VALUES_CONVERGED, NOT_CONVERGED),
    )
    return reason.astype(jnp.int32)


def finalize_reason(reason: Array, it: Array, max_iterations: int) -> Array:
    """Map a still-running loop that hit the iteration cap to MAX_ITERATIONS."""
    return jnp.where(
        (reason == NOT_CONVERGED) & (it >= max_iterations),
        MAX_ITERATIONS,
        reason,
    ).astype(jnp.int32)


@dataclasses.dataclass(frozen=True)
class Optimizer:
    """Common interface: ``optimize(value_and_grad, x0) -> OptimizerResult``.

    Subclasses (LBFGS/OWLQN/TRON) implement ``optimize`` as a pure jittable
    function of device arrays; they carry only static config so instances can
    be closed over inside jit.
    """

    config: OptimizerConfig = OptimizerConfig()

    def optimize(self, value_and_grad: ValueAndGrad, x0: Array, **kw) -> OptimizerResult:
        raise NotImplementedError
