"""Feature index: (name, term) → column index maps."""
from photon_tpu.index.index_map import (  # noqa: F401
    DELIMITER,
    INTERCEPT_NAME,
    INTERCEPT_TERM,
    DefaultIndexMap,
    IndexMap,
    MmapIndexMap,
    build_index_from_features,
    build_mmap_index,
    feature_key,
)
