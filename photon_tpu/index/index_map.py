"""(name, term) → feature-column index maps, in-memory and mmap-backed.

Parity: reference ⟦photon-api/.../index/IndexMap.scala, DefaultIndexMap,
PalDBIndexMap + loaders⟧ (SURVEY.md §2.2 "Feature index"): photon feature
spaces are string ``(name, term)`` pairs joined by the \\x01 delimiter, mapped
to dense column ids; at 10M+ features the map is held **off-heap** in
partitioned memory-mapped PalDB stores so every Spark executor can share one
copy.

TPU-native equivalent: the training hot path never touches strings — batches
carry int32 ELL ids — so the index map is a host-side structure used at data
ingest and model export. ``DefaultIndexMap`` is a plain dict; ``MmapIndexMap``
is the PalDB replacement: hash-partitioned, binary-searched, memory-mapped
numpy arrays (sorted u64 key hashes + key-byte blob for collision
verification + a reverse blob ordered by index), so a 10M-feature index costs
~zero resident memory per process and loads in O(1) — same property PalDB
gave the reference.
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Iterable, Optional, Sequence

import numpy as np

# Reference convention: feature key = name + "\x01" + term; the intercept is
# a regular feature named "(INTERCEPT)" with empty term.
DELIMITER = "\x01"
INTERCEPT_NAME = "(INTERCEPT)"
INTERCEPT_TERM = ""


def feature_key(name: str, term: Optional[str]) -> str:
    return f"{name}{DELIMITER}{term or ''}"


def _hash64(key: bytes) -> int:
    # Stable across processes/pythons (unlike hash()); 8 bytes of blake2b.
    return int.from_bytes(hashlib.blake2b(key, digest_size=8).digest(), "little")


class IndexMap:
    """Interface: get_index / get_feature / size / intercept lookup."""

    def get_index(self, name: str, term: Optional[str] = None) -> int:
        """Column id for (name, term), or -1 if absent (reference returns
        IndexMap.NULL_KEY = -1 for unindexed features)."""
        return self.index_of(feature_key(name, term))

    def index_of(self, key: str) -> int:
        raise NotImplementedError

    def get_feature(self, index: int) -> tuple[str, str]:
        """(name, term) for a column id — used at model export."""
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError

    @property
    def intercept_index(self) -> Optional[int]:
        i = self.get_index(INTERCEPT_NAME, INTERCEPT_TERM)
        return None if i < 0 else i


class DefaultIndexMap(IndexMap):
    """In-memory dict index — reference ⟦DefaultIndexMap⟧."""

    def __init__(self, keys_in_order: Sequence[str]):
        self._keys = list(keys_in_order)
        self._map = {k: i for i, k in enumerate(self._keys)}
        if len(self._map) != len(self._keys):
            raise ValueError("duplicate feature keys in index")

    def index_of(self, key: str) -> int:
        return self._map.get(key, -1)

    def get_feature(self, index: int) -> tuple[str, str]:
        name, _, term = self._keys[index].partition(DELIMITER)
        return name, term

    def __len__(self) -> int:
        return len(self._keys)

    @property
    def keys_in_order(self) -> list[str]:
        return self._keys


def build_index_from_features(
    name_term_pairs: Iterable[tuple[str, Optional[str]]],
    add_intercept: bool = True,
) -> DefaultIndexMap:
    """Index features in first-seen order (intercept first, as the reference's
    indexing job seeds it)."""
    seen: dict[str, None] = {}
    if add_intercept:
        seen[feature_key(INTERCEPT_NAME, INTERCEPT_TERM)] = None
    for name, term in name_term_pairs:
        seen.setdefault(feature_key(name, term), None)
    return DefaultIndexMap(list(seen.keys()))


# ---------------------------------------------------------------------------
# mmap-backed store (the PalDB replacement)

_META = "index-meta.json"


def build_mmap_index(
    index: DefaultIndexMap, out_dir: str, num_partitions: int = 1
) -> None:
    """Write a DefaultIndexMap as a partitioned mmap store.

    Layout (reference ⟦PalDBIndexMap⟧ partitioning: key → hash % P):
      partition-{p}.hash.npy   sorted u64 key hashes           [M_p]
      partition-{p}.idx.npy    global column ids, hash order   [M_p]
      partition-{p}.off.npy    key-blob offsets, hash order    [M_p + 1]
      partition-{p}.keys.bin   utf-8 key bytes
      reverse.off.npy / reverse.keys.bin   key blob ordered by column id
      index-meta.json          {size, num_partitions}
    """
    os.makedirs(out_dir, exist_ok=True)
    keys = index.keys_in_order
    kb = [k.encode("utf-8") for k in keys]
    hashes = np.fromiter((_hash64(b) for b in kb), np.uint64, len(kb))
    parts = (hashes % np.uint64(num_partitions)).astype(np.int64)

    for p in range(num_partitions):
        members = np.nonzero(parts == p)[0]
        order = members[np.argsort(hashes[members], kind="stable")]
        np.save(os.path.join(out_dir, f"partition-{p}.hash.npy"), hashes[order])
        np.save(
            os.path.join(out_dir, f"partition-{p}.idx.npy"),
            order.astype(np.int64),
        )
        blob = b"".join(kb[i] for i in order)
        off = np.zeros(len(order) + 1, np.int64)
        np.cumsum([len(kb[i]) for i in order], out=off[1:])
        np.save(os.path.join(out_dir, f"partition-{p}.off.npy"), off)
        with open(os.path.join(out_dir, f"partition-{p}.keys.bin"), "wb") as f:
            f.write(blob)

    rev_off = np.zeros(len(kb) + 1, np.int64)
    np.cumsum([len(b) for b in kb], out=rev_off[1:])
    np.save(os.path.join(out_dir, "reverse.off.npy"), rev_off)
    with open(os.path.join(out_dir, "reverse.keys.bin"), "wb") as f:
        f.write(b"".join(kb))
    with open(os.path.join(out_dir, _META), "w") as f:
        json.dump({"size": len(kb), "num_partitions": num_partitions}, f)


class MmapIndexMap(IndexMap):
    """Memory-mapped partitioned index — loads lazily, shares page cache
    across processes (the PalDB property the reference relied on)."""

    def __init__(self, store_dir: str):
        with open(os.path.join(store_dir, _META)) as f:
            meta = json.load(f)
        self._dir = store_dir
        self._size = int(meta["size"])
        self._nparts = int(meta["num_partitions"])
        self._parts: dict[int, tuple] = {}
        self._rev: Optional[tuple] = None

    @property
    def store_dir(self) -> str:
        """On-disk store directory — the public handle for reopening this
        map in another process (io/parallel_ingest ships it to workers)."""
        return self._dir

    def _partition(self, p: int):
        if p not in self._parts:
            d = self._dir
            self._parts[p] = (
                np.load(os.path.join(d, f"partition-{p}.hash.npy"), mmap_mode="r"),
                np.load(os.path.join(d, f"partition-{p}.idx.npy"), mmap_mode="r"),
                np.load(os.path.join(d, f"partition-{p}.off.npy"), mmap_mode="r"),
                np.memmap(
                    os.path.join(d, f"partition-{p}.keys.bin"), np.uint8, "r"
                )
                if os.path.getsize(os.path.join(d, f"partition-{p}.keys.bin"))
                else np.zeros(0, np.uint8),
            )
        return self._parts[p]

    def preload(self) -> None:
        """Open every partition now (serve-path warmup): point lookups on a
        hot request path must not pay the lazy mmap open + first-touch page
        faults of a cold partition."""
        for p in range(self._nparts):
            self._partition(p)

    def index_of(self, key: str) -> int:
        kb = key.encode("utf-8")
        h = _hash64(kb)
        hashes, idx, off, blob = self._partition(h % self._nparts)
        lo = int(np.searchsorted(hashes, np.uint64(h), side="left"))
        while lo < len(hashes) and int(hashes[lo]) == h:
            s, e = int(off[lo]), int(off[lo + 1])
            if blob[s:e].tobytes() == kb:
                return int(idx[lo])
            lo += 1  # u64-hash collision: scan the run
        return -1

    def get_feature(self, index: int) -> tuple[str, str]:
        if self._rev is None:
            self._rev = (
                np.load(os.path.join(self._dir, "reverse.off.npy"), mmap_mode="r"),
                np.memmap(
                    os.path.join(self._dir, "reverse.keys.bin"), np.uint8, "r"
                ),
            )
        off, blob = self._rev
        s, e = int(off[index]), int(off[index + 1])
        name, _, term = blob[s:e].tobytes().decode("utf-8").partition(DELIMITER)
        return name, term

    def __len__(self) -> int:
        return self._size
