"""Multi-host runtime bring-up and host-side data feeding.

Parity: the reference's communication backend is the Spark driver/executor
runtime (SURVEY.md §1 layer R, §5.8 comm backend): cluster membership from
YARN, data distribution via HDFS splits, gradients via ``treeAggregate``.
Here the same responsibilities map to the JAX distributed runtime:

* membership   → ``jax.distributed.initialize`` (one process per host; on
  TPU pods coordinator/process ids auto-detect from the metadata server),
* data feed    → per-process file shards (``StreamingAvroReader.iter_chunks``
  with ``file_shard``) assembled into globally-sharded arrays with
  ``jax.make_array_from_process_local_data``,
* collectives  → XLA psum/all-gather over ICI/DCN inside the jitted step
  (see ``parallel/mesh.py`` / ``parallel/data_parallel.py``).

Everything degrades to a no-op in a single-process run, so the same driver
code serves a laptop, one TPU VM, and a multi-host pod slice.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

from photon_tpu.parallel.mesh import DATA_AXIS

_initialized = False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join the multi-host runtime; returns True iff it actually initialized.

    Call once at driver start, BEFORE any other JAX API touches the backend.
    With no arguments, TPU pod environments auto-detect everything; on other
    platforms a single-process run is detected and left untouched (no-op).
    """
    global _initialized
    if _initialized:
        return False
    if coordinator_address is None and num_processes is None:
        # Decide from the environment ONLY — probing jax (even
        # ``jax.process_count()``) would initialize the XLA backend and make
        # ``jax.distributed.initialize`` unusable afterwards. Auto-initialize
        # only where multi-host auto-detection exists: a multi-worker TPU pod
        # (comma-separated TPU_WORKER_HOSTNAMES) or a megascale (multi-slice)
        # coordinator.
        import os

        hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        multi_host = "," in hosts
        multi_slice = bool(os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"))
        if not (multi_host or multi_slice):
            return False
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        # Only "double init" / "backend already up" is benign (proceed
        # single-runtime). jax 0.9.0's exact texts: "distributed.initialize
        # should only be called once." and "jax.distributed.initialize() must
        # be called before any JAX calls that might initialise the XLA
        # backend". Match those precisely — a looser pattern (e.g. bare
        # "already") would also swallow genuine coordination failures like
        # "process already registered". Anything else — coordinator
        # unreachable, barrier timeout — must fail LOUD: swallowing it would
        # let every pod worker silently proceed as an independent single-host
        # job, training on partial data and clobbering the shared output dir.
        msg = str(e).lower()
        benign = (
            "only be called once" in msg
            or "must be called before" in msg
            or "already initialized" in msg
        )
        if not benign:
            raise
        import logging

        logging.getLogger("photon_tpu.parallel").warning(
            "jax.distributed.initialize skipped: %s", e
        )
        return False
    _initialized = True
    return True


def process_file_shard() -> tuple[int, int]:
    """(process_index, process_count) — the per-host input-file shard spec,
    directly usable as ``StreamingAvroReader.iter_chunks(..., file_shard=...)``
    (the reference's per-executor HDFS splits)."""
    return jax.process_index(), jax.process_count()


def global_batch_from_local(batch, mesh: Mesh, axis=DATA_AXIS):
    """Assemble a globally row-sharded batch from THIS process's local rows.

    Each process passes its own local pytree (rows it read via its file
    shard); the result is one global array pytree whose leading dimension is
    the concatenation over processes, sharded over ``axis``. Single-process
    this is exactly ``shard_batch_pytree``.

    Local row counts must be equal across processes (pad the tail shard —
    ``pad_rows_to_multiple`` — as the reference pads partitions).
    """
    from photon_tpu.parallel.mesh import batch_sharding

    sharding = batch_sharding(mesh, axis)

    def put(leaf):
        return jax.make_array_from_process_local_data(sharding, np.asarray(leaf))

    return jax.tree.map(put, batch)
