"""Multi-host runtime bring-up, host-sharded data feeding, and ELASTIC
mesh membership.

Parity: the reference's communication backend is the Spark driver/executor
runtime (SURVEY.md §1 layer R, §5.8 comm backend): cluster membership from
YARN, data distribution via HDFS splits, gradients via ``treeAggregate``,
and executor loss survived by rescheduling the lost partitions. Here the
same responsibilities split across TWO transports:

* **Static pod bring-up** (``initialize_distributed`` + ``multihost_mesh``)
  — the ``jax.distributed`` runtime: one process per host, a
  ``("dcn", "data")`` tuple-axis mesh spanning hosts, the fixed-effect psum
  lowering hierarchically (``SpmdGLMObjective``/``fit_spmd`` — ICI within a
  host, DCN across), per-host input files via ``process_file_shard``, and
  local rows assembled into globally sharded arrays with
  ``jax.make_array_from_process_local_data``. Fast, but NOT elastic: XLA
  collectives block forever on a dead peer and the runtime cannot shrink.
* **Elastic membership** (:class:`MeshMembership`) — a shared-filesystem
  protocol over the supervisor's liveness beacons. Barriers, per-file
  partial reductions, and epoch-journaled shrink/grow live in host space,
  so a SIGKILLed host is *classified* (``host_lost``, see
  ``runtime/backend_guard``), its file and entity shards are redistributed,
  and survivors resume from the last committed step — the treeAggregate-
  cluster analogue of Spark rescheduling lost executors
  (``parallel/elastic.ElasticTrainer`` is the consumer; drill:
  ``scripts/multihost_smoke.py``).

Everything degrades to a no-op in a single-process run, so the same driver
code serves a laptop, one TPU VM, and a multi-host pod slice.
"""
from __future__ import annotations

import json
import os
import time
from typing import Optional, Sequence

from photon_tpu.runtime.backend_guard import BackendUnusable

__all__ = [
    "DistributedInitError",
    "HostLostError",
    "MeshMembership",
    "assign_file_shards",
    "global_batch_from_local",
    "initialize_distributed",
    "multihost_mesh",
    "process_file_shard",
    "resolve_distributed_policy",
]

_initialized = False

DISTRIBUTED_POLICIES = ("strict", "degrade")
POLICY_ENV = "PHOTON_DISTRIBUTED_POLICY"

# Epoch-defining ledger events (mesh-epochs.jsonl). Any row whose ``event``
# is one of these redefines (epoch, members, file assignment); everything
# else (host_lost / host_rejoined / shard_redistributed) is commentary the
# fleet report renders as the host-loss ledger.
EPOCH_EVENTS = ("mesh_formed", "mesh_shrunk", "mesh_grown")


def resolve_distributed_policy(policy: Optional[str] = None) -> str:
    """'strict' | 'degrade' from the arg, else $PHOTON_DISTRIBUTED_POLICY,
    else strict (the PR 8 backend-policy convention: never silently train
    a different topology than the operator asked for)."""
    pol = (policy or os.environ.get(POLICY_ENV) or "strict").strip().lower()
    if pol not in DISTRIBUTED_POLICIES:
        raise ValueError(
            f"distributed policy must be one of {DISTRIBUTED_POLICIES}, "
            f"got {pol!r}"
        )
    return pol


class DistributedInitError(BackendUnusable):
    """``jax.distributed`` bring-up failed under --distributed-policy
    strict. Subclasses ``BackendUnusable`` so ``cli.params.console_main``
    surfaces it as the classified one-liner ``fatal [<cause>]: ...`` with
    exit 2 — a pod worker that cannot join the mesh must never silently
    train as an independent single-host job."""


class HostLostError(RuntimeError):
    """A peer host of the elastic mesh died (stale beacon) or a mesh
    barrier/reduction timed out waiting for it. The message deliberately
    matches ``backend_guard.classify_backend_error`` → ``host_lost``."""

    def __init__(self, dead: Sequence[int], detail: str = ""):
        self.dead = sorted(int(d) for d in dead)
        msg = f"peer host lost: missed beacon from host(s) {self.dead}"
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    *,
    policy: Optional[str] = None,
    journal=None,
    logger=None,
) -> bool:
    """Join the multi-host runtime; returns True iff it actually initialized.

    Call once at driver start, BEFORE any other JAX API touches the backend.
    With no arguments, TPU pod environments auto-detect everything; on other
    platforms a single-process run is detected and left untouched (no-op).

    A FAILED bring-up is never silent (the one failure mode worse than a
    crash is N pod workers each silently proceeding as an independent
    single-host job, training on partial data and clobbering the shared
    output dir). Under ``policy='strict'`` (default; also
    ``$PHOTON_DISTRIBUTED_POLICY``) the failure raises
    :class:`DistributedInitError` — classified via
    ``backend_guard.classify_backend_error`` and surfaced by
    ``console_main`` as ``fatal [<cause>]: ...`` with exit 2. Under
    ``'degrade'`` the downgrade to single-host is journaled as a
    ``distributed_init_failed`` event (``journal`` — a
    ``supervisor.RecoveryJournal`` — when given), counted
    (``distributed_init_failed_total{cause=...}``), and logged, then the
    run proceeds single-host.
    """
    global _initialized
    if _initialized:
        return False
    if coordinator_address is None and num_processes is None:
        # Decide from the environment ONLY — probing jax (even
        # ``jax.process_count()``) would initialize the XLA backend and make
        # ``jax.distributed.initialize`` unusable afterwards. Auto-initialize
        # only where multi-host auto-detection exists: a multi-worker TPU pod
        # (comma-separated TPU_WORKER_HOSTNAMES) or a megascale (multi-slice)
        # coordinator.
        hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        multi_host = "," in hosts
        multi_slice = bool(os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"))
        if not (multi_host or multi_slice):
            return False
    import logging

    import jax

    log = logger or logging.getLogger("photon_tpu.parallel")
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        # Only "double init" / "backend already up" is benign (proceed
        # single-runtime). jax 0.9.0's exact texts: "distributed.initialize
        # should only be called once." and "jax.distributed.initialize() must
        # be called before any JAX calls that might initialise the XLA
        # backend". Match those precisely — a looser pattern (e.g. bare
        # "already") would also swallow genuine coordination failures like
        # "process already registered".
        msg = str(e).lower()
        benign = (
            "only be called once" in msg
            or "must be called before" in msg
            or "already initialized" in msg
        )
        if benign:
            log.warning("jax.distributed.initialize skipped: %s", e)
            return False
        return _init_failed(e, policy, journal, log)
    except Exception as e:  # noqa: BLE001 - unreachable coordinator raises
        # grpc/OS errors too; every non-benign failure takes the policy path
        return _init_failed(e, policy, journal, log)
    _initialized = True
    return True


def _init_failed(e, policy, journal, log) -> bool:
    from photon_tpu.obs import trace as _trace
    from photon_tpu.obs.metrics import REGISTRY
    from photon_tpu.runtime.backend_guard import classify_backend_error

    cause = classify_backend_error(e)
    pol = resolve_distributed_policy(policy)
    reason = (
        f"jax.distributed bring-up failed ({type(e).__name__}: {e}) — "
        f"policy={pol}"
    )
    REGISTRY.counter(
        "distributed_init_failed_total",
        "jax.distributed.initialize failures by classified cause",
    ).inc(cause=cause, policy=pol)
    _trace.instant("distributed_init_failed", cat="warning",
                   cause=cause, policy=pol)
    if journal is not None:
        journal.record("distributed_init_failed", cause=cause, policy=pol,
                       error=str(e)[:500])
    if pol == "strict":
        raise DistributedInitError(cause, reason) from e
    log.error(
        "DEGRADED to single-host: %s — this worker now trains alone on its "
        "file shard only (journaled distributed_init_failed)", reason,
    )
    return False


def process_file_shard(files: Optional[Sequence] = None):
    """The per-host input-file shard.

    Without arguments: ``(process_index, process_count)`` — directly usable
    as ``StreamingAvroReader.iter_chunks(..., file_shard=...)`` (the
    reference's per-executor HDFS splits).

    With ``files`` (the canonical, ordered global file list): the sublist
    THIS process owns under :func:`assign_file_shards` — each host streams
    ONLY its shard. May be empty (fewer files than hosts); an empty-shard
    host still participates in every collective.
    """
    import jax

    if files is None:
        return jax.process_index(), jax.process_count()
    shards = assign_file_shards(files, range(jax.process_count()))
    return shards[jax.process_index()]


def assign_file_shards(files: Sequence, members: Sequence[int]) -> dict:
    """Deterministic round-robin file→host assignment: {host: [files]}.

    Every file lands on exactly one host; every member gets a key (possibly
    an empty list — ragged counts and fewer-files-than-hosts are fine). The
    assignment depends only on (file order, sorted member set), so every
    host of an epoch computes the identical map locally — no negotiation
    round — and a membership change yields a deterministic redistribution.
    """
    hosts = sorted(set(int(m) for m in members))
    if not hosts:
        raise ValueError("assign_file_shards: no members")
    out: dict = {h: [] for h in hosts}
    for i, f in enumerate(files):
        out[hosts[i % len(hosts)]].append(f)
    return out


def multihost_mesh(axis_sizes: Optional[dict] = None):
    """The ``("dcn", "data")`` tuple-axis mesh spanning a jax.distributed
    pod: the outer ``dcn`` axis crosses hosts (slowest-varying — one slice
    per process), the inner axes ride ICI within each host. Single-process
    this is a plain local mesh; pass the result + ``data_axis=("dcn",
    "data")`` to ``SpmdGLMObjective``/``fit_spmd``/``_mesh_puts`` and the
    psums lower hierarchically."""
    import jax

    from photon_tpu.parallel.mesh import make_mesh, make_multislice_mesh

    n = jax.process_count()
    if n <= 1:
        return make_mesh(axis_sizes)
    return make_multislice_mesh(n, axis_sizes)


def global_batch_from_local(batch, mesh, axis=None):
    """Assemble a globally row-sharded batch from THIS process's local rows.

    Each process passes its own local pytree (rows it read via its file
    shard); the result is one global array pytree whose leading dimension is
    the concatenation over processes, sharded over ``axis``. Single-process
    this is exactly ``shard_batch_pytree``.

    Local row counts must be equal across processes (pad the tail shard —
    ``pad_rows_to_multiple`` — as the reference pads partitions).
    """
    import jax
    import numpy as np

    from photon_tpu.parallel.mesh import DATA_AXIS, batch_sharding

    sharding = batch_sharding(mesh, DATA_AXIS if axis is None else axis)

    def put(leaf):
        return jax.make_array_from_process_local_data(sharding, np.asarray(leaf))

    return jax.tree.map(put, batch)


# ---------------------------------------------------------------------------
# Elastic mesh membership
# ---------------------------------------------------------------------------


def _atomic_write_json(path: str, payload: dict) -> None:
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


class MeshMembership:
    """Shared-filesystem elastic membership: beacons, epochs, barriers, and
    per-file partial reductions for an N-host mesh on one coordinated run.

    Design (docs/scaling.md §"Multi-host mesh"):

    * **Liveness** rides ``supervisor.Heartbeat`` beacon files in
      ``<mesh_dir>/beacons`` (atomic writes, staleness judged by shared-fs
      mtime). Every wait loop in the protocol checks beacons, so a
      SIGKILLed host converts a would-be hang into :class:`HostLostError`
      within ~3 beat intervals. Beacon ages are exported as
      ``host_beacon_age_seconds{host=...}`` gauges (the fleet report and
      the live ``/fleet`` view show dead hosts without reading journals).
    * **Epochs** live in the append-only ledger ``mesh-epochs.jsonl``
      (``supervisor.RecoveryJournal`` rows). An epoch row names (epoch,
      members, file assignment); only the coordinator — the smallest live
      host id — appends. Shrink and grow are therefore single-writer;
      everyone else adopts the newest epoch row.
    * **Barriers** are per-(epoch, name) arrival files: a host arrives by
      touching ``barriers/e<epoch>/<name>/host-<id>`` and waits for every
      member of ITS epoch, beacon-checked. Empty-shard hosts barrier like
      everyone else — membership, not data volume, defines the collective.
    * **Reductions** (:meth:`reduce_parts`) are keyed by *part id*, not by
      host: each host publishes one partial file per input part it owns and
      waits for the canonical global part set. Summing in canonical part
      order makes the reduced value bit-identical under ANY assignment of
      parts to hosts — the property that lets a shrink resume ≤1e-12 (in
      fact exactly) equal to the uninterrupted run.
    * **Shrink** (:meth:`handle_loss`): the surviving coordinator journals
      classified ``host_lost`` rows, per-shard ``shard_redistributed``
      rows, and a ``mesh_shrunk`` epoch row; survivors adopt it and redo
      the in-flight step under the new epoch (reduce/exchange namespaces
      are epoch-scoped, so a dead host's stale partials are never read).
      Bounded by the existing recovery budget
      (``backend_guard.max_inrun_recoveries``).
    * **Grow** (:meth:`maybe_grow`, coordinator, at step boundaries): a
      returning host beacons + drops a join request; the next boundary
      journals ``host_rejoined`` + redistribution rows and a ``mesh_grown``
      epoch row scaling the mesh back up.
    """

    def __init__(
        self,
        mesh_dir: str,
        host_id: int,
        n_hosts: int,
        part_ids: Sequence[str],
        *,
        beat_seconds: float = 0.4,
        stale_factor: float = 3.0,
        wait_timeout: float = 120.0,
        poll_seconds: float = 0.03,
        max_shrinks: Optional[int] = None,
        logger=None,
    ):
        import logging

        from photon_tpu.runtime.backend_guard import max_inrun_recoveries
        from photon_tpu.supervisor import Heartbeat, RecoveryJournal

        self.mesh_dir = mesh_dir
        self.host_id = int(host_id)
        self.expected = list(range(int(n_hosts)))
        self.part_ids = [str(p) for p in part_ids]
        self.beat_seconds = float(beat_seconds)
        self.stale_seconds = float(stale_factor) * self.beat_seconds
        self.wait_timeout = float(wait_timeout)
        self.poll_seconds = float(poll_seconds)
        self.max_shrinks = (max_inrun_recoveries()
                            if max_shrinks is None else int(max_shrinks))
        self.log = logger or logging.getLogger("photon_tpu.parallel")
        os.makedirs(mesh_dir, exist_ok=True)
        self.ledger_path = os.path.join(mesh_dir, "mesh-epochs.jsonl")
        self.journal = RecoveryJournal(self.ledger_path)
        self.hb = Heartbeat(
            os.path.join(mesh_dir, "beacons"),
            process_id=self.host_id,
            interval_seconds=self.beat_seconds,
            memory_guard=None,
            peer_gauges=self.expected,
        )
        self.epoch = -1
        self.members: list[int] = []
        self.files: dict[int, list[str]] = {}
        self.shrinks = 0
        self.rejoined = False  # True when this host joined via request_join

    # -- ledger ------------------------------------------------------------

    def _read_ledger(self) -> list[dict]:
        rows = []
        try:
            with open(self.ledger_path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rows.append(json.loads(line))
                    except ValueError:
                        continue  # torn tail mid-append; next poll sees it
        except OSError:
            pass
        return rows

    def _newest_epoch_row(self) -> Optional[dict]:
        newest = None
        for row in self._read_ledger():
            if row.get("event") in EPOCH_EVENTS:
                newest = row
        return newest

    def _adopt(self, row: dict) -> None:
        self.epoch = int(row["epoch"])
        self.members = [int(m) for m in row["members"]]
        self.files = {int(h): list(fs)
                      for h, fs in (row.get("files") or {}).items()}

    def _write_epoch(self, event: str, members: Sequence[int],
                     **fields) -> dict:
        members = sorted(int(m) for m in members)
        files = assign_file_shards(self.part_ids, members)
        row = dict(event=event, epoch=self.epoch + 1, members=members,
                   files={str(h): fs for h, fs in files.items()}, **fields)
        self.journal.record(**row)
        self._adopt(row)
        return row

    def _journal_redistribution(self, old_files: dict, new_files: dict,
                                old_members: Sequence[int]) -> None:
        """One ``shard_redistributed`` row per host whose file shard
        changed, plus one for the entity re-hash (ownership is
        ``entity % len(members)``, so ANY membership change remaps it)."""
        for h, fs in new_files.items():
            gained = [f for f in fs if f not in (old_files.get(h) or [])]
            if gained:
                self.journal.record(
                    "shard_redistributed", kind="files", host=h,
                    n_items=len(gained), items=gained[:32],
                )
        self.journal.record(
            "shard_redistributed", kind="entities",
            members_before=sorted(old_members),
            members_after=sorted(self.members),
        )

    # -- liveness ----------------------------------------------------------

    def beacon_ages(self, hosts: Optional[Sequence[int]] = None) -> dict:
        """host → seconds since its last beacon (-1: no beacon file),
        judged against our own beacon's mtime (shared-fs clock)."""
        hosts = list(self.expected if hosts is None else hosts)
        try:
            now = os.path.getmtime(self.hb._path(self.host_id))
        except OSError:
            now = time.time()
        out = {}
        for h in hosts:
            try:
                out[h] = max(0.0, now - os.path.getmtime(self.hb._path(h)))
            except OSError:
                out[h] = -1.0
        return out

    def _check_members(self, detail: str) -> None:
        """Raise :class:`HostLostError` if any CURRENT member's beacon is
        stale or missing (self excluded — we are demonstrably alive)."""
        peers = [m for m in self.members if m != self.host_id]
        if not peers:
            return
        report = self.hb.check_peers(peers, max_age_seconds=self.stale_seconds)
        dead = sorted(report.dead + report.missing)
        if dead:
            raise HostLostError(dead, detail)

    # -- lifecycle ---------------------------------------------------------

    @property
    def coordinator(self) -> int:
        return min(self.members) if self.members else min(self.expected)

    @property
    def is_coordinator(self) -> bool:
        return self.host_id == self.coordinator

    def my_files(self) -> list[str]:
        return list(self.files.get(self.host_id, []))

    def owner_of_entity(self, entity_id: int) -> int:
        """Deterministic entity→host hash over the CURRENT members."""
        members = sorted(self.members)
        return members[int(entity_id) % len(members)]

    def start(self, form_timeout: float = 60.0) -> "MeshMembership":
        """Beacon up and join the mesh: form it (first boot), adopt the
        current epoch, or — when the ledger shows a mesh we are not a
        member of — request a rejoin and wait for the scale-up epoch."""
        self.hb.start()
        row = self._newest_epoch_row()
        if row is None:
            if self.host_id == min(self.expected):
                self._form(form_timeout)
            else:
                self._wait_for_membership(form_timeout,
                                          "initial mesh formation")
            return self
        if self.host_id in [int(m) for m in row["members"]]:
            self._adopt(row)
            return self
        self.request_join()
        self._wait_for_membership(self.wait_timeout, "rejoin scale-up")
        self.rejoined = True
        return self

    def _form(self, timeout: float) -> None:
        """Coordinator first boot: wait for every expected beacon (or the
        deadline), then journal epoch 0. Hosts that never showed are formed
        around — journaled ``host_lost`` so the absence is never silent."""
        deadline = time.monotonic() + timeout
        while True:
            ages = self.beacon_ages(self.expected)
            live = [h for h, a in ages.items()
                    if 0.0 <= a <= self.stale_seconds]
            if len(live) == len(self.expected) or time.monotonic() > deadline:
                break
            time.sleep(self.poll_seconds)
        for h in sorted(set(self.expected) - set(live)):
            self.journal.record("host_lost", host=h, cause="host_lost",
                                phase="formation",
                                beacon_age_seconds=ages.get(h, -1.0))
        self._write_epoch("mesh_formed", live or [self.host_id])

    def _wait_for_membership(self, timeout: float, detail: str) -> None:
        deadline = time.monotonic() + timeout
        while True:
            row = self._newest_epoch_row()
            if row is not None and self.host_id in [int(m)
                                                    for m in row["members"]]:
                self._adopt(row)
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"host {self.host_id} never became a mesh member "
                    f"({detail}; ledger={self.ledger_path})"
                )
            time.sleep(self.poll_seconds * 4)

    def stop(self) -> None:
        self.hb.stop()

    # -- barriers + reductions --------------------------------------------

    def barrier(self, name: str, timeout: Optional[float] = None) -> None:
        """Arrive at the named barrier of the CURRENT epoch and wait for
        every member, beacon-checked (a dead member raises
        :class:`HostLostError` instead of hanging)."""
        d = os.path.join(self.mesh_dir, "barriers", f"e{self.epoch}", name)
        os.makedirs(d, exist_ok=True)
        mine = os.path.join(d, f"host-{self.host_id}")
        with open(mine, "w") as f:
            f.write(str(time.time()))
        deadline = time.monotonic() + (timeout or self.wait_timeout)
        while True:
            try:
                present = set(os.listdir(d))
            except OSError:
                present = set()
            missing = [m for m in self.members
                       if f"host-{m}" not in present]
            if not missing:
                return
            self._check_members(f"mesh barrier {name!r} epoch {self.epoch}")
            if time.monotonic() > deadline:
                raise HostLostError(
                    missing, f"mesh barrier timeout at {name!r} "
                             f"epoch {self.epoch}")
            time.sleep(self.poll_seconds)

    def reduce_parts(self, tag: str, payloads: dict,
                     timeout: Optional[float] = None) -> dict:
        """All-reduce keyed by canonical part id.

        ``payloads``: {part_id: {name: np.ndarray}} for the parts THIS host
        owns (possibly empty — the host still waits, i.e. participates).
        Publishes one npz per part under the CURRENT epoch's namespace and
        blocks until every part id of the canonical global list is present,
        beacon-checked. Returns {part_id: {name: np.ndarray}} for ALL
        parts; the caller folds them in canonical order so the global sum
        is independent of which host computed which part.
        """
        import numpy as np

        d = os.path.join(self.mesh_dir, "reduce", f"e{self.epoch}", tag)
        os.makedirs(d, exist_ok=True)
        for pid, arrs in payloads.items():
            path = os.path.join(d, f"part-{pid}.npz")
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "wb") as f:
                np.savez(f, **arrs)
            os.replace(tmp, path)
        want = {f"part-{pid}.npz" for pid in self.part_ids}
        deadline = time.monotonic() + (timeout or self.wait_timeout)
        while True:
            try:
                present = want & set(os.listdir(d))
            except OSError:
                present = set()
            if present == want:
                break
            self._check_members(f"reduction {tag!r} epoch {self.epoch}")
            if time.monotonic() > deadline:
                raise HostLostError(
                    sorted(m for m in self.members if m != self.host_id),
                    f"collective {tag!r} timed out waiting for host parts "
                    f"{sorted(want - present)[:8]}")
            time.sleep(self.poll_seconds)
        out = {}
        for pid in self.part_ids:
            with np.load(os.path.join(d, f"part-{pid}.npz")) as z:
                out[pid] = {k: z[k] for k in z.files}
        return out

    def exchange(self, tag: str, outbound: dict,
                 timeout: Optional[float] = None) -> dict:
        """All-to-all under the current epoch: ``outbound`` maps target
        host → {name: array} (EVERY member except self must have an entry,
        even if its arrays are empty — an empty-shard host still
        participates). Returns {source host: {name: array}} for every
        member except self."""
        import numpy as np

        d = os.path.join(self.mesh_dir, "exchange", f"e{self.epoch}", tag)
        os.makedirs(d, exist_ok=True)
        for target, arrs in outbound.items():
            path = os.path.join(d, f"from-{self.host_id}-to-{target}.npz")
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "wb") as f:
                np.savez(f, **arrs)
            os.replace(tmp, path)
        peers = [m for m in self.members if m != self.host_id]
        want = {f"from-{s}-to-{self.host_id}.npz" for s in peers}
        deadline = time.monotonic() + (timeout or self.wait_timeout)
        while want:
            try:
                present = want & set(os.listdir(d))
            except OSError:
                present = set()
            if present == want:
                break
            self._check_members(f"exchange {tag!r} epoch {self.epoch}")
            if time.monotonic() > deadline:
                raise HostLostError(peers, f"exchange {tag!r} timed out")
            time.sleep(self.poll_seconds)
        out = {}
        for s in peers:
            p = os.path.join(d, f"from-{s}-to-{self.host_id}.npz")
            with np.load(p) as z:
                out[s] = {k: z[k] for k in z.files}
        return out

    # -- shrink / grow -----------------------------------------------------

    def handle_loss(self, dead_hint: Sequence[int]) -> None:
        """Coordinated shrink after :class:`HostLostError`.

        The smallest SURVIVING host journals the classified ``host_lost``
        rows, the redistribution rows, and the ``mesh_shrunk`` epoch; every
        other survivor waits for that row and adopts it. Coordinator death
        is covered: if the writer-elect also goes stale during the wait,
        the next-smallest survivor takes over (the loop re-runs with the
        larger dead set). Budget: more than
        ``backend_guard.max_inrun_recoveries()`` shrinks in one run
        escalates to the supervisor restart path."""
        ages = self.beacon_ages(self.members)
        dead = sorted(
            {int(d) for d in dead_hint}
            | {h for h, a in ages.items()
               if h != self.host_id and (a < 0.0 or a > self.stale_seconds)}
        )
        dead = [d for d in dead if d in self.members]
        if not dead:
            return  # spurious (e.g. barrier raced a slow beacon); retry
        self.shrinks += 1
        if self.shrinks > self.max_shrinks:
            self.journal.record("recovery_budget_exhausted",
                                scope="mesh_shrink", shrinks=self.shrinks,
                                budget=self.max_shrinks)
            raise RuntimeError(
                f"mesh shrink budget exhausted ({self.shrinks} > "
                f"{self.max_shrinks}); escalating to supervisor restart"
            )
        survivors = [m for m in self.members if m not in dead]
        old_members, old_files = list(self.members), dict(self.files)
        if self.host_id == min(survivors):
            for h in dead:
                self.journal.record(
                    "host_lost", host=h, cause="host_lost",
                    epoch=self.epoch, beacon_age_seconds=ages.get(h, -1.0),
                )
            self._write_epoch("mesh_shrunk", survivors, dead=dead)
            self._journal_redistribution(
                old_files, {int(h): f for h, f in self.files.items()},
                old_members)
            self.log.warning(
                "mesh shrunk: epoch %d, lost %s, members %s",
                self.epoch, dead, self.members)
            return
        # Non-coordinator survivor: wait for the shrink row; if the elected
        # writer dies mid-shrink, re-enter with it added to the dead set.
        deadline = time.monotonic() + self.wait_timeout
        while True:
            row = self._newest_epoch_row()
            if row is not None and int(row["epoch"]) > self.epoch:
                if self.host_id in [int(m) for m in row["members"]]:
                    self._adopt(row)
                    return
            writer = min(survivors)
            age = self.beacon_ages([writer]).get(writer, -1.0)
            if age < 0.0 or age > self.stale_seconds:
                return self.handle_loss(dead + [writer])
            if time.monotonic() > deadline:
                raise HostLostError(
                    [writer], "waiting for mesh_shrunk epoch row")
            time.sleep(self.poll_seconds)

    def request_join(self) -> None:
        d = os.path.join(self.mesh_dir, "join")
        os.makedirs(d, exist_ok=True)
        _atomic_write_json(
            os.path.join(d, f"host-{self.host_id}.json"),
            {"host": self.host_id, "pid": os.getpid(), "time": time.time()},
        )

    def maybe_grow(self) -> bool:
        """Coordinator, at a step boundary: admit rejoin requests whose
        beacons are fresh. Journals ``host_rejoined`` + redistribution rows
        and the ``mesh_grown`` epoch; returns True when the mesh grew."""
        if not self.is_coordinator:
            return False
        d = os.path.join(self.mesh_dir, "join")
        try:
            reqs = [int(n[len("host-"):-len(".json")])
                    for n in os.listdir(d)
                    if n.startswith("host-") and n.endswith(".json")]
        except OSError:
            return False
        ages = self.beacon_ages(sorted(set(reqs)))
        joiners = [h for h in sorted(set(reqs))
                   if h not in self.members
                   and 0.0 <= ages.get(h, -1.0) <= self.stale_seconds]
        stale_reqs = [h for h in reqs if h in self.members]
        for h in stale_reqs:  # already members: consumed requests
            try:
                os.remove(os.path.join(d, f"host-{h}.json"))
            except OSError:
                pass
        if not joiners:
            return False
        old_members, old_files = list(self.members), dict(self.files)
        for h in joiners:
            self.journal.record("host_rejoined", host=h, epoch=self.epoch)
        self._write_epoch("mesh_grown", old_members + joiners,
                          joined=joiners)
        self._journal_redistribution(
            old_files, {int(h): f for h, f in self.files.items()},
            old_members)
        for h in joiners:
            try:
                os.remove(os.path.join(d, f"host-{h}.json"))
            except OSError:
                pass
        self.log.warning("mesh grown: epoch %d, rejoined %s, members %s",
                         self.epoch, joiners, self.members)
        return True

    def sync_epoch(self) -> bool:
        """Adopt the newest ledger epoch (non-coordinators see grow rows
        here). Returns True when (epoch, members, assignment) changed. A
        host finding itself EXCLUDED from the newest epoch (a conservative
        peer declared us dead while we were merely slow) self-heals by
        filing a rejoin request and waiting for the scale-up."""
        row = self._newest_epoch_row()
        if row is None or int(row["epoch"]) == self.epoch:
            return False
        if self.host_id not in [int(m) for m in row["members"]]:
            self.log.warning(
                "host %d evicted at epoch %s; requesting rejoin",
                self.host_id, row["epoch"])
            self.request_join()
            self._wait_for_membership(self.wait_timeout, "post-eviction")
            self.rejoined = True
            return True
        self._adopt(row)
        return True
