"""Elastic multi-host GAME training over :class:`MeshMembership`.

The treeAggregate cluster, done honest (docs/scaling.md §"Multi-host
mesh"): upstream photon-ml broadcasts coefficients per L-BFGS iteration and
``treeAggregate``s per-partition (value, gradient) partials across Spark
executors, surviving executor loss because the partials are keyed by
*partition*, not by executor — a lost executor's partitions are simply
rescheduled. This module is that design over explicit collectives:

* **Fixed-effect coordinate** — every host runs the SAME deterministic
  host-space L-BFGS (:func:`_host_lbfgs`, numpy f64, two-loop recursion +
  Armijo backtracking) in lockstep; each (value, grad) evaluation is one
  jitted per-part kernel per owned file part (a local shard_map+psum over
  this host's forced devices when a local mesh is given — the ICI level)
  plus one :meth:`MeshMembership.reduce_parts` round (the DCN/treeAggregate
  level). Partials are keyed by canonical part id and folded in canonical
  part order, so the global (value, grad) — and therefore the whole
  optimizer trajectory — is **bit-identical under any assignment of parts
  to hosts**. That is the entire ≤1e-12 elasticity argument for this
  coordinate: a shrink changes who computes which part, not what is summed.
* **Random-effect coordinate** — entities hash to hosts over the CURRENT
  members (``owner_of_entity``); hosts exchange rows so each owns all rows
  of its entities (the Spark shuffle analogue, via
  :meth:`MeshMembership.exchange`), then run the blessed
  ``train_random_effects`` kernels on a host-local dataset, warm-started
  from the last committed per-entity coefficients. Buckets are padded to a
  fixed entity capacity (``_pad_bucket`` to ``e_cap``), so bucket shapes
  are membership-invariant and survivors never retrace after warmup.
  Per-entity coefficients and per-row scores are published per step;
  every host folds all publications, so state is replicated and any host
  can inherit a dead host's entities from the last committed step.
* **Commit / redo** — after every coordinate step the coordinator writes
  ``commits/commit-<n>`` (fixed w, global RE score vector, all-entity CSR
  coefficients). On :class:`HostLostError` anywhere, survivors run the
  coordinated shrink (``handle_loss``) and redo the in-flight step from
  the last commit under the new epoch — epoch-scoped reduce/exchange
  namespaces mean a dead host's stale partials are never read. A rejoining
  host is admitted at the next step boundary (``maybe_grow``) and resumes
  from the same commit.

Why not ``jax.distributed`` for this path: XLA collectives cannot survive a
peer death (the runtime blocks in C++ and the process group cannot shrink
or re-form), so elasticity REQUIRES host-space collectives. The
``jax.distributed`` + ``("dcn","data")`` ``fit_spmd`` path
(``parallel/distributed.initialize_distributed`` / ``multihost_mesh``)
remains the static bring-up for healthy pods; this module is the one that
survives losing one.

Drill: ``scripts/multihost_smoke.py`` (SIGKILL + rejoin, ci.sh stage).
Figures: ``bench.py`` ``game_scale_multihost`` leg.
"""
from __future__ import annotations

import dataclasses
import glob
import json
import os
import time
from typing import Optional, Sequence

import numpy as np

from photon_tpu.parallel.distributed import HostLostError, MeshMembership

__all__ = [
    "ElasticConfig",
    "ElasticTrainer",
    "load_manifest",
    "make_synthetic_parts",
    "merge_mesh_cost_tables",
    "worker_main",
]

FIXED_KERNEL = "elastic_fixed_vg"  # retrace-sentinel name for the part kernel


# ---------------------------------------------------------------------------
# Synthetic part files (smoke / bench / tests fixture)
# ---------------------------------------------------------------------------


def make_synthetic_parts(
    out_dir: str,
    n_parts: int = 6,
    rows_per_part: int = 48,
    dim: int = 10,
    n_entities: int = 18,
    seed: int = 7,
    task: str = "LOGISTIC_REGRESSION",
) -> str:
    """Write ``n_parts`` npz part files + a manifest; returns the manifest
    path. Rows are dense ELL (K == dim) and entities interleave across
    parts (``entity = global_row % n_entities``), so every host's file
    shard holds rows of every entity — the row exchange is genuinely
    exercised. Keep ``(n_parts * rows_per_part) % n_entities == 0`` so
    every entity has the same global row count (membership-invariant RE
    bucket shapes; see module docstring)."""
    os.makedirs(out_dir, exist_ok=True)
    rng = np.random.default_rng(seed)
    n_rows = n_parts * rows_per_part
    w_fix = rng.normal(0.0, 0.7, dim)
    w_ent = rng.normal(0.0, 0.4, (n_entities, dim))
    parts = []
    for p in range(n_parts):
        rows = np.arange(p * rows_per_part, (p + 1) * rows_per_part)
        ent = rows % n_entities
        val = rng.normal(0.0, 1.0, (rows_per_part, dim))
        z = val @ w_fix + np.einsum("rd,rd->r", val, w_ent[ent])
        if task == "LINEAR_REGRESSION":
            labels = z + rng.normal(0.0, 0.1, rows_per_part)
        else:
            labels = (rng.random(rows_per_part)
                      < 1.0 / (1.0 + np.exp(-z))).astype(np.float64)
        pid = f"p{p:03d}"
        path = os.path.join(out_dir, f"{pid}.npz")
        np.savez(
            path,
            idx=np.tile(np.arange(dim, dtype=np.int32), (rows_per_part, 1)),
            val=val.astype(np.float64),
            labels=labels.astype(np.float64),
            weights=np.ones(rows_per_part),
            entity=ent.astype(np.int64),
            row_id=rows.astype(np.int64),
        )
        parts.append({"id": pid, "path": f"{pid}.npz",
                      "rows": int(rows_per_part)})
    manifest = {
        "schema": "photon-elastic-manifest/1",
        "task": task,
        "dim": int(dim),
        "n_rows": int(n_rows),
        "n_entities": int(n_entities),
        "rows_per_part": int(rows_per_part),
        "parts": parts,
    }
    mpath = os.path.join(out_dir, "manifest.json")
    tmp = f"{mpath}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
    os.replace(tmp, mpath)
    return mpath


def load_manifest(path: str) -> dict:
    with open(path) as f:
        m = json.load(f)
    if m.get("schema") != "photon-elastic-manifest/1":
        raise ValueError(f"not an elastic manifest: {path}")
    base = os.path.dirname(os.path.abspath(path))
    for p in m["parts"]:
        if not os.path.isabs(p["path"]):
            p["path"] = os.path.join(base, p["path"])
    return m


# ---------------------------------------------------------------------------
# Deterministic host-space L-BFGS (replicated identically on every host)
# ---------------------------------------------------------------------------


def _host_lbfgs(vg, w0: np.ndarray, max_iterations: int = 40,
                memory: int = 10, gtol: float = 1e-10):
    """Two-loop-recursion L-BFGS with Armijo backtracking, pure numpy f64.

    Every host runs this identical deterministic loop on identical reduced
    (value, grad) pairs, so the iterates stay bit-equal across hosts — the
    property the elastic protocol leans on (no coefficient broadcast is
    ever needed; the "broadcast" is replicated computation). ``vg`` may
    raise :class:`HostLostError`; no state is mutated on the way out."""
    w = np.asarray(w0, np.float64).copy()
    f, g = vg(w)
    S: list = []
    Y: list = []
    rho: list = []
    evals = 1
    it = 0
    for it in range(max_iterations):
        if float(np.max(np.abs(g))) <= gtol:
            break
        q = g.copy()
        alphas = []
        for s, y, r in zip(reversed(S), reversed(Y), reversed(rho)):
            a = r * float(np.dot(s, q))
            alphas.append(a)
            q -= a * y
        if Y:
            q *= float(np.dot(S[-1], Y[-1])) / float(np.dot(Y[-1], Y[-1]))
        for (s, y, r), a in zip(zip(S, Y, rho), reversed(alphas)):
            b = r * float(np.dot(y, q))
            q += (a - b) * s
        d = -q
        dg = float(np.dot(d, g))
        if dg >= 0.0:  # stale curvature turned d uphill; steepest descent
            d = -g
            dg = -float(np.dot(g, g))
        t = 1.0 if S else min(1.0, 1.0 / max(1e-12, float(np.sum(np.abs(g)))))
        w_try, f_try, g_try = w, f, g
        for _ in range(30):
            w_try = w + t * d
            f_try, g_try = vg(w_try)
            evals += 1
            if f_try <= f + 1e-4 * t * dg:
                break
            t *= 0.5
        s = w_try - w
        y = g_try - g
        sy = float(np.dot(s, y))
        w, f, g = w_try, f_try, g_try
        if sy > 1e-12:
            S.append(s)
            Y.append(y)
            rho.append(1.0 / sy)
            if len(S) > memory:
                S.pop(0)
                Y.pop(0)
                rho.pop(0)
    return w, f, it, evals


# ---------------------------------------------------------------------------
# Per-part fixed-effect kernel (one compile, shared by every part)
# ---------------------------------------------------------------------------

_KERNELS: dict = {}


def _fixed_part_kernel(task: str, dim: int, mesh, data_axis):
    """The jitted data-only (value, grad) kernel for ONE padded part.

    One closure per (task, dim, mesh) — NOT per part — so all parts (and
    any part a survivor inherits after a shrink) share a single XLA
    executable: shapes are fixed by the manifest and function identity is
    fixed by this cache, which is what keeps the retrace sentinel at zero
    across membership changes. With a local mesh the body is the
    ``SpmdGLMObjective`` shard_map+psum pattern over this host's devices;
    L2 is NOT applied here (the trainer adds it once, globally)."""
    key = (task, int(dim), None if mesh is None else id(mesh),
           str(data_axis))
    got = _KERNELS.get(key)
    if got is not None:
        return got
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from photon_tpu.data.batch import LabeledBatch, SparseFeatures
    from photon_tpu.functions.objective import GLMObjective
    from photon_tpu.obs import retrace
    from photon_tpu.ops.losses import loss_for_task
    from photon_tpu.parallel.mesh import axis_tuple, shard_map
    from photon_tpu.types import TaskType

    data_obj = GLMObjective(loss=loss_for_task(TaskType[task]), l2_weight=0.0)

    def body(w, idx, val, labels, offsets, weights):
        retrace.note_trace(FIXED_KERNEL)
        batch = LabeledBatch(
            features=SparseFeatures(idx=idx, val=val, dim=dim),
            labels=labels, offsets=offsets, weights=weights,
        )
        return data_obj.value_and_grad(w, batch)

    if mesh is None:
        kern = jax.jit(body)
    else:
        axes = axis_tuple(data_axis)
        row = P(axes)
        ell = P(axes, None)

        def sharded(w, idx, val, labels, offsets, weights):
            v, g = body(w, idx, val, labels, offsets, weights)
            return lax.psum(v, axes), lax.psum(g, axes)

        kern = jax.jit(shard_map(
            sharded, mesh=mesh,
            in_specs=(P(), ell, ell, row, row, row),
            out_specs=(P(), P()),
        ))
    _KERNELS[key] = kern
    return kern


# ---------------------------------------------------------------------------
# The elastic trainer
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ElasticConfig:
    sweeps: int = 2
    fixed_l2: float = 1e-3
    re_l2: float = 1.0
    max_iterations: int = 40
    re_max_iterations: int = 40
    gtol: float = 1e-10
    lbfgs_memory: int = 10
    min_step_seconds: float = 0.0  # drill knob: widens the rejoin window


class ElasticTrainer:
    """One host's view of an elastic multi-host GAME run (see module doc).

    ``run()`` executes ``2 * sweeps`` coordinate steps (fixed, RE, fixed,
    ...), surviving host loss via shrink+redo and admitting rejoining
    hosts at step boundaries. The coordinator additionally writes commits,
    the final model, and the merged per-host ``SolverCostTable``."""

    def __init__(self, membership: MeshMembership, manifest: dict,
                 config: Optional[ElasticConfig] = None, local_mesh=None,
                 data_axis: str = "data"):
        self.mem = membership
        self.man = manifest
        self.cfg = config or ElasticConfig()
        self.local_mesh = local_mesh
        self.data_axis = data_axis
        self.task = manifest["task"]
        self.dim = int(manifest["dim"])
        self.n_rows = int(manifest["n_rows"])
        self.part_ids = [p["id"] for p in manifest["parts"]]
        self.part_paths = {p["id"]: p["path"] for p in manifest["parts"]}
        # Fixed entity capacity: bucket shapes must not depend on how many
        # entities THIS host happens to own this epoch (multiple of 8 so
        # any local mesh axis up to 8 divides it).
        self.e_cap = -(-int(manifest["n_entities"]) // 8) * 8
        if local_mesh is not None:
            from photon_tpu.parallel.mesh import axes_size

            self._axis_mult = axes_size(local_mesh, data_axis)
        else:
            self._axis_mult = 1
        self.s_pad = -(-int(manifest["rows_per_part"])
                       // self._axis_mult) * self._axis_mult
        # Replicated model state (identical on every host at every commit)
        self.w = np.zeros(self.dim)
        self.re_scores = np.zeros(self.n_rows)
        self.re_coefs: dict = {}  # entity id -> (global idx, values)
        # Per-epoch caches
        self._cache_epoch = -1
        self._parts: dict = {}
        self._re_cache = None
        self._round = 0
        self._warm_marked = False
        self.step_seconds: list = []

    # -- per-epoch data ----------------------------------------------------

    def _ensure_epoch_caches(self) -> None:
        if self._cache_epoch == self.mem.epoch:
            return
        import jax.numpy as jnp

        self._parts = {}
        for pid in self.mem.my_files():
            with np.load(self.part_paths[pid]) as z:
                d = {k: z[k] for k in z.files}
            pad = self.s_pad - d["labels"].shape[0]
            if pad:
                d["idx"] = np.pad(d["idx"], ((0, pad), (0, 0)),
                                  constant_values=self.dim)
                d["val"] = np.pad(d["val"], ((0, pad), (0, 0)))
                d["labels"] = np.pad(d["labels"], (0, pad))
                d["weights"] = np.pad(d["weights"], (0, pad))
                d["entity"] = np.pad(d["entity"], (0, pad),
                                     constant_values=-1)
                d["row_id"] = np.pad(d["row_id"], (0, pad),
                                     constant_values=self.n_rows)
            d["_jidx"] = jnp.asarray(d["idx"])
            d["_jval"] = jnp.asarray(d["val"])
            d["_jlabels"] = jnp.asarray(d["labels"])
            d["_jweights"] = jnp.asarray(d["weights"])
            self._parts[pid] = d
        self._re_cache = None
        self._cache_epoch = self.mem.epoch

    # -- fixed-effect coordinate ------------------------------------------

    def _fixed_step(self, n: int) -> None:
        import jax.numpy as jnp

        kern = _fixed_part_kernel(self.task, self.dim, self.local_mesh,
                                  self.data_axis)
        self._round = 0
        re_ext = np.concatenate([self.re_scores, [0.0]])
        l2 = self.cfg.fixed_l2

        def vg(w):
            payloads = {}
            wj = jnp.asarray(w)
            for pid, d in self._parts.items():
                offs = jnp.asarray(re_ext[d["row_id"]])
                v, g = kern(wj, d["_jidx"], d["_jval"], d["_jlabels"],
                            offs, d["_jweights"])
                payloads[pid] = {"v": np.asarray(v, np.float64).reshape(1),
                                 "g": np.asarray(g, np.float64)}
            tag = f"s{n}-r{self._round}"
            self._round += 1
            parts = self.mem.reduce_parts(tag, payloads)
            val = 0.0
            grad = np.zeros(self.dim)
            for pid in self.part_ids:  # canonical fold order: part id, not host
                val += float(parts[pid]["v"][0])
                grad += np.asarray(parts[pid]["g"], np.float64)
            return (val + 0.5 * l2 * float(np.dot(w, w)), grad + l2 * w)

        self.w, _, _, _ = _host_lbfgs(
            vg, self.w, self.cfg.max_iterations, self.cfg.lbfgs_memory,
            self.cfg.gtol)

    # -- random-effect coordinate -----------------------------------------

    def _re_rows(self) -> dict:
        """This epoch's exchanged row set for entities we own: canonical
        (sorted by global row id) arrays idx/val/labels/weights/entity/
        row_id. Cached per epoch (the shuffle is membership-dependent,
        not step-dependent)."""
        if self._re_cache is not None:
            return self._re_cache
        names = ("idx", "val", "labels", "weights", "entity", "row_id")
        keep: dict = {m: {k: [] for k in names} for m in self.mem.members}
        for pid, d in self._parts.items():
            ent = d["entity"]
            real = ent >= 0  # drop part padding rows
            owner = np.array([self.mem.owner_of_entity(e) if e >= 0 else -1
                              for e in ent])
            for m in self.mem.members:
                sel = real & (owner == m)
                for k in names:
                    keep[m][k].append(d[k][sel])

        def cat(chunks, k):
            if chunks:
                return np.concatenate(chunks)
            width = self.dim if k in ("idx", "val") else None
            shape = (0, width) if width else (0,)
            dt = (np.int32 if k == "idx"
                  else np.int64 if k in ("entity", "row_id") else np.float64)
            return np.zeros(shape, dt)

        outbound = {
            m: {k: cat(keep[m][k], k) for k in names}
            for m in self.mem.members if m != self.mem.host_id
        }
        inbound = self.mem.exchange("re-rows", outbound)
        mine = [{k: cat(keep[self.mem.host_id][k], k) for k in names}]
        mine.extend(inbound.values())
        rows = {k: np.concatenate([c[k] for c in mine])
                if mine else cat([], k) for k in names}
        order = np.argsort(rows["row_id"], kind="stable")
        rows = {k: v[order] for k, v in rows.items()}
        self._re_cache = rows
        return rows

    def _re_step(self, n: int) -> None:
        import jax.numpy as jnp

        from photon_tpu.functions.problem import GLMOptimizationProblem
        from photon_tpu.data.random_effect import build_random_effect_dataset
        from photon_tpu.game.random_effect import (
            _pad_bucket,
            train_random_effects,
        )
        from photon_tpu.optim import (
            OptimizerConfig,
            RegularizationContext,
            RegularizationType,
        )
        from photon_tpu.types import TaskType

        rows = self._re_rows()
        n_local = rows["labels"].shape[0]
        if n_local:
            ds = build_random_effect_dataset(
                "per-entity", rows["entity"], rows["idx"], rows["val"],
                rows["labels"], self.dim, weights=rows["weights"],
                min_entity_rows=1, dtype=np.float64,
            )
            ds = dataclasses.replace(ds, buckets=tuple(
                _pad_bucket(b, self.e_cap, ds.n_rows, self.dim)
                for b in ds.buckets))
            # Offsets: the fixed coordinate's scores for OUR rows, in the
            # dataset's (canonical) local row order.
            w_ext = np.concatenate([self.w, [0.0]])
            fixed_scores = np.einsum("rk,rk->r", w_ext[rows["idx"]],
                                     rows["val"])
            init = self._warm_start(ds)
            problem = GLMOptimizationProblem(
                task=TaskType[self.task],
                optimizer_config=OptimizerConfig(
                    max_iterations=self.cfg.re_max_iterations),
                regularization=RegularizationContext(RegularizationType.L2),
                reg_weight=self.cfg.re_l2,
            )
            model, _ = train_random_effects(
                problem, ds, jnp.asarray(fixed_scores),
                mesh=self.local_mesh, entity_axis=self.data_axis,
                init_coefs=init,
            )
            scores_local = np.asarray(model.score_dataset(ds), np.float64)
            ents, indptr, cols, vals = [], [0], [], []
            for key in model.entity_keys:
                gi, gv = model.coefficients_for(key)
                ents.append(int(key))
                cols.append(np.asarray(gi, np.int64))
                vals.append(np.asarray(gv, np.float64))
                indptr.append(indptr[-1] + len(gi))
            pub = {
                "row_id": rows["row_id"],
                "scores": scores_local,
                "ents": np.asarray(ents, np.int64),
                "indptr": np.asarray(indptr, np.int64),
                "cols": (np.concatenate(cols) if cols
                         else np.zeros(0, np.int64)),
                "vals": (np.concatenate(vals) if vals
                         else np.zeros(0, np.float64)),
            }
        else:  # empty entity shard: publish an empty, still participate
            z = np.zeros(0)
            pub = {"row_id": np.zeros(0, np.int64), "scores": z,
                   "ents": np.zeros(0, np.int64),
                   "indptr": np.zeros(1, np.int64),
                   "cols": np.zeros(0, np.int64), "vals": z}
        d = os.path.join(self.mem.mesh_dir, "scores",
                         f"e{self.mem.epoch}", f"s{n}")
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"host-{self.mem.host_id}.npz")
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez(f, **pub)
        os.replace(tmp, path)
        self.mem.barrier(f"re-pub-{n}")
        # Fold every member's publication (all present: the barrier passed)
        for m in self.mem.members:
            p = os.path.join(d, f"host-{m}.npz")
            with np.load(p) as z:
                rid = z["row_id"]
                self.re_scores[rid] = z["scores"]
                ents, indptr = z["ents"], z["indptr"]
                cols, vals = z["cols"], z["vals"]
            for i, e in enumerate(ents):
                lo, hi = int(indptr[i]), int(indptr[i + 1])
                self.re_coefs[int(e)] = (cols[lo:hi].copy(),
                                         vals[lo:hi].copy())

    def _warm_start(self, ds) -> Optional[list]:
        if not self.re_coefs:
            return None
        inits = [np.zeros((b.n_entities, b.local_dim)) for b in ds.buckets]
        for dense, (bi, lane) in ds.entity_to_slot.items():
            got = self.re_coefs.get(int(ds.entity_keys[dense]))
            if got is None:
                continue
            gi, gv = got
            ext = np.zeros(self.dim + 1)
            ext[gi] = gv
            inits[bi][lane] = ext[np.asarray(ds.buckets[bi].proj[lane])]
        return inits

    # -- commit / resume ---------------------------------------------------

    def _commit_dir(self) -> str:
        return os.path.join(self.mem.mesh_dir, "commits")

    def _commit(self, n: int) -> None:
        d = self._commit_dir()
        os.makedirs(d, exist_ok=True)
        meta_path = os.path.join(d, f"commit-{n}.json")
        if self.mem.is_coordinator:
            ents = sorted(self.re_coefs)
            indptr, cols, vals = [0], [], []
            for e in ents:
                gi, gv = self.re_coefs[e]
                cols.append(gi)
                vals.append(gv)
                indptr.append(indptr[-1] + len(gi))
            path = os.path.join(d, f"commit-{n}.npz")
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "wb") as f:
                np.savez(
                    f, w=self.w, re_scores=self.re_scores,
                    ents=np.asarray(ents, np.int64),
                    indptr=np.asarray(indptr, np.int64),
                    cols=(np.concatenate(cols) if cols
                          else np.zeros(0, np.int64)),
                    vals=(np.concatenate(vals) if vals
                          else np.zeros(0, np.float64)),
                )
            os.replace(tmp, path)
            tmpj = f"{meta_path}.tmp{os.getpid()}"
            with open(tmpj, "w") as f:
                json.dump({"n": n, "epoch": self.mem.epoch,
                           "members": self.mem.members,
                           "time": time.time()}, f)
            os.replace(tmpj, meta_path)
            return
        deadline = time.monotonic() + self.mem.wait_timeout
        while not os.path.exists(meta_path):
            self.mem._check_members(f"commit {n}")
            if time.monotonic() > deadline:
                raise HostLostError([self.mem.coordinator],
                                    f"commit {n} never appeared")
            time.sleep(self.mem.poll_seconds)

    def _latest_commit(self) -> int:
        best = -1
        for p in glob.glob(os.path.join(self._commit_dir(), "commit-*.json")):
            try:
                best = max(best, int(os.path.basename(p)[7:-5]))
            except ValueError:
                continue
        return best

    def _load_commit(self, n: int) -> None:
        if n < 0:
            self.w = np.zeros(self.dim)
            self.re_scores = np.zeros(self.n_rows)
            self.re_coefs = {}
            return
        with np.load(os.path.join(self._commit_dir(),
                                  f"commit-{n}.npz")) as z:
            self.w = np.asarray(z["w"], np.float64)
            self.re_scores = np.asarray(z["re_scores"], np.float64)
            ents, indptr = z["ents"], z["indptr"]
            cols, vals = z["cols"], z["vals"]
        self.re_coefs = {
            int(e): (cols[int(indptr[i]):int(indptr[i + 1])].copy(),
                     vals[int(indptr[i]):int(indptr[i + 1])].copy())
            for i, e in enumerate(ents)
        }

    def _resume(self) -> int:
        """After a shrink (or on rejoin): reload the last committed state —
        a partially-executed step may have mutated replicated state, and
        redoing it MUST start from exactly the committed inputs."""
        n = self._latest_commit()
        self._load_commit(n)
        self._cache_epoch = -1  # assignment changed: reload parts, re-shuffle
        return n + 1

    # -- step boundary -----------------------------------------------------

    def _boundary(self, n: int) -> None:
        """Synchronize (epoch, membership) before step ``n``: the
        coordinator admits rejoiners and announces the step's epoch in a
        single-writer marker; everyone else adopts it. The marker breaks
        the race between a grow row landing and a peer reading the ledger
        a poll earlier — a host never waits in the wrong epoch's barrier."""
        mem = self.mem
        if mem.is_coordinator:
            mem.maybe_grow()
        changed = mem.sync_epoch()
        marker = os.path.join(mem.mesh_dir, "boundary", f"step-{n}.json")
        if mem.is_coordinator:
            os.makedirs(os.path.dirname(marker), exist_ok=True)
            tmp = f"{marker}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"epoch": mem.epoch, "members": mem.members}, f)
            os.replace(tmp, marker)
        else:
            deadline = time.monotonic() + mem.wait_timeout
            while True:
                try:
                    with open(marker) as f:
                        ep = int(json.load(f).get("epoch", -1))
                except (OSError, ValueError):
                    ep = -1
                if ep >= mem.epoch and ep >= 0:
                    if ep > mem.epoch:
                        changed = mem.sync_epoch() or changed
                    break
                mem._check_members(f"boundary marker step {n}")
                if time.monotonic() > deadline:
                    raise HostLostError([mem.coordinator],
                                        f"no boundary marker for step {n}")
                time.sleep(mem.poll_seconds)
        if changed:
            self._cache_epoch = -1
        self._ensure_epoch_caches()
        from photon_tpu.obs.metrics import REGISTRY

        REGISTRY.gauge(
            "mesh_epoch", "Current elastic mesh epoch on this host",
        ).set(float(mem.epoch))
        mem.barrier(f"step-{n}")

    # -- run ---------------------------------------------------------------

    def run(self) -> dict:
        from photon_tpu.obs import retrace

        total = 2 * self.cfg.sweeps
        n = self._resume() if self.mem.rejoined else 0
        if n == 0:
            self._ensure_epoch_caches()
        while n < total:
            t0 = time.perf_counter()
            try:
                self._boundary(n)
                if self.cfg.min_step_seconds:
                    time.sleep(self.cfg.min_step_seconds)
                if n % 2 == 0:
                    self._fixed_step(n)
                else:
                    self._re_step(n)
                self._commit(n)
            except HostLostError as e:
                self.mem.log.warning("host loss during step %d: %s", n, e)
                self.mem.handle_loss(e.dead)
                n = self._resume()
                continue
            self.step_seconds.append(time.perf_counter() - t0)
            if n == 1 and not self._warm_marked and not self.mem.rejoined:
                # First sweep compiled the whole ladder; any compile a
                # survivor pays after this is a real elasticity bug.
                retrace.mark_warm(FIXED_KERNEL)
                for k in retrace.RE_SOLVER_KERNELS:
                    retrace.mark_warm(k)
                self._warm_marked = True
            n += 1
        return self._finalize(total)

    def _finalize(self, total: int) -> dict:
        from photon_tpu.game.solver_routing import process_table
        from photon_tpu.obs import fleet

        mem = self.mem
        table = process_table()
        if table.to_json()["entries"]:
            table.save(os.path.join(
                mem.mesh_dir, f"solver_costs.host-{mem.host_id}.json"))
        mem.hb.export_peer_gauges()
        retr = _retrace_count()
        fleet.write_registry_shard(
            os.path.join(mem.mesh_dir,
                         f"registry.mesh-host-{mem.host_id}.json"),
            role="mesh-host",
            extra={"host_id": mem.host_id, "mesh_epoch": mem.epoch},
        )
        mem.barrier("done")
        summary = {
            "steps": total,
            "epoch": mem.epoch,
            "members": mem.members,
            "shrinks": mem.shrinks,
            "rejoined": mem.rejoined,
            "host_id": mem.host_id,
            "retraces_after_warmup": retr,
            "step_seconds_mean": (float(np.mean(self.step_seconds))
                                  if self.step_seconds else None),
        }
        if mem.is_coordinator:
            merged = merge_mesh_cost_tables(mem.mesh_dir)
            summary["merged_cost_table"] = merged
            path = os.path.join(mem.mesh_dir, "final-model.npz")
            tmp = f"{path}.tmp{os.getpid()}"
            ents = sorted(self.re_coefs)
            with open(tmp, "wb") as f:
                np.savez(f, w=self.w, re_scores=self.re_scores,
                         ents=np.asarray(ents, np.int64))
            os.replace(tmp, path)
            fpath = os.path.join(mem.mesh_dir, "final.json")
            tmpj = f"{fpath}.tmp{os.getpid()}"
            with open(tmpj, "w") as f:
                json.dump(summary, f, indent=1)
            os.replace(tmpj, fpath)
        return summary


def _retrace_count() -> int:
    from photon_tpu.obs import retrace

    kernels = (FIXED_KERNEL,) + tuple(retrace.RE_SOLVER_KERNELS)
    return sum(retrace.retraces_after_warmup(k) for k in kernels)


def merge_mesh_cost_tables(mesh_dir: str) -> Optional[str]:
    """Coordinator: fold every ``solver_costs.host-*.json`` into ONE
    ``solver_costs.merged.json`` (``SolverCostTable.merge`` — mean where
    two hosts measured the same candidate). A warm restart of ANY host
    then points ``PHOTON_RE_COST_TABLE`` at the merged file and skips
    calibration; the ``@devN`` suffix in the shape keys keeps tables from
    a different local-mesh topology inert (the existing refusal
    contract)."""
    from photon_tpu.game.solver_routing import merge_host_tables

    paths = sorted(glob.glob(os.path.join(mesh_dir,
                                          "solver_costs.host-*.json")))
    if not paths:
        return None
    out = os.path.join(mesh_dir, "solver_costs.merged.json")
    merge_host_tables(paths, out)
    return out


# ---------------------------------------------------------------------------
# Worker entry (python -m photon_tpu.parallel.elastic)
# ---------------------------------------------------------------------------


def worker_main(argv: Optional[Sequence[str]] = None) -> int:
    """One elastic host process. Sets the backend env BEFORE importing jax
    (forced host devices need XLA_FLAGS at import time), joins the mesh,
    trains, and prints the summary JSON on the last line of stdout."""
    import argparse

    p = argparse.ArgumentParser(prog="python -m photon_tpu.parallel.elastic")
    p.add_argument("--mesh-dir", required=True)
    p.add_argument("--host-id", type=int, required=True)
    p.add_argument("--hosts", type=int, required=True)
    p.add_argument("--manifest", required=True)
    p.add_argument("--sweeps", type=int, default=2)
    p.add_argument("--local-devices", type=int, default=1)
    p.add_argument("--fixed-l2", type=float, default=1e-3)
    p.add_argument("--re-l2", type=float, default=1.0)
    p.add_argument("--max-iterations", type=int, default=40)
    p.add_argument("--min-step-seconds", type=float, default=0.0)
    p.add_argument("--beat-seconds", type=float, default=0.4)
    # Staleness window = beat * factor. On an oversubscribed box (CI: N
    # python processes timesharing one core) the beat thread can starve
    # for whole seconds, so drills pass a LARGE factor — a false host_lost
    # is self-healing but splits the ledger's story.
    p.add_argument("--stale-factor", type=float, default=3.0)
    p.add_argument("--wait-timeout", type=float, default=120.0)
    args = p.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.local_devices > 1 and ("xla_force_host_platform_device_count"
                                   not in os.environ.get("XLA_FLAGS", "")):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.local_devices}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from photon_tpu.game.solver_routing import TABLE_ENV

    os.environ.setdefault(TABLE_ENV, os.path.join(
        args.mesh_dir, f"solver_costs.host-{args.host_id}.json"))

    manifest = load_manifest(args.manifest)
    mem = MeshMembership(
        args.mesh_dir, args.host_id, args.hosts,
        [q["id"] for q in manifest["parts"]],
        beat_seconds=args.beat_seconds, stale_factor=args.stale_factor,
        wait_timeout=args.wait_timeout,
    )
    local_mesh = None
    if args.local_devices > 1:
        from photon_tpu.parallel.mesh import make_mesh

        local_mesh = make_mesh({"data": args.local_devices})
    trainer = ElasticTrainer(
        mem.start(), manifest,
        ElasticConfig(sweeps=args.sweeps, fixed_l2=args.fixed_l2,
                      re_l2=args.re_l2,
                      max_iterations=args.max_iterations,
                      min_step_seconds=args.min_step_seconds),
        local_mesh=local_mesh,
    )
    try:
        summary = trainer.run()
    finally:
        mem.stop()
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(worker_main())
