"""Explicit-collective (shard_map + psum) fixed-effect objective: data-
parallel value/gradient AND Hessian-vector products over a device mesh.

Parity: reference ⟦DistributedGLMLossFunction⟧ + the three aggregators
⟦ValueAndGradientAggregator / HessianVectorAggregator⟧ (SURVEY.md §2.1/
§2.2) — every L-BFGS/TRON iteration of upstream photon-ml broadcasts the
coefficients and ``treeAggregate``s partition-wise partials back to the
driver. Here the batch lives row-sharded over the mesh, each device
computes its shard's partial (value, grad) or H·v contribution, and ONE
``lax.psum`` per evaluation is the treeAggregate analogue — riding ICI
inside the jitted optimizer loop instead of a cluster shuffle per job.

Relationship to ``parallel/data_parallel.fit_data_parallel`` (GSPMD): that
path hands XLA the whole ``problem.run`` with sharded inputs and lets the
partitioner insert the all-reduces. This module is the EXPLICIT spec of
the same program — shard_map bodies with hand-placed psums — consumed by
all three in-core optimizers (L-BFGS via ``vg``, OWL-QN via ``vg`` +
orthant machinery, TRON via the hoisted ``hvp_at``) and by the out-of-core
solvers (``optim/out_of_core._kernels_for_spmd`` builds its streamed
per-chunk kernels from the same shard_map pattern). Use it when collective
placement must be controlled (multi-slice DCN meshes: pass
``data_axis=("dcn", "data")`` and the psum lowers hierarchically) or when
the program must be auditable; both paths agree to ≤1e-12 at f64
(tests/test_mesh_invariance.py).
"""
from __future__ import annotations

import dataclasses
import functools
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from photon_tpu.functions.objective import GLMObjective
from photon_tpu.parallel.mesh import (
    DATA_AXIS,
    axis_tuple,
    pad_and_shard_batch,
    replicated,
    shard_map,
)

Array = jax.Array

__all__ = ["SpmdGLMObjective", "fit_spmd"]


def _batch_specs(batch, axes):
    return jax.tree.map(
        lambda leaf: P(axes, *([None] * (leaf.ndim - 1))), batch
    )


@dataclasses.dataclass(frozen=True)
class SpmdGLMObjective:
    """One GLM objective bound to a row-sharded batch on a mesh.

    ``value_and_grad`` / ``hvp_at`` have exactly the signatures the in-core
    optimizers consume (``optim.base.ValueAndGrad``; TRON's
    ``hvp_at(x) -> (v -> H·v)``), so LBFGS/OWLQN/TRON run unmodified over
    the sharded data — the psum is invisible to them, exactly as
    treeAggregate was invisible to Breeze upstream. The L2 term and any
    prior are applied ONCE globally (outside the psum), never per shard.

    Construction pads the row count to the axis-size multiple with
    weight-0 rows (invisible to the objective) and shards the batch;
    closures are pure and jit-safe, so the whole optimizer loop still
    compiles to one XLA program with the collectives inside.
    """

    obj: GLMObjective
    batch: object            # row-sharded LabeledBatch pytree
    mesh: object
    data_axis: object = DATA_AXIS

    @classmethod
    def build(cls, obj: GLMObjective, batch, mesh,
              data_axis=DATA_AXIS) -> "SpmdGLMObjective":
        batch = pad_and_shard_batch(batch, mesh, data_axis)
        return cls(obj=obj, batch=batch, mesh=mesh, data_axis=data_axis)

    # -- shard-local data objective (no L2/prior: those apply globally) ----

    @property
    def _data_obj(self) -> GLMObjective:
        return GLMObjective(loss=self.obj.loss, l2_weight=0.0,
                            reg_mask=None, prior=None)

    def _specs(self):
        axes = axis_tuple(self.data_axis)
        return axes, _batch_specs(self.batch, axes)

    # -- ValueAndGrad ------------------------------------------------------

    @functools.cached_property
    def _vg_sharded(self):
        """The shard_map'd (value, grad) kernel, built ONCE per instance:
        jax's dispatch cache keys on function identity, so an eager
        consumer calling ``value_and_grad`` in an optimizer loop must hit
        the same closure every iteration or it re-traces (and re-compiles
        the collective program) per call. cached_property writes through
        the instance ``__dict__``, which the frozen dataclass permits."""
        axes, bspecs = self._specs()
        data_obj = self._data_obj

        @partial(shard_map, mesh=self.mesh,
                 in_specs=(P(), bspecs), out_specs=(P(), P()))
        def _vg(wv, local):
            v, g = data_obj.value_and_grad(wv, local)
            return lax.psum(v, axes), lax.psum(g, axes)

        return _vg

    def value_and_grad(self, w: Array) -> tuple[Array, Array]:
        v, g = self._vg_sharded(w, self.batch)
        lam = self.obj._l2_vec(w)
        v = v + 0.5 * jnp.sum(lam * w * w)
        g = g + lam * w
        if self.obj.prior is not None:
            v = v + self.obj.prior.value(w)
            g = g + self.obj.prior.gradient(w)
        return v, g

    def bind(self):
        """``w ↦ (value, grad)`` for ``Optimizer.optimize``."""
        return self.value_and_grad

    # -- Hessian-vector products ------------------------------------------

    @functools.cached_property
    def _hvp_sharded(self):
        """``(_d2, _hv)`` shard_map kernels, built once per instance (see
        ``_vg_sharded`` for why closure identity must be stable)."""
        axes, bspecs = self._specs()
        loss = self.obj.loss

        @partial(shard_map, mesh=self.mesh,
                 in_specs=(P(), bspecs), out_specs=P(axes))
        def _d2(wv, local):
            z = local.features.matvec(wv) + local.offsets
            return local.weights * loss.d2(z, local.labels)

        @partial(shard_map, mesh=self.mesh,
                 in_specs=(P(), P(axes), bspecs), out_specs=P())
        def _hv(v, d2_local, local):
            hv = local.features.rmatvec(d2_local * local.features.matvec(v))
            return lax.psum(hv, axes)

        return _d2, _hv

    def hvp_at(self, w: Array):
        """``w ↦ (v ↦ H(w)·v)`` with the margins z and loss curvature d2
        computed ONCE per outer TRON iteration — the same explicit hoist as
        ``GLMObjective.bind_hvp_at``, so each CG-loop H·v costs exactly two
        sharded data passes (Xv matvec + rmatvec) and one psum."""
        _d2, _hv = self._hvp_sharded
        d2 = _d2(w, self.batch)  # row-sharded, stays on-shard for every H·v

        def hv(v: Array) -> Array:
            out = _hv(v, d2, self.batch) + self.obj._l2_vec(v) * v
            if self.obj.prior is not None:
                out = out + self.obj.prior.hessian_vector(v)
            return out

        return hv

    def hessian_vector(self, w: Array, v: Array) -> Array:
        """One-shot H(w)·v (3 sharded passes); prefer ``hvp_at`` in loops."""
        return self.hvp_at(w)(v)


def fit_spmd(problem, batch, w0, mesh, data_axis=DATA_AXIS,
             reg_mask=None):
    """Full fixed-effect solve through the explicit-collective objective.

    Mirrors ``GLMOptimizationProblem.run``'s optimizer routing (L-BFGS /
    OWL-QN / TRON — the same L1-pairing guard), with the batch row-sharded
    and every value/grad/H·v evaluation reduced by one psum. Returns
    ``(GeneralizedLinearModel, OptimizerResult)``, both replicated.

    Scope: the explicit path covers the smooth/L1 optimizer surface;
    normalization contexts and variance computation stay on the GSPMD path
    (``fit_data_parallel``), which supports them already — this function
    raises on either so a silent semantics gap is impossible.
    """
    from photon_tpu.functions.problem import VarianceComputationType
    from photon_tpu.optim import OptimizerType

    if problem.variance_type != VarianceComputationType.NONE:
        raise NotImplementedError(
            "fit_spmd computes no variances; use fit_data_parallel")

    mask = reg_mask if reg_mask is not None else problem.reg_mask
    key = dataclasses.replace(problem, reg_mask=None, prior=None)
    rep = replicated(mesh)
    w0 = jax.device_put(jnp.asarray(w0), rep)
    sharded = pad_and_shard_batch(batch, mesh, data_axis)
    axes = tuple(axis_tuple(data_axis))

    l1 = problem.regularization.l1_weight(float(problem.reg_weight))
    if problem.optimizer_type != OptimizerType.OWLQN and l1 > 0.0:
        raise ValueError(
            f"{problem.regularization.reg_type.name} regularization "
            f"requires OptimizerType.OWLQN, got "
            f"{problem.optimizer_type.name}")

    result = _fit_spmd_jitted(key, mesh, axes, sharded, w0, mask,
                              problem.prior)

    from photon_tpu.models.coefficients import Coefficients
    from photon_tpu.models.glm import GeneralizedLinearModel

    model = GeneralizedLinearModel(
        Coefficients(means=result.x, variances=None), problem.task)
    return model, result


@partial(jax.jit, static_argnums=(0, 1, 2))
def _fit_spmd_jitted(pkey, mesh, axes, sharded_batch, wv, maskv, priorv):
    """One XLA program: the whole optimizer loop with psum collectives
    inside. Static key = (problem-sans-arrays, mesh, axes), so every
    coordinate-descent step over the same config reuses one executable."""
    from photon_tpu.ops.losses import loss_for_task
    from photon_tpu.optim import OptimizerType
    from photon_tpu.optim.lbfgs import LBFGS
    from photon_tpu.optim.owlqn import OWLQN
    from photon_tpu.optim.tron import TRON

    obj = GLMObjective(
        loss=loss_for_task(pkey.task),
        l2_weight=pkey.regularization.l2_weight(float(pkey.reg_weight)),
        reg_mask=maskv, prior=priorv)
    so = SpmdGLMObjective(obj=obj, batch=sharded_batch, mesh=mesh,
                          data_axis=axes)
    vg = so.bind()
    if pkey.optimizer_type == OptimizerType.LBFGS:
        result = LBFGS(pkey.optimizer_config).optimize(vg, wv)
    elif pkey.optimizer_type == OptimizerType.OWLQN:
        l1 = pkey.regularization.l1_weight(float(pkey.reg_weight))
        m = maskv if maskv is not None else jnp.ones_like(wv)
        result = OWLQN(pkey.optimizer_config).optimize(vg, wv, l1 * m)
    elif pkey.optimizer_type == OptimizerType.TRON:
        result = TRON(pkey.optimizer_config).optimize(vg, wv, so.hvp_at)
    else:  # pragma: no cover - enum is closed
        raise ValueError(f"unknown optimizer {pkey.optimizer_type}")
    rep = replicated(mesh)
    return jax.tree.map(
        lambda a: jax.lax.with_sharding_constraint(a, rep), result)
