"""Device-mesh construction and sharding helpers.

This is the rebuild's replacement for the reference's Spark runtime substrate
(SURVEY.md §1 layer R / §5.8): instead of executors + treeAggregate +
TorrentBroadcast, a `jax.sharding.Mesh` with named axes and XLA collectives
over ICI/DCN.

Axis conventions (SURVEY.md §2.6):
  * ``data``    — batch rows (P1 data parallelism; gradient psum),
  * ``entity``  — random-effect entities (P2/P6 expert-style sharding),
  * ``feature`` — coefficient dimension (P3 sharded optimizer state).

A mesh may use any subset; a multi-slice deployment adds an outer DCN axis by
listing it first (slowest-varying) so collectives ride ICI within a slice.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
ENTITY_AXIS = "entity"
FEATURE_AXIS = "feature"
DCN_AXIS = "dcn"

try:  # jax >= 0.6 exports shard_map at top level (check_vma kwarg)
    from jax import shard_map
except ImportError:  # older jax: experimental home + the pre-rename kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_compat

    def shard_map(f, **kw):
        if "check_vma" in kw:  # renamed from check_rep in newer jax
            kw["check_rep"] = kw.pop("check_vma")
        return _shard_map_compat(f, **kw)

# An axis argument throughout parallel/ may be one mesh axis name or a tuple
# of names (e.g. ("dcn", "data") — rows sharded over slices x chips, with
# psum lowering hierarchically: ICI within a slice, DCN across slices).
AxisSpec = "str | tuple[str, ...]"


def axis_tuple(axis) -> tuple:
    return (axis,) if isinstance(axis, str) else tuple(axis)


def axes_size(mesh: Mesh, axis) -> int:
    return int(np.prod([mesh.shape[a] for a in axis_tuple(axis)]))


def make_mesh(
    axis_sizes: dict[str, int] | None = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a mesh from {axis: size}. Default: all devices on ``data``."""
    devices = list(devices if devices is not None else jax.devices())
    if not axis_sizes:
        axis_sizes = {DATA_AXIS: len(devices)}
    names = tuple(axis_sizes)
    sizes = tuple(axis_sizes.values())
    n = int(np.prod(sizes))
    if n != len(devices):
        raise ValueError(
            f"mesh wants {n} devices ({axis_sizes}) but {len(devices)} available"
        )
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, names)


def make_multislice_mesh(
    n_slices: int,
    axis_sizes: dict[str, int] | None = None,
    devices: Optional[Sequence[jax.Device]] = None,
    dcn_axis: str = DCN_AXIS,
) -> Mesh:
    """2-level mesh: an outer ``dcn`` axis over slices (slowest-varying) and
    the given ICI axes within each slice — the multi-slice deployment shape
    (SURVEY.md §5.8: hierarchical psum replaces treeAggregate; ICI within a
    slice, DCN across).

    On real multi-slice TPU topologies the device order comes from
    ``mesh_utils.create_hybrid_device_mesh`` so that the outer axis truly
    crosses slice boundaries (minimizing DCN traffic for inner-axis
    collectives); on single-slice or host-simulated devices it falls back to
    a plain reshape, which exercises identical program structure.
    """
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) % n_slices:
        raise ValueError(f"{len(devices)} devices not divisible by {n_slices} slices")
    per_slice = len(devices) // n_slices
    if not axis_sizes:
        axis_sizes = {DATA_AXIS: per_slice}
    inner = tuple(axis_sizes.values())
    if int(np.prod(inner)) != per_slice:
        raise ValueError(
            f"inner axes {axis_sizes} want {int(np.prod(inner))} devices/slice, "
            f"have {per_slice}"
        )
    names = (dcn_axis,) + tuple(axis_sizes)
    slice_ids = {getattr(d, "slice_index", 0) for d in devices}
    if len(slice_ids) > 1 and len(slice_ids) != n_slices:
        # On real multi-slice hardware a mismatched dcn size would silently
        # put inner-axis collectives on DCN links — exactly the pathology a
        # 2-level mesh exists to prevent. Refuse instead.
        raise ValueError(
            f"devices span {len(slice_ids)} slices but n_slices={n_slices}; "
            "the dcn axis must match the physical slice count"
        )
    if n_slices > 1 and len(slice_ids) == n_slices:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(1,) + inner,
            dcn_mesh_shape=(n_slices,) + (1,) * len(inner),
            devices=devices,
        )
    else:
        dev_array = np.asarray(devices).reshape((n_slices,) + inner)
    return Mesh(dev_array, names)


def batch_sharding(mesh: Mesh, axis=DATA_AXIS) -> NamedSharding:
    """Shard the leading (row) dimension over ``axis``; replicate the rest
    (PartitionSpec leaves unmentioned trailing dims unsharded, for any rank).

    The one spec used by every batch-distribution path (device_put here,
    ``make_array_from_process_local_data`` in parallel/distributed.py), so
    shardings from either compare equal."""
    return NamedSharding(mesh, P(axis_tuple(axis)))

def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch_pytree(batch, mesh: Mesh, axis=DATA_AXIS):
    """Device-put every array leaf of a batch pytree row-sharded over ``axis``
    (one name or a tuple, e.g. ``("dcn", "data")``).

    All leaves of a LabeledBatch share the same leading row count, so one
    spec applies uniformly (ELL idx/val are [N, K]; labels/offsets/weights
    are [N]).
    """
    sharding = batch_sharding(mesh, axis)
    return jax.tree.map(lambda leaf: jax.device_put(leaf, sharding), batch)


def strip_unshardable_aux(batch_or_features):
    """Drop fast/Pallas aux tables before row distribution — their
    column-sorted layouts are NOT partitionable along the row axis and
    sharding them would corrupt results. Accepts a LabeledBatch or a bare
    features container; the one definition every distribution path uses."""
    import dataclasses

    obj = batch_or_features
    feats = getattr(obj, "features", None)
    if feats is not None:
        if getattr(feats, "fast", None) is not None or \
                getattr(feats, "pallas", None) is not None:
            return dataclasses.replace(obj, features=feats.without_fast_path())
        return obj
    if getattr(obj, "fast", None) is not None or \
            getattr(obj, "pallas", None) is not None:
        return obj.without_fast_path()
    return obj


def pad_and_shard_batch(batch, mesh: Mesh, axis=DATA_AXIS):
    """The canonical row-distribution preamble: strip the non-row-shardable
    aux tables (``strip_unshardable_aux``), pad rows to the axis-size
    multiple (weight-0 / zero-feature padding), and device_put row-sharded.
    Accepts a LabeledBatch or a bare features container — shared by
    training (``fit_data_parallel``) and scoring (``GameTransformer``)."""
    axis_size = axes_size(mesh, axis)
    batch = strip_unshardable_aux(batch)
    if batch.n_rows % axis_size:
        batch = pad_rows_to_multiple(batch, axis_size)
    return shard_batch_pytree(batch, mesh, axis)


def pad_rows_to_multiple(arrs_n_leading, multiple: int):
    """Host-side: pad row count to a multiple (for even sharding), returning
    the padded pytree. Padding is zero-fill — for a LabeledBatch the padded
    rows carry weight 0 and are invisible to objectives/evaluators, no
    further masking required — except ELL sparse index arrays, whose padded
    rows point at the ghost column ``dim`` to keep the SparseFeatures
    sentinel invariant ("id == D marks padding")."""
    import numpy as _np

    def pad(a, fill=0):
        n = a.shape[0]
        r = (-n) % multiple
        if r == 0:
            return a
        pad_width = [(0, r)] + [(0, 0)] * (a.ndim - 1)
        return _np.pad(_np.asarray(a), pad_width, constant_values=fill)

    from photon_tpu.data.batch import (
        DenseFeatures,
        LabeledBatch,
        SparseFeatures,
    )

    # Bare feature containers: arrays ALREADY on device pad device-side
    # (no host round-trip of [N, K] arrays to append a few zero rows);
    # host-numpy arrays pad host-side so the subsequent
    # device_put(NamedSharding) still streams shards directly to their
    # devices without ever materializing the whole array on one.
    def _pad2(a, fill):
        r = (-a.shape[0]) % multiple
        if isinstance(a, jax.Array):
            ext = (jax.numpy.full((r, a.shape[1]), fill, a.dtype)
                   if fill else jax.numpy.zeros((r, a.shape[1]), a.dtype))
            return jax.numpy.concatenate([a, ext])
        return pad(a, fill)

    if isinstance(arrs_n_leading, SparseFeatures):
        sf = arrs_n_leading
        if (-sf.n_rows) % multiple == 0:
            return sf
        return SparseFeatures(
            idx=_pad2(sf.idx, sf.dim), val=_pad2(sf.val, 0), dim=sf.dim
        )
    if isinstance(arrs_n_leading, DenseFeatures):
        if (-arrs_n_leading.x.shape[0]) % multiple == 0:
            return arrs_n_leading
        return DenseFeatures(_pad2(arrs_n_leading.x, 0))

    if isinstance(arrs_n_leading, LabeledBatch) and isinstance(
        arrs_n_leading.features, SparseFeatures
    ):
        # Stays HOST numpy on purpose: the caller's device_put(NamedSharding)
        # then streams shards directly to their devices; wrapping in
        # jnp.asarray here would first materialize the whole padded batch on
        # the default device.
        batch = arrs_n_leading
        sf = batch.features
        return LabeledBatch(
            features=SparseFeatures(
                idx=pad(sf.idx, fill=sf.dim),
                val=pad(sf.val),
                dim=sf.dim,
            ),
            labels=pad(batch.labels),
            offsets=pad(batch.offsets),
            weights=pad(batch.weights),
        )
    return jax.tree.map(pad, arrs_n_leading)
