"""Device-mesh construction and sharding helpers.

This is the rebuild's replacement for the reference's Spark runtime substrate
(SURVEY.md §1 layer R / §5.8): instead of executors + treeAggregate +
TorrentBroadcast, a `jax.sharding.Mesh` with named axes and XLA collectives
over ICI/DCN.

Axis conventions (SURVEY.md §2.6):
  * ``data``    — batch rows (P1 data parallelism; gradient psum),
  * ``entity``  — random-effect entities (P2/P6 expert-style sharding),
  * ``feature`` — coefficient dimension (P3 sharded optimizer state).

A mesh may use any subset; a multi-slice deployment adds an outer DCN axis by
listing it first (slowest-varying) so collectives ride ICI within a slice.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
ENTITY_AXIS = "entity"
FEATURE_AXIS = "feature"


def make_mesh(
    axis_sizes: dict[str, int] | None = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a mesh from {axis: size}. Default: all devices on ``data``."""
    devices = list(devices if devices is not None else jax.devices())
    if not axis_sizes:
        axis_sizes = {DATA_AXIS: len(devices)}
    names = tuple(axis_sizes)
    sizes = tuple(axis_sizes.values())
    n = int(np.prod(sizes))
    if n != len(devices):
        raise ValueError(
            f"mesh wants {n} devices ({axis_sizes}) but {len(devices)} available"
        )
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, names)


def batch_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (row) dimension over ``axis``; replicate the rest."""
    return NamedSharding(mesh, P(axis))

def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch_pytree(batch, mesh: Mesh, axis: str = DATA_AXIS):
    """Device-put every array leaf of a batch pytree row-sharded over ``axis``.

    All leaves of a LabeledBatch share the same leading row count, so one
    spec applies uniformly (ELL idx/val are [N, K]; labels/offsets/weights
    are [N]).
    """

    def put(leaf):
        spec = P(axis, *([None] * (leaf.ndim - 1)))
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree.map(put, batch)


def pad_rows_to_multiple(arrs_n_leading, multiple: int):
    """Host-side: pad row count to a multiple (for even sharding), returning
    the padded pytree. Padding is zero-fill — for a LabeledBatch the padded
    rows carry weight 0 and are invisible to objectives/evaluators, no
    further masking required — except ELL sparse index arrays, whose padded
    rows point at the ghost column ``dim`` to keep the SparseFeatures
    sentinel invariant ("id == D marks padding")."""
    import numpy as _np

    def pad(a, fill=0):
        n = a.shape[0]
        r = (-n) % multiple
        if r == 0:
            return a
        pad_width = [(0, r)] + [(0, 0)] * (a.ndim - 1)
        return _np.pad(_np.asarray(a), pad_width, constant_values=fill)

    from photon_tpu.data.batch import LabeledBatch, SparseFeatures

    if isinstance(arrs_n_leading, LabeledBatch) and isinstance(
        arrs_n_leading.features, SparseFeatures
    ):
        batch = arrs_n_leading
        sf = batch.features
        return LabeledBatch(
            features=SparseFeatures(
                idx=jax.numpy.asarray(pad(sf.idx, fill=sf.dim)),
                val=jax.numpy.asarray(pad(sf.val)),
                dim=sf.dim,
            ),
            labels=jax.numpy.asarray(pad(batch.labels)),
            offsets=jax.numpy.asarray(pad(batch.offsets)),
            weights=jax.numpy.asarray(pad(batch.weights)),
        )
    return jax.tree.map(pad, arrs_n_leading)
