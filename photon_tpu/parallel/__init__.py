"""Parallelism: meshes, data-parallel fitting, collectives."""
from photon_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    ENTITY_AXIS,
    FEATURE_AXIS,
    batch_sharding,
    make_mesh,
    replicated,
    shard_batch_pytree,
)
from photon_tpu.parallel.data_parallel import (  # noqa: F401
    fit_data_parallel,
    spmd_value_and_grad,
)
from photon_tpu.parallel.spmd_objective import (  # noqa: F401
    SpmdGLMObjective,
    fit_spmd,
)
from photon_tpu.parallel.distributed import (  # noqa: F401
    global_batch_from_local,
    initialize_distributed,
    process_file_shard,
)
