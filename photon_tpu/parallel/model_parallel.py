"""Feature-dimension-sharded training: 2D (data × model) mesh L-BFGS.

Parity/North-star: SURVEY.md §2.6 P3 — the reference broadcasts the whole
coefficient vector every iteration and holds it on the driver; at 10M
features that is the scalability wall. Here the coefficient vector, gradient,
and the L-BFGS S/Y history live SHARDED over the ``model`` mesh axis while
batch rows shard over the ``data`` axis:

* margins: each model shard computes the partial zᵢ from its own feature
  columns; one ``psum`` over the model axis completes z (communication is
  O(rows_per_device), NOT O(D) — no all-gather of coefficients, ever);
* loss/value: summed over the data axis with a second ``psum``;
* gradient: each model shard scatter-accumulates only its own columns, then
  psums over the data axis — gradient shards never leave their device;
* two-loop recursion: every coefficient-space inner product is a local dot +
  scalar ``psum`` over the model axis (``LBFGS(axis_name=...)``).

The whole multi-iteration solve is ONE ``shard_map``-ped XLA program on the
mesh — zero host round trips, optimizer state O(D / n_model_shards) per
device.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from photon_tpu.parallel.mesh import shard_map  # version-compat wrapper

from photon_tpu.data.batch import DenseFeatures, LabeledBatch, SparseFeatures
from photon_tpu.functions.problem import GLMOptimizationProblem
from photon_tpu.models.coefficients import Coefficients
from photon_tpu.models.glm import GeneralizedLinearModel
from photon_tpu.optim import LBFGS, OWLQN, TRON, OptimizerType
from photon_tpu.ops.losses import loss_for_task
from photon_tpu.parallel.mesh import axes_size, axis_tuple, pad_rows_to_multiple

Array = jax.Array

DATA_AXIS = "data"
MODEL_AXIS = "model"


def _pad_dim_sparse(feats: SparseFeatures, new_dim: int) -> SparseFeatures:
    # Ghost column moves from dim to new_dim; remap ghost entries.
    idx = jnp.where(feats.idx >= feats.dim, new_dim, feats.idx)
    return SparseFeatures(idx=idx, val=feats.val, dim=new_dim)


def fit_model_parallel(
    problem: GLMOptimizationProblem,
    batch: LabeledBatch,
    w0: Array,
    mesh,
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
    normalization=None,
):
    """Full solve with coefficients sharded over ``model_axis`` and rows over
    ``data_axis`` (one axis or a tuple — e.g. ``("dcn", "data")``). Returns
    (GeneralizedLinearModel, OptimizerResult) with full-length
    (host-assembled) coefficients.

    Supports L-BFGS, OWL-QN, and TRON (orthant/CG vector ops are elementwise
    → shard-local; inner products psum over the model axis, and TRON's
    Hessian-vector product composes the same margins-psum + shard-local
    transpose as the gradient), NONE/SIMPLE variances (SIMPLE's Hessian
    diagonal is computed per feature shard), and normalization contexts (the
    coefficient-space map's shift correction is one scalar psum over the
    model axis; SURVEY.md §7 hard-part #5). FULL variance uses the
    data-parallel path: a D×D inverse doesn't fit the sharded-state design.
    """
    # Guards a future OptimizerType addition from silently training with the
    # wrong solver; every CURRENT member is supported.
    if problem.optimizer_type not in (
        OptimizerType.LBFGS, OptimizerType.OWLQN, OptimizerType.TRON
    ):
        raise ValueError(
            "model-parallel training supports LBFGS, OWLQN, and TRON "
            f"(got {problem.optimizer_type.name})"
        )
    if problem.variance_type.name == "FULL":
        raise ValueError(
            "model-parallel training computes NONE/SIMPLE variances only "
            "(FULL materializes a DxD Hessian)"
        )
    if normalization is not None and normalization.is_identity:
        normalization = None
    if normalization is not None and problem.prior is not None:
        raise ValueError(
            "model-parallel training does not combine a normalization "
            "context with an incremental-training prior"
        )

    data_axes = axis_tuple(data_axis)
    n_data = axes_size(mesh, data_axes)
    n_model = mesh.shape[model_axis]
    d = batch.dim
    d_pad = -d % n_model
    d_full = d + d_pad

    if batch.n_rows % n_data:
        batch = pad_rows_to_multiple(batch, n_data)
    feats = batch.features
    if isinstance(feats, SparseFeatures):
        feats = _pad_dim_sparse(feats, d_full)
        feats_specs = SparseFeatures(
            idx=P(data_axes, None), val=P(data_axes, None), dim=feats.dim
        )
    elif isinstance(feats, DenseFeatures):
        if d_pad:
            feats = DenseFeatures(jnp.pad(feats.x, ((0, 0), (0, d_pad))))
        feats_specs = DenseFeatures(x=P(data_axes, model_axis))
    else:  # pragma: no cover - union is closed
        raise TypeError(f"unknown feature container {type(feats)}")
    batch = dataclasses.replace(batch, features=feats)

    w0 = jnp.pad(w0, (0, d_pad))
    lam_mask = problem.reg_mask
    if lam_mask is not None:
        lam_mask = jnp.pad(lam_mask.astype(w0.dtype), (0, d_pad))
    else:
        # padding columns must carry 0 penalty? They stay at 0 anyway (no
        # data touches them); keep 1 to preserve SPD behavior.
        lam_mask = jnp.pad(jnp.ones((d,), w0.dtype), (0, d_pad), constant_values=1.0)

    shard_d = d_full // n_model
    l2 = problem.regularization.l2_weight(problem.reg_weight)
    l1 = problem.regularization.l1_weight(problem.reg_weight)
    if l1 > 0.0 and problem.optimizer_type != OptimizerType.OWLQN:
        # Reference parity (same guard as GLMOptimizationProblem.run): L1 is
        # only handled by OWL-QN; silently training unregularized is worse.
        raise ValueError(
            f"{problem.regularization.reg_type.name} regularization requires "
            f"OptimizerType.OWLQN, got {problem.optimizer_type.name}"
        )
    loss = loss_for_task(problem.task)
    prior = problem.prior
    if prior is not None:
        prior = jax.tree.map(lambda a: jnp.pad(a, (0, d_pad)), prior)

    # Normalization arrays, sanitized (intercept slot forced to factor 1 /
    # shift 0) and padded to the sharded width. Padding columns get factor 1
    # so the map stays invertible there (they carry zero data and zero w).
    norm_f = norm_s = norm_onehot = None
    if normalization is not None:
        nf, ns = normalization._effective()
        if nf is not None:
            norm_f = jnp.pad(nf.astype(w0.dtype), (0, d_pad), constant_values=1.0)
        if ns is not None:
            norm_s = jnp.pad(ns.astype(w0.dtype), (0, d_pad))
            norm_onehot = (
                jnp.zeros((d_full,), w0.dtype)
                .at[normalization.intercept_index]
                .set(1.0)
            )

    row_specs = P(data_axes)
    batch_specs = LabeledBatch(
        features=feats_specs, labels=row_specs, offsets=row_specs,
        weights=row_specs,
    )
    key = dataclasses.replace(problem, reg_mask=None, prior=None)

    from photon_tpu.optim.base import OptimizerResult

    res_specs = OptimizerResult(
        x=P(), value=P(), grad_norm=P(), iterations=P(),
        converged_reason=P(), values=P(), grad_norms=P(), data_passes=P(),
    )

    norm_arrays = (norm_f, norm_s, norm_onehot)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(model_axis),
            batch_specs,
            P(model_axis),
            jax.tree.map(lambda _: P(model_axis), prior),
            jax.tree.map(lambda _: P(model_axis), norm_arrays),
        ),
        out_specs=((P(model_axis), P(model_axis)), res_specs),
        check_vma=False,
    )
    def solve(w_shard, local_batch, lam_shard, prior_shard, norm_shards):
        lf = local_batch.features
        f_sh, s_sh, onehot_sh = norm_shards

        if isinstance(lf, SparseFeatures):
            lo = lax.axis_index(model_axis) * shard_d

            def margins(ws):
                li = lf.idx - lo
                own = (li >= 0) & (li < shard_d)
                li = jnp.where(own, li, shard_d)
                w_ext = jnp.concatenate([ws, jnp.zeros((1,), ws.dtype)])
                zp = jnp.sum(w_ext[li] * lf.val, axis=-1)
                return lax.psum(zp, model_axis)

            def grad_shard(dz):
                li = lf.idx - lo
                own = (li >= 0) & (li < shard_d)
                li = jnp.where(own, li, shard_d)
                contrib = lf.val * dz[:, None]
                g = jnp.zeros((shard_d + 1,), contrib.dtype)
                g = g.at[li.ravel()].add(contrib.ravel())
                return g[:shard_d]

            def sq_shard(dz):
                li = lf.idx - lo
                own = (li >= 0) & (li < shard_d)
                li = jnp.where(own, li, shard_d)
                contrib = lf.val * lf.val * dz[:, None]
                g = jnp.zeros((shard_d + 1,), contrib.dtype)
                g = g.at[li.ravel()].add(contrib.ravel())
                return g[:shard_d]
        else:

            def margins(ws):
                return lax.psum(lf.x @ ws, model_axis)

            def grad_shard(dz):
                return lf.x.T @ dz

            def sq_shard(dz):
                return (lf.x * lf.x).T @ dz

        # Coefficient-space maps for normalization (SURVEY.md §7 hard-part
        # #5): shard-local elementwise scaling; the shift correction and its
        # pullback each cost ONE scalar psum over the model axis.
        #   to_original:  w = (I − e·sᵀ)·F·w'      (e = intercept one-hot)
        #   pullback:     ∇w' = F·(∇w − s·(eᵀ∇w))
        def to_original(wp):
            out = wp if f_sh is None else wp * f_sh
            if s_sh is not None:
                corr = lax.psum(jnp.sum(out * s_sh), model_axis)
                out = out - onehot_sh * corr
            return out

        def pullback(g):
            if s_sh is not None:
                g_int = lax.psum(jnp.sum(onehot_sh * g), model_axis)
                g = g - s_sh * g_int
            if f_sh is None:
                return g
            return g * f_sh

        def to_transformed(w):
            if s_sh is not None:
                corr = lax.psum(jnp.sum(w * s_sh), model_axis)
                w = w + onehot_sh * corr
            return w if f_sh is None else w / f_sh

        use_norm = f_sh is not None or s_sh is not None

        def data_vg(w_orig):
            z = margins(w_orig) + local_batch.offsets
            lv = jnp.sum(local_batch.weights * loss.loss(z, local_batch.labels))
            lv = lax.psum(lv, data_axes)
            dz = local_batch.weights * loss.d1(z, local_batch.labels)
            g = lax.psum(grad_shard(dz), data_axes)
            return lv, g

        lam = l2 * lam_shard

        def vg(ws):
            # Data term at the original-space point; regularization on the
            # transformed-space coefficients (what the optimizer sees) —
            # reference semantics.
            lv, g = data_vg(to_original(ws) if use_norm else ws)
            if use_norm:
                g = pullback(g)
            # L2 value is a model-axis-sharded sum; data term already global.
            lv = lv + lax.psum(0.5 * jnp.sum(lam * ws * ws), model_axis)
            g = g + lam * ws
            if prior_shard is not None:
                lv = lv + lax.psum(prior_shard.value(ws), model_axis)
                g = g + prior_shard.gradient(ws)
            return lv, g

        w_start = to_transformed(w_shard) if use_norm else w_shard
        if key.optimizer_type == OptimizerType.OWLQN:
            result = OWLQN(key.optimizer_config, axis_name=model_axis).optimize(
                vg, w_start, l1 * lam_shard
            )
        elif key.optimizer_type == OptimizerType.TRON:
            # Sharded HVP: H'v = Jᵀ(Xᵀ D X)(Jv) + λv (+ prior precisions),
            # with J the (linear) normalization coefficient map. Margins and
            # curvature hoist per outer iterate, exactly like the
            # single-device GLMObjective.bind_hvp_at.
            def hvp_at(ws):
                w_orig = to_original(ws) if use_norm else ws
                z = margins(w_orig) + local_batch.offsets
                d2w = local_batch.weights * loss.d2(z, local_batch.labels)

                def hv(v):
                    v_orig = to_original(v) if use_norm else v
                    zv = margins(v_orig)
                    out = lax.psum(grad_shard(d2w * zv), data_axes)
                    if use_norm:
                        out = pullback(out)
                    out = out + lam * v
                    if prior_shard is not None:
                        out = out + prior_shard.hessian_vector(v)
                    return out

                return hv

            result = TRON(key.optimizer_config, axis_name=model_axis).optimize(
                vg, w_start, hvp_at
            )
        else:
            result = LBFGS(key.optimizer_config, axis_name=model_axis).optimize(
                vg, w_start
            )
        x_orig = to_original(result.x) if use_norm else result.x

        # SIMPLE variance (reference VarianceComputationType.SIMPLE): inverse
        # Hessian diagonal of the trained objective, per feature shard. Under
        # normalization the effective original-space penalty is λ/f².
        if key.variance_type.name == "SIMPLE":
            z = margins(x_orig) + local_batch.offsets
            d2 = local_batch.weights * loss.d2(z, local_batch.labels)
            diag = lax.psum(sq_shard(d2), data_axes)
            lam_eff = lam if f_sh is None else lam / (f_sh * f_sh)
            diag = diag + lam_eff
            if prior_shard is not None:
                diag = diag + prior_shard.hessian_diagonal()
            variances = 1.0 / jnp.maximum(diag, 1e-12)
        else:
            variances = jnp.zeros_like(x_orig)

        return (x_orig, variances), dataclasses.replace(
            result, x=jnp.zeros((0,), w_shard.dtype)
        )

    put_model = lambda a: (
        None if a is None
        else jax.device_put(a, NamedSharding(mesh, P(model_axis)))
    )
    (x_sharded, var_sharded), result = solve(
        put_model(w0),
        _shard_batch(batch, mesh, batch_specs),
        put_model(lam_mask),
        jax.tree.map(put_model, prior),
        jax.tree.map(put_model, norm_arrays),
    )
    x = jnp.asarray(x_sharded)[:d]
    result = dataclasses.replace(result, x=x)
    variances = (
        jnp.asarray(var_sharded)[:d]
        if problem.variance_type.name == "SIMPLE"
        else None
    )
    model = GeneralizedLinearModel(
        Coefficients(means=x, variances=variances), problem.task
    )
    return model, result


def _shard_batch(batch: LabeledBatch, mesh, specs) -> LabeledBatch:
    return jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        batch,
        specs,
    )
