"""Feature-dimension-sharded training: 2D (data × model) mesh L-BFGS.

Parity/North-star: SURVEY.md §2.6 P3 — the reference broadcasts the whole
coefficient vector every iteration and holds it on the driver; at 10M
features that is the scalability wall. Here the coefficient vector, gradient,
and the L-BFGS S/Y history live SHARDED over the ``model`` mesh axis while
batch rows shard over the ``data`` axis:

* margins: each model shard computes the partial zᵢ from its own feature
  columns; one ``psum`` over the model axis completes z (communication is
  O(rows_per_device), NOT O(D) — no all-gather of coefficients, ever);
* loss/value: summed over the data axis with a second ``psum``;
* gradient: each model shard scatter-accumulates only its own columns, then
  psums over the data axis — gradient shards never leave their device;
* two-loop recursion: every coefficient-space inner product is a local dot +
  scalar ``psum`` over the model axis (``LBFGS(axis_name=...)``).

The whole multi-iteration solve is ONE ``shard_map``-ped XLA program on the
mesh — zero host round trips, optimizer state O(D / n_model_shards) per
device.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

from photon_tpu.data.batch import DenseFeatures, LabeledBatch, SparseFeatures
from photon_tpu.functions.problem import GLMOptimizationProblem
from photon_tpu.models.coefficients import Coefficients
from photon_tpu.models.glm import GeneralizedLinearModel
from photon_tpu.optim import LBFGS, OptimizerType
from photon_tpu.ops.losses import loss_for_task
from photon_tpu.parallel.mesh import pad_rows_to_multiple

Array = jax.Array

DATA_AXIS = "data"
MODEL_AXIS = "model"


def _pad_dim_sparse(feats: SparseFeatures, new_dim: int) -> SparseFeatures:
    # Ghost column moves from dim to new_dim; remap ghost entries.
    idx = jnp.where(feats.idx >= feats.dim, new_dim, feats.idx)
    return SparseFeatures(idx=idx, val=feats.val, dim=new_dim)


def fit_model_parallel(
    problem: GLMOptimizationProblem,
    batch: LabeledBatch,
    w0: Array,
    mesh,
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
):
    """Full L-BFGS solve with coefficients sharded over ``model_axis`` and
    rows over ``data_axis``. Returns (GeneralizedLinearModel, OptimizerResult)
    with full-length (host-assembled) coefficients.

    Supports LBFGS with NONE variance and no normalization (the P3
    scale path; other optimizers/options use the data-parallel path).
    """
    if problem.optimizer_type != OptimizerType.LBFGS:
        raise ValueError(
            "model-parallel training currently supports LBFGS only "
            f"(got {problem.optimizer_type.name})"
        )
    if problem.variance_type.name != "NONE":
        raise ValueError("model-parallel training does not compute variances")
    if problem.regularization.l1_weight(problem.reg_weight) > 0.0:
        raise ValueError("model-parallel training supports smooth (L2) regularization only")

    n_data = mesh.shape[data_axis]
    n_model = mesh.shape[model_axis]
    d = batch.dim
    d_pad = -d % n_model
    d_full = d + d_pad

    if batch.n_rows % n_data:
        batch = pad_rows_to_multiple(batch, n_data)
    feats = batch.features
    if isinstance(feats, SparseFeatures):
        feats = _pad_dim_sparse(feats, d_full)
        feats_specs = SparseFeatures(
            idx=P(data_axis, None), val=P(data_axis, None), dim=feats.dim
        )
    elif isinstance(feats, DenseFeatures):
        if d_pad:
            feats = DenseFeatures(jnp.pad(feats.x, ((0, 0), (0, d_pad))))
        feats_specs = DenseFeatures(x=P(data_axis, model_axis))
    else:  # pragma: no cover - union is closed
        raise TypeError(f"unknown feature container {type(feats)}")
    batch = dataclasses.replace(batch, features=feats)

    w0 = jnp.pad(w0, (0, d_pad))
    lam_mask = problem.reg_mask
    if lam_mask is not None:
        lam_mask = jnp.pad(lam_mask.astype(w0.dtype), (0, d_pad))
    else:
        # padding columns must carry 0 penalty? They stay at 0 anyway (no
        # data touches them); keep 1 to preserve SPD behavior.
        lam_mask = jnp.pad(jnp.ones((d,), w0.dtype), (0, d_pad), constant_values=1.0)

    shard_d = d_full // n_model
    l2 = problem.regularization.l2_weight(problem.reg_weight)
    loss = loss_for_task(problem.task)
    prior = problem.prior
    if prior is not None:
        prior = jax.tree.map(lambda a: jnp.pad(a, (0, d_pad)), prior)

    row_specs = P(data_axis)
    batch_specs = LabeledBatch(
        features=feats_specs, labels=row_specs, offsets=row_specs,
        weights=row_specs,
    )
    key = dataclasses.replace(problem, reg_mask=None, prior=None)

    from photon_tpu.optim.base import OptimizerResult

    res_specs = OptimizerResult(
        x=P(), value=P(), grad_norm=P(), iterations=P(),
        converged_reason=P(), values=P(), grad_norms=P(), data_passes=P(),
    )

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(model_axis),
            batch_specs,
            P(model_axis),
            jax.tree.map(lambda _: P(model_axis), prior),
        ),
        out_specs=(P(model_axis), res_specs),
        check_vma=False,
    )
    def solve(w_shard, local_batch, lam_shard, prior_shard):
        lf = local_batch.features

        if isinstance(lf, SparseFeatures):
            lo = lax.axis_index(model_axis) * shard_d

            def margins(ws):
                li = lf.idx - lo
                own = (li >= 0) & (li < shard_d)
                li = jnp.where(own, li, shard_d)
                w_ext = jnp.concatenate([ws, jnp.zeros((1,), ws.dtype)])
                zp = jnp.sum(w_ext[li] * lf.val, axis=-1)
                return lax.psum(zp, model_axis)

            def grad_shard(dz):
                li = lf.idx - lo
                own = (li >= 0) & (li < shard_d)
                li = jnp.where(own, li, shard_d)
                contrib = lf.val * dz[:, None]
                g = jnp.zeros((shard_d + 1,), contrib.dtype)
                g = g.at[li.ravel()].add(contrib.ravel())
                return g[:shard_d]
        else:

            def margins(ws):
                return lax.psum(lf.x @ ws, model_axis)

            def grad_shard(dz):
                return lf.x.T @ dz

        def vg(ws):
            z = margins(ws) + local_batch.offsets
            lv = jnp.sum(local_batch.weights * loss.loss(z, local_batch.labels))
            lv = lax.psum(lv, data_axis)
            dz = local_batch.weights * loss.d1(z, local_batch.labels)
            g = lax.psum(grad_shard(dz), data_axis)
            lam = l2 * lam_shard
            # L2 value is a model-axis-sharded sum; data term already global.
            lv = lv + lax.psum(0.5 * jnp.sum(lam * ws * ws), model_axis)
            g = g + lam * ws
            if prior_shard is not None:
                lv = lv + lax.psum(prior_shard.value(ws), model_axis)
                g = g + prior_shard.gradient(ws)
            return lv, g

        result = LBFGS(key.optimizer_config, axis_name=model_axis).optimize(
            vg, w_shard
        )
        return result.x, dataclasses.replace(result, x=jnp.zeros((0,), w_shard.dtype))

    x_sharded, result = solve(
        jax.device_put(
            w0, NamedSharding(mesh, P(model_axis))
        ),
        _shard_batch(batch, mesh, batch_specs),
        jax.device_put(lam_mask, NamedSharding(mesh, P(model_axis))),
        jax.tree.map(
            lambda a: jax.device_put(a, NamedSharding(mesh, P(model_axis))), prior
        ),
    )
    x = jnp.asarray(x_sharded)[:d]
    result = dataclasses.replace(result, x=x)
    model = GeneralizedLinearModel(Coefficients(means=x), problem.task)
    return model, result


def _shard_batch(batch: LabeledBatch, mesh, specs) -> LabeledBatch:
    return jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        batch,
        specs,
    )
