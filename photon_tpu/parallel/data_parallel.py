"""Data-parallel fixed-effect training over a device mesh.

Parity: reference ⟦DistributedOptimizationProblem + DistributedGLMLossFunction⟧
— the Spark path where every L-BFGS iteration broadcasts coefficients and
``treeAggregate``s (loss, gradient) partials back to the driver (SURVEY.md
§3.4, the reference's scalability bottleneck).

TPU-native replacement (SURVEY.md §2.6 P1): the batch lives row-sharded over
the ``data`` mesh axis; coefficients are replicated. Two equivalent
implementations are provided:

1. ``fit_data_parallel`` — GSPMD: jit with explicit in/out shardings; XLA
   partitions the whole optimizer loop and inserts a single fused AllReduce
   over ICI for the row-sum in each value/grad evaluation. The entire
   multi-iteration solve is ONE XLA program — zero host round trips.

2. ``spmd_value_and_grad`` — explicit ``shard_map`` + ``psum``: per-device
   partial (loss, grad) reduced with one collective. Useful when manual
   control of the collective placement is needed (multi-slice DCN meshes)
   and as an executable spec of what (1) compiles to.

Both are verified equal to the single-device solve in tests/test_distributed.py
on an 8-device mesh (the reference's `local[*]` equivalent).
"""
from __future__ import annotations

import dataclasses
from functools import partial

import jax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from photon_tpu.parallel.mesh import shard_map  # version-compat wrapper

from photon_tpu.data.batch import LabeledBatch
from photon_tpu.functions.objective import GLMObjective
from photon_tpu.functions.problem import GLMOptimizationProblem
from photon_tpu.parallel.mesh import (
    DATA_AXIS,
    axes_size,
    axis_tuple,
    replicated,
    shard_batch_pytree,
)

Array = jax.Array


def fit_data_parallel(
    problem: GLMOptimizationProblem,
    batch: LabeledBatch,
    w0: Array,
    mesh,
    data_axis: str = DATA_AXIS,
    normalization=None,
):
    """Run the full solve with the batch row-sharded over ``data_axis``.

    ``data_axis`` may be one mesh axis or a tuple — pass ``("dcn", "data")``
    on a 2-level multi-slice mesh (``make_multislice_mesh``) to shard rows
    over slices × chips; XLA lowers the gradient AllReduce hierarchically
    (ICI within each slice, DCN across slices — SURVEY.md §5.8).

    Row counts that don't divide the axis size are padded with weight-0 rows
    (padding is invisible to the objective — SURVEY.md batch semantics).
    Returns (GeneralizedLinearModel, OptimizerResult), both replicated.
    """
    from photon_tpu.parallel.mesh import pad_and_shard_batch

    batch = pad_and_shard_batch(batch, mesh, data_axis)
    rep = replicated(mesh)
    w0 = jax.device_put(w0, rep)
    # Array-valued reg_mask / prior / normalization can't be part of the
    # static jit key; pass them dynamically (same convention as
    # GLMOptimizationProblem.fit).
    mask, prior = problem.reg_mask, problem.prior
    key = (
        dataclasses.replace(problem, reg_mask=None, prior=None)
        if (mask is not None or prior is not None)
        else problem
    )
    return _fit_dp_jitted(key, rep, batch, w0, mask, prior, normalization)


@partial(jax.jit, static_argnums=(0, 1))
def _fit_dp_jitted(problem, out_sharding, batch, w0, reg_mask, prior, normalization):
    # out_sharding (a NamedSharding: hashable) is applied via lax constraint
    # so the whole (problem, sharding) pair stays one cached executable.
    model, result = problem.run(batch, w0, reg_mask, normalization, prior)
    return jax.tree.map(
        lambda a: jax.lax.with_sharding_constraint(a, out_sharding),
        (model, result),
    )


def spmd_value_and_grad(
    obj: GLMObjective,
    batch: LabeledBatch,
    mesh,
    data_axis: str = DATA_AXIS,
):
    """Explicit-collective objective: w ↦ psum over shards of (value, grad).

    The returned closure can be handed straight to any Optimizer — the psum
    rides ICI inside whatever jit the optimizer loop compiles into. The L2
    term is added once globally (outside the psum), not once per shard.
    ``data_axis`` may be a tuple (multi-slice: the psum over
    ``("dcn", "data")`` is the hierarchical treeAggregate replacement).
    """
    from photon_tpu.parallel.mesh import strip_unshardable_aux

    axes = axis_tuple(data_axis)
    data_obj = GLMObjective(loss=obj.loss, l2_weight=0.0, reg_mask=None)
    batch = strip_unshardable_aux(batch)
    batch_specs = jax.tree.map(
        lambda leaf: P(axes, *([None] * (leaf.ndim - 1))), batch
    )

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), batch_specs),
        out_specs=(P(), P()),
    )
    def _vg(w, local_batch):
        v, g = data_obj.value_and_grad(w, local_batch)
        return lax.psum(v, axes), lax.psum(g, axes)

    sharded = shard_batch_pytree(batch, mesh, data_axis)

    def vg(w):
        import jax.numpy as jnp

        v, g = _vg(w, sharded)
        lam = obj._l2_vec(w)
        v = v + 0.5 * jnp.sum(lam * w * w)
        g = g + lam * w
        return v, g

    return vg
