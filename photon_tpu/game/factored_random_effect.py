"""Factored random effects: per-entity models in a learned latent space.

Parity: reference ⟦photon-api/.../algorithm/FactoredRandomEffectCoordinate⟧ +
⟦.../projector/ProjectionMatrix, RandomProjectionMatrix⟧ (SURVEY.md §2.2
Projectors, L5 layer map — fork-vintage component). Each entity's
coefficients are constrained to ``w_e = P · β_e`` with a SHARED projection
``P [D, p]`` and per-entity latent vectors ``β_e [p]``; training alternates

  1. latent step — fit every entity's ``β_e`` against features projected
     through the current ``P`` (small dense per-entity problems), and
  2. projection step — refit ``P`` against the pooled data with all ``β_e``
     fixed (one D·p-parameter smooth problem).

TPU-first: the latent step is ONE vmapped dense solve per bucket (the
reference trains per-entity models executor-side and the matrix step as a
separate Spark job); the projection step differentiates straight through the
feature-projection gather with autodiff and runs the shared L-BFGS core.
``P`` is initialized as a Gaussian random projection (reference
⟦RandomProjectionMatrix⟧) and the final model also materializes the
EFFECTIVE per-entity coefficients ``P_local · β_e`` as a standard
:class:`RandomEffectModel`, so scoring, validation, export, and warm-start
projection all reuse the plain random-effect machinery.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from photon_tpu.data.batch import DenseFeatures, LabeledBatch
from photon_tpu.data.random_effect import EntityBucket, RandomEffectDataset
from photon_tpu.functions.problem import GLMOptimizationProblem
from photon_tpu.game.random_effect import RandomEffectModel
from photon_tpu.ops.losses import loss_for_task
from photon_tpu.optim import LBFGS, OptimizerResult
from photon_tpu.types import TaskType

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class FactoredRandomEffectModel:
    """``w_e = P · β_e`` plus the materialized effective RE model.

    ``effective`` carries the per-entity coefficients in each entity's local
    subspace and serves every scoring/export path; ``projection`` and
    ``bucket_latent`` are kept for warm-starting further factored training.
    """

    re_type: str
    task: TaskType
    projection: Array                   # [D, p]
    bucket_latent: Sequence[Array]      # per bucket: [E, p]
    effective: RandomEffectModel

    @property
    def latent_dim(self) -> int:
        return self.projection.shape[1]

    @property
    def n_entities(self) -> int:
        return self.effective.n_entities

    # Scoring/export delegate to the effective per-entity model so the
    # factored coordinate plugs into descent/validation/IO unchanged.
    def score_dataset(self, dataset: RandomEffectDataset) -> Array:
        return self.effective.score_dataset(dataset)

    def score_new_dataset(self, dataset: RandomEffectDataset) -> Array:
        return self.effective.score_new_dataset(dataset)

    def coefficients_for(self, entity_key):
        return self.effective.coefficients_for(entity_key)


def _project_bucket_features(P_ext: Array, bucket: EntityBucket) -> Array:
    """Latent features ``Xp[e, s, :] = Σ_k val[e,s,k] · P[col(e,s,k), :]``.

    ``P_ext`` is ``P`` with one zero ghost row; ``bucket.proj`` routes local
    column ids to global rows of ``P`` (its own ghost slots hit the zero
    row), so padded entries contribute nothing. Differentiable w.r.t. ``P``
    (the projection step's autodiff path goes through these gathers).
    """
    Pl = P_ext[bucket.proj]                           # [E, Ppad, p]
    Pl = jnp.concatenate(                             # local ghost row
        [Pl, jnp.zeros_like(Pl[:, :1])], axis=1
    )

    def one(pl, idx, val):
        return jnp.einsum("skp,sk->sp", pl[idx], val)

    return jax.vmap(one)(Pl, bucket.idx, bucket.val)


@partial(jax.jit, static_argnums=0)
def _latent_step(problem, P, bucket, offsets, b0):
    """Vmapped dense solve for all of one bucket's latent vectors."""
    P_ext = jnp.concatenate([P, jnp.zeros_like(P[:1])])
    xp = _project_bucket_features(P_ext, bucket)
    base = bucket.local_batches(offsets)

    def solve(x, lab, off, wts, w0):
        b = LabeledBatch(DenseFeatures(x), lab, off, wts)
        model, result = problem.run(b, w0)
        return model.coefficients.means, result

    return jax.vmap(solve)(xp, base.labels, base.offsets, base.weights, b0)


@partial(jax.jit, static_argnums=(0, 1))
def _projection_step(problem, n_iter: int, P, buckets, offsets, lats):
    """Refit ``P`` with every β fixed: L-BFGS over vec(P) through autodiff."""
    loss = loss_for_task(problem.task)
    lam = problem.regularization.l2_weight(problem.reg_weight)
    shape = P.shape
    # Loop-invariant: batch assembly (offset gather) depends only on
    # (buckets, offsets) — hoist it out of the L-BFGS objective.
    bases = [bucket.local_batches(offsets) for bucket in buckets]

    def objective(p_flat):
        P_ = p_flat.reshape(shape)
        P_ext = jnp.concatenate([P_, jnp.zeros_like(P_[:1])])
        total = 0.0
        for bucket, base, beta in zip(buckets, bases, lats):
            xp = _project_bucket_features(P_ext, bucket)
            z = jnp.einsum("esp,ep->es", xp, beta) + base.offsets
            total = total + jnp.sum(base.weights * loss.loss(z, base.labels))
        return total + 0.5 * lam * jnp.sum(p_flat * p_flat)

    cfg = dataclasses.replace(problem.optimizer_config, max_iterations=n_iter)
    result = LBFGS(cfg).optimize(jax.value_and_grad(objective), P.reshape(-1))
    return result.x.reshape(shape), result


def _spectral_init(
    problem: GLMOptimizationProblem,
    dataset: RandomEffectDataset,
    offsets: Array,
    latent_dim: int,
    seed: int,
) -> tuple[Array, list[Array]]:
    """(P0, β0) from the top-``latent_dim`` SVD of the plain per-entity fit.

    The plain coefficients form a sparse [E, D] matrix (each entity's local
    subspace scattered to global columns); ``W ≈ U S Vᵀ`` gives ``P0 = V``
    (orthonormal) and ``β0 = U S`` — the best rank-p summary of what
    unconstrained per-entity fits learned.
    """
    from photon_tpu.game.random_effect import train_random_effects

    if not dataset.buckets:
        return (
            jnp.zeros((dataset.global_dim, latent_dim)),
            [],
        )
    plain, _ = train_random_effects(problem, dataset, offsets)
    return _factor_model(plain, dataset, latent_dim, seed)


def _factor_model(
    source: "RandomEffectModel",
    dataset: RandomEffectDataset,
    latent_dim: int,
    seed: int,
) -> tuple[Array, list[Array]]:
    """Top-p SVD of ``source``'s sparse per-entity coefficients, with β rows
    matched to ``dataset``'s entities BY KEY (entities the source never saw
    start at 0). Used both for the spectral init and for re-factoring a
    loaded effective model (whose coefficient matrix is exactly rank-p)."""
    import numpy as np
    import scipy.sparse as sp
    from scipy.sparse.linalg import svds

    rows, cols, vals = [], [], []
    for coefs, proj, eids in zip(
        source.bucket_coefs, source.bucket_proj, source.bucket_entity_ids
    ):
        c = np.asarray(coefs, np.float64)
        p = np.asarray(proj)
        e = np.asarray(eids)
        lane_ok = e >= 0
        col_ok = p < dataset.global_dim
        ok = lane_ok[:, None] & col_ok
        rows.append(np.broadcast_to(e[:, None], p.shape)[ok])
        cols.append(p[ok])
        vals.append(c[ok])
    n_src = source.n_entities
    W = sp.csr_matrix(
        (np.concatenate(vals), (np.concatenate(rows), np.concatenate(cols))),
        shape=(n_src, source.global_dim),
    )
    k = min(latent_dim, min(W.shape) - 1)
    P0 = np.zeros((dataset.global_dim, latent_dim))
    B_src = np.zeros((n_src, latent_dim))
    if k >= 1:
        # deterministic ARPACK start vector (svds' random_state plumbing
        # varies across scipy versions)
        v0 = np.random.default_rng(seed).normal(size=min(W.shape))
        u, s, vt = svds(W, k=k, v0=v0)
        order = np.argsort(-s)
        u, s, vt = u[:, order], s[order], vt[order]
        P0[: source.global_dim, :k] = vt.T
        B_src[:, :k] = u * s
    # β rows matched by entity KEY (source == dataset for the fresh-init
    # path, where this reduces to the identity mapping).
    B0 = np.zeros((dataset.n_entities + 1, latent_dim))
    if source.entity_keys is dataset.entity_keys:
        B0[:-1] = B_src                              # fresh-init fast path
    else:
        key_to_src = source._key_to_dense
        for dense_new, key in enumerate(dataset.entity_keys):
            src = key_to_src.get(key)
            if src is not None:
                B0[dense_new] = B_src[src]
    lats = [
        jnp.asarray(B0[np.asarray(b.entity_ids)])   # -1 pad -> zero last row
        for b in dataset.buckets
    ]
    return jnp.asarray(P0), lats


def train_factored_random_effects(
    problem: GLMOptimizationProblem,
    dataset: RandomEffectDataset,
    offsets: Array,
    latent_dim: int = 8,
    n_alternations: int = 2,
    seed: int = 0,
    init=None,
) -> tuple[FactoredRandomEffectModel, list[OptimizerResult]]:
    """Alternating factored-RE training over all buckets.

    ``problem`` configures both steps (its optimizer config drives the latent
    solves; the projection step reuses its L2 weight and iteration budget).
    ``init`` may be a :class:`FactoredRandomEffectModel` (same structure →
    resume its factors) or a plain :class:`RandomEffectModel` (a loaded
    warm start → its coefficients are re-factored spectrally).
    """
    dtype = dataset.buckets[0].val.dtype if dataset.buckets else jnp.float32
    d = dataset.global_dim
    same_init = (
        isinstance(init, FactoredRandomEffectModel)
        and init.projection.shape == (d, latent_dim)
        and len(init.bucket_latent) == len(dataset.buckets)
        and all(
            b.shape[0] == bk.n_entities
            for b, bk in zip(init.bucket_latent, dataset.buckets)
        )
    )
    if same_init:
        P = init.projection.astype(dtype)
        lats = [b.astype(dtype) for b in init.bucket_latent]
    elif (
        isinstance(init, RandomEffectModel) and init.global_dim == d
        and dataset.buckets
    ):
        # Loaded effective model (the saved form of a factored coordinate,
        # or any plain RE warm start): re-factor ITS coefficients instead of
        # refitting the plain solve from scratch.
        P, lats = _factor_model(init, dataset, latent_dim, seed)
        P = P.astype(dtype)
        lats = [b.astype(dtype) for b in lats]
    else:
        # Spectral init: one plain per-entity solve, then the top-p SVD of
        # its sparse coefficient matrix seeds (P, β). A Gaussian random P
        # (the reference RandomProjectionMatrix) makes the alternation lock
        # onto the random subspace — the first β-step fits noise the random
        # P happens to span and the P-step then reinforces it; starting in
        # the plain solution's principal subspace lands in the right basin.
        P, lats = _spectral_init(problem, dataset, offsets, latent_dim, seed)
        P = P.astype(dtype)
        lats = [b.astype(dtype) for b in lats]

    results: list[OptimizerResult] = []
    for _ in range(max(1, n_alternations)):
        results = []
        for i, bucket in enumerate(dataset.buckets):
            lats[i], res = _latent_step(problem, P, bucket, offsets, lats[i])
            results.append(res)
        P, _ = _projection_step(
            problem, problem.optimizer_config.max_iterations, P,
            tuple(dataset.buckets), offsets, tuple(lats),
        )
    # Final latent refresh so β is optimal for the returned P.
    results = []
    for i, bucket in enumerate(dataset.buckets):
        lats[i], res = _latent_step(problem, P, bucket, offsets, lats[i])
        results.append(res)

    # Effective per-entity coefficients in each local subspace.
    P_ext = jnp.concatenate([P, jnp.zeros_like(P[:1])])
    eff_coefs = [
        jnp.einsum("eqp,ep->eq", P_ext[b.proj], lat)
        for b, lat in zip(dataset.buckets, lats)
    ]
    effective = RandomEffectModel(
        re_type=dataset.re_type,
        task=problem.task,
        bucket_coefs=eff_coefs,
        bucket_proj=[b.proj for b in dataset.buckets],
        bucket_entity_ids=[b.entity_ids for b in dataset.buckets],
        entity_keys=dataset.entity_keys,
        entity_to_slot=dataset.entity_to_slot,
        global_dim=dataset.global_dim,
    )
    model = FactoredRandomEffectModel(
        re_type=dataset.re_type,
        task=problem.task,
        projection=P,
        bucket_latent=lats,
        effective=effective,
    )
    return model, results
