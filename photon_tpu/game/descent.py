"""Coordinate descent: the GAME outer loop.

Parity: reference ⟦photon-api/.../algorithm/CoordinateDescent.scala⟧ (SURVEY.md
§3.3): for each sweep, for each coordinate in the update sequence — remove the
coordinate's own score from the total, train against the residual as offset,
add the new score back; evaluate on validation after every coordinate update
and keep the best model seen.

TPU-first: per-coordinate scores are plain [N] device arrays in a fixed global
sample order, so the reference's score-RDD zip/joins are elementwise adds, and
"subtract own score" is literally ``total - scores[cid]`` (SURVEY.md §2.6 P7).
"""
from __future__ import annotations

import dataclasses
import logging
from typing import Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.evaluation import EvaluationResults, EvaluationSuite
from photon_tpu.faults import fault_point
from photon_tpu.game.coordinates import Coordinate, DatumScoringModel
from photon_tpu.obs import instant, trace_span

Array = jax.Array

logger = logging.getLogger("photon_tpu.game")


@dataclasses.dataclass(frozen=True)
class GameModel:
    """Composite model keyed by coordinate id — reference ⟦GameModel⟧."""

    models: Mapping[str, DatumScoringModel]

    def __getitem__(self, cid: str) -> DatumScoringModel:
        return self.models[cid]

    def keys(self):
        return self.models.keys()


@dataclasses.dataclass
class CoordinateStepRecord:
    """One (sweep, coordinate) step of the tracker — reference
    ⟦OptimizationStatesTracker⟧ + per-step validation logging."""

    sweep: int
    coordinate_id: str
    seconds: float
    validation: Optional[EvaluationResults] = None


@dataclasses.dataclass(frozen=True)
class ValidationData:
    """Validation rows + per-coordinate scorers.

    ``scorers[cid](model) -> [n_rows]`` raw coordinate scores on the
    validation rows (fixed effect: matvec on the validation batch; random
    effect: cross-dataset projection). Built by the estimator.
    """

    labels: Array
    weights: Array
    offsets: Array
    scorers: Mapping[str, object]
    group_ids_by_column: Optional[Mapping[str, Array]] = None
    num_groups_by_column: Optional[Mapping[str, int]] = None


@dataclasses.dataclass(frozen=True)
class CoordinateDescent:
    """Run block-coordinate descent over an ordered update sequence."""

    update_sequence: Sequence[str]
    n_sweeps: int = 1

    def run(
        self,
        coordinates: Mapping[str, Coordinate],
        n_rows: int,
        base_offsets: Optional[Array] = None,
        validation: Optional[ValidationData] = None,
        suite: Optional[EvaluationSuite] = None,
        initial_models: Optional[Mapping[str, DatumScoringModel]] = None,
        checkpointer=None,
        resume: Optional[dict] = None,
        step_base: int = 0,
        checkpoint_meta: Optional[dict] = None,
        extra_state: Optional[dict] = None,
    ) -> tuple[GameModel, list[CoordinateStepRecord]]:
        """``checkpointer`` (a ``photon_tpu.checkpoint.CheckpointManager``)
        snapshots the full descent state after every coordinate step
        (SURVEY.md §5.4 rebuild note); ``resume`` is a payload from
        ``load_latest`` whose position is fast-forwarded past. Resumed runs
        reproduce the uninterrupted run bit-identically.
        """
        for cid in self.update_sequence:
            if cid not in coordinates:
                raise ValueError(f"update sequence names unknown coordinate {cid!r}")
        if validation is not None and suite is None:
            raise ValueError("validation data provided without an evaluation suite")

        base = (
            jnp.zeros((n_rows,), jnp.float32)
            if base_offsets is None
            else jnp.asarray(base_offsets)
        )

        resumed_pos = None
        if resume is not None:
            st = resume["state"]
            models = dict(st["models"])
            scores = dict(st["scores"])
            total = st["total"]
            v_cache = dict(st["v_cache"])
            best_metric = st["best_metric"]
            best_models = st["best_models"]
            tracker = list(st["tracker"])
            resumed_pos = (resume["meta"]["sweep"], resume["meta"]["coord_index"])
            logger.info(
                "resuming after sweep %d coordinate %d",
                resumed_pos[0], resumed_pos[1],
            )
        else:
            models = dict(initial_models or {})
            scores = {}
            # Initial scores from warm-start models, else zero. Models OUTSIDE
            # the update sequence are "locked" coordinates (reference partial
            # retraining): scored so residuals are right, never retrained,
            # kept in the output model.
            for cid in self.update_sequence:
                if cid in models:
                    scores[cid] = coordinates[cid].score(models[cid])
                else:
                    scores[cid] = jnp.zeros((n_rows,), base.dtype)
            for cid in sorted(set(models) - set(self.update_sequence)):
                if cid not in coordinates:
                    raise ValueError(
                        f"initial model {cid!r} is outside the update sequence "
                        "and has no coordinate to score it (locked coordinates "
                        "need a coordinate for residual bookkeeping)"
                    )
                scores[cid] = coordinates[cid].score(models[cid])
            total = base + sum(scores.values())
            tracker = []
            best_metric = None
            best_models = None
            # Validation scores cached per coordinate — only the coordinate
            # just trained is re-scored (random-effect cross-dataset
            # projection is host-side work, so re-scoring every coordinate
            # each step is O(C²)).
            v_cache = {
                cid: validation.scorers[cid](models[cid])
                for cid in models
                if validation is not None
            }
        if validation is not None:
            need = set(self.update_sequence) | set(models)
            missing = sorted(c for c in need if c not in validation.scorers)
            if missing:
                raise ValueError(
                    f"validation scorers missing for coordinates {missing}"
                )

        # Retrace-sentinel contract for the RE bucket solvers: sweep 0
        # compiles the whole blessed shape ladder (full-bucket shapes,
        # chunk-ladder shapes, calibration probes — all closed sets), so
        # after the first sweep the kernels are marked warm and ANY further
        # compile is a watched retrace-after-warmup. A new run() (new
        # config / new λ) legitimately re-compiles, so warm state is
        # cleared on entry.
        from photon_tpu.obs import retrace as _retrace

        for k in _retrace.RE_SOLVER_KERNELS:
            _retrace.clear_warm(k)

        step = step_base
        # Device-loss recovery clears the RE kernels' warm marks along with
        # the executable caches; the sentinel re-arms only after the NEXT
        # fully-executed sweep (the recovery sweep's remainder legitimately
        # recompiles shapes whose executables were purged).
        rearm_sweep = None
        for sweep in range(self.n_sweeps):
            # Manual span, not ``with`` (the inner loop body is long): on a
            # mid-sweep exception the sweep span is simply not emitted — the
            # failing step span records the error for the timeline.
            sweep_span = trace_span("descent.sweep", cat="descent",
                                    sweep=sweep).__enter__()
            for ci, cid in enumerate(self.update_sequence):
                if resumed_pos is not None and (sweep, ci) <= resumed_pos:
                    step += 1
                    continue
                # Chaos hook: a preemption delivered here kills the attempt
                # between steps — after the previous step's checkpoint, before
                # this one's work — the exact window resume must cover.
                fault_point(
                    "descent.step", sweep=sweep, coordinate=cid, step=step
                )
                coord = coordinates[cid]
                # In-run device-loss recovery (docs/robustness.md): the step
                # body COMMITS (total/scores/models mutate) only after the
                # D2H sync proves the device work completed, so a device
                # loss anywhere inside leaves the pre-step state intact and
                # the step simply re-runs after recovery — bit-identically,
                # because the step is a pure function of that state.
                recoveries = 0
                while True:
                    try:
                        with trace_span(
                            "descent.step", cat="descent", sweep=sweep,
                            coordinate=cid, step=step,
                        ) as step_span:
                            # Chaos hook: error="device_lost" here drives
                            # the in-run path (vs descent.step, whose
                            # preemption kills the whole attempt).
                            fault_point("descent.device", sweep=sweep,
                                        coordinate=cid, step=step)
                            residual_offset = total - scores[cid]
                            model, _ = coord.train(
                                residual_offset, models.get(cid))
                            new_score = coord.score(model)
                            new_total = residual_offset + new_score
                            # Tiny D2H fetch: the step record (and span) must
                            # report COMPLETED compute, not async dispatch
                            # (without this the tracker claimed ~4s of a 70s
                            # fit; block_until_ready alone does not
                            # synchronize on the axon tunnel backend, a D2H
                            # does). The data dependency
                            # new_score <- model <- solve forces the whole
                            # step — and is the commit gate above.
                            np.asarray(new_score[:1])
                        total = new_total
                        scores[cid] = new_score
                        models[cid] = model
                        break
                    except Exception as e:  # noqa: BLE001 - classified below
                        from photon_tpu.runtime import backend_guard as _bg

                        if (not _bg.is_device_lost(e)
                                or recoveries >= _bg.max_inrun_recoveries()):
                            raise
                        recoveries += 1
                        # Checkpoint FIRST (pre-step state is still exact),
                        # then clear-and-reenter; a failing snapshot means
                        # the device state is unfetchable and the loss must
                        # escalate to the supervisor restart instead.
                        if checkpointer is not None:
                            try:
                                checkpointer.save(
                                    step,
                                    state={
                                        "models": models,
                                        "scores": scores,
                                        "total": total,
                                        "v_cache": v_cache,
                                        "best_metric": best_metric,
                                        "best_models": best_models,
                                        "tracker": tracker,
                                        **(extra_state or {}),
                                    },
                                    meta={
                                        "phase": "recovery",
                                        "sweep": sweep,
                                        # pre-step state == "resume after
                                        # the previous coordinate"
                                        "coord_index": ci - 1,
                                        **(checkpoint_meta or {}),
                                    },
                                )
                                checkpointer.wait()
                            except KeyboardInterrupt:
                                raise  # a user abort is never "recovery"
                            except Exception:
                                raise e
                        logger.warning(
                            "device lost in sweep %d coord %s (%s: %s); "
                            "in-run recovery %d/%d, re-running the step",
                            sweep, cid, type(e).__name__, e, recoveries,
                            _bg.max_inrun_recoveries(),
                        )
                        _bg.recover_from_device_loss(
                            f"descent sweep {sweep} coord {cid}",
                            logger=logger,
                        )
                        rearm_sweep = sweep + 1
                # Close the supervisor's restart→first-step clock on the
                # FIRST committed step of a supervised attempt (no-op when
                # no clock is armed — runtime/compile_store.py).
                from photon_tpu.runtime.compile_store import note_first_step

                note_first_step("descent.step")
                dt = step_span.seconds

                record = CoordinateStepRecord(sweep, cid, dt)
                if validation is not None:
                    v_cache[cid] = validation.scorers[cid](model)
                    v_scores = sum(v_cache.values())
                    record.validation = suite.evaluate(
                        validation.offsets + v_scores,
                        validation.labels,
                        validation.weights,
                        validation.group_ids_by_column,
                        validation.num_groups_by_column,
                    )
                    primary = record.validation.primary
                    # Only a complete model (every coordinate trained at least
                    # once) is eligible for best-model tracking — a partial
                    # GameModel would break scoring downstream.
                    complete = all(c in models for c in self.update_sequence)
                    if complete and (
                        best_metric is None
                        or suite.primary.better_than(primary, best_metric)
                    ):
                        best_metric = primary
                        best_models = dict(models)
                    logger.info(
                        "sweep %d coord %s: %s (%.2fs)",
                        sweep, cid, record.validation, dt,
                    )
                else:
                    logger.info("sweep %d coord %s done (%.2fs)", sweep, cid, dt)
                tracker.append(record)

                if checkpointer is not None:
                    checkpointer.save(
                        step,
                        state={
                            "models": models,
                            "scores": scores,
                            "total": total,
                            "v_cache": v_cache,
                            "best_metric": best_metric,
                            "best_models": best_models,
                            "tracker": tracker,
                            **(extra_state or {}),
                        },
                        meta={
                            "phase": "step",
                            "sweep": sweep,
                            "coord_index": ci,
                            **(checkpoint_meta or {}),
                        },
                    )
                step += 1
            sweep_span.__exit__(None, None, None)
            # Sweep-cache residency marker (data/device_cache.py): the
            # timeline shows per sweep whether the dataset was device-pinned
            # (sweep 1+ re-uploading here is the regression the cache
            # exists to kill — docs/scaling.md §"Data path").
            from photon_tpu.obs.metrics import REGISTRY as _REG

            instant(
                "cache.sweep_residency", cat="ingest", sweep=sweep,
                resident_bytes=_REG.gauge("sweep_cache_bytes").value(),
                spilled_bytes=_REG.gauge("sweep_cache_spilled_bytes").value(),
            )
            # Arm after the first sweep that executed EVERY coordinate step
            # (a resumed run's first sweep may be partial, leaving later
            # coordinates' shapes uncompiled — warming then would turn their
            # legitimate first compiles into false retrace alarms). An
            # in-run device-loss recovery pushes the arming point out the
            # same way: its cache purge makes every shape recompile once
            # more across the remainder of that sweep.
            first_full = (0 if resumed_pos is None else resumed_pos[0] + 1)
            arm_at = (first_full if rearm_sweep is None
                      else max(first_full, rearm_sweep))
            if sweep == arm_at:
                for k in _retrace.RE_SOLVER_KERNELS:
                    _retrace.mark_warm(k)

        final = best_models if best_models is not None else models
        return GameModel(dict(final)), tracker
