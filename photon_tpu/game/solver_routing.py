"""Measured cost-model routing for random-effect bucket solvers.

Why: the static eligibility gates in ``game/newton_re.py`` answer "can this
solver run here?" — they cannot answer "which solver is FASTEST here?".
VERDICT r5 weak #1 showed the cost of conflating the two: the S=512 buckets
that dominate the 50M rehearsal were budget-excluded from every Newton
variant and silently surrendered to the vmapped L-BFGS ``while_loop``, and
nothing ever measured the road not taken.

This module replaces preference-by-gate with preference-by-measurement:

* Buckets are classified by **shape class** (``S``, ``K``, ``P``, dtype —
  the entity count does not change per-entity cost, so it is deliberately
  not part of the key).
* The first time a shape class is seen, a **calibration race** times every
  feasible ``(solver, chunk)`` candidate on ONE sync-timed probe slice of
  the bucket; the XLA compile the probe pays (host-synchronous, measured
  by ``obs.retrace.compile_watch``) is subtracted so the race never
  charges a solver for its first-trace compile. Per-entity costs land in
  a process-global :class:`SolverCostTable`.
* Later buckets of the same class route straight to the measured winner —
  including every later sweep of coordinate descent, so the race is a
  one-time cost per (config, shape class).
* The table round-trips as JSON. ``PHOTON_RE_COST_TABLE=<path>`` (set by
  the drivers' ``--re-cost-table`` flag) loads the table at first use and
  persists it after every calibration, so a warm restart — the supervisor
  relaunching a preempted driver — skips calibration entirely and, just as
  important, reproduces the original run's routing decisions exactly
  (calibration is a timing race; re-racing on a restart could flip a
  winner and break bit-identical resume).

Every candidate is **chunked** at a blessed ladder size
(``newton_re.chunk_ladder()``), including the vmapped L-BFGS baseline:
probe shapes are then execution shapes, so calibration warms exactly the
executables the real solve uses (the retrace sentinel stays quiet), and
the probe's per-entity cost honestly includes the convergence-decoupling
behavior of the chunk size it recommends.

Routing mode is ``PHOTON_RE_ROUTING``: ``static`` (default — the
deterministic gate ladder in ``random_effect._solve_bucket``, now with
chunked Newton tiers) or ``measured``. Measured mode is the default for
``bench.py``'s game_scale stage and opt-in for the drivers via
``--re-routing measured``; it is intentionally NOT the library default
because a timing race is not bit-deterministic across processes unless the
table is persisted (see above).
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Callable, Optional, Sequence

import jax
import numpy as np

from photon_tpu.game import newton_re

ROUTING_ENV = "PHOTON_RE_ROUTING"
TABLE_ENV = "PHOTON_RE_COST_TABLE"

# Largest chunk the vmapped L-BFGS baseline is raced/executed at under
# measured routing (its per-entity cost is nearly chunk-flat, and probing
# full-history L-BFGS at a 16K chunk costs more than the race saves).
VMAPPED_CHUNK_CAP = 4096

_MODES = ("static", "measured")


def routing_mode() -> str:
    mode = (os.environ.get(ROUTING_ENV) or "static").strip().lower()
    if mode not in _MODES:
        raise ValueError(
            f"{ROUTING_ENV} must be one of {_MODES}, got {mode!r}"
        )
    return mode


def shape_class(bucket, shards: int = 1) -> str:
    """Bucket shape key for the cost table: rows-per-entity S, ELL width K,
    local dim P, dtype. Entity count E is EXCLUDED — per-entity solve cost
    is what the table stores, and chunking makes it E-independent.

    ``shards`` (the entity-axis mesh size) lands in the key as a ``@devN``
    suffix: a per-entity cost measured across an N-device mesh prices the
    collective dispatch + per-device slice and is NOT comparable to a
    single-device cost, so a table persisted by an 8-device run can never
    steer a 1-device restart (and vice versa) — the same refusal contract
    as the bench gate's cross-device-count comparisons."""
    _, s, k = bucket.idx.shape
    key = f"s{s}k{k}p{bucket.local_dim}:{np.dtype(bucket.val.dtype).name}"
    return key if shards <= 1 else f"{key}@dev{shards}"


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One raceable (solver, chunk-size) combination."""

    solver: str   # newton_primal | newton_dual | vmapped_lbfgs
    chunk: int

    @property
    def key(self) -> str:
        return f"{self.solver}@{self.chunk}"


class SolverCostTable:
    """Thread-safe per-(shape class, candidate) measured cost store.

    Costs are seconds per PADDED entity lane at the candidate's chunk size
    (every candidate races at its own chunk, so padding waste is priced
    in). ``winner`` returns the cheapest recorded candidate that is still
    feasible for the caller's bucket.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: dict = {}            # shape_key -> {cand_key: cost}

    def record(self, shape_key: str, cand: Candidate,
               seconds_per_entity: float) -> None:
        with self._lock:
            self._entries.setdefault(shape_key, {})[cand.key] = float(
                seconds_per_entity)

    def costs(self, shape_key: str) -> dict:
        with self._lock:
            return dict(self._entries.get(shape_key, {}))

    def winner(self, shape_key: str,
               feasible: Sequence[Candidate]) -> Optional[Candidate]:
        """Cheapest recorded candidate among ``feasible``, or None unless
        EVERY feasible candidate has a recorded cost (the caller then
        calibrates the missing ones). Requiring full coverage matters: a
        table persisted by a run whose budget/ladder admitted fewer
        candidates must not permanently pin routing to the only solver it
        happened to measure — the unraced candidate could be the winner."""
        by_key = {c.key: c for c in feasible}
        with self._lock:
            entries = self._entries.get(shape_key)
            if not entries:
                return None
            hits = [(cost, k) for k, cost in entries.items() if k in by_key]
        if len(hits) < len(by_key):
            return None
        return by_key[min(hits)[1]]

    def to_json(self) -> dict:
        with self._lock:
            return {"version": 1,
                    "entries": {k: dict(v) for k, v in self._entries.items()}}

    def load_json(self, payload: dict) -> None:
        if payload.get("version") != 1:
            raise ValueError(
                f"unsupported cost-table version {payload.get('version')!r}"
            )
        entries = payload.get("entries", {})
        with self._lock:
            for k, v in entries.items():
                self._entries.setdefault(k, {}).update(
                    {ck: float(c) for ck, c in v.items()})

    def save(self, path: str) -> None:
        """Atomic write (tmp + rename): a preemption mid-save must not leave
        a torn table for the restarted attempt to refuse."""
        payload = self.to_json()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
        os.replace(tmp, path)

    def load(self, path: str) -> None:
        with open(path) as f:
            self.load_json(json.load(f))

    def merge(self, other: "SolverCostTable") -> None:
        """Merge another table's measurements in (mean where both measured
        a candidate, adopt where only one did). The multi-process mesh
        story: each host races its local shard of the calibration probe
        and the driver merges per-host tables into ONE persisted table —
        keys carry the device count (``shape_class`` ``@devN`` suffix), so
        merging never averages across different mesh sizes."""
        with other._lock:
            theirs = {k: dict(v) for k, v in other._entries.items()}
        with self._lock:
            for key, cands in theirs.items():
                mine = self._entries.setdefault(key, {})
                for ck, cost in cands.items():
                    if ck in mine:
                        mine[ck] = 0.5 * (mine[ck] + float(cost))
                    else:
                        mine[ck] = float(cost)

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()


_TABLE = SolverCostTable()
_loaded_paths: set = set()
_load_lock = threading.Lock()


def process_table() -> SolverCostTable:
    """The process-global table, hydrated once per distinct
    ``PHOTON_RE_COST_TABLE`` path (warm restarts skip calibration)."""
    path = os.environ.get(TABLE_ENV)
    if path:
        with _load_lock:
            if path not in _loaded_paths:
                _loaded_paths.add(path)
                if os.path.exists(path):
                    _TABLE.load(path)
    return _TABLE


def _persist(table: SolverCostTable) -> None:
    path = os.environ.get(TABLE_ENV)
    if path:
        table.save(path)


def reset_process_table() -> None:
    """Forget measurements and load history (tests)."""
    with _load_lock:
        _TABLE.reset()
        _loaded_paths.clear()


def merge_host_tables(paths, out_path: str) -> SolverCostTable:
    """Fold several per-host cost tables into one persisted table.

    The multi-host run writes ``solver_costs.host-<id>.json`` per host;
    the coordinator folds them here into ``solver_costs.merged.json`` so a
    warm restart of ANY host (pointing ``PHOTON_RE_COST_TABLE`` at the
    merged file) skips calibration outright. ``merge`` means overlapping
    measurements and the ``@devN`` shape-class suffix keeps entries from a
    different local-mesh topology inert, so folding is always safe.
    Unreadable shards are skipped — a torn per-host file must not poison
    the merged table."""
    merged = SolverCostTable()
    for p in paths:
        other = SolverCostTable()
        try:
            other.load(p)
        except (OSError, ValueError, KeyError):
            continue
        merged.merge(other)
    merged.save(out_path)
    return merged


def candidates_for(problem, bucket, normalization, u_max: int,
                   shards: int = 1) -> list:
    """Feasible chunked candidates for this bucket, Newton variants first.

    The primal candidate is admitted up to ``NEWTON_CHUNK_MAX_P`` (wider
    than the static gate): in (64, 128] the dense Hessian may or may not
    beat L-BFGS depending on S — exactly the call the race exists to make.
    The vmapped baseline is always feasible and always raced, so "Newton
    by default" is a measured claim, not an assumption. ``shards`` > 1
    restricts chunks to mesh-divisible blessed sizes and prices the
    per-device slice (``newton_re.newton_chunk_size``).
    """
    out = []
    c = newton_re.newton_chunk_size(
        problem, bucket, normalization, max_p=newton_re.NEWTON_CHUNK_MAX_P,
        shards=shards)
    if c:
        out.append(Candidate("newton_primal", c))
    # u_max < 0 means the caller's dual precheck already refused the bucket
    # (so the device-synced unpenalized-column count was never computed).
    c = (newton_re.dual_chunk_size(problem, bucket, normalization, u_max,
                                   shards=shards)
         if u_max >= 0 else None)
    if c:
        out.append(Candidate("newton_dual", c))
    if out:
        # Baseline races (and, if it wins, executes) at a capped chunk:
        # probing full-history L-BFGS at a 16K-entity chunk would cost more
        # than the race saves, and its per-entity cost is nearly flat in
        # chunk size. Probe shape == execution shape either way. Under a
        # mesh the cap rounds down to a shard-divisible size.
        cap = VMAPPED_CHUNK_CAP
        if shards > 1:
            cap = max(shards, cap - cap % shards)
        out.append(Candidate(
            "vmapped_lbfgs", min(max(cand.chunk for cand in out), cap)))
    return out


def solve_measured(
    problem,
    bucket,
    batches,
    w0,
    local_mask,
    local_prior,
    normalization,
    u_max: int,
    fit_for: Callable[[str], Callable],
    sync: Callable,
    table: Optional[SolverCostTable] = None,
    shards: int = 1,
    place: Optional[Callable] = None,
):
    """Route one bucket through the measured cost table.

    ``fit_for(solver) -> fit_one(batches, w0, mask, prior)`` supplies the
    per-solver chunk closures (built by ``random_effect._solve_bucket`` so
    this module stays import-cycle-free); ``sync`` forces one leaf of a
    solve output to the host (the repo-standard tiny-D2H sync —
    ``block_until_ready`` does not synchronize on the axon tunnel backend).

    Under a mesh (``shards`` > 1, ``place`` the entity-sharded device_put)
    the calibration probes dispatch SHARDED — every device races its slice
    of the probe chunk concurrently, so one timed probe IS the per-device
    calibration, merged by construction — and costs land under the
    ``@devN``-suffixed shape key (``shape_class``), persisted with the
    device count so cross-mesh routing can never cross-read.

    Returns ``(models, result, info)`` with ``info`` carrying the routing
    decision and the calibration cost:
    ``{solver, chunk, routing, calibration_seconds, calibrated}``.
    """
    table = table if table is not None else process_table()
    key = shape_class(bucket, shards)
    cands = candidates_for(problem, bucket, normalization, u_max,
                           shards=shards)
    info = {"routing": "measured", "calibration_seconds": 0.0,
            "calibrated": False}

    if not any(c.solver != "vmapped_lbfgs" for c in cands):
        # Calibration refused every Newton variant (non-smooth objective,
        # normalization context, S+U over the dual cap AND P over the
        # chunked-primal cap, or nothing fits the budget): nothing to race
        # — the general vmapped path solves the whole bucket unchunked,
        # exactly as static routing would.
        args = (batches, w0, local_mask, local_prior)
        if place is not None:
            args = place(args)
        models, result = fit_for("vmapped_lbfgs")(*args)
        info.update(solver="vmapped_lbfgs", chunk=None)
        return models, result, info

    win = table.winner(key, cands)
    if win is None:
        from photon_tpu.obs.retrace import compile_watch

        t0 = time.perf_counter()
        cal_compile = 0.0
        e = w0.shape[0]
        recorded = table.costs(key)
        for cand in cands:
            if cand.key in recorded:
                continue  # incremental race: only unmeasured candidates pay
            fit_one = fit_for(cand.solver)
            probe_e = min(e, cand.chunk)
            probe_args = (
                newton_re._slice_pad_batches(batches, 0, probe_e, cand.chunk),
                newton_re._slice_pad_lanes(w0, 0, probe_e, cand.chunk),
                newton_re._slice_pad_lanes(local_mask, 0, probe_e,
                                           cand.chunk, fill=1),
                (jax.tree.map(
                    lambda a: newton_re._slice_pad_lanes(
                        a, 0, probe_e, cand.chunk), local_prior)
                 if local_prior is not None else None),
            )
            if place is not None:
                # Probe shape == execution shape INCLUDING the sharding:
                # the race times the sharded dispatch the real solve uses.
                probe_args = place(probe_args)
            # ONE sync-timed probe per candidate; the XLA compile it pays
            # (host-synchronous before dispatch returns) is measured by the
            # sentinel watch and subtracted, so the recorded cost is the
            # executable's — which the real solve reuses (same blessed
            # shape) — without a second full probe solve.
            t1 = time.perf_counter()
            with compile_watch() as cw:
                out = fit_one(*probe_args)
            sync(out)
            exec_s = max(time.perf_counter() - t1 - cw.compile_seconds,
                         1e-9)
            cal_compile += cw.compile_seconds
            table.record(key, cand, exec_s / cand.chunk)
        # The probes' first-trace compiles are already accounted under
        # compile_seconds (the caller's watched dispatch wrappers saw the
        # same traces) — subtract them here so the two columns partition
        # the wall instead of double-counting it.
        info["calibration_seconds"] = round(
            max(time.perf_counter() - t0 - cal_compile, 0.0), 3)
        info["calibrated"] = True
        _persist(table)
        win = table.winner(key, cands)

    fit_one = fit_for(win.solver)
    models, result = newton_re.fit_bucket_in_chunks(
        fit_one, win.chunk, batches, w0, local_mask, local_prior,
        put=place, ahead=1 if place is not None else 0)
    info.update(solver=win.solver, chunk=win.chunk)
    return models, result, info
