"""GAME coordinates: one unit of block-coordinate descent.

Parity: reference ⟦photon-api/.../algorithm/Coordinate.scala,
FixedEffectCoordinate.scala, RandomEffectCoordinate.scala⟧ (SURVEY.md §2.2,
§3.4/§3.5). A coordinate owns its training data and optimization problem and
exposes ``train(offsets, init) -> model`` and ``score(model) -> [N]``.

TPU-first: offsets are a plain per-row array aligned with the global sample
order (fixed at dataset build time), so the reference's score-RDD joins by
``UniqueSampleId`` become elementwise adds (SURVEY.md §2.6 comm table).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Union

import jax
import jax.numpy as jnp

from photon_tpu.data.batch import LabeledBatch
from photon_tpu.data.random_effect import RandomEffectDataset
from photon_tpu.functions.problem import GLMOptimizationProblem
from photon_tpu.game.random_effect import (
    RandomEffectModel,
    train_random_effects,
)
from photon_tpu.models.glm import GeneralizedLinearModel
from photon_tpu.parallel.data_parallel import fit_data_parallel

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class FixedEffectModel:
    """Population-level GLM for one feature shard — reference
    ⟦FixedEffectModel(coefficientsBroadcast, featureShardId)⟧. Replication
    over the mesh replaces the broadcast."""

    model: GeneralizedLinearModel
    feature_shard: str

    def score_batch(self, batch: LabeledBatch) -> Array:
        """Raw per-row scores WITHOUT offsets (GAME sums coordinate scores)."""
        return batch.features.matvec(self.model.coefficients.means)


@dataclasses.dataclass(frozen=True)
class FixedEffectCoordinate:
    """Train one GLM on all rows, data-parallel over the mesh (SURVEY §3.4)."""

    batch: LabeledBatch            # offsets field ignored; passed per train()
    problem: GLMOptimizationProblem
    feature_shard: str = "global"
    mesh: Optional[object] = None
    data_axis: str = "data"

    def train(self, offsets: Array, init: Optional[FixedEffectModel] = None):
        batch = self.batch.with_offsets(offsets.astype(self.batch.labels.dtype))
        if init is not None:
            w0 = init.model.coefficients.means
        else:
            w0 = jnp.zeros((batch.dim,), batch.labels.dtype)
        if self.mesh is not None:
            model, result = fit_data_parallel(
                self.problem, batch, w0, self.mesh, self.data_axis
            )
        else:
            model, result = self.problem.fit(batch, w0)
        return FixedEffectModel(model, self.feature_shard), result

    def score(self, model: FixedEffectModel) -> Array:
        return model.score_batch(self.batch)


@dataclasses.dataclass(frozen=True)
class RandomEffectCoordinate:
    """Per-entity GLMs over a RandomEffectDataset (SURVEY §3.5)."""

    dataset: RandomEffectDataset
    problem: GLMOptimizationProblem
    mesh: Optional[object] = None
    entity_axis: str = "data"
    global_reg_mask: Optional[Array] = None

    def train(self, offsets: Array, init: Optional[RandomEffectModel] = None):
        # Warm start is structural: same dataset -> same buckets, so the
        # previous coefficient stacks are valid initial points.
        init_coefs = init.bucket_coefs if init is not None else None
        return train_random_effects(
            self.problem, self.dataset, offsets,
            mesh=self.mesh, entity_axis=self.entity_axis,
            global_reg_mask=self.global_reg_mask,
            init_coefs=init_coefs,
        )

    def score(self, model: RandomEffectModel) -> Array:
        return model.score_dataset(self.dataset)


Coordinate = Union[FixedEffectCoordinate, RandomEffectCoordinate]
DatumScoringModel = Union[FixedEffectModel, RandomEffectModel]
