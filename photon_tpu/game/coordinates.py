"""GAME coordinates: one unit of block-coordinate descent.

Parity: reference ⟦photon-api/.../algorithm/Coordinate.scala,
FixedEffectCoordinate.scala, RandomEffectCoordinate.scala⟧ (SURVEY.md §2.2,
§3.4/§3.5). A coordinate owns its training data and optimization problem and
exposes ``train(offsets, init) -> model`` and ``score(model) -> [N]``.

TPU-first: offsets are a plain per-row array aligned with the global sample
order (fixed at dataset build time), so the reference's score-RDD joins by
``UniqueSampleId`` become elementwise adds (SURVEY.md §2.6 comm table).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

from photon_tpu.data.batch import LabeledBatch
from photon_tpu.data.random_effect import RandomEffectDataset
from photon_tpu.functions.problem import GLMOptimizationProblem
from photon_tpu.game.random_effect import (
    RandomEffectModel,
    train_random_effects,
)
from photon_tpu.models.glm import GeneralizedLinearModel
from photon_tpu.obs import trace_span, tracing_active
from photon_tpu.parallel.data_parallel import fit_data_parallel

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class FixedEffectModel:
    """Population-level GLM for one feature shard — reference
    ⟦FixedEffectModel(coefficientsBroadcast, featureShardId)⟧. Replication
    over the mesh replaces the broadcast."""

    model: GeneralizedLinearModel
    feature_shard: str

    def score_batch(self, batch: LabeledBatch) -> Array:
        """Raw per-row scores WITHOUT offsets (GAME sums coordinate scores)."""
        return batch.features.matvec(self.model.coefficients.means)


@dataclasses.dataclass(frozen=True)
class FixedEffectCoordinate:
    """Train one GLM on all rows, data-parallel over the mesh (SURVEY §3.4)."""

    batch: LabeledBatch            # offsets field ignored; passed per train()
    problem: GLMOptimizationProblem
    feature_shard: str = "global"
    mesh: Optional[object] = None
    data_axis: str = "data"
    normalization: Optional[object] = None   # NormalizationContext or None
    # When set (with a mesh that has this axis), coefficients/gradients/
    # L-BFGS history shard over it — the P3 feature-dimension path for very
    # wide feature spaces (SURVEY.md §2.6 P3).
    model_axis: Optional[str] = None

    def train(self, offsets: Array, init: Optional[FixedEffectModel] = None):
        batch = self.batch.with_offsets(offsets.astype(self.batch.labels.dtype))
        if init is not None:
            w0 = init.model.coefficients.means
        else:
            w0 = jnp.zeros((batch.dim,), batch.labels.dtype)
        with trace_span("optim.fixed_solve", cat="optim",
                        shard=self.feature_shard, rows=batch.n_rows,
                        dim=batch.dim) as sp:
            if self.mesh is not None and self.model_axis is not None:
                from photon_tpu.parallel.model_parallel import fit_model_parallel

                model, result = fit_model_parallel(
                    self.problem, batch, w0, self.mesh,
                    self.data_axis, self.model_axis,
                    normalization=self.normalization,
                )
            elif self.mesh is not None:
                model, result = fit_data_parallel(
                    self.problem, batch, w0, self.mesh, self.data_axis,
                    normalization=self.normalization,
                )
            else:
                model, result = self.problem.fit(batch, w0, normalization=self.normalization)
            if tracing_active():
                # One tiny D2H per solve, paid only when a trace is being
                # collected: iteration count + convergence reason make the
                # optimizer lane of the timeline self-describing.
                sp.set(iterations=int(result.iterations),
                       reason=result.reason_name())
        return FixedEffectModel(model, self.feature_shard), result

    def score(self, model: FixedEffectModel) -> Array:
        return model.score_batch(self.batch)


@dataclasses.dataclass(frozen=True)
class RandomEffectCoordinate:
    """Per-entity GLMs over a RandomEffectDataset (SURVEY §3.5)."""

    dataset: RandomEffectDataset
    problem: GLMOptimizationProblem
    mesh: Optional[object] = None
    # One mesh axis or a tuple (mesh.AxisSpec; e.g. ("dcn", "data")).
    entity_axis: "str | tuple" = "data"
    global_reg_mask: Optional[Array] = None
    normalization: Optional[object] = None   # shard-level NormalizationContext
    # Per-bucket PriorDistribution pytrees for incremental training
    # (RandomEffectModel.project_prior_to output).
    priors: Optional[Sequence] = None
    # Device-resident sweep cache (data/device_cache.py): host-resident
    # bucket datasets pin on device at first touch, so sweep 1+ of a
    # multi-sweep descent (train AND score) stops re-uploading per bucket.
    # The cache's mirror is identity-stable, so _same_structure keeps
    # detecting "trained on this dataset" across sweeps.
    device_cache: Optional[object] = None

    def _data(self) -> RandomEffectDataset:
        """The dataset every train/score consumes: the device-resident
        mirror when a sweep cache holds it, else the original (device-backed
        builds and budget-busted spills are both the original object)."""
        if self.device_cache is None:
            return self.dataset
        return self.device_cache.dataset_mirror(self.dataset)

    def _same_structure(self, model: RandomEffectModel) -> bool:
        # A model trained on THIS dataset (every coordinate-descent sweep)
        # shares bucket structure by object identity. Anything else — a
        # loaded model, a model from different data — must be re-projected
        # into this dataset's bucket/subspace structure.
        dataset = self._data()
        return len(model.bucket_coefs) == len(dataset.buckets) and all(
            p is b.proj for p, b in zip(model.bucket_proj, dataset.buckets)
        )

    def _init_coefs(self, init: Optional[RandomEffectModel]):
        if init is None:
            return None
        return (
            init.bucket_coefs
            if self._same_structure(init)
            else init.project_to(self._data())
        )

    def train(self, offsets: Array, init: Optional[RandomEffectModel] = None):
        return train_random_effects(
            self.problem, self._data(), offsets,
            mesh=self.mesh, entity_axis=self.entity_axis,
            global_reg_mask=self.global_reg_mask,
            init_coefs=self._init_coefs(init),
            normalization=self.normalization,
            priors=self.priors,
        )

    def score(self, model: RandomEffectModel) -> Array:
        dataset = self._data()
        if self._same_structure(model):
            return model.score_dataset(dataset)
        # Foreign model (loaded warm start / locked coordinate): project its
        # per-entity coefficients into this dataset's structure first.
        return model.score_new_dataset(dataset)


@dataclasses.dataclass(frozen=True)
class FactoredRandomEffectCoordinate:
    """Per-entity models in a learned latent space — reference
    ⟦FactoredRandomEffectCoordinate⟧ (see game/factored_random_effect.py)."""

    dataset: RandomEffectDataset
    problem: GLMOptimizationProblem
    latent_dim: int = 8
    n_alternations: int = 2
    seed: int = 0

    def train(self, offsets: Array, init=None):
        from photon_tpu.game.factored_random_effect import (
            FactoredRandomEffectModel,
            train_factored_random_effects,
        )

        # A loaded warm start arrives as the saved EFFECTIVE RandomEffectModel;
        # train_factored_random_effects re-factors it spectrally (the
        # effective matrix is exactly rank-p, so the SVD recovers the saved
        # factorization's subspace).
        if not isinstance(init, (FactoredRandomEffectModel, RandomEffectModel)):
            init = None
        return train_factored_random_effects(
            self.problem, self.dataset, offsets,
            latent_dim=self.latent_dim,
            n_alternations=self.n_alternations,
            seed=self.seed,
            init=init,
        )

    def score(self, model) -> Array:
        # Score through the effective per-entity model; a foreign model
        # (loaded warm start / locked coordinate, possibly a plain
        # RandomEffectModel) goes through key-matched re-projection.
        eff = getattr(model, "effective", model)
        same = len(eff.bucket_proj) == len(self.dataset.buckets) and all(
            p is b.proj for p, b in zip(eff.bucket_proj, self.dataset.buckets)
        )
        return (
            eff.score_dataset(self.dataset)
            if same
            else eff.score_new_dataset(self.dataset)
        )


Coordinate = Union[
    FixedEffectCoordinate, RandomEffectCoordinate, FactoredRandomEffectCoordinate
]
DatumScoringModel = Union[FixedEffectModel, RandomEffectModel]
