"""Random-effect training: vmapped per-entity solves, sharded over the mesh.

Parity: reference ⟦photon-api/.../algorithm/RandomEffectCoordinate.scala⟧ +
⟦SingleNodeOptimizationProblem⟧ (SURVEY.md §3.5): thousands of independent
per-entity GLM solves. The reference runs one Breeze L-BFGS per entity inside
``mapPartitions``; here each bucket of same-shape entities is ONE
``vmap``-batched masked solve (entities converge at different iterations —
``lax.while_loop`` under vmap runs until every lane's convergence flag is
set, which is exactly the masked-convergence semantics SURVEY.md §7
hard-part #1 calls for), compiled once and sharded across chips over the
mesh's entity axis with zero communication in the inner loop (SPMD ≙ the
reference's embarrassing parallelism, without the shuffle).
"""
from __future__ import annotations

import dataclasses
import functools
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from photon_tpu.data.random_effect import EntityBucket, RandomEffectDataset
from photon_tpu.faults import fault_point
from photon_tpu.functions.problem import GLMOptimizationProblem
from photon_tpu.parallel.mesh import axes_size, batch_sharding
from photon_tpu.optim.base import OptimizerResult
from photon_tpu.types import TaskType

Array = jax.Array

# Per-bucket record of the MOST RECENT train_random_effects call:
# [{bucket, entities, entities_padded, rows, local_dim, solver,
#   h2d_seconds, solve_seconds}]. Module-level on purpose — host_resident
# streaming makes the H2D-vs-solve split the number that decides whether
# bucket streaming is overhead-bound (VERDICT r4 ask #3's "per-bucket
# H2D/solve timing"); the dress rehearsal and profiling scripts read it
# after a fit without threading a collector through the estimator stack.
# The sync-gated TIMING fields are populated only under PHOTON_RE_TIMINGS=1:
# splitting H2D from solve needs two blocking device syncs per bucket, which
# would serialize the transfer/compute overlap of every production sweep —
# the solver-choice and compile/calibration fields cost nothing and are
# always recorded (compile time is host-synchronous dispatch wall, no device
# sync needed — see obs.retrace.compile_watch).
LAST_BUCKET_TIMINGS: list = []

# Process-global routing/compile counters (obs registry → /metrics): the
# bench and rehearsal artifacts read deltas of these around a fit to report
# "fraction of RE rows on a history-free solver" and "RE compile seconds"
# without threading a collector through the estimator stack.
from photon_tpu.obs.metrics import REGISTRY as _OBS_REGISTRY  # noqa: E402

_RE_ROWS_ROUTED = _OBS_REGISTRY.counter(
    "re_rows_routed_total",
    "Random-effect row SLOTS (entities x padded rows-per-entity) dispatched "
    "per bucket solver",
)
_RE_COMPILE_SECONDS = _OBS_REGISTRY.counter(
    "re_solver_compile_seconds_total",
    "Wall seconds of RE bucket-solver dispatches that included a first-trace "
    "XLA compile (compile/solve split; obs.retrace.compile_watch)",
)
_RE_CALIBRATION_SECONDS = _OBS_REGISTRY.counter(
    "re_calibration_seconds_total",
    "Wall seconds spent in solver-routing calibration races "
    "(game/solver_routing.py)",
)


@dataclasses.dataclass(frozen=True)
class RandomEffectModel:
    """Per-entity GLMs for one random-effect coordinate.

    Parity: reference ⟦RandomEffectModel(modelsRDD: RDD[(REId, GLM)])⟧ — here
    a list of per-bucket coefficient stacks ``[E, P]`` in each entity's local
    feature subspace, plus the projection/slot structure to interpret them.
    Unseen entities score 0 (the reference's fallback to the zero model).
    """

    re_type: str
    task: TaskType
    bucket_coefs: Sequence[Array]               # per bucket: [E, P]
    bucket_proj: Sequence[Array]                # per bucket: [E, P] -> global col
    bucket_entity_ids: Sequence[Array]          # per bucket: [E] dense REId
    entity_keys: Sequence                       # dense REId -> original key
    entity_to_slot: dict                        # dense REId -> (bucket, lane)
    global_dim: int
    bucket_variances: Optional[Sequence[Array]] = None

    @property
    def n_entities(self) -> int:
        return len(self.entity_keys)

    @functools.cached_property
    def _key_to_dense(self) -> dict:
        return {k: i for i, k in enumerate(self.entity_keys)}

    def _sparse_for(
        self, entity_key, stacks: Sequence[Sequence[Array]]
    ) -> tuple[np.ndarray, list[np.ndarray]]:
        """(global_indices, [values per stack]) for one entity — one slot
        lookup and proj gather shared by means/variances export."""
        dense = self._key_to_dense.get(entity_key)
        if dense is None:
            return np.zeros(0, np.int64), [
                np.zeros(0, np.float32) for _ in stacks
            ]
        b, lane = self.entity_to_slot[dense]
        proj = np.asarray(self.bucket_proj[b][lane])
        valid = proj < self.global_dim
        return proj[valid].astype(np.int64), [
            np.asarray(s[b][lane])[valid] for s in stacks
        ]

    def coefficients_for(self, entity_key) -> tuple[np.ndarray, np.ndarray]:
        """(global_indices, values) sparse coefficient vector for one entity
        (host-side; for model export and cross-dataset scoring)."""
        gi, (gv,) = self._sparse_for(entity_key, [self.bucket_coefs])
        return gi, gv

    def variances_for(self, entity_key) -> Optional[tuple[np.ndarray, np.ndarray]]:
        """Sparse posterior variances for one entity (same index set as
        ``coefficients_for``), or None if variances were not computed."""
        if self.bucket_variances is None:
            return None
        gi, (gv,) = self._sparse_for(entity_key, [self.bucket_variances])
        return gi, gv

    def export_for(
        self, entity_key
    ) -> tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
        """(indices, means, variances-or-None) in one slot lookup — the model
        export path's per-entity gather."""
        if self.bucket_variances is None:
            gi, (gv,) = self._sparse_for(entity_key, [self.bucket_coefs])
            return gi, gv, None
        gi, (gv, vv) = self._sparse_for(
            entity_key, [self.bucket_coefs, self.bucket_variances]
        )
        return gi, gv, vv

    def score_dataset(self, dataset: RandomEffectDataset) -> Array:
        """Scores for every row of the dataset this model was trained on
        (or any dataset with identical bucket structure)."""
        per_bucket = [
            b.scores(c) for b, c in zip(dataset.buckets, self.bucket_coefs)
        ]
        return dataset.scatter_scores(per_bucket)

    def _project_stacks(
        self,
        dataset: RandomEffectDataset,
        sources: Sequence[Sequence[Array]],
        fills: Sequence[float],
    ) -> list[list[Array]]:
        """Project per-entity [E, P] stacks (aligned with this model's bucket
        structure) into ``dataset``'s local subspaces, several value sets in
        ONE pass over the entities. Host-side per-entity remap — the
        reference's model-RDD join by REId (SURVEY.md §3.6).
        Entities/columns absent from this model get the per-source fill.
        Returns one projected per-bucket list per source."""
        key_to_dense = self._key_to_dense
        old_proj = [np.asarray(p) for p in self.bucket_proj]
        old_vals = [[np.asarray(c) for c in src] for src in sources]
        out: list[list[Array]] = [[] for _ in sources]
        for b in dataset.buckets:
            proj = np.asarray(b.proj)
            eids = np.asarray(b.entity_ids)
            vals = [
                np.full(proj.shape, fill, src[0].dtype)
                for src, fill in zip(old_vals, fills)
            ]
            for lane in range(b.n_entities):
                dense_new = eids[lane]
                if dense_new < 0:
                    continue
                dense_old = key_to_dense.get(dataset.entity_keys[dense_new])
                if dense_old is None:
                    continue
                bo, lo = self.entity_to_slot[dense_old]
                pv = old_proj[bo][lo]
                valid = pv < self.global_dim
                gi = pv[valid]
                if len(gi) == 0:
                    continue
                # match new local columns against the trained sparse vector
                cols_new = proj[lane]
                pos = np.clip(np.searchsorted(gi, cols_new), 0, len(gi) - 1)
                hit = gi[pos] == cols_new
                for s, src in enumerate(old_vals):
                    gv = src[bo][lo][valid]
                    vals[s][lane][hit] = gv[pos[hit]]
            for s, v in enumerate(vals):
                out[s].append(jnp.asarray(v))
        return out

    def project_to(self, dataset: RandomEffectDataset) -> list[Array]:
        """Coefficient stacks re-projected into a *different* dataset's local
        subspaces (validation / scoring data); entities unseen at training
        time get the zero model."""
        return self._project_stacks(dataset, [self.bucket_coefs], [0.0])[0]

    def project_posteriors_to(
        self, dataset: RandomEffectDataset
    ) -> tuple[list[Array], list[Array]]:
        """(means, variances) per-bucket stacks projected into ``dataset`` in
        one entity pass — the raw material for incremental-training priors.
        Unseen entities/columns get the N(0, 1) default posterior."""
        if self.bucket_variances is not None:
            means, variances = self._project_stacks(
                dataset, [self.bucket_coefs, self.bucket_variances], [0.0, 1.0]
            )
        else:
            means = self.project_to(dataset)
            variances = [jnp.ones_like(m) for m in means]
        return means, variances

    def project_prior_to(
        self, dataset: RandomEffectDataset, incremental_weight: float = 1.0
    ) -> list:
        """Per-bucket PriorDistribution pytrees ([E, P] leaves) for
        incremental training on ``dataset`` (reference ⟦PriorDistribution⟧)."""
        from photon_tpu.functions.prior import PriorDistribution

        means, variances = self.project_posteriors_to(dataset)
        return [
            PriorDistribution.from_model(m, v, incremental_weight)
            for m, v in zip(means, variances)
        ]

    def score_new_dataset(self, dataset: RandomEffectDataset) -> Array:
        """Scores for a dataset built from different rows (e.g. validation)."""
        coef_stacks = self.project_to(dataset)
        per_bucket = [
            b.scores(c) for b, c in zip(dataset.buckets, coef_stacks)
        ]
        return dataset.scatter_scores(per_bucket)


def _pad_bucket(
    bucket: EntityBucket, multiple: int, n_rows: int, global_dim: int
) -> EntityBucket:
    """Pad the entity axis to a multiple of the mesh axis size with inert
    lanes: weight-0 rows, ghost row_ids (so no score scatters anywhere),
    ghost proj columns, and entity_id −1. Host numpy buckets stay host
    numpy (np.pad) so a subsequent SHARDED device_put streams each shard
    straight to its device instead of round-tripping through device 0."""
    e = bucket.n_entities
    r = (-e) % multiple
    if r == 0:
        return bucket

    def pad(a, fill):
        widths = [(0, r)] + [(0, 0)] * (a.ndim - 1)
        if isinstance(a, np.ndarray):
            return np.pad(a, widths, constant_values=fill)
        return jnp.pad(a, widths, constant_values=fill)

    return EntityBucket(
        idx=pad(bucket.idx, bucket.local_dim),      # local ghost column
        val=pad(bucket.val, 0),
        labels=pad(bucket.labels, 0),
        weights=pad(bucket.weights, 0),
        train_weights=pad(bucket.train_weights, 0),
        row_ids=pad(bucket.row_ids, n_rows),        # global ghost row
        proj=pad(bucket.proj, global_dim),          # global ghost column
        entity_ids=pad(bucket.entity_ids, -1),
    )


@partial(jax.jit, static_argnums=0)
def _fit_bucket_jitted(problem, batches, w0, local_mask, local_norm, local_prior):
    """One vmapped bucket solve; static problem key keeps the XLA executable
    cached across coordinate-descent sweeps (same config + bucket shapes).
    ``local_norm`` / ``local_prior`` are per-entity pytrees (leaves [E, P])
    or None."""
    from photon_tpu.obs import retrace

    retrace.note_trace("fit_bucket_vmapped")  # 1 trace == 1 XLA compile
    return jax.vmap(
        lambda b, w, m, nm, pr: problem.run(
            b, w, reg_mask=m, normalization=nm, prior=pr
        ),
        in_axes=(0, 0, 0, 0, 0),
    )(batches, w0, local_mask, local_norm, local_prior)


def _plan_desc(solver: str, chunk) -> str:
    return f"{solver}@{'full' if chunk is None else chunk}"


def _oom_next_tier(solver: str, chunk, e: int,
                   vmapped_chunkable: bool = True, multiple_of: int = 1):
    """The next-cheaper (solver, chunk) plan below ``(solver, chunk)`` for
    an E-entity bucket, or None when the degradation ladder is exhausted.
    ``chunk`` None means the full-bucket solve (effective chunk = E).

    Order (docs/robustness.md §"Memory pressure"): the SAME solver one
    blessed chunk tier down — PR 4's chunked==full equivalence keeps the
    result unchanged — until the smallest tier, then the vmapped fallback
    (chunked when the bucket outgrows the smallest blessed size), then
    nothing: an OOM below the cheapest plan is a real capacity wall.
    ``vmapped_chunkable=False`` (a per-entity normalization context is in
    play — it is NOT sliced by ``fit_bucket_in_chunks``) restricts the
    vmapped fallback to the full-bucket dispatch. ``multiple_of`` (the
    entity-axis mesh size) keeps every chunked tier mesh-divisible."""
    from photon_tpu.game.newton_re import chunk_ladder

    ladder = [c for c in chunk_ladder() if c % max(1, multiple_of) == 0]
    eff = e if chunk is None else chunk
    smaller = [c for c in ladder if c < eff]
    if solver != "vmapped_lbfgs":
        if smaller:
            return solver, max(smaller)
        if vmapped_chunkable and ladder and e > ladder[0]:
            return "vmapped_lbfgs", ladder[0]
        return "vmapped_lbfgs", None
    if smaller and vmapped_chunkable:
        return "vmapped_lbfgs", max(smaller)
    return None


def _apply_sticky_plan(plan, sticky, e: int, vmapped_chunkable: bool = True,
                       multiple_of: int = 1):
    """Clamp a static plan to the run's sticky OOM downshift (the proven-
    too-big tiers are skipped outright instead of re-OOMing per sweep).
    Under a mesh (``multiple_of`` > 1) the clamped chunk snaps DOWN to the
    nearest mesh-divisible blessed size so the sharded dispatch stays
    even; a cap below every divisible size keeps the cap verbatim only
    when it divides (else the smallest divisible tier — still cheaper per
    device than the plan that OOM'd)."""
    if not sticky:
        return plan
    solver, chunk = plan
    if sticky.get("solver"):
        solver = sticky["solver"]
    cap = sticky.get("chunk")
    if cap:
        eff = e if chunk is None else chunk
        if eff > cap:
            chunk = cap
            if multiple_of > 1 and chunk % multiple_of:
                from photon_tpu.game.newton_re import chunk_ladder

                div = [c for c in chunk_ladder()
                       if c % multiple_of == 0]
                under = [c for c in div if c <= cap]
                # No mesh-divisible blessed size at all (a device count
                # that divides no ladder entry): honor the cap with an
                # off-ladder multiple rather than degrading to None — a
                # FULL-bucket dispatch above the cap that just OOM'd would
                # invert the sticky clamp into an unbounded solve.
                chunk = (max(under) if under
                         else (min(div) if div
                               else max(multiple_of,
                                        cap - cap % multiple_of)))
    if solver == "vmapped_lbfgs" and not vmapped_chunkable:
        chunk = None
    return solver, chunk


def _solve_bucket(problem, bucket, batches, w0, local_mask, local_norm,
                  local_prior, normalization, mesh=None,
                  entity_axis="data"):
    """Pick and dispatch one bucket's solver; ``(models, result, info)``.

    Under a mesh the bucket runs ENTITY-SHARDED: full-bucket dispatches
    place every per-entity array row-sharded over ``entity_axis``, and the
    chunked Newton tiers — no longer skipped under mesh — slice blessed
    mesh-divisible chunks host-side and fan each chunk's ``device_put``
    out per shard (each device owns chunk/n lanes of every chunk), with
    chunk N+1's transfer double-buffered behind chunk N's solve. Budget
    gates price the PER-DEVICE slice, so a mesh widens what Newton admits.
    Measured routing and the OOM ladder run under the mesh too; the cost
    table keys carry the device count (``solver_routing.shape_class``).

    Smooth solves take a history-free batched Newton fast path
    (game/newton_re.py): primal dense Newton for small local dims,
    span-reduced (dual) Newton for the canonical few-rows-in-a-wide-
    subspace regime. Both replace the vmapped L-BFGS while_loop whose
    O(E·m·P) history traffic dominates the RE step (VERDICT r4 weak #3;
    measured: halving m halves the step). Same optimum, same result
    pytree; the gates fall back for L1/normalization/etc.

    A bucket whose FULL-bucket footprint busts the memory budget no longer
    surrenders straight to vmapped L-BFGS: the entity axis is sub-batched
    into blessed chunk sizes and solved through the same jitted Newton
    kernels (``fit_bucket_in_chunks``). Under ``PHOTON_RE_ROUTING=measured``
    (and no mesh — chunk slicing would break the entity-axis sharding
    contract) the static preference ladder is replaced by the measured
    cost table + calibration race in ``game/solver_routing.py``.

    ``info``: {solver, chunk, routing, compile_seconds, compile_by_solver,
    calibration_seconds, calibrated}. ``compile_seconds`` is the wall time
    of dispatches in which the retrace sentinel saw a new trace — jit
    tracing + XLA compilation run synchronously before dispatch returns,
    so this splits compile from solve without any blocking device sync
    (``obs.retrace.compile_watch``).
    """
    from photon_tpu.game import solver_routing
    from photon_tpu.game.newton_re import (
        dual_chunk_size,
        dual_eligible,
        dual_precheck,
        fit_bucket_in_chunks,
        fit_bucket_newton,
        fit_bucket_newton_dual,
        newton_chunk_size,
        newton_eligible,
        penalty_terms,
        u_max_for,
    )
    from photon_tpu.obs.retrace import compile_watch

    compile_by_solver: dict = {}

    def watched(name, fit_fn, record_fn=None):
        """Accumulate compile time of every dispatch, PER solver — under
        measured routing the calibration race compiles every candidate, and
        charging the losers' compiles to the winner's label would corrupt
        the per-solver compile split the counters exist to report.

        ``record_fn(*args)`` runs once per detected compile: it records the
        compiled signature into the AOT compile store
        (runtime/compile_store.py) so restarts and device-loss recoveries
        pre-warm the blessed kernel set instead of re-tracing cold. Not
        under a mesh — sharded avals would not replay to the same HLO."""
        def run(*args):
            with compile_watch() as cw:
                out = fit_fn(*args)
            if cw.compile_seconds:
                compile_by_solver[name] = (
                    compile_by_solver.get(name, 0.0) + cw.compile_seconds)
                if record_fn is not None:
                    record_fn(*args)
            return out
        return run

    if mesh is not None:
        rec_primal = rec_dual = rec_vmapped = None
    else:
        from photon_tpu.runtime.compile_store import record_if_active

        def rec_primal(b, w, m, pr):
            record_if_active("fit_bucket_newton", fit_bucket_newton,
                             (problem, b, w, m, pr))

        def rec_dual(b, w, m, pr):
            record_if_active("fit_bucket_newton_dual", fit_bucket_newton_dual,
                             (problem, b, w, m, pr, get_u_max()))

        def rec_vmapped(b, w, m, pr):
            record_if_active("fit_bucket_vmapped", _fit_bucket_jitted,
                             (problem, b, w, m, local_norm, pr))

    fit_primal = watched(
        "newton_primal",
        lambda b, w, m, pr: fit_bucket_newton(problem, b, w, m, pr),
        record_fn=rec_primal)
    fit_vmapped = watched(
        "vmapped_lbfgs",
        lambda b, w, m, pr: _fit_bucket_jitted(
            problem, b, w, m, local_norm, pr),
        record_fn=rec_vmapped)

    # u_max is a device reduction + blocking D2H sync per bucket — memoized
    # and computed LAZILY, so it is only paid once a bucket actually
    # consults a dual gate (a primal-routed bucket syncing here would
    # serialize the streaming loop's transfer/compute overlap for nothing).
    # The count uses the shared penalty_terms definition so the gate's
    # zeros and the dual solver's D⁺ can never disagree on which columns
    # are unpenalized.
    u_max_cell = [None]

    def get_u_max() -> int:
        if u_max_cell[0] is None:
            u_max_cell[0] = (
                u_max_for(penalty_terms(problem, local_mask, local_prior)[3])
                if dual_precheck(problem, bucket, normalization) else -1
            )
        return u_max_cell[0]

    fit_dual = watched(
        "newton_dual",
        lambda b, w, m, pr: fit_bucket_newton_dual(
            problem, b, w, m, pr, get_u_max()),
        record_fn=rec_dual)

    def finish(models, result, **info):
        info.setdefault("chunk", None)
        info.setdefault("routing", "static")
        info.setdefault("calibration_seconds", 0.0)
        info.setdefault("calibrated", False)
        info["compile_seconds"] = round(sum(compile_by_solver.values()), 3)
        info["compile_by_solver"] = {
            k: round(v, 3) for k, v in compile_by_solver.items()}
        return models, result, info

    from photon_tpu.runtime import memory_guard as _mg

    fits = {"newton_primal": fit_primal, "newton_dual": fit_dual,
            "vmapped_lbfgs": fit_vmapped}

    # Entity-axis sharding (tentpole: chunked tiers run UNDER the mesh).
    # ``place`` device_puts a pytree row-sharded over the entity axis —
    # full-bucket dispatches place once (memoized), chunked dispatches
    # place per chunk with the transfer double-buffered behind the solve.
    if mesh is not None:
        n_shards = axes_size(mesh, entity_axis)
        _sharding = batch_sharding(mesh, entity_axis)

        def place(tree):
            return jax.tree.map(
                lambda leaf: jax.device_put(leaf, _sharding), tree)
    else:
        n_shards = 1
        place = None

    if place is not None and local_norm is not None:
        # Only the full-bucket vmapped dispatch consumes the normalization
        # context (the chunked gates exclude it) — place it sharded once.
        local_norm = place(local_norm)

    _full_placed = [None]

    def full_args():
        """(batches, w0, mask, prior) for a FULL-bucket dispatch — placed
        entity-sharded once per bucket under a mesh (every ladder retry
        and the calibration race reuse the same placed arrays)."""
        if place is None:
            return batches, w0, local_mask, local_prior
        if _full_placed[0] is None:
            _full_placed[0] = place(
                (batches, w0, local_mask, local_prior))
        return _full_placed[0]

    def dispatch(solver, chunk):
        """One (solver, chunk) plan; ``chunk`` None = full bucket."""
        fit = fits[solver]
        if mesh is not None:
            # Chaos hook: error="device_lost" here simulates losing ONE
            # shard of the mesh mid-dispatch; train_random_effects
            # redistributes the bucket's entities over the surviving
            # devices instead of restarting the world.
            fault_point("re.shard", solver=solver, shards=n_shards,
                        chunk=0 if chunk is None else chunk)
        if chunk is None:
            b, w, m, pr = full_args()
            return fit(b, w, m, pr)
        return fit_bucket_in_chunks(
            fit, chunk, batches, w0, local_mask, local_prior,
            put=place, ahead=1 if place is not None else 0)

    def run_ladder(solver, chunk, downshifted=False):
        """Dispatch with the OOM degradation ladder (docs/robustness.md
        §"Memory pressure"): an ``oom``-classified failure retries at the
        next-cheaper plan — one blessed chunk tier down, then the vmapped
        fallback — bounded per run and STICKY (later buckets/sweeps start
        at the surviving tier; re-promotion only on a fresh run's cost-
        table race). Anything else propagates untouched. ``downshifted``
        starts True when the plan was sticky-clamped on entry: a degraded
        plan's first compile of a new shape class — possibly after the
        descent loop marked the kernels warm — is deliberate, not an
        alarm."""
        while True:
            try:
                # Chaos hook: error="device_oom" here drives this ladder
                # deterministically on CPU (sibling of descent.device's
                # device_lost).
                fault_point("re.solve", solver=solver,
                            chunk=0 if chunk is None else chunk)
                if downshifted:
                    # The cheaper tier may compile a shape first seen
                    # after the warm mark — deliberate, not an alarm.
                    with _retrace_mod.expected_compiles():
                        models, result = dispatch(solver, chunk)
                else:
                    models, result = dispatch(solver, chunk)
                return models, result, solver, chunk
            except Exception as err:  # noqa: BLE001 - classified below
                if not _mg.is_oom(err):
                    raise
                nxt = _oom_next_tier(solver, chunk, int(w0.shape[0]),
                                     vmapped_chunkable=local_norm is None,
                                     multiple_of=n_shards)
                before = _plan_desc(solver, chunk)
                if nxt is None:
                    _mg.journal_event(
                        "oom_exhausted", site="re.solve", cause="oom",
                        plan=before,
                        reason=f"no cheaper plan below {before}")
                    raise
                if not _mg.downshifter("re.solve").absorb(
                        err, before=before, after=_plan_desc(*nxt)):
                    raise
                solver, chunk = nxt
                _mg.set_sticky_plan("re.solve", {
                    "chunk": chunk,
                    "solver": (solver if solver == "vmapped_lbfgs"
                               else None),
                })
                downshifted = True

    from photon_tpu.obs import retrace as _retrace_mod

    sticky = _mg.sticky_plan("re.solve")

    measured_oom = None
    if (solver_routing.routing_mode() == "measured" and sticky is None):
        def sync(out):
            np.asarray(out[1].value[:1])  # tiny D2H (repo-standard sync)

        try:
            # Same chaos hook as the static ladder: an injected
            # device_oom here drives the measured-plan demotion below.
            fault_point("re.solve", routing="measured")
            models, result, info = solver_routing.solve_measured(
                problem, bucket, batches, w0, local_mask, local_prior,
                normalization, get_u_max(), fits.__getitem__, sync,
                shards=n_shards, place=place,
            )
            return finish(models, result, **info)
        except Exception as err:  # noqa: BLE001 - classified below
            if not _mg.is_oom(err):
                raise
            # The measured plan (or its calibration race) OOM'd. The
            # downshift tier is computed from the STATIC plan below — the
            # plan that will actually run next — not guessed from the
            # (unknown) measured winner, so the absorbed downshift can
            # never be a no-op or an up-shift.
            measured_oom = err

    # Static preference ladder (now expressed as a plan): full primal ->
    # full dual -> chunked primal -> chunked dual -> vmapped. Under a mesh
    # the full tiers gate on the PER-DEVICE footprint and the chunked
    # tiers pick mesh-divisible blessed sizes (each chunk itself sharded),
    # so every tier runs under the mesh instead of being skipped.
    plan = ("vmapped_lbfgs", None)
    if newton_eligible(problem, bucket, normalization, shards=n_shards):
        plan = ("newton_primal", None)
    else:
        u_max = get_u_max()
        if u_max >= 0 and dual_eligible(problem, bucket, normalization,
                                        u_max, shards=n_shards):
            plan = ("newton_dual", None)
        else:
            chunk = newton_chunk_size(problem, bucket, normalization,
                                      shards=n_shards)
            if chunk:
                plan = ("newton_primal", chunk)
            else:
                chunk = (dual_chunk_size(problem, bucket, normalization,
                                         u_max, shards=n_shards)
                         if u_max >= 0 else None)
                if chunk:
                    plan = ("newton_dual", chunk)

    clamped = _apply_sticky_plan(plan, sticky, int(w0.shape[0]),
                                 vmapped_chunkable=local_norm is None,
                                 multiple_of=n_shards)
    if measured_oom is not None:
        # Demote one tier below the static plan and make it sticky, so
        # later buckets skip the measured winner that cannot fit.
        nxt = _oom_next_tier(*clamped, int(w0.shape[0]),
                             vmapped_chunkable=local_norm is None,
                             multiple_of=n_shards)
        before = f"measured({_plan_desc(*clamped)})"
        if nxt is None:
            _mg.journal_event(
                "oom_exhausted", site="re.solve", cause="oom", plan=before,
                reason=f"no cheaper plan below {before}")
            raise measured_oom
        if not _mg.downshifter("re.solve").absorb(
                measured_oom, before=before, after=_plan_desc(*nxt)):
            raise measured_oom
        clamped = nxt
        _mg.set_sticky_plan("re.solve", {
            "chunk": clamped[1],
            "solver": (clamped[0] if clamped[0] == "vmapped_lbfgs"
                       else None),
        })
    models, result, solver, chunk = run_ladder(
        *clamped, downshifted=clamped != plan)
    return finish(models, result, solver=solver, chunk=chunk)


# ------------------------------------------------------ shard-loss recovery

_RE_SHARD_LOSSES = _OBS_REGISTRY.counter(
    "re_shard_losses_total",
    "Mesh shards lost mid-RE-solve and absorbed by entity redistribution "
    "(docs/robustness.md §shard loss)",
)


def _alive_devices(devices, want: int):
    """The first ``want`` devices that answer a trivial device_put probe —
    after a real shard loss the dead device must not land in the degraded
    mesh. Cheap (one tiny put + D2H fetch per device, stops at ``want``).
    The fetch IS the sync: the repo-standard tiny D2H read, because
    ``block_until_ready`` does not synchronize on the axon tunnel backend
    and would let a dead device pass the probe."""
    alive = []
    for d in devices:
        try:
            np.asarray(jax.device_put(np.zeros((1,), np.float32), d))
            alive.append(d)
        except Exception:  # noqa: BLE001 - a dead device is the point
            continue
        if len(alive) >= want:
            break
    return alive


def _degrade_mesh(mesh, entity_axis):
    """The next-smaller entity mesh after a shard loss, or None when no
    degradation exists (single device). The surviving size is the LARGEST
    PROPER DIVISOR of the current axis size (8 → 4): the already-padded
    entity axes and the blessed pow-2 chunk ladder stay evenly divisible,
    so the redistributed re-solve reuses the same chunk contract. The
    choice is STICKY for the run (``memory_guard`` sticky plan ``re.shard``)
    — later buckets and sweeps start degraded instead of re-failing."""
    from photon_tpu.parallel.mesh import axes_size as _axes_size
    from photon_tpu.parallel.mesh import axis_tuple, make_mesh
    from photon_tpu.runtime import memory_guard as _mg

    n = _axes_size(mesh, entity_axis)
    if n <= 1:
        return None
    m = next(n // k for k in range(2, n + 1) if n % k == 0)
    devices = list(np.asarray(mesh.devices).flat)
    alive = _alive_devices(devices, m)
    if len(alive) < m:
        return None  # not enough survivors for an even degraded mesh
    axis = axis_tuple(entity_axis)[-1]
    _mg.set_sticky_plan("re.shard", {"shards": m})
    return make_mesh({axis: m}, devices=alive), axis


def _effective_mesh(mesh, entity_axis):
    """Apply the run's sticky shard degradation (a shard lost earlier in
    this run) to a caller-supplied mesh before any solve dispatches."""
    from photon_tpu.parallel.mesh import axes_size as _axes_size
    from photon_tpu.parallel.mesh import axis_tuple, make_mesh
    from photon_tpu.runtime import memory_guard as _mg

    sticky = _mg.sticky_plan("re.shard")
    if not sticky:
        return mesh, entity_axis
    m = int(sticky.get("shards") or 0)
    n = _axes_size(mesh, entity_axis)
    if m <= 0 or m >= n:
        return mesh, entity_axis
    devices = list(np.asarray(mesh.devices).flat)
    alive = _alive_devices(devices, m)
    if len(alive) < m:
        return mesh, entity_axis
    axis = axis_tuple(entity_axis)[-1]
    return make_mesh({axis: m}, devices=alive), axis


def _shard_lost_recover(err, **ctx) -> None:
    """One absorbed shard loss: classified recovery-journal row (via the
    supervisor-registered journal when one is active, else the trace
    instant), metric bump, and the shared device-loss recovery step
    (executable-cache purge + sweep-cache release + compile-store prewarm
    — ``backend_guard.recover_from_device_loss``)."""
    import logging

    from photon_tpu.runtime import backend_guard as _bg
    from photon_tpu.runtime import memory_guard as _mg

    log = logging.getLogger("photon_tpu.game")
    cause = _bg.classify_backend_error(err)
    _RE_SHARD_LOSSES.inc()
    _mg.journal_event(
        "shard_lost", site="re.shard", cause=cause,
        error=f"{type(err).__name__}: {str(err)[:200]}", **ctx)
    log.warning(
        "mesh shard lost mid-RE-solve (%s: %s) — redistributing bucket %s "
        "entities over %s devices (recovery %s)", type(err).__name__, err,
        ctx.get("bucket"), ctx.get("devices_after"), ctx.get("recovery"))
    _bg.recover_from_device_loss(
        f"re shard loss (bucket {ctx.get('bucket')})", logger=log)


def train_random_effects(
    problem: GLMOptimizationProblem,
    dataset: RandomEffectDataset,
    offsets: Array,
    mesh=None,
    entity_axis="data",  # one mesh axis or a tuple (mesh.AxisSpec),
                         # e.g. ("dcn", "data") on a multi-slice mesh
    global_reg_mask: Optional[Array] = None,
    init_coefs: Optional[Sequence[Array]] = None,
    normalization=None,
    priors: Optional[Sequence] = None,
) -> tuple[RandomEffectModel, list[OptimizerResult]]:
    """Fit one GLM per entity; returns the model + per-bucket solver results.

    ``offsets`` is the global per-sample residual score from the other GAME
    coordinates (reference: dataset offsets updated by CoordinateDescent).
    ``global_reg_mask`` (e.g. 0 on the intercept column) is projected into
    each entity's local subspace, as is the shard-level ``normalization``
    context (reference: one NormalizationContext per feature shard applies to
    every per-entity solve too). ``priors`` is an optional per-bucket list of
    PriorDistribution pytrees ([E, P] leaves — see
    ``RandomEffectModel.project_prior_to``) for incremental training.
    """
    from photon_tpu.data.normalization import project_context

    import os as _os
    import time as _time

    coefs_out, var_out, results = [], [], []
    want_var = problem.variance_type.name != "NONE"
    LAST_BUCKET_TIMINGS.clear()
    _want_timings = _os.environ.get("PHOTON_RE_TIMINGS") == "1"

    # A shard lost earlier in this run degraded the mesh stickily; apply it
    # before any placement so this call never re-discovers the dead device.
    if mesh is not None:
        mesh, entity_axis = _effective_mesh(mesh, entity_axis)
    shard_recoveries = 0

    for b_i, bucket in enumerate(dataset.buckets):
        orig_e = bucket.n_entities
        _t_start = _time.perf_counter()
        if mesh is not None:
            axis_size = axes_size(mesh, entity_axis)
            bucket = _pad_bucket(bucket, axis_size, dataset.n_rows, dataset.global_dim)

        p = bucket.local_dim
        e = bucket.n_entities
        if init_coefs is not None:
            w0 = jnp.asarray(init_coefs[b_i], bucket.val.dtype)
            if w0.shape[0] < e:  # mesh padding added inert lanes
                w0 = jnp.pad(w0, ((0, e - w0.shape[0]), (0, 0)))
        else:
            w0 = jnp.zeros((e, p), bucket.val.dtype)

        # Project the global regularization mask into each local subspace.
        # Ghost slots get mask 1 (their coefficients stay 0 regardless).
        if global_reg_mask is not None:
            ext = jnp.concatenate(
                [global_reg_mask.astype(bucket.val.dtype), jnp.ones((1,), bucket.val.dtype)]
            )
            local_mask = ext[bucket.proj]
        else:
            local_mask = jnp.ones((e, p), bucket.val.dtype)

        batches = bucket.local_batches(offsets)
        local_norm = (
            project_context(normalization, bucket.proj, dataset.global_dim)
            if normalization is not None
            else None
        )
        local_prior = priors[b_i] if priors is not None else None
        if local_prior is not None and local_prior.means.shape[0] < e:
            # mesh padding added inert lanes: extend with zero-precision rows
            pad = e - local_prior.means.shape[0]
            local_prior = jax.tree.map(
                lambda a: jnp.pad(a, ((0, pad), (0, 0))), local_prior
            )

        # Placement now happens INSIDE _solve_bucket (full-bucket plans
        # place once; chunked plans slice host-side and fan each chunk's
        # device_put out per shard with the transfer double-buffered).

        # H2D boundary: with host_resident buckets the arrays above are
        # still host numpy; under PHOTON_RE_TIMINGS=1 force the transfer
        # here (tiny D2H fetch as the sync — block_until_ready does not
        # synchronize on the axon tunnel backend) to split per-bucket time
        # into transfer vs solve. NOT default: the two syncs per bucket
        # would serialize the async dispatcher's transfer/compute overlap.
        # Mesh runs skip it: committing to the default device here would
        # double-transfer everything the sharded placement re-puts.
        if _want_timings and mesh is None:
            batches = jax.tree.map(jnp.asarray, batches)
            np.asarray(batches.features.val.ravel()[:1])
        _t_h2d = _time.perf_counter()

        from photon_tpu.obs import trace_span as _trace_span

        re_span = _trace_span(
            "optim.re_bucket", cat="optim", bucket=b_i, entities=orig_e,
            local_dim=p,
        ).__enter__()
        info = {"solver": None}
        # Span closes on dispatch, not completed compute (the async
        # dispatcher overlaps buckets on purpose); descent's step-level
        # D2H sync bounds the whole step. Explicit except (not
        # finally+exc_info, which could pick up an unrelated exception a
        # caller is mid-handling) so a failing bucket lands in the
        # timeline error-tagged and a clean one never does.
        while True:
            try:
                models, result, info = _solve_bucket(
                    problem, bucket, batches, w0, local_mask, local_norm,
                    local_prior, normalization, mesh=mesh,
                    entity_axis=entity_axis,
                )
                break
            except KeyboardInterrupt:
                raise  # a user abort is never a shard loss
            except BaseException as _err:
                # Single-shard device loss under a mesh (docs/robustness.md
                # §"Shard loss"): redistribute this bucket's entities over
                # the surviving devices and re-solve — don't restart the
                # world. Anything else (or an exhausted recovery budget)
                # propagates with the span error-tagged.
                from photon_tpu.runtime import backend_guard as _bg

                degraded = (
                    _degrade_mesh(mesh, entity_axis)
                    if (mesh is not None and _bg.is_device_lost(_err)
                        and shard_recoveries < _bg.max_inrun_recoveries())
                    else None
                )
                rehosted = None
                if degraded is not None:
                    # The retry must not read solve inputs sharded over
                    # the OLD mesh (a cache-mirror bucket has a shard ON
                    # the dead device): pull everything to host numpy
                    # first. If the pull itself fails, the source data
                    # died with the device — the bucket is unrecoverable
                    # in-process, so escalate to the caller's checkpoint-
                    # based recovery (descent re-enters from the host
                    # originals) instead of burning the recovery budget
                    # on re-reads that can never succeed.
                    try:
                        rehosted = jax.tree.map(
                            np.asarray,
                            (bucket, batches, w0, local_mask, local_prior),
                        )
                    except Exception:  # noqa: BLE001 - data lost with device
                        degraded = None
                if degraded is None:
                    import sys as _sys

                    re_span.set(solver=info["solver"]).__exit__(
                        *_sys.exc_info())
                    raise
                bucket, batches, w0, local_mask, local_prior = rehosted
                shard_recoveries += 1
                old_n = axes_size(mesh, entity_axis)
                mesh, entity_axis = degraded
                _shard_lost_recover(
                    _err, bucket=b_i, coordinate=dataset.re_type,
                    entities=orig_e, devices_before=old_n,
                    devices_after=axes_size(mesh, entity_axis),
                    recovery=shard_recoveries,
                )
        # Compile/solve split on the span (VERDICT r5 weak #6: decision-
        # grade artifacts need first-call XLA compile separated out).
        re_span.set(
            solver=info["solver"], chunk=info["chunk"],
            routing=info["routing"],
            compile_seconds=info["compile_seconds"],
            calibration_seconds=info["calibration_seconds"],
        ).__exit__(None, None, None)
        _RE_ROWS_ROUTED.inc(int(bucket.max_samples) * orig_e,
                            solver=info["solver"])
        # Per-solver attribution: under measured routing the calibration
        # race compiles every candidate — the losers' compiles must land on
        # their own labels, not the winner's.
        for _cs_solver, _cs in info.get("compile_by_solver", {}).items():
            _RE_COMPILE_SECONDS.inc(_cs, solver=_cs_solver)
        if info["calibration_seconds"]:
            _RE_CALIBRATION_SECONDS.inc(info["calibration_seconds"])
        coefs_out.append(models.coefficients.means[:orig_e])
        if want_var:
            var_out.append(models.coefficients.variances[:orig_e])
        results.append(jax.tree.map(lambda a: a[:orig_e], result))
        if _want_timings:
            np.asarray(coefs_out[-1][:1])  # completed-solve sync
        _t_solve = _time.perf_counter()
        LAST_BUCKET_TIMINGS.append({
            "bucket": b_i,
            "entities": orig_e,
            "entities_padded": e,
            # SLOTS, not rows: [E, S] includes per-entity padding (weight-0
            # rows). The true row count needs a reduction over weights, so
            # it is computed only in sync-gated timing mode.
            "row_slots": int(bucket.max_samples) * orig_e,
            "rows": (
                int(float(jnp.sum(bucket.weights[:orig_e] > 0)))
                if _want_timings else None
            ),
            "local_dim": p,
            "solver": info["solver"],
            "chunk": info["chunk"],
            "routing": info["routing"],
            # Compile + calibration walls need NO sync gate: jit tracing +
            # XLA compilation are host-synchronous before dispatch returns
            # (obs.retrace.compile_watch), and calibration probes sync
            # internally — so the split is always recorded.
            "compile_seconds": info["compile_seconds"],
            "compile_by_solver": info.get("compile_by_solver", {}),
            "calibration_seconds": info["calibration_seconds"],
            "calibrated": info["calibrated"],
            # Without the sync gate these splits would time async dispatch,
            # not work — record them only when they mean something.
            # ``solve_seconds`` is EXECUTION-only: the sync-gated wall minus
            # the compile + calibration time measured above (BENCH schema
            # note in docs/scaling.md).
            "h2d_seconds": round(_t_h2d - _t_start, 3)
            if _want_timings else None,
            "solve_seconds": round(
                max(0.0, (_t_solve - _t_h2d) - info["compile_seconds"]
                    - info["calibration_seconds"]), 3)
            if _want_timings else None,
        })

    model = RandomEffectModel(
        re_type=dataset.re_type,
        task=problem.task,
        bucket_coefs=coefs_out,
        bucket_proj=[b.proj for b in dataset.buckets],
        bucket_entity_ids=[b.entity_ids for b in dataset.buckets],
        entity_keys=dataset.entity_keys,
        entity_to_slot=dataset.entity_to_slot,
        global_dim=dataset.global_dim,
        bucket_variances=var_out if want_var else None,
    )
    return model, results
