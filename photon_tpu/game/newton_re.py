"""Batched Newton solvers for random-effect buckets (primal and dual).

Why: the general RE path (``game/random_effect.py``) vmaps the full L-BFGS
``lax.while_loop`` over entities. Profiled at the ``game_scale`` bench
shape (100K users × 16 rows × 256-wide local subspaces, CPU), the dominant
cost is the O(E·m·P) L-BFGS HISTORY traffic — the [E, m, P] s/y stacks the
two-loop recursion reads and ``update_history`` rewrites every iteration
(measured: halving m halves the step; data passes are few and cheap).
Quasi-Newton memory is exactly the wrong data structure for a hundred
thousand tiny coupled solves.

Two history-free replacements, picked per bucket by shape:

* **Primal dense Newton** (``fit_bucket_newton``), for small local dims
  (P ≤ 64): the per-entity Hessian is [P, P], assembled as ONE batched
  einsum ``es,esp,esq->epq`` — an MXU-shaped contraction, no per-lane
  control flow — and solved as a batched factorization.
* **Span-reduced (dual) Newton** (``fit_bucket_newton_dual``), for the
  canonical RE regime of FEW ROWS in a WIDE subspace (S ≪ P, e.g. 16 rows
  × 256 features): for an L2/Gaussian-prior objective the stationarity
  condition ``D·w = −Xᵀ(tw·ℓ') + q`` puts the penalized coordinates of
  the optimum in the row span scaled by D⁻¹ (D = λ·mask + prior
  precision, q = precision·prior-mean). Parametrize
  ``w = D⁺(Xᵀα + q) + Σ_u β_u e_u`` (β for the ≤U unpenalized columns,
  typically just the intercept) and the whole solve lives in S+U ≈ 17
  dimensions: margins are LINEAR in θ=(α,β) via the Gram matrix
  G = X D⁺ Xᵀ [S,S], the penalty collapses to ½αᵀGα (+ a constant), and
  each Newton system is (S+U)². G builds once per solve as one batched
  einsum; iterations cost O(E·S³) instead of O(E·m·P) memory traffic.

Both paths share one damped-Newton driver (``_newton_loop``): ridge-damped
batched solves, steepest-descent fallback, and a vectorized line search —
ALL backtracking steps evaluate in one [L, E] pass over resident margins,
so no lane ever stalls another (the masked-divergence cost class of
vmapped while_loops is gone). Convergence is quadratic: ~5 Newton
iterations replace 15+ L-BFGS iterations. All four pointwise losses ship
analytic d2 (``ops/losses.py``), the L2 term and Gaussian priors are
quadratic (exact in the Hessian), and SIMPLE variances derive from the
primal Hessian diagonal — same formulas as
``GLMOptimizationProblem._variances``.

Scope (the eligibility gates in ``train_random_effects``): smooth
objectives only (no L1/OWL-QN — the orthant machinery needs its own
treatment), no normalization context, dense buffers within
``PHOTON_RE_NEWTON_BUDGET_MB``. Everything else falls back to the general
vmapped path; ``PHOTON_RE_NEWTON=0`` forces the fallback.

**Entity sub-batching** (``fit_bucket_in_chunks``): the per-entity solves
are embarrassingly parallel over the entity axis, so a bucket whose
``[E,P]``/``[E,S]`` probe footprint exceeds the budget gate no longer
surrenders to the vmapped L-BFGS fallback — it is split into entity chunks
drawn from a small CLOSED ladder of blessed sizes (``chunk_ladder()``),
each chunk solved through the same jitted kernel (one XLA compile per
ladder size, so the retrace sentinel stays quiet across sweeps), and the
results restacked. The last partial chunk is padded with inert lanes
(weight-0 rows, ghost columns, mask 1, precision-0 priors) — the same
convention as ``_pad_bucket`` — so chunking never adds compiled shapes
beyond the ladder. Chunking also *decouples convergence*: each chunk's
``while_loop`` stops when ITS slowest lane converges, instead of every
entity in the bucket iterating until the bucket-wide straggler is done.

**CPU/TPU kernel shape discipline**: the hot contractions (Gram build,
Hessian assembly) are written as explicit batched ``matmul``s over
``optimization_barrier``-materialized operands. Measured on the CPU
backend at the ``game_scale`` bench shape ([100K,16,256]): letting XLA
fuse the scatter/scale producers into the dot turns a 1.4 s batched GEMM
into a 9 s fused loop — the barrier forces the operands into contiguous
buffers the fast GEMM path can consume. Newton systems are solved via
batched Cholesky (the damped Hessian is symmetric PD by construction),
which halves the per-iteration factorization cost vs generic LU.

Parity: reference ⟦RandomEffectCoordinate.scala⟧ + ⟦SingleNodeOptimizationProblem⟧
(SURVEY.md §3.5) run one Breeze L-BFGS per entity; these solvers reach the
same optimum of the same objective, re-shaped for a batched accelerator.
"""
from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.models.coefficients import Coefficients
from photon_tpu.models.glm import GeneralizedLinearModel
from photon_tpu.ops.losses import loss_for_task
from photon_tpu.optim.base import (
    FUNCTION_VALUES_CONVERGED,
    NOT_CONVERGED,
    OptimizerResult,
    check_convergence,
    finalize_reason,
)

Array = jax.Array

NEWTON_MAX_P = 64           # [P,P] solves stay tiny; beyond this, fall back
                            # (documented gate: module doc, docs/scaling.md,
                            # docs/round5.md all say P <= 64 — keep in sync)
NEWTON_CHUNK_MAX_P = 128    # wider P admitted for CHUNKED primal candidates
                            # under MEASURED routing only — at P in (64,128]
                            # the dense Hessian may or may not beat L-BFGS
                            # depending on S, so the calibration race (not a
                            # static gate) decides (game/solver_routing.py)
DUAL_MAX_T = 80  # S + U cap; beyond this the (S+U)^2 systems stop being tiny
_DEFAULT_BUDGET_MB = 2048   # dense X + H + probe buffers cap

# Blessed entity-chunk sizes for sub-batched solves. A CLOSED set on
# purpose: every chunked solve compiles at one of these sizes (last chunk
# padded up), so the number of XLA executables per (solver, S, P, dtype)
# class is bounded by the ladder length and the retrace sentinel stays
# quiet across sweeps. Override: PHOTON_RE_CHUNK_LADDER=256,1024,...
_DEFAULT_CHUNK_LADDER = (256, 1024, 4096, 16384)


def chunk_ladder() -> tuple:
    raw = os.environ.get("PHOTON_RE_CHUNK_LADDER", "")
    if raw:
        sizes = tuple(sorted({int(x) for x in raw.split(",") if x.strip()}))
        if not sizes or min(sizes) < 1:
            raise ValueError(
                f"PHOTON_RE_CHUNK_LADDER must be positive ints, got {raw!r}"
            )
        return sizes
    return _DEFAULT_CHUNK_LADDER


def _budget_bytes() -> float:
    return float(os.environ.get("PHOTON_RE_NEWTON_BUDGET_MB",
                                _DEFAULT_BUDGET_MB)) * 1e6


def _smooth_ok(problem, normalization) -> bool:
    if os.environ.get("PHOTON_RE_NEWTON", "") == "0":
        return False
    from photon_tpu.optim import OptimizerType

    if problem.optimizer_type not in (OptimizerType.LBFGS,
                                      OptimizerType.TRON):
        return False  # OWL-QN/L1: non-smooth, orthant semantics
    if problem.regularization.l1_weight(float(problem.reg_weight)) > 0.0:
        return False
    return normalization is None


def penalty_terms(problem, local_mask, local_prior, dtype=jnp.float32):
    """``(l2v, pm, pp, d_pen)`` in ``dtype`` — the quadratic-penalty pieces
    BOTH solvers and the eligibility gate derive everything from. ONE
    definition on purpose: the u_max gate counts ``d_pen <= 0`` and the dual
    solver inverts ``d_pen > 0`` — computed anywhere else (other dtype, other
    threshold) a divergence would silently pin a coefficient to zero. The
    gate's zero-count is dtype-insensitive (masks and λ are exact in f32),
    so callers may pass any float dtype without moving the threshold."""
    lam = problem.regularization.l2_weight(float(problem.reg_weight))
    l2v = lam * local_mask.astype(dtype)
    if local_prior is not None:
        pm = local_prior.means.astype(dtype)
        pp = local_prior.precisions.astype(dtype)
    else:
        pm = jnp.zeros_like(l2v)
        pp = jnp.zeros_like(l2v)
    return l2v, pm, pp, l2v + pp


def u_max_for(d_pen) -> int:
    """Worst-per-entity count of UNPENALIZED columns (d_pen == 0) that the
    dual path must carry as explicit β parameters — typically 1 (the
    reg-masked intercept). Static for jit."""
    return int(jnp.max(jnp.sum(d_pen <= 0.0, axis=1)))


def _primal_need_bytes(e: int, s: int, p: int, esize: float) -> float:
    """Dominant dense buffers of an E-entity primal solve (in the data
    dtype): X [E,S,P+1], H [E,P,P], and the probe batch's [L,E,S] margins +
    [L,E,S] loss temporary + [L,E,P] trial parameters (L capped at 12)."""
    return esize * (e * s * (p + 1) + e * p * p + 12 * e * (2 * s + p))


def _dual_need_bytes(e: int, s: int, p: int, u: int, esize: float) -> float:
    """Dominant dense buffers of an E-entity dual solve: dense X [E,S,P+1]
    + G/J [E,S,S+U] + the probe batch's [12,E,S] margins + [12,E,S] loss
    temporary + [12,E,S+U] trial parameters. Dense X dominates at wide P."""
    return esize * (e * s * (p + 1) + 2 * e * s * (s + u)
                    + 12 * e * (2 * s + s + u))


def newton_eligible(problem, bucket, normalization, shards: int = 1) -> bool:
    """True when this bucket's solve may take the PRIMAL dense-Newton path.

    ``shards`` is the entity-axis mesh size: a sharded dispatch places
    E/shards lanes per device, so the budget gate prices the PER-DEVICE
    footprint — a bucket too big for one device's budget can still run
    full-bucket across the mesh (the gates get MORE permissive, exactly
    the reference's "add executors" scaling axis)."""
    if os.environ.get("PHOTON_RE_NEWTON", "") == "dual":
        return False  # test/debug override: route to the dual path
    if not _smooth_ok(problem, normalization):
        return False
    e, s, _ = bucket.idx.shape
    p = bucket.local_dim
    if p > NEWTON_MAX_P:
        return False
    esize = float(np.dtype(bucket.val.dtype).itemsize)
    e_dev = -(-e // max(1, shards))
    return _primal_need_bytes(e_dev, s, p, esize) <= _budget_bytes()


def _largest_fitting_chunk(need_at, e: int, multiple_of: int = 1):
    """Best blessed chunk size for an E-entity bucket, or None when even
    the smallest ladder size busts the budget. Padding lanes do FULL
    solver work, so a 2000-entity bucket should solve as 2x1024, not one
    4096-padded chunk — but shaving the last few padding percent is not
    worth an order of magnitude more dispatches (100K entities at chunk
    256 is 391 kernel calls). Rule: the LARGEST budget-fitting size whose
    total padded lanes ``ceil(E/C)*C`` stay within 12.5% of E; if none
    qualifies (tiny buckets), the size minimizing padded lanes.
    ``multiple_of`` (the entity-axis mesh size) filters the ladder to
    sizes that shard evenly — a chunk that doesn't divide over the mesh
    would leave devices with ragged lanes and re-lay the sharding out."""
    budget = _budget_bytes()
    fitting = []
    for c in chunk_ladder():
        if need_at(c) > budget:
            break  # ladder is sorted: larger sizes only need more
        if c % multiple_of:
            continue
        fitting.append(c)
        if c >= e:
            break  # larger sizes only add padding
    if not fitting:
        return None
    for c in reversed(fitting):
        if -(-e // c) * c <= e + (e >> 3):
            return c
    return min(fitting, key=lambda c: (-(-e // c) * c, -c))


def newton_chunk_size(problem, bucket, normalization,
                      max_p: int = NEWTON_MAX_P, shards: int = 1):
    """Blessed chunk size for an entity-sub-batched PRIMAL solve of this
    bucket, or None when the primal path is shape-excluded or even the
    smallest chunk busts the budget. ``max_p`` lets MEASURED routing admit
    wider subspaces (NEWTON_CHUNK_MAX_P) than the static gate. ``shards``
    > 1 prices the per-device slice of each sharded chunk and restricts
    the ladder to mesh-divisible sizes."""
    if os.environ.get("PHOTON_RE_NEWTON", "") == "dual":
        return None
    if not _smooth_ok(problem, normalization):
        return None
    e, s, _ = bucket.idx.shape
    p = bucket.local_dim
    if p > max_p:
        return None
    esize = float(np.dtype(bucket.val.dtype).itemsize)
    sh = max(1, shards)
    return _largest_fitting_chunk(
        lambda c: _primal_need_bytes(-(-c // sh), s, p, esize), e,
        multiple_of=sh)


def dual_chunk_size(problem, bucket, normalization, u_max: int,
                    shards: int = 1):
    """Blessed chunk size for an entity-sub-batched DUAL solve, or None."""
    if not dual_precheck(problem, bucket, normalization):
        return None
    e, s, _ = bucket.idx.shape
    p = bucket.local_dim
    if s + u_max > DUAL_MAX_T:
        return None
    esize = float(np.dtype(bucket.val.dtype).itemsize)
    sh = max(1, shards)
    return _largest_fitting_chunk(
        lambda c: _dual_need_bytes(-(-c // sh), s, p, u_max, esize), e,
        multiple_of=sh)


def dual_precheck(problem, bucket, normalization) -> bool:
    """The CHEAP dual-path gates — everything that does not need u_max.
    Callers check this FIRST: computing u_max is a device reduction + D2H
    sync per bucket, and paying it for a bucket that can never take the
    dual path (L1 run, wide rows, FULL variance) would serialize the
    streaming loop's transfer/compute overlap for nothing."""
    if not _smooth_ok(problem, normalization):
        return False
    from photon_tpu.functions.problem import VarianceComputationType

    if problem.variance_type == VarianceComputationType.FULL:
        return False  # diag(H^-1) needs the [P,P] primal Hessian
    _, s, _ = bucket.idx.shape
    p = bucket.local_dim
    # s+0 lower-bounds s+u_max, so this never rejects an eligible bucket.
    return s < p and s <= DUAL_MAX_T


def dual_eligible(problem, bucket, normalization, u_max: int,
                  shards: int = 1) -> bool:
    """True when this bucket may take the span-reduced Newton path.
    ``shards`` prices the per-device slice (see ``newton_eligible``)."""
    if not dual_precheck(problem, bucket, normalization):
        return False
    e, s, _ = bucket.idx.shape
    p = bucket.local_dim
    if s + u_max > DUAL_MAX_T:
        return False
    esize = float(np.dtype(bucket.val.dtype).itemsize)
    e_dev = -(-e // max(1, shards))
    return _dual_need_bytes(e_dev, s, p, u_max, esize) <= _budget_bytes()


def _dense_design(batches, dtype):
    """Dense local design [E,S,P+1] via scatter-add — the ELL ghost column
    (== P) lands in the extra zero column. ONE buffer replaces per-probe
    ELL gathers for the whole solve. Also returns (y, off, tw) in ``dtype``
    (the solve precision — f64 datasets keep full precision, ADVICE r5)."""
    idx = batches.features.idx
    val = batches.features.val.astype(dtype)
    e, s, _ = idx.shape
    p = batches.features.dim
    ei = jnp.arange(e)[:, None, None]
    si = jnp.arange(s)[None, :, None]
    x_ext = jnp.zeros((e, s, p + 1), dtype).at[ei, si, idx].add(val)
    # Materialization boundary: without it XLA fuses the scatter into every
    # downstream dot, and the batched GEMMs degrade to a scalar loop
    # (measured 6x slower at the game_scale shape on CPU — module doc).
    x_ext = jax.lax.optimization_barrier(x_ext)
    return (
        x_ext,
        batches.labels.astype(dtype),
        batches.offsets.astype(dtype),
        batches.weights.astype(dtype),
    )


def _newton_loop(x0, z0, cfg, value_at, grad_at, hess_at, lin_map,
                 probe_values, ridge):
    """Shared damped-Newton driver over a batch of independent lanes.

    ``x0`` [E,T] parameters, ``z0`` [E,S] resident margins. Closures:
    ``value_at(x, z) -> [E]``, ``grad_at(x, z) -> [E,T]``,
    ``hess_at(x, z) -> [E,T,T]``, ``lin_map(d) -> [E,S]`` (margin delta of
    a parameter direction — margins are linear in the parameters on both
    paths), ``probe_values(x, z, d, zd, ts) -> [L,E]`` (objective at every
    backtracking step in one vectorized pass). ``ridge`` scales the
    trace-relative jitter that keeps the batched factorization PD on
    degenerate lanes (all-zero padded entities; dual G nullspace).

    Returns ``(x, z, f, g, reason, it, values, gnorms, passes, iters)``
    with the same per-lane bookkeeping conventions as the vmapped L-BFGS
    path (inf-filled trajectory tails, accepted-step iteration counts).
    """
    e, t_dim = x0.shape
    dt = x0.dtype
    max_it = cfg.max_iterations
    # 12 vectorized backtracking probes reach t = 2^-11 ≈ 5e-4 — below
    # that a damped-Newton step on a smooth convex objective is noise.
    n_probe = min(cfg.max_line_search_iterations, 12)
    ts = 0.5 ** jnp.arange(n_probe, dtype=dt)
    eye = jnp.eye(t_dim, dtype=dt)
    c1 = 1e-4

    f = value_at(x0, z0)
    g = grad_at(x0, z0)
    gnorm0 = jnp.linalg.norm(g, axis=1)
    values = jnp.full((e, max_it + 1), jnp.inf, dt).at[:, 0].set(f)
    gnorms = jnp.full((e, max_it + 1), jnp.inf, dt).at[:, 0].set(gnorm0)

    state = (
        x0, z0, f, g,
        jnp.full((e,), NOT_CONVERGED, jnp.int32),          # reason
        jnp.asarray(0, jnp.int32),                         # it (loop)
        values, gnorms,
        jnp.full((e,), 2, jnp.int32),                      # passes
        jnp.zeros((e,), jnp.int32),                        # per-lane steps
    )

    def cond(st):
        _, _, _, _, reason, it, *_ = st
        return jnp.any(reason == NOT_CONVERGED) & (it < max_it)

    def body(st):
        x, z, f, g, reason, it, values, gnorms, passes, iters = st
        active = reason == NOT_CONVERGED

        h = hess_at(x, z)
        scale = 1.0 + jax.vmap(jnp.trace)(h) / t_dim
        h_damped = h + (ridge * scale)[:, None, None] * eye
        # The damped Hessian is symmetric PD by construction, so a batched
        # Cholesky halves the factorization cost vs generic LU (measured
        # 2x on the [E,17,17] dual systems, CPU backend). Under --debug-nans
        # take LU instead: a lane whose Hessian lost PD to rounding makes
        # Cholesky EMIT NaN by design (caught by the fallback below), which
        # debug_nans would escalate to FloatingPointError on an otherwise
        # healthy run — LU returns a finite non-descent direction the same
        # guard handles. Trace-time read: the flag is process-static.
        if jax.config.jax_debug_nans:
            d = -jnp.linalg.solve(h_damped, g[..., None])[..., 0]
        else:
            chol = jnp.linalg.cholesky(h_damped)
            d = -jax.scipy.linalg.cho_solve(
                (chol, True), g[..., None])[..., 0]
        dg = jnp.sum(d * g, axis=1)
        # H is PD(+ridge) so d is descent; a numerically non-descent lane —
        # including a failed factorization (NaN Cholesky of a lane whose
        # Hessian lost PD to rounding) — falls back to steepest descent
        # (mirrors the L-BFGS restart rule).
        bad = (dg >= 0.0) | ~jnp.isfinite(dg)
        d = jnp.where(bad[:, None], -g, d)
        dg = jnp.where(bad, -jnp.sum(g * g, axis=1), dg)

        zd = lin_map(d)                                        # [E, S]
        ft = probe_values(x, z, d, zd, ts)                     # [L, E]
        armijo = jnp.isfinite(ft) & (ft <= f[None] + c1 * ts[:, None]
                                     * dg[None])
        any_ok = jnp.any(armijo, axis=0)
        first = jnp.argmax(armijo, axis=0)                     # largest t
        # No probe passes: smallest step that still decreases f (same
        # terminal fallback as the streamed L-BFGS), else freeze the lane.
        last = ft[-1]
        salvage = (~any_ok) & jnp.isfinite(last) & (last < f)
        t_pick = jnp.where(any_ok, ts[first],
                           jnp.where(salvage, ts[-1], 0.0))
        stepped = active & (t_pick > 0.0)

        x_new = jnp.where(stepped[:, None], x + t_pick[:, None] * d, x)
        z_new = jnp.where(stepped[:, None], z + t_pick[:, None] * zd, z)
        fs = value_at(x_new, z_new)
        gs = grad_at(x_new, z_new)
        f_new = jnp.where(stepped, fs, f)
        g_new = jnp.where(stepped[:, None], gs, g)

        it = it + 1
        gn = jnp.linalg.norm(g_new, axis=1)
        conv = check_convergence(it, f, f_new, gn, gnorm0, cfg)
        reason_new = jnp.where(
            active,
            jnp.where(stepped, conv,
                      jnp.asarray(FUNCTION_VALUES_CONVERGED, jnp.int32)),
            reason,
        )
        values = values.at[:, it].set(jnp.where(stepped, f_new, jnp.inf))
        gnorms = gnorms.at[:, it].set(jnp.where(stepped, gn, jnp.inf))
        # Hessian+grad assembly ≈ 2 data-equivalent passes, the probe
        # batch 1 — instrumented like the other solvers' pass counters.
        passes = passes + jnp.where(active, 3, 0).astype(jnp.int32)
        return (x_new, z_new, f_new, g_new, reason_new, it, values,
                gnorms, passes, iters + stepped.astype(jnp.int32))

    out = jax.lax.while_loop(cond, body, state)
    (x, z, f, g, reason, it, values, gnorms, passes, iters) = out
    return (x, z, f, g, finalize_reason(reason, it, cfg.max_iterations),
            it, values, gnorms, passes, iters)


@partial(jax.jit, static_argnums=0)
def fit_bucket_newton(problem, batches, w0, local_mask, local_prior):
    """Primal damped-Newton solve of every entity in one bucket (module
    doc). Same inputs as ``_fit_bucket_jitted`` (minus normalization, which
    the eligibility gate excludes) and the same ``(models, result)`` pytree
    shapes out, so ``train_random_effects`` can swap it in per bucket."""
    from photon_tpu.functions.problem import VarianceComputationType
    from photon_tpu.obs import retrace

    retrace.note_trace("fit_bucket_newton")  # 1 trace == 1 XLA compile

    # Solve in the data/warm-start precision: f64 RE configs must not
    # silently drop to f32 on the default fast path (ADVICE r5).
    dt = w0.dtype
    loss = loss_for_task(problem.task)
    x_ext, y, off, tw = _dense_design(batches, dt)
    # Contiguous copy of the ghost-stripped design: the batched GEMMs below
    # need a materialized operand, not a strided slice fused per-element.
    x = jax.lax.optimization_barrier(x_ext[..., : batches.features.dim])
    xt = jnp.swapaxes(x, 1, 2)                              # [E, P, S]
    l2v, pm, pp, _ = penalty_terms(problem, local_mask, local_prior, dt)

    def value_at(w, z):
        return (
            jnp.sum(tw * loss.loss(z, y), axis=1)
            + 0.5 * jnp.sum(l2v * w * w, axis=1)
            + 0.5 * jnp.sum(pp * (w - pm) ** 2, axis=1)
        )

    def grad_at(w, z):
        d1 = tw * loss.d1(z, y)
        return (jnp.matmul(d1[:, None, :], x)[:, 0]
                + l2v * w + pp * (w - pm))

    def hess_at(w, z):
        d2 = tw * loss.d2(z, y)
        # Xᵀ diag(d2) X as one batched GEMM over a materialized weighted
        # design (barrier: keep XLA from re-fusing the scale into the dot).
        xw = jax.lax.optimization_barrier(x * d2[..., None])
        h = jnp.matmul(xt, xw)
        return h + jax.vmap(jnp.diag)(l2v + pp)

    def lin_map(d):
        return jnp.matmul(x, d[..., None])[..., 0]

    def probe_values(w, z, d, zd, ts):
        zt = z[None] + ts[:, None, None] * zd[None]            # [L, E, S]
        wt = w[None] + ts[:, None, None] * d[None]             # [L, E, P]
        return (
            jnp.sum(tw[None] * loss.loss(zt, y[None]), axis=2)
            + 0.5 * jnp.sum(l2v[None] * wt * wt, axis=2)
            + 0.5 * jnp.sum(pp[None] * (wt - pm[None]) ** 2, axis=2)
        )

    w = w0.astype(dt)
    z = off + lin_map(w)
    (w, z, f, g, reason, _, values, gnorms, passes, iters) = _newton_loop(
        w, z, problem.optimizer_config, value_at, grad_at, hess_at,
        lin_map, probe_values, ridge=1e-8,
    )

    variances = None
    if problem.variance_type != VarianceComputationType.NONE:
        # Same formulas as GLMOptimizationProblem._variances, from the
        # final Hessian this solver already assembles: SIMPLE = 1/diag H,
        # FULL = diag H⁻¹ (H includes the L2 term and prior precision).
        h = hess_at(w, z)
        if problem.variance_type == VarianceComputationType.SIMPLE:
            diag = jax.vmap(jnp.diag)(h)
            variances = 1.0 / jnp.maximum(diag, 1e-12)
        else:
            eye = jnp.eye(w.shape[1], dtype=dt)
            hinv = jnp.linalg.inv(h + 1e-12 * eye)
            variances = jax.vmap(jnp.diag)(hinv)
        variances = variances.astype(w0.dtype)

    result = OptimizerResult(
        x=w.astype(w0.dtype),
        value=f,
        grad_norm=jnp.linalg.norm(g, axis=1),
        iterations=iters,  # accepted steps per lane, like the vmapped path
        converged_reason=reason,
        values=values,
        grad_norms=gnorms,
        data_passes=passes,
    )
    model = GeneralizedLinearModel(
        Coefficients(means=w.astype(w0.dtype), variances=variances),
        problem.task,
    )
    return model, result


@partial(jax.jit, static_argnums=(0, 5))
def fit_bucket_newton_dual(problem, batches, w0, local_mask, local_prior,
                           u_max: int):
    """Span-reduced Newton solve of every entity in one bucket (module doc).

    Same ``(models, result)`` pytree shapes as ``_fit_bucket_jitted``.
    ``w0`` is intentionally unused: an arbitrary warm start is outside the
    span parametrization, and quadratic convergence from θ=0 costs at most
    a couple of extra iterations — the trade for a history-free solver.
    """
    from photon_tpu.functions.problem import VarianceComputationType
    from photon_tpu.obs import retrace

    retrace.note_trace("fit_bucket_newton_dual")  # 1 trace == 1 XLA compile

    # Same dtype contract as the primal path: solve in w0.dtype so f64
    # datasets keep full precision (ADVICE r5). w0's VALUES stay unused
    # (module doc); only its dtype steers the compute precision.
    dt = w0.dtype
    loss = loss_for_task(problem.task)
    x_ext, y, off, tw = _dense_design(batches, dt)
    e, s, _ = x_ext.shape
    p = batches.features.dim
    # Contiguous ghost-stripped design for the batched GEMMs (module doc).
    x = jax.lax.optimization_barrier(x_ext[..., :p])

    _, pm, pp, d_pen = penalty_terms(problem, local_mask, local_prior, dt)
    d_pinv = jnp.where(d_pen > 0.0, 1.0 / jnp.maximum(d_pen, 1e-30), 0.0)
    q = pp * pm                                            # [E, P]

    # Unpenalized columns (d_pen == 0, typically the reg-masked intercept):
    # top-u_max indices per entity, ghost-padded with column P (zero in
    # x_ext, so an absent slot is inert).
    if u_max > 0:
        zero_d = d_pen <= 0.0                              # [E, P]
        # argsort puts False (penalized) last; take the first u_max true.
        order = jnp.argsort(~zero_d, axis=1, stable=True)[:, :u_max]
        have = jnp.take_along_axis(zero_d, order, axis=1)
        u_idx = jnp.where(have, order, p)                  # ghost when absent
        x_u = jnp.take_along_axis(
            x_ext, u_idx[:, None, :].repeat(s, axis=1), axis=2
        )                                                  # [E, S, U]
    else:
        u_idx = jnp.zeros((e, 0), jnp.int32)
        x_u = jnp.zeros((e, s, 0), dt)

    xd = jax.lax.optimization_barrier(
        x * d_pinv[:, None, :]                             # X·D⁺  [E,S,P]
    )
    gram = jnp.matmul(xd, jnp.swapaxes(x, 1, 2))           # G = XD⁺Xᵀ [E,S,S]
    j_mat = jax.lax.optimization_barrier(
        jnp.concatenate([gram, x_u], axis=2)               # [E, S, T]
    )
    j_t = jnp.swapaxes(j_mat, 1, 2)                        # [E, T, S]
    if local_prior is None:
        # q ≡ 0: the θ=0 margins are just the offsets and the primal
        # regularization constant vanishes — skip two [E,S,P] matvecs.
        z0 = off
        c_reg = jnp.zeros((e,), dt)
    else:
        z0 = off + jnp.matmul(xd, q[..., None])[..., 0]    # margins at θ=0
        # Primal-objective constant: reg(w(θ)) = ½αᵀGα + c_reg (module doc).
        c_reg = 0.5 * jnp.sum(pp * pm * pm, axis=1) - 0.5 * jnp.sum(
            d_pinv * q * q, axis=1
        )

    def ga_of(alpha):
        return jnp.einsum("est,...et->...es", gram, alpha)

    def value_at(theta, z):
        alpha = theta[:, :s]
        return (jnp.sum(tw * loss.loss(z, y), axis=1)
                + 0.5 * jnp.sum(alpha * ga_of(alpha), axis=1) + c_reg)

    def grad_at(theta, z):
        d1 = tw * loss.d1(z, y)
        g = jnp.matmul(d1[:, None, :], j_mat)[:, 0]
        return g.at[:, :s].add(ga_of(theta[:, :s]))

    def hess_at(theta, z):
        d2 = tw * loss.d2(z, y)
        # Jᵀ diag(d2) J as one batched GEMM (barrier: module doc).
        jw = jax.lax.optimization_barrier(j_mat * d2[..., None])
        h = jnp.matmul(j_t, jw)
        return h.at[:, :s, :s].add(gram)

    def lin_map(d):
        return jnp.matmul(j_mat, d[..., None])[..., 0]

    def probe_values(theta, z, d, zd, ts):
        zt = z[None] + ts[:, None, None] * zd[None]          # [L, E, S]
        alpha_t = theta[None, :, :s] + ts[:, None, None] * d[None, :, :s]
        return (jnp.sum(tw[None] * loss.loss(zt, y[None]), axis=2)
                + 0.5 * jnp.sum(alpha_t * ga_of(alpha_t), axis=2)
                + c_reg[None])

    theta0 = jnp.zeros((e, s + u_max), dt)
    (theta, z, f, g, reason, _, values, gnorms, passes,
     iters) = _newton_loop(
        theta0, z0, problem.optimizer_config, value_at, grad_at, hess_at,
        # The G-induced curvature can be singular along directions outside
        # the row span (α nullspace — w(θ) is unaffected there), so a
        # slightly larger ridge both damps and selects the min-norm step.
        lin_map, probe_values, ridge=1e-7,
    )

    # Recover primal coefficients: w = D⁺(Xᵀα + q) + scatter(β at u_idx).
    alpha, beta = theta[:, :s], theta[:, s:]
    w = d_pinv * (jnp.matmul(alpha[:, None, :], x)[:, 0] + q)
    if u_max > 0:
        w_full = jnp.concatenate([w, jnp.zeros((e, 1), dt)], axis=1)
        w_full = w_full.at[jnp.arange(e)[:, None], u_idx].add(beta)
        w = w_full[:, :p]

    # Primal gradient norm for the reported result (θ-space norms steer
    # the loop; the artifact-facing number matches the other solvers).
    z_w = off + jnp.matmul(x, w[..., None])[..., 0]
    d1 = tw * loss.d1(z_w, y)
    g_primal = jnp.matmul(d1[:, None, :], x)[:, 0] + d_pen * w - q

    variances = None
    if problem.variance_type == VarianceComputationType.SIMPLE:
        d2 = tw * loss.d2(z_w, y)
        diag = jnp.einsum("es,esp->ep", d2, x * x) + d_pen
        variances = (1.0 / jnp.maximum(diag, 1e-12)).astype(w0.dtype)

    result = OptimizerResult(
        x=w.astype(w0.dtype),
        value=f,
        grad_norm=jnp.linalg.norm(g_primal, axis=1),
        iterations=iters,
        converged_reason=reason,
        values=values,
        grad_norms=gnorms,
        data_passes=passes,
    )
    model = GeneralizedLinearModel(
        Coefficients(means=w.astype(w0.dtype), variances=variances),
        problem.task,
    )
    return model, result


# ------------------------------------------------------- entity sub-batching


def _slice_pad_batches(batches, lo: int, hi: int, chunk: int):
    """``batches[lo:hi]`` padded on the entity axis to exactly ``chunk``
    lanes. Padding lanes are inert by the same convention as
    ``_pad_bucket``: ghost feature columns (== local dim, dropped by the
    dense scatter), value/label/offset 0, weight 0."""
    from photon_tpu.data.batch import LabeledBatch, SparseFeatures

    f = batches.features

    def pz(a, fill=0):
        return _slice_pad_lanes(a, lo, hi, chunk, fill)

    return LabeledBatch(
        features=SparseFeatures(idx=pz(f.idx, f.dim), val=pz(f.val),
                                dim=f.dim),
        labels=pz(batches.labels),
        offsets=pz(batches.offsets),
        weights=pz(batches.weights),
    )


def _slice_pad_lanes(a, lo: int, hi: int, chunk: int, fill=0):
    """One [E, ...] per-entity leaf sliced and padded to ``chunk`` lanes.

    Host numpy leaves stay HOST numpy (np.pad, not jnp.pad): under a mesh
    the per-chunk placement device_puts each chunk row-sharded, and a host
    source streams each shard straight to its device — a jnp.pad here
    would first commit the chunk to the default device and pay the
    transfer twice."""
    a = a[lo:hi]
    pad = chunk - (hi - lo)
    if pad:
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        if isinstance(a, np.ndarray):
            return np.pad(a, widths, constant_values=fill)
        a = jnp.pad(a, widths, constant_values=fill)
    return a


def fit_bucket_in_chunks(fit_one, chunk: int, batches, w0, local_mask,
                         local_prior, put=None, ahead: int = 0):
    """Solve one bucket in entity chunks of a blessed size and restack.

    ``fit_one(batches, w0, local_mask, local_prior) -> (model, result)`` is
    a closure over the solver + its static arguments (problem, u_max, ...).
    Every chunk — including the padded tail — has EXACTLY ``chunk`` lanes,
    so the underlying jitted kernel compiles once per ladder size and the
    retrace sentinel stays quiet across sweeps. Padded lanes carry weight-0
    rows, mask 1 (so the ridge keeps their Hessians PD), and precision-0
    priors; they converge at the zero model on the first iteration and are
    sliced away before the restack.

    ``put`` (optional) places each chunk's argument pytree before dispatch
    — under a mesh it is the entity-sharded ``device_put`` that fans every
    chunk out across the devices (each device owns ``chunk/n_devices``
    lanes of EVERY chunk, so all devices work on every dispatch). With
    ``ahead > 0`` the placements run through ``pipelined_puts`` so chunk
    N+1's per-shard H2D is issued before chunk N's solve dispatches —
    the RE-side analogue of the out-of-core ``ell_feed`` double buffer.
    """
    e = w0.shape[0]
    spans = [(lo, min(lo + chunk, e)) for lo in range(0, e, chunk)]

    def args_for(span):
        lo, hi = span
        sl_prior = (
            jax.tree.map(lambda a: _slice_pad_lanes(a, lo, hi, chunk),
                         local_prior)
            if local_prior is not None else None
        )
        args = (
            _slice_pad_batches(batches, lo, hi, chunk),
            _slice_pad_lanes(w0, lo, hi, chunk),
            _slice_pad_lanes(local_mask, lo, hi, chunk, fill=1),
            sl_prior,
        )
        return args if put is None else put(args)

    if put is not None and ahead > 0 and len(spans) > 1:
        from photon_tpu.io.prefetch import pipelined_puts

        feed = pipelined_puts(spans, args_for, ahead=ahead)
    else:
        feed = (args_for(s) for s in spans)

    outs = []
    for (lo, hi), args in zip(spans, feed):
        model, result = fit_one(*args)
        n = hi - lo
        outs.append(jax.tree.map(lambda a: a[:n], (model, result)))
    if len(outs) == 1:
        return outs[0]
    return jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0), *outs)
