"""GAME (Generalized Additive Mixed Effects) layer — SURVEY.md §1 L5/L6.

Coordinates, coordinate descent, composite models, estimator/transformer:
the TPU-native rebuild of the reference's ⟦photon-api/.../algorithm/⟧,
⟦.../model/⟧ and ⟦.../estimators/⟧ packages.
"""
from photon_tpu.game.coordinates import (  # noqa: F401
    FixedEffectCoordinate,
    FixedEffectModel,
    RandomEffectCoordinate,
)
from photon_tpu.game.descent import (  # noqa: F401
    CoordinateDescent,
    CoordinateStepRecord,
    GameModel,
    ValidationData,
)
from photon_tpu.game.random_effect import (  # noqa: F401
    RandomEffectModel,
    train_random_effects,
)
