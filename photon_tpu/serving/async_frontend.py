"""Async front-end worker for the serving front line (docs/serving.md).

One of N identical **accelerator-free** processes the scorer-side
:class:`~photon_tpu.serving.frontline.FrontLine` supervisor spawns. Each
worker:

* binds the box's public scoring port with ``SO_REUSEPORT`` (the kernel
  load-balances accepted connections across workers — no userspace
  router in front of the box);
* speaks a hand-rolled asyncio HTTP/1.1 (keep-alive) edge accepting BOTH
  JSON ``POST /score`` bodies (the classic contract) and pre-encoded
  binary :mod:`wire` frames (``Content-Type: application/x-photon-wire``,
  the co-located fast lane) — a wire request gets a wire response;
* parses + pre-resolves rows itself: feature names resolve against the
  model's ``MmapIndexMap``s and entity keys are membership-checked
  against a **read-only mmap** of the exported ``CoefficientStore``
  (page cache shared with every sibling worker), so the scorer process
  receives only packed index/value arrays;
* forwards rows to the single device-owning scorer over the lock-free
  shared-memory ring (or unix-socket fallback) and maps wire statuses
  back onto the HTTP shed/deadline/drain contract.

The worker is deliberately **jax-free**: importing an accelerator
runtime here would multiply device memory by N and serialize startup
behind N× jit warmup — the entire point of the topology is that exactly
one process pays for the device.

Observability spans the process split (docs/observability.md): the
worker owns the worker-side stages (``admission`` / ``parse`` / ``ipc``
/ ``response``) in ITS registry shard (role ``frontend``); the scorer
owns queue_wait/batch_assembly/store_resolve/kernel in its own — merged,
every stage of the box waterfall is counted exactly once, and the
opt-in ``X-Photon-Timing`` response header reports all of them because
the scorer ships its stages back on every response frame. Tail sampling
promotes cross-process chains as a unit: the scorer judges its half
first and flags the frame; the worker forwards that verdict as
``force=`` to its own sampler.

Run as ``python -m photon_tpu.serving.async_frontend`` (the FrontLine
supervisor builds the command line; it is also runnable by hand against
an exported ``frontline.json`` for debugging).
"""
from __future__ import annotations

import argparse
import asyncio
import itertools
import json
import logging
import os
import signal
import socket
import sys
import threading
import time
from typing import Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from photon_tpu.index.index_map import MmapIndexMap
from photon_tpu.obs import trace as obs_trace
from photon_tpu.obs.metrics import MetricsRegistry
from photon_tpu.obs.trace import new_trace_id
from photon_tpu.serving import ipc, wire
from photon_tpu.serving.coefficient_store import CoefficientStore

log = logging.getLogger("photon_tpu.frontend")

_REASONS = {
    200: "OK", 400: "Bad Request", 404: "Not Found", 405: "Method Not "
    "Allowed", 500: "Internal Server Error", 503: "Service Unavailable",
}

# wire status -> (http code, counter outcome)
_STATUS_HTTP = {
    wire.STATUS_OK: (200, "ok"),
    wire.STATUS_BAD_REQUEST: (400, "bad_request"),
    wire.STATUS_OVERLOADED: (503, "shed"),
    wire.STATUS_DEADLINE: (503, "expired"),
    wire.STATUS_INTERNAL: (500, "error"),
    wire.STATUS_DRAINING: (503, "draining"),
}


class ParseError(ValueError):
    """Client-side request defect (HTTP 400)."""


class RowParser:
    """JSON request → pre-resolved :class:`wire.WireRow`, mirroring
    ``RowScorer.parse_request`` semantics exactly (same bags, same
    intercept injection, same unindexed-feature drop, same nnz cap) —
    tests assert score parity between the two paths.

    Entity keys are additionally membership-checked against the
    worker's read-only store mmap; a verified miss is flagged
    ``KNOWN_MISS`` so the scorer can skip the dead lookup — but only
    while the store generation still matches (``check_miss`` flips off
    the moment the scorer reports a newer generation, because a delta
    may have ADDED the entity the worker's stale export lacks)."""

    def __init__(self, manifest: dict):
        self.k = int(manifest["max_row_nnz"])
        self.generation = int(manifest["generation"])
        self.request_timeout_s = float(manifest["request_timeout_s"])
        self.model_version = int(manifest["model_version"])
        self.check_miss = True
        self.shards: dict[str, tuple] = {}
        for name, cfg in manifest["shards"].items():
            imap = MmapIndexMap(cfg["index_dir"])
            imap.preload()
            self.shards[name] = (
                imap, list(cfg["feature_bags"]), cfg["intercept_index"],
                int(cfg["dim"]))
        self.re: dict[str, tuple] = {}
        for cid, rcfg in manifest["re_coordinates"].items():
            store = CoefficientStore.load(rcfg["store_dir"], mmap=True)
            self.re[cid] = (rcfg["re_type"], store)

    def parse(self, payload) -> wire.WireRow:
        if not isinstance(payload, dict):
            raise ParseError("request body must be a JSON object")
        shard_idx, shard_val = {}, {}
        for shard, (imap, bags, icpt, dim) in self.shards.items():
            idxs, vals = [], []
            if icpt is not None:
                idxs.append(int(icpt))
                vals.append(1.0)
            for bag in bags:
                feats = payload.get(bag)
                if feats is None:
                    continue
                if not isinstance(feats, (list, tuple)):
                    raise ParseError(f"feature bag {bag!r} must be a list")
                for feat in feats:
                    try:
                        i = imap.get_index(feat["name"], feat.get("term"))
                        v = float(feat["value"])
                    except (TypeError, KeyError, ValueError) as e:
                        raise ParseError(
                            f"bad feature entry in bag {bag!r}: {e}"
                        ) from None
                    if i >= 0:  # unindexed features dropped, as the reader
                        idxs.append(i)
                        vals.append(v)
            if len(idxs) > self.k:
                raise ParseError(
                    f"row has {len(idxs)} features in shard {shard!r}; "
                    f"serving caps rows at max_row_nnz={self.k} "
                    "(raise the knob, don't truncate)")
            row_i = np.full(self.k, dim, np.int32)
            row_v = np.zeros(self.k, np.float32)
            row_i[: len(idxs)] = idxs
            row_v[: len(vals)] = vals
            shard_idx[shard] = row_i
            shard_val[shard] = row_v
        entities = payload.get("entities") or {}
        if not isinstance(entities, dict):
            raise ParseError('"entities" must be a map of RE type -> id')
        keys, miss = {}, set()
        for cid, (re_type, store) in self.re.items():
            key = entities.get(re_type)
            if key is None:
                key = payload.get(re_type)  # top-level fallback, as reader
            if key is None:
                keys[cid] = None
                continue
            key = str(key)
            keys[cid] = key
            if self.check_miss:
                try:
                    if store.lookup(key) is None:
                        miss.add(cid)
                except Exception:  # noqa: BLE001 - sick mmap: let scorer decide
                    pass
        try:
            offset = float(payload.get("offset") or 0.0)
        except (TypeError, ValueError):
            raise ParseError("offset must be a number") from None
        return wire.WireRow(
            shard_idx=shard_idx, shard_val=shard_val, offset=offset,
            entity_keys=keys, known_miss=frozenset(miss))


class ScorerClient:
    """This worker's end of the IPC link: one response-reader thread
    resolves asyncio futures by req_id; sends are non-blocking against
    the ring (``RingFull`` backpressure becomes an async backoff, never
    an event-loop stall)."""

    def __init__(self, channel, loop: asyncio.AbstractEventLoop):
        self._chan = channel
        self._ring = isinstance(channel, ipc.RingChannel)
        self._loop = loop
        self._pending: dict[int, asyncio.Future] = {}
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self.closed = False
        self._reader = threading.Thread(
            target=self._read_loop, name="photon-fe-reader", daemon=True)
        self._reader.start()

    def next_id(self) -> int:
        return next(self._ids)

    def _read_loop(self) -> None:
        while not self.closed:
            try:
                frame = self._chan.recv(timeout=0.5)
            except ipc.TransportClosed:
                break
            if frame is None:
                continue
            try:
                kind, req_id = wire.frame_kind(frame)
                if kind == wire.KIND_SCORE_RESP:
                    result = wire.decode_score_response(frame)
                elif kind in (wire.KIND_CTL_RESP, wire.KIND_HEARTBEAT):
                    result = wire.decode_control(frame)[2]
                else:
                    continue
            except wire.WireError as e:
                log.warning("dropping undecodable frame: %s", e)
                continue
            with self._lock:
                fut = self._pending.pop(req_id, None)
            if fut is not None:
                self._loop.call_soon_threadsafe(self._resolve, fut, result)
        self.closed = True
        with self._lock:
            pending, self._pending = dict(self._pending), {}
        for fut in pending.values():
            self._loop.call_soon_threadsafe(
                self._reject, fut, ipc.TransportClosed("scorer link down"))

    @staticmethod
    def _resolve(fut: asyncio.Future, result) -> None:
        if not fut.done():
            fut.set_result(result)

    @staticmethod
    def _reject(fut: asyncio.Future, exc: BaseException) -> None:
        if not fut.done():
            fut.set_exception(exc)

    async def _send(self, frame: bytes, budget_s: float = 0.25) -> None:
        if not self._ring:
            # Unix-socket sends complete in one syscall at these frame
            # sizes; the kernel buffer is the backpressure.
            self._chan.send(frame, timeout=5.0)
            return
        deadline = time.monotonic() + budget_s
        while True:
            try:
                self._chan.send(frame, timeout=0)
                return
            except ipc.RingFull:
                if time.monotonic() >= deadline:
                    raise
                await asyncio.sleep(0.002)

    async def request(self, frame: bytes, req_id: int, timeout: float):
        if self.closed:
            raise ipc.TransportClosed("scorer link down")
        fut = self._loop.create_future()
        with self._lock:
            self._pending[req_id] = fut
        try:
            await self._send(frame)
            return await asyncio.wait_for(fut, timeout)
        finally:
            with self._lock:
                self._pending.pop(req_id, None)

    async def control(self, payload: dict, timeout: float = 5.0,
                      kind: int = wire.KIND_CTL_REQ) -> dict:
        rid = self.next_id()
        return await self.request(
            wire.encode_control(kind, rid, payload), rid, timeout)

    def close(self) -> None:
        self.closed = True
        self._chan.close()


class FrontendWorker:
    def __init__(self, worker_id: int, parser: RowParser,
                 client: ScorerClient, *, host: str, port: int,
                 heartbeat_s: float = 1.0,
                 telemetry_dir: Optional[str] = None):
        self.worker_id = worker_id
        self.parser = parser
        self.client = client
        self.host = host
        self.port = port
        self.heartbeat_s = float(heartbeat_s)
        self.telemetry_dir = telemetry_dir
        self.served = 0
        self.inflight = 0
        self.draining = False
        self._box_health: dict = {}
        self._box_health_at = 0.0
        self._server: Optional[asyncio.AbstractServer] = None
        self._stopped = asyncio.Event()
        self.metrics = MetricsRegistry()
        self._requests = self.metrics.counter(
            "frontend_http_requests_total",
            "HTTP requests at this front-end worker, by outcome")
        self._stage_hist = self.metrics.histogram(
            "serve_stage_latency_seconds",
            "worker-side stage waterfall: admission / parse / ipc / "
            "response (this shard owns ONLY the worker stages; the "
            "scorer shard owns queue_wait/batch_assembly/store_resolve/"
            "kernel — merged, each stage counts once)")
        self._latency = self.metrics.histogram(
            "frontend_request_latency_seconds",
            "end-to-end worker-observed /score latency (successful)")
        self._ring_stalls = self.metrics.counter(
            "frontend_ipc_backpressure_total",
            "score requests shed because the scorer ring stayed full "
            "past the send budget")
        self.metrics.gauge_fn(
            "frontend_inflight", lambda: float(self.inflight),
            "requests currently inside this worker")

    # ----------------------------------------------------------- HTTP edge

    async def start(self) -> None:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # SO_REUSEPORT is the whole load-balancing story: every worker
        # binds the same (host, port) and the kernel spreads accepts.
        if hasattr(socket, "SO_REUSEPORT"):
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        sock.bind((self.host, self.port))
        sock.setblocking(False)
        self._server = await asyncio.start_server(self._serve_conn,
                                                  sock=sock)

    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                t0 = time.perf_counter()
                line = await reader.readline()
                if not line:
                    return
                try:
                    method, target, proto = (
                        line.decode("latin-1").strip().split(" ", 2))
                except ValueError:
                    return
                headers = {}
                while True:
                    h = await reader.readline()
                    if h in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = h.decode("latin-1").partition(":")
                    headers[k.strip().lower()] = v.strip()
                n = int(headers.get("content-length") or 0)
                body = await reader.readexactly(n) if n else b""
                conn = headers.get("connection", "").lower()
                keep = (conn != "close"
                        and (proto == "HTTP/1.1" or conn == "keep-alive"))
                code, extra, out, ctype = await self._dispatch(
                    method, target, headers, body, t0)
                writer.write(_http_response(code, out, ctype=ctype,
                                            extra=extra, keep=keep))
                await writer.drain()
                if not keep:
                    return
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            return
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001 - peer already gone
                pass

    async def _dispatch(self, method, target, headers, body, t0):
        url = urlparse(target)
        path = url.path
        if path == "/score" and method == "POST":
            return await self._score(headers, body, t0)
        if path == "/healthz" and method == "GET":
            return await self._healthz()
        if path == "/metrics" and method == "GET":
            return self._metrics(parse_qs(url.query))
        if path == "/admin/tune" and method == "POST":
            return await self._tune(body)
        code = 405 if path in ("/score", "/admin/tune", "/healthz",
                               "/metrics") else 404
        return _json(code, {"error": f"no route {method} {path}"})

    # --------------------------------------------------------------- score

    async def _score(self, headers, body, t0):
        tid = headers.get("x-photon-trace-id") or new_trace_id()
        tail = obs_trace.tail_sampler()
        if tail is not None:
            tail.begin(tid)
        admission = time.perf_counter() - t0
        if self.draining:
            self._requests.inc(outcome="draining")
            self._tail_done(tail, tid, t0, error=False)
            return _json(503, {"error": "worker draining", "shed": True},
                         extra=(("Retry-After", "1"),))
        wants_wire = wire.is_wire(body) or headers.get(
            "content-type", "").startswith(wire.WIRE_CONTENT_TYPE)
        p0 = time.perf_counter()
        client_req_id = 0
        deadline_ms = 0.0
        try:
            if wants_wire:
                creq = wire.decode_score_request(body)
                rows = creq.rows
                client_req_id = creq.req_id
                deadline_ms = creq.deadline_ms
                # The client encoded against ITS store knowledge; only a
                # frame claiming our export's generation may keep its
                # known-miss flags through the scorer's gate.
                gen = creq.store_generation or self.parser.generation
            else:
                payload = json.loads(body.decode("utf-8"))
                rows = [self.parser.parse(payload)]
                gen = self.parser.generation
        except (ParseError, wire.WireError, UnicodeDecodeError,
                json.JSONDecodeError) as e:
            self._requests.inc(outcome="bad_request")
            self._tail_done(tail, tid, t0, error=False)
            return _json(400, {"error": str(e)})
        parse_s = time.perf_counter() - p0

        i0 = time.perf_counter()
        rid = self.client.next_id()
        frame = wire.encode_score_request(
            rows, req_id=rid, trace_id=tid, deadline_ms=deadline_ms,
            store_generation=gen)
        self.inflight += 1
        try:
            resp = await self.client.request(
                frame, rid, timeout=self.parser.request_timeout_s + 1.0)
        except ipc.RingFull:
            self._ring_stalls.inc()
            self._requests.inc(outcome="shed")
            self._tail_done(tail, tid, t0, error=False)
            return _json(503, {"error": "scorer ring backpressure",
                               "shed": True},
                         extra=(("Retry-After", "1"),))
        except asyncio.TimeoutError:
            self._requests.inc(outcome="expired")
            self._tail_done(tail, tid, t0, error=False)
            return _json(503, {"error": "request deadline exceeded"},
                         extra=(("Retry-After", "1"),))
        except ipc.TransportClosed:
            self._requests.inc(outcome="error")
            self._tail_done(tail, tid, t0, error=True)
            return _json(503, {"error": "scorer unavailable"},
                         extra=(("Retry-After", "1"),))
        finally:
            self.inflight -= 1
        ipc_total = time.perf_counter() - i0

        code, outcome = _STATUS_HTTP.get(resp.status, (500, "error"))
        self._requests.inc(outcome=outcome)
        total = time.perf_counter() - t0
        # Worker-side waterfall. The scorer's stages happened INSIDE the
        # ipc window, so the worker's ipc stage reports only the transport
        # overhead (encode + ring + decode + future handoff) — stages must
        # tile the request, never double-cover it.
        scorer_s = sum((resp.stages or {}).values())
        stages = {
            "admission": admission,
            "parse": parse_s,
            "ipc": max(0.0, ipc_total - scorer_s),
        }
        if code == 200:
            full = {"admission": admission, "parse": parse_s,
                    **(resp.stages or {}), "ipc": stages["ipc"]}
            full["response"] = max(0.0, total - sum(full.values()))
            stages["response"] = full["response"]
            for st, sec in stages.items():
                self._stage_hist.observe(sec, stage=st)
            self._latency.observe(total)
            self.served += 1
            col = obs_trace.active_collector()
            if col is not None:
                base = t0
                for st in ("admission", "parse", "ipc"):
                    col.complete(f"frontend.{st}", "serving", base,
                                 stages[st], {"trace_id": tid})
                    base += stages[st]
                col.complete("frontend.request", "serving", t0, total,
                             {"trace_id": tid, "worker": self.worker_id})
        promoted = self._tail_done(
            tail, tid, t0, error=code >= 500 and outcome == "error",
            force=resp.trace_promoted)
        extra = []
        if code == 200 and (headers.get("x-photon-timing", "").lower()
                            in ("1", "true", "yes", "on")):
            parts = [f"{st};dur={sec * 1e3:.3f}"
                     for st, sec in full.items()]
            parts.append(f"total;dur={total * 1e3:.3f}")
            extra.append(("X-Photon-Timing", ", ".join(parts)))
        extra.append(("X-Photon-Worker", str(self.worker_id)))

        if wants_wire:
            flags = resp.flags | (
                wire.RESP_FLAG_TRACE_PROMOTED if promoted else 0)
            out = wire.encode_score_response(
                client_req_id, status=resp.status, error=resp.error,
                retry_after_s=resp.retry_after_s,
                model_version=resp.model_version, flags=flags,
                scores=resp.scores, degraded=resp.degraded,
                stages=(full if code == 200 else resp.stages))
            return code, tuple(extra), out, wire.WIRE_CONTENT_TYPE
        if code != 200:
            payload_out = {"error": resp.error}
            if outcome in ("shed", "draining"):
                payload_out["shed"] = True
                extra.append(("Retry-After",
                              str(max(1, int(resp.retry_after_s or 1)))))
            return _json(code, payload_out, extra=tuple(extra))
        out = {"score": float(resp.scores[0]),
               "model_version": resp.model_version}
        if resp.degraded and resp.degraded[0]:
            out["degraded"] = sorted(resp.degraded[0])
        if not wants_wire and "uid" in payload:
            out["uid"] = payload["uid"]
        return _json(200, out, extra=tuple(extra))

    def _tail_done(self, tail, tid, t0, error: bool,
                   force: bool = False) -> bool:
        if tail is None:
            return force
        return tail.finish(tid, time.perf_counter() - t0, error=error,
                           force=force)

    # ------------------------------------------------------------- control

    async def _healthz(self):
        health = await self._box_health_fresh()
        if not health:
            return _json(503, {
                "status": "unhealthy", "role": "frontend",
                "worker_id": self.worker_id,
                "degraded": ["scorer_unreachable"], "pid": os.getpid()})
        health = dict(health)
        health.update({
            "role": "frontend", "worker_id": self.worker_id,
            "pid": os.getpid(), "served": self.served,
            "worker_draining": self.draining,
        })
        code = 503 if health.get("status") == "unhealthy" else 200
        return _json(code, health)

    async def _box_health_fresh(self, max_age_s: float = 2.0) -> dict:
        if time.monotonic() - self._box_health_at <= max_age_s:
            return self._box_health
        try:
            health = await self.client.control({"op": "healthz"},
                                               timeout=3.0)
        except (ipc.TransportClosed, asyncio.TimeoutError, ipc.RingFull):
            return {}
        self._box_health = health
        self._box_health_at = time.monotonic()
        return health

    def _metrics(self, query: dict):
        if (query.get("format") or [""])[0] == "prom":
            text = self.metrics.to_prometheus()
            return 200, (), text.encode("utf-8"), "text/plain; version=0.0.4"
        tail = obs_trace.tail_sampler()
        return _json(200, {
            "role": "frontend", "worker_id": self.worker_id,
            "pid": os.getpid(), "served": self.served,
            "inflight": self.inflight, "draining": self.draining,
            "store_generation": self.parser.generation,
            "known_miss_active": self.parser.check_miss,
            "tail_sampler": tail.snapshot() if tail is not None else None,
            "metrics": self.metrics.snapshot(),
        })

    async def _tune(self, body: bytes):
        try:
            payload = json.loads(body.decode("utf-8")) if body else {}
            if not isinstance(payload, dict):
                raise ValueError("request body must be a JSON object")
        except (ValueError, UnicodeDecodeError) as e:
            return _json(400, {"error": str(e)})
        try:
            reply = await self.client.control(
                {"op": "tune", **payload}, timeout=5.0)
        except (ipc.TransportClosed, asyncio.TimeoutError, ipc.RingFull):
            return _json(503, {"error": "scorer unavailable"})
        if reply.pop("bad_request", None):
            return _json(400, reply)
        return _json(200, {**reply, "proxied_by_worker": self.worker_id})

    # ----------------------------------------------------------- lifecycle

    async def run(self) -> None:
        loop = asyncio.get_running_loop()
        hello = await self.client.control(
            {"op": "hello", "worker_id": self.worker_id,
             "pid": os.getpid()}, timeout=10.0)
        gen = int(hello.get("generation", self.parser.generation))
        if gen != self.parser.generation:
            self.parser.check_miss = False
        await self.start()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(
                sig, lambda: asyncio.ensure_future(self.shutdown()))
        hb = asyncio.ensure_future(self._heartbeat_loop())
        log.info("frontend worker %d serving on %s:%d (pid %d, %s)",
                 self.worker_id, self.host, self.port, os.getpid(),
                 "shm ring" if isinstance(self.client._chan,
                                          ipc.RingChannel) else "socket")
        await self._stopped.wait()
        hb.cancel()

    async def _heartbeat_loop(self) -> None:
        boss = os.getppid()  # the scorer process that spawned us
        misses = 0
        while not self._stopped.is_set():
            if os.getppid() != boss:
                # Re-parented to init: the scorer died. A socket link
                # reports this as TransportClosed, but a shm ring has no
                # peer-death signal — without this check a SIGKILLed
                # scorer leaves orphan workers squatting the REUSEPORT
                # group, answering 503 forever next to its replacement.
                log.error("scorer process gone (orphaned); exiting")
                await self.shutdown()
                return
            try:
                reply = await self.client.control(
                    {"op": "heartbeat", "worker_id": self.worker_id,
                     "served": self.served}, timeout=3.0)
                misses = 0
                if reply.get("health"):
                    self._box_health = reply["health"]
                    self._box_health_at = time.monotonic()
                gen = reply.get("generation")
                if gen is not None and int(gen) != self.parser.generation:
                    self.parser.check_miss = False
            except (ipc.TransportClosed, asyncio.TimeoutError,
                    ipc.RingFull):
                misses += 1
                if self.client.closed or misses >= 5:
                    log.error("scorer link down (%d missed heartbeats); "
                              "exiting", misses)
                    await self.shutdown()
                    return
            self._export_shard()
            try:
                await asyncio.wait_for(self._stopped.wait(),
                                       timeout=self.heartbeat_s)
            except asyncio.TimeoutError:
                pass

    def _export_shard(self) -> None:
        """Live fleet view (docs/observability.md §"Fleet view"): flush
        this worker's registry shard every heartbeat, same convention as
        the scoring server's flush loop."""
        if not self.telemetry_dir:
            return
        try:
            from photon_tpu.obs import fleet

            fleet.write_registry_shard(
                os.path.join(
                    self.telemetry_dir,
                    f"registry.frontend.{os.getpid()}.json"),
                registries=[self.metrics], role="frontend",
                extra={"worker_id": self.worker_id})
        except Exception as e:  # noqa: BLE001 - evidence, never a failure mode
            log.debug("shard export failed: %s", e)

    async def shutdown(self, grace_s: float = 10.0) -> None:
        if self.draining:
            return
        self.draining = True
        log.info("worker %d draining (%d inflight)", self.worker_id,
                 self.inflight)
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        deadline = time.monotonic() + grace_s
        while self.inflight > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        self._export_shard()
        self._stopped.set()


def _http_response(code: int, body: bytes, *, ctype: str = "application/"
                   "json", extra=(), keep: bool = True) -> bytes:
    head = [
        f"HTTP/1.1 {code} {_REASONS.get(code, 'OK')}",
        f"Content-Type: {ctype}",
        f"Content-Length: {len(body)}",
        f"Connection: {'keep-alive' if keep else 'close'}",
    ]
    for k, v in extra:
        head.append(f"{k}: {v}")
    return ("\r\n".join(head) + "\r\n\r\n").encode("latin-1") + body


def _json(code: int, payload: dict, extra=()):
    return (code, tuple(extra), json.dumps(payload).encode("utf-8"),
            "application/json")


def build_channel(spec: str, worker_id: int):
    """``shm:<token>`` → attach the scorer-created ring pair;
    ``sock:<path>`` → connect the unix-socket fallback."""
    scheme, _, arg = spec.partition(":")
    if scheme == "shm":
        return ipc.attach_worker_rings(arg, worker_id)
    if scheme == "sock":
        return ipc.SocketChannel.connect(arg)
    raise ValueError(f"unknown ipc spec {spec!r} (want shm:… or sock:…)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="photon-tpu async serving front-end worker")
    ap.add_argument("--manifest", required=True,
                    help="frontline.json written by ModelRegistry."
                         "export_frontline")
    ap.add_argument("--worker-id", type=int, required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--ipc", required=True,
                    help="shm:<token> | sock:<path>")
    ap.add_argument("--heartbeat-s", type=float, default=1.0)
    ap.add_argument("--telemetry-dir", default=None)
    ap.add_argument("--trace-out", default=None)
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO, stream=sys.stderr,
        format=f"%(asctime)s fe{args.worker_id} %(levelname)s %(message)s")
    assert "jax" not in sys.modules, (
        "front-end workers must stay jax-free; an import above pulled in "
        "the accelerator runtime")

    from photon_tpu.cli import params

    params.enable_telemetry(args, role="frontend")
    params.enable_trace(args.trace_out)
    if obs_trace.tail_sampler() is None:
        obs_trace.install_tail_sampler(obs_trace._env_tail_sampler())

    with open(args.manifest) as f:
        manifest = json.load(f)
    parser = RowParser(manifest)
    channel = build_channel(args.ipc, args.worker_id)

    async def _amain() -> None:
        loop = asyncio.get_running_loop()
        client = ScorerClient(channel, loop)
        worker = FrontendWorker(
            args.worker_id, parser, client, host=args.host, port=args.port,
            heartbeat_s=args.heartbeat_s, telemetry_dir=args.telemetry_dir)
        try:
            await worker.run()
        finally:
            client.close()

    try:
        asyncio.run(_amain())
        return 0
    finally:
        params.finish_trace(args.trace_out)


if __name__ == "__main__":
    sys.exit(main())
