"""Online GAME scoring subsystem (docs/serving.md).

Four parts, composing the low-latency serve path the batch scoring driver
cannot provide:

* ``registry``      — versioned model registry with atomic hot-swap;
* ``coefficient_store`` — host-resident per-entity random-effect
  coefficient table (mmap-friendly flat layout) + LRU device hot-set;
* ``batcher``       — request micro-batcher coalescing concurrent
  single-row requests into padded bucket shapes for the shared jitted
  additive scoring kernel (``estimators.game_transformer
  .additive_score_rows``), which never recompiles after warmup;
* ``server``        — stdlib ``ThreadingHTTPServer`` JSON front-end with
  latency histograms and JSONL metrics.

Robustness (docs/robustness.md): bounded admission queue with load
shedding (503 + Retry-After), request deadlines enforced inside the
batcher, a circuit breaker that degrades a sick coefficient store to
fixed-effect-only scoring, and worker-crash detection surfaced through
``/healthz`` — all exercised by the chaos suite (``pytest -m chaos``).

CLI entry point: ``photon_tpu/cli/serving_driver.py``.
"""
from photon_tpu.serving.batcher import (
    DeadlineExceeded,
    MicroBatcher,
    Overloaded,
    ScoreResult,
)
from photon_tpu.serving.circuit import CircuitBreaker
from photon_tpu.serving.coefficient_store import (
    CoefficientStore,
    DeviceCoefficientCache,
)
from photon_tpu.serving.registry import (
    ModelRegistry,
    ModelVersion,
    ServingConfig,
)
from photon_tpu.serving.scorer import ParsedRow, RowScorer
from photon_tpu.serving.server import ScoringServer

__all__ = [
    "CircuitBreaker",
    "CoefficientStore",
    "DeadlineExceeded",
    "DeviceCoefficientCache",
    "MicroBatcher",
    "ModelRegistry",
    "ModelVersion",
    "Overloaded",
    "ParsedRow",
    "RowScorer",
    "ScoreResult",
    "ScoringServer",
    "ServingConfig",
]
