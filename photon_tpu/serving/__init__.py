"""Online GAME scoring subsystem (docs/serving.md).

Four parts, composing the low-latency serve path the batch scoring driver
cannot provide:

* ``registry``      — versioned model registry with atomic hot-swap;
* ``coefficient_store`` — host-resident per-entity random-effect
  coefficient table (mmap-friendly flat layout) + LRU device hot-set;
* ``batcher``       — request micro-batcher coalescing concurrent
  single-row requests into padded bucket shapes for the shared jitted
  additive scoring kernel (``estimators.game_transformer
  .additive_score_rows``), which never recompiles after warmup;
* ``server``        — stdlib ``ThreadingHTTPServer`` JSON front-end with
  latency histograms and JSONL metrics.

Robustness (docs/robustness.md): bounded admission queue with load
shedding (503 + Retry-After), request deadlines enforced inside the
batcher, a circuit breaker that degrades a sick coefficient store to
fixed-effect-only scoring, and worker-crash detection surfaced through
``/healthz`` — all exercised by the chaos suite (``pytest -m chaos``).

Front line (PR 19, docs/serving.md §"Front line"): ``wire`` (versioned
binary row encoding), ``ipc`` (lock-free shm ring + socket fallback),
``async_frontend`` (accelerator-free asyncio worker processes),
``frontline`` (scorer-side IPC service + worker supervisor) and
``autotune`` (histogram-driven micro-batch tuning) rebuild the serving
box as a multi-process pipeline; the threaded server above remains the
single-process mode and the bench's A/B baseline.

CLI entry point: ``photon_tpu/cli/serving_driver.py``.

NOTE: exports resolve lazily (PEP 562) so that accelerator-FREE users of
this package — front-end workers importing ``wire``/``ipc``/
``coefficient_store`` — never drag in jax through the registry/scorer
modules.
"""
_EXPORTS = {
    "DeadlineExceeded": "photon_tpu.serving.batcher",
    "MicroBatcher": "photon_tpu.serving.batcher",
    "Overloaded": "photon_tpu.serving.batcher",
    "ScoreResult": "photon_tpu.serving.batcher",
    "CircuitBreaker": "photon_tpu.serving.circuit",
    "CoefficientStore": "photon_tpu.serving.coefficient_store",
    "DeviceCoefficientCache": "photon_tpu.serving.coefficient_store",
    "ModelRegistry": "photon_tpu.serving.registry",
    "ModelVersion": "photon_tpu.serving.registry",
    "ServingConfig": "photon_tpu.serving.registry",
    "ParsedRow": "photon_tpu.serving.scorer",
    "RowScorer": "photon_tpu.serving.scorer",
    "ScoringServer": "photon_tpu.serving.server",
    "BatchAutotuner": "photon_tpu.serving.autotune",
    "FrontLine": "photon_tpu.serving.frontline",
}


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))


__all__ = [
    "BatchAutotuner",
    "FrontLine",
    "CircuitBreaker",
    "CoefficientStore",
    "DeadlineExceeded",
    "DeviceCoefficientCache",
    "MicroBatcher",
    "ModelRegistry",
    "ModelVersion",
    "Overloaded",
    "ParsedRow",
    "RowScorer",
    "ScoreResult",
    "ScoringServer",
    "ServingConfig",
]
