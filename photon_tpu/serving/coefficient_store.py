"""Per-entity random-effect coefficients for serving: host table + device LRU.

What makes GAME serving harder than plain GLM serving is the random-effect
structure: millions of per-entity coefficient vectors, of which any single
request needs exactly one per RE coordinate. The reference stack held the
model as an ``RDD[(REId, GLM)]`` and only ever joined it against batch data
(SURVEY.md §3.6); an online server needs point lookups instead:

* ``CoefficientStore`` — the FULL per-entity table, host-resident in a flat
  CSR-style layout (``offsets/cols/vals`` arrays + key index). The arrays
  are plain numpy, so a saved store reopens as ``np.load(mmap_mode="r")``
  views: a multi-process deployment shares one page-cache copy, the same
  property ``MmapIndexMap`` gives the feature index.
* ``DeviceCoefficientCache`` — an LRU hot-set of entities staged on device
  as fixed-shape ``[capacity+1, P]`` projection/coefficient tables the
  jitted scoring kernel gathers from. Row ``capacity`` is a permanent
  all-ghost zero row: unseen entities (and rows with no entity) map there
  and score fixed-effect-only — the same zero-model fallback as the batch
  scorer. Staging a miss rewrites one table row (functional ``.at[].set``);
  table SHAPES never change, so the scoring kernel never recompiles on
  cache churn.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict
from typing import Optional

import numpy as np

from photon_tpu.faults import fault_point
from photon_tpu.serving.circuit import CircuitBreaker


class _LazyJnp:
    """Defer the jax import to first DEVICE use: the host-side
    ``CoefficientStore`` is mmap-loaded read-only by accelerator-free
    front-end workers (docs/serving.md §"Front line"), which must never
    pay for — or depend on — an accelerator runtime just to resolve
    entity keys. Only ``DeviceCoefficientCache`` touches the device."""

    def __getattr__(self, name):
        import jax.numpy as jnp

        return getattr(jnp, name)


jnp = _LazyJnp()

_META = "store-meta.json"


class CoefficientStore:
    """Host-resident sparse per-entity coefficient table for ONE random-effect
    coordinate. ``cols`` are global feature columns, ascending per entity
    (the layout ``additive_score_rows``'s binary search requires)."""

    def __init__(
        self,
        keys,
        offsets: np.ndarray,
        cols: np.ndarray,
        vals: np.ndarray,
        global_dim: int,
    ):
        self.keys = list(keys)
        self._key_to_row = {k: i for i, k in enumerate(self.keys)}
        self.offsets = offsets
        self.cols = cols
        self.vals = vals
        self.global_dim = int(global_dim)
        # Online-delta overlay (docs/online.md): patched entities resolve
        # here BEFORE the base CSR arrays (which may be read-only mmaps).
        # ``apply_patches`` swaps the whole dict reference in one
        # assignment, so a concurrent ``lookup`` sees the entire delta or
        # none of it — never a torn mix.
        self._patches: dict = {}

    @property
    def n_entities(self) -> int:
        """Distinct resolvable entities: base table + patched-in NEW keys."""
        extra = sum(1 for k in self._patches if k not in self._key_to_row)
        return len(self.keys) + extra

    @property
    def n_patched(self) -> int:
        return len(self._patches)

    def validate_patches(self, patches) -> dict:
        """Validate (and normalize) a patch batch WITHOUT applying it:
        ``{key: (cols, vals)}`` → staged dict of int32/float32 arrays.
        Raises on mismatched shapes, non-ascending columns (the kernel's
        binary-search layout), or out-of-range columns. Callers that need
        cross-store atomicity (``ModelRegistry.apply_delta``) validate
        EVERY store first, then apply."""
        staged = {}
        for key, (cols, vals) in patches.items():
            cols = np.asarray(cols, np.int32)
            vals = np.asarray(vals, np.float32)
            if cols.shape != vals.shape or cols.ndim != 1:
                raise ValueError(
                    f"patch for {key!r}: cols/vals must be matching 1-D "
                    f"arrays, got {cols.shape} vs {vals.shape}"
                )
            if len(cols) > 1 and np.any(np.diff(cols) < 0):
                raise ValueError(
                    f"patch for {key!r}: cols must be ascending "
                    "(additive_score_rows binary-searches them)"
                )
            if len(cols) and (cols[0] < 0 or cols[-1] >= self.global_dim):
                raise ValueError(
                    f"patch for {key!r}: cols out of range "
                    f"[0, {self.global_dim})"
                )
            staged[key] = (cols, vals)
        return staged

    def apply_patches(self, patches) -> int:
        """Atomically overlay full replacement coefficient vectors.

        ``patches`` maps entity key → ``(cols, vals)`` (global columns,
        ascending — validated via :meth:`validate_patches` so a bad
        producer can never corrupt scoring). Entities absent from the
        base table are ADDED (cold-start entities streaming in). The base
        arrays are never touched: they may be ``mmap_mode="r"`` views
        shared across processes. Returns the number of entities patched.

        The overlay is PROCESS state — the durable record of published
        deltas is the trainer's patch journal (docs/online.md); ``save``
        persists the base table only.
        """
        staged = self.validate_patches(patches)
        # Build-then-swap: one reference assignment publishes everything.
        merged = dict(self._patches)
        merged.update(staged)
        self._patches = merged
        return len(staged)

    @property
    def max_width(self) -> int:
        if len(self.offsets) <= 1:
            return 1
        return max(1, int(np.max(np.diff(self.offsets))))

    @classmethod
    def from_model(cls, model) -> "CoefficientStore":
        """Build from a trained/loaded ``RandomEffectModel``: same sparse
        view as ``coefficients_for``, but with each bucket's stacks pulled
        host-side ONCE — per-entity jax indexing would cost one device
        dispatch + D2H sync per entity, minutes of swap latency at the
        millions-of-entities scale this store exists for."""
        keys = list(model.entity_keys)
        proj_np = [np.asarray(p) for p in model.bucket_proj]
        coef_np = [np.asarray(c) for c in model.bucket_coefs]
        offsets = np.zeros(len(keys) + 1, np.int64)
        cols_parts, vals_parts = [], []
        for i in range(len(keys)):
            b, lane = model.entity_to_slot[i]
            pv = proj_np[b][lane]
            valid = pv < model.global_dim
            gi = pv[valid].astype(np.int64)
            gv = coef_np[b][lane][valid]
            if len(gi) > 1 and np.any(np.diff(gi) < 0):
                order = np.argsort(gi)  # defensive: kernel needs sorted cols
                gi, gv = gi[order], gv[order]
            cols_parts.append(gi.astype(np.int32))
            vals_parts.append(np.asarray(gv, np.float32))
            offsets[i + 1] = offsets[i] + len(gi)
        cols = (
            np.concatenate(cols_parts) if cols_parts else np.zeros(0, np.int32)
        )
        vals = (
            np.concatenate(vals_parts)
            if vals_parts
            else np.zeros(0, np.float32)
        )
        return cls(keys, offsets, cols, vals, model.global_dim)

    def lookup(self, key) -> Optional[tuple[np.ndarray, np.ndarray]]:
        """(global_cols, values) views for one entity, or None if unseen."""
        # Chaos hook: latency spikes (delay_s) and IO errors on the store
        # path — what an mmap'd table on a sick filesystem really does.
        fault_point("serving.store_lookup", key=key)
        patched = self._patches.get(key)  # one dict read; ref-swap atomic
        if patched is not None:
            return patched
        row = self._key_to_row.get(key)
        if row is None:
            return None
        s, e = int(self.offsets[row]), int(self.offsets[row + 1])
        return self.cols[s:e], self.vals[s:e]

    # ------------------------------------------------------------- on disk

    def save(self, out_dir: str) -> None:
        """Persist as npy arrays + key list; ``load`` reopens them memory-
        mapped so a 10M-entity table costs ~zero resident RAM per process."""
        os.makedirs(out_dir, exist_ok=True)
        np.save(os.path.join(out_dir, "offsets.npy"), self.offsets)
        np.save(os.path.join(out_dir, "cols.npy"), self.cols)
        np.save(os.path.join(out_dir, "vals.npy"), self.vals)
        with open(os.path.join(out_dir, "keys.json"), "w") as f:
            json.dump([str(k) for k in self.keys], f)
        with open(os.path.join(out_dir, _META), "w") as f:
            json.dump(
                {"global_dim": self.global_dim, "n_entities": len(self.keys)},
                f,
            )

    @classmethod
    def load(cls, store_dir: str, mmap: bool = True) -> "CoefficientStore":
        with open(os.path.join(store_dir, _META)) as f:
            meta = json.load(f)
        with open(os.path.join(store_dir, "keys.json")) as f:
            keys = json.load(f)
        mode = "r" if mmap else None
        return cls(
            keys,
            np.load(os.path.join(store_dir, "offsets.npy"), mmap_mode=mode),
            np.load(os.path.join(store_dir, "cols.npy"), mmap_mode=mode),
            np.load(os.path.join(store_dir, "vals.npy"), mmap_mode=mode),
            meta["global_dim"],
        )


def _next_pow2(n: int) -> int:
    return 1 << max(0, int(n - 1).bit_length())


class DeviceCoefficientCache:
    """LRU hot-set of ``CoefficientStore`` rows staged on device (module doc).

    Internal state is lock-protected, but the eviction pin only lasts for
    one ``slots_for`` call: a resolve-then-``gather`` sequence is NOT
    atomic against other threads resolving slots in between (an interleaved
    eviction could restage a returned slot). The server upholds this by
    funneling ALL resolution + gather through the micro-batcher's single
    worker thread; direct users of ``RowScorer.score_rows`` must likewise
    serialize scoring calls per cache. ``stats`` counts hits/misses/
    evictions/fallbacks for the /metrics endpoint.
    """

    def __init__(
        self, store: CoefficientStore, capacity: int = 4096,
        width: Optional[int] = None,
        breaker: Optional[CircuitBreaker] = None,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.store = store
        self.capacity = int(capacity)
        self.width = _next_pow2(width or store.max_width)
        # Optional circuit breaker around store lookups: when open, misses
        # degrade to the fallback zero row (fixed-effect-only) instead of
        # touching — or failing on — a sick store. Cache HITS still serve
        # full RE scores; only the store path degrades.
        self.breaker = breaker
        # +1 row: the permanent fallback zero row (all-ghost projection).
        self.proj = jnp.full(
            (self.capacity + 1, self.width), store.global_dim, jnp.int32
        )
        self.coef = jnp.zeros((self.capacity + 1, self.width), jnp.float32)
        self._slots: OrderedDict = OrderedDict()   # key -> slot, LRU order
        self._free = list(range(self.capacity))
        self._lock = threading.Lock()
        self.stats = {
            "hits": 0, "misses": 0, "evictions": 0, "fallbacks": 0,
            "degraded": 0, "invalidations": 0,
        }

    def invalidate(self, keys) -> int:
        """Drop patched entities from the hot-set so their next resolve
        restages fresh coefficients from the (just-patched) store.

        Bookkeeping only: the device tables are NOT rewritten here — a
        freed slot's stale row is overwritten by the next ``resolve`` that
        reuses it, and every resolve+gather runs on the micro-batcher's
        single worker thread (class doc), so an in-flight batch that
        already resolved the old slot gathers consistent PRE-delta rows,
        never a torn mix. Returns the number of entities dropped.
        """
        n = 0
        with self._lock:
            for key in keys:
                slot = self._slots.pop(key, None)
                if slot is not None:
                    self._free.append(slot)
                    n += 1
            self.stats["invalidations"] += n
        return n

    @property
    def fallback_slot(self) -> int:
        return self.capacity

    def slot_for(self, key) -> int:
        """Cache slot for ONE entity, staging its coefficients on a miss.
        ``None`` keys and unseen entities get the fallback zero row."""
        return int(self.slots_for([key])[0])

    def slots_for(self, keys) -> np.ndarray:
        """Cache slots for a batch of entity keys (see :meth:`resolve`)."""
        return self.resolve(keys)[0]

    def resolve(self, keys) -> tuple[np.ndarray, np.ndarray]:
        """``(slots, degraded)`` for a batch of entity keys, staging misses.
        ``degraded[i]`` marks rows routed to the fallback zero row because
        the store breaker was open or the store call failed — NOT rows whose
        entity is simply unseen (those are correct fallbacks, not degraded).

        Slots already handed out WITHIN this batch are pinned against
        eviction until the batch resolves — without the pin, a batch
        touching more distinct entities than fit would evict a slot it
        already assigned, and the later gather would read another entity's
        coefficients. Requires ``capacity >= distinct keys per batch``
        (the scorer floors capacity at ``max_batch``).

        All of the batch's missed rows land on device in ONE batched
        ``.at[rows].set`` per table — per-miss eager sets would copy the
        whole [capacity+1, width] table once per missed entity, turning
        cold starts and long-tail churn O(capacity) per row.
        """
        out = np.empty(len(keys), np.int32)
        degraded = np.zeros(len(keys), bool)
        with self._lock:
            pinned: set = set()
            staged: list = []  # (slot, padded cols row, padded vals row)
            for i, key in enumerate(keys):
                out[i], degraded[i] = self._slot_locked(key, pinned, staged)
                if out[i] != self.capacity:
                    pinned.add(int(out[i]))
            if staged:
                rows = jnp.asarray(
                    np.fromiter((s for s, _, _ in staged), np.int32,
                                len(staged))
                )
                self.proj = self.proj.at[rows].set(
                    jnp.asarray(np.stack([p for _, p, _ in staged]))
                )
                self.coef = self.coef.at[rows].set(
                    jnp.asarray(np.stack([c for _, _, c in staged]))
                )
        return out, degraded

    def _guarded_lookup(self, key) -> tuple[Optional[tuple], bool]:
        """``store.lookup`` behind the breaker: ``(hit, degraded)``.
        Degraded = the store was not consulted (breaker open) or its call
        failed / ran slow — the row scores fixed-effect-only but the
        request survives."""
        br = self.breaker
        if br is None:
            return self.store.lookup(key), False
        if not br.allow():
            self.stats["degraded"] += 1
            return None, True
        t0 = time.monotonic()
        try:
            hit = self.store.lookup(key)
        except Exception:  # noqa: BLE001 - degrade, never fail the request
            br.record_failure()
            self.stats["degraded"] += 1
            return None, True
        br.record_success(time.monotonic() - t0)
        return hit, False

    def _slot_locked(self, key, pinned: set, staged: list) -> tuple[int, bool]:
        slot = self._slots.get(key) if key is not None else None
        if slot is not None:
            self._slots.move_to_end(key)
            self.stats["hits"] += 1
            return slot, False
        hit, degraded = (
            self._guarded_lookup(key) if key is not None else (None, False)
        )
        if hit is None:
            if not degraded:
                self.stats["fallbacks"] += 1
            return self.capacity, degraded
        cols, vals = hit
        if len(cols) > self.width:
            raise ValueError(
                f"entity {key!r} has {len(cols)} coefficients but the "
                f"device cache width is {self.width}"
            )
        if self._free:
            slot = self._free.pop()
        else:
            victim = next(
                (k for k, s in self._slots.items() if s not in pinned), None
            )
            if victim is None:
                raise RuntimeError(
                    f"batch needs more than {self.capacity} distinct "
                    "entities; raise cache capacity above max_batch"
                )
            slot = self._slots.pop(victim)
            self.stats["evictions"] += 1
        row_p = np.full(self.width, self.store.global_dim, np.int32)
        row_c = np.zeros(self.width, np.float32)
        row_p[: len(cols)] = cols
        row_c[: len(vals)] = vals
        staged.append((slot, row_p, row_c))
        self._slots[key] = slot
        self.stats["misses"] += 1
        return slot, False

    def gather(self, slots) -> tuple:
        """Per-row (proj, coef) ``[B, P]`` device arrays for a slot vector —
        the eager gather feeding the jitted scoring kernel."""
        s = jnp.asarray(np.asarray(slots, np.int32))
        return self.proj[s], self.coef[s]

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "capacity": self.capacity,
                "width": self.width,
                "resident": len(self._slots),
                "store_patched": self.store.n_patched,
                **self.stats,
            }
        if self.breaker is not None:
            out["breaker"] = self.breaker.snapshot()
        return out
