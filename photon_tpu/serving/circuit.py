"""Circuit breaker for the serving coefficient-store path
(docs/robustness.md §degradation ladder).

A misbehaving coefficient store — IO errors from an mmap'd table on a sick
filesystem, or latency spikes that stall the batcher's single worker —
must not fail or stall scoring requests: GAME scoring degrades cleanly to
fixed-effect-only (the same zero-model fallback unseen entities already
take), which is a worse score but a correct one. The breaker makes that
degradation *deliberate and bounded* instead of per-call:

* CLOSED: calls flow; consecutive failures (and calls slower than
  ``slow_call_s``, if set) count toward ``failure_threshold``.
* OPEN: every call is short-circuited to the fallback for ``cooldown_s`` —
  a sick store is not hammered while it is sick, and scoring latency stays
  flat instead of absorbing per-request store timeouts.
* HALF_OPEN: after the cooldown one probe call is let through; success
  closes the breaker, failure re-opens it for another cooldown.

Thread-safe; the ``clock`` parameter exists for deterministic tests.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

__all__ = ["CircuitBreaker"]

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    def __init__(
        self,
        failure_threshold: int = 5,
        cooldown_s: float = 2.0,
        slow_call_s: Optional[float] = None,
        clock=time.monotonic,
    ):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}"
            )
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self.slow_call_s = slow_call_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._open_until = 0.0
        self._probe_in_flight = False
        self.stats = {
            "successes": 0, "failures": 0, "slow_calls": 0,
            "opens": 0, "short_circuited": 0,
        }

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May the protected call proceed? ``False`` = degrade right now.
        In OPEN past the cooldown, admits exactly ONE probe (HALF_OPEN);
        the caller must follow up with ``record_success``/``record_failure``
        to resolve the probe."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN and self._clock() >= self._open_until:
                self._state = HALF_OPEN
                self._probe_in_flight = False
            if self._state == HALF_OPEN and not self._probe_in_flight:
                self._probe_in_flight = True
                return True
            self.stats["short_circuited"] += 1
            return False

    def record_success(self, duration_s: float = 0.0) -> None:
        with self._lock:
            slow = (
                self.slow_call_s is not None and duration_s > self.slow_call_s
            )
            if slow:
                # The call returned a usable value, but a store this slow is
                # failing its latency contract: count toward opening.
                self.stats["slow_calls"] += 1
                self._record_failure_locked()
                return
            self.stats["successes"] += 1
            self._consecutive_failures = 0
            if self._state == HALF_OPEN:
                self._state = CLOSED
                self._probe_in_flight = False

    def record_failure(self) -> None:
        with self._lock:
            self._record_failure_locked()

    def _record_failure_locked(self) -> None:
        self.stats["failures"] += 1
        self._consecutive_failures += 1
        if self._state == HALF_OPEN or (
            self._state == CLOSED
            and self._consecutive_failures >= self.failure_threshold
        ):
            self._state = OPEN
            self._open_until = self._clock() + self.cooldown_s
            self._probe_in_flight = False
            self.stats["opens"] += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "failure_threshold": self.failure_threshold,
                "cooldown_s": self.cooldown_s,
                **self.stats,
            }
