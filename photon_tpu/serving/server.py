"""Concurrent JSON scoring server on stdlib ``ThreadingHTTPServer``.

Routes (docs/serving.md §schema):

* ``POST /score``       — one JSON row → ``{"score": .., "model_version"}``
  (plus ``"degraded": [..]`` when RE coordinates scored fixed-effect-only
  behind an open coefficient-store circuit breaker)
* ``GET  /healthz``     — liveness + current model version; 503 once the
  batcher worker has died
* ``GET  /metrics``     — latency histogram (p50/p95/p99), lifetime +
  interval throughput, shed/expired counters, batcher + coefficient-cache
  + breaker stats, per-kernel compile/retrace counts (JSON)
* ``GET  /metrics?format=prom`` — the same state as Prometheus text
  exposition (docs/observability.md §scrape): latency summary, request
  counters, queue depth, device-memory watermark, kernel retrace counters
* ``POST /admin/swap``  — ``{"model_dir": ..}`` → hot-swap; blocking,
  atomic, in-flight requests unaffected

Handler threads only parse and wait; all device work funnels through the
micro-batcher's single worker. Overload story (docs/robustness.md): a full
admission queue sheds the request with HTTP 503 + ``Retry-After`` instead
of queueing unboundedly, and each admitted request carries a deadline the
batcher honors — an expired row is dropped before the kernel runs and its
waiter gets 503, never a hang. Metrics snapshots append to the output
directory's ``serving-metrics.jsonl`` through ``utils/logging``'s JSONL
writer (periodically and at shutdown).
"""
from __future__ import annotations

import json
import threading
import time
import urllib.parse
from collections import OrderedDict
from concurrent.futures import TimeoutError as FuturesTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from photon_tpu.estimators.game_transformer import SCORE_KERNEL_NAME
from photon_tpu.obs import (
    MetricsRegistry,
    REGISTRY as GLOBAL_REGISTRY,
    instant,
    new_trace_id,
    retrace,
    trace_context,
    trace_span,
)
from photon_tpu.obs import trace as obs_trace
from photon_tpu.serving.batcher import (
    DeadlineExceeded,
    MicroBatcher,
    Overloaded,
)
from photon_tpu.serving.registry import ModelRegistry
from photon_tpu.serving.scorer import RequestError
from photon_tpu.utils import write_metrics_jsonl

_REQUEST_TIMEOUT_S = 30.0


class ScoringServer:
    """Owns the HTTP front-end + instrumentation around registry/batcher."""

    def __init__(
        self,
        registry: ModelRegistry,
        batcher: MicroBatcher,
        host: str = "127.0.0.1",
        port: int = 0,
        logger=None,
        metrics_path: Optional[str] = None,
        metrics_interval_s: float = 60.0,
        request_timeout_s: float = _REQUEST_TIMEOUT_S,
        slo_config=None,
    ):
        self.registry = registry
        self.batcher = batcher
        self.logger = logger
        self.metrics_path = metrics_path
        self.request_timeout_s = float(request_timeout_s)
        # Declarative SLOs (docs/observability.md §SLO): a config path or
        # SloConfig, judged against each periodic metrics flush and the
        # shutdown flush — violations bump the process-global
        # slo_violations_total{slo=...} (visible on this server's
        # /metrics?format=prom via the registry merge) and emit trace
        # instants; the last report rides the JSON snapshot under "slo".
        if isinstance(slo_config, str):
            from photon_tpu.obs.analysis.slo import SloConfig

            slo_config = SloConfig.from_file(slo_config)
        self.slo_config = slo_config
        self._slo_last = None
        # Per-server metrics registry (docs/observability.md): the old
        # hand-rolled counter dict, the latency histogram, and the batcher/
        # cache/breaker snapshots all live here now, giving one state with
        # two exports — the JSON snapshot below and the Prometheus text
        # exposition at /metrics?format=prom. Per-instance (not the process
        # global) so multiple servers in one process never collide; the
        # process-global registry (kernel retrace counters, device-memory
        # watermark) is merged at exposition time.
        self.metrics = MetricsRegistry()
        self._counters = {
            name: self.metrics.counter(
                f"serve_{name}_total", f"scoring requests: {name}")
            for name in (
                "requests", "errors", "swaps", "patches", "shed", "expired",
                "degraded", "patch_duplicates", "tunes", "memory_sheds",
            )
        }
        # /admin/patch idempotency (docs/online.md): a publisher whose
        # POST timed out AFTER the server applied the delta retries the
        # same logical delta; replaying the cached result instead of
        # re-applying keeps the patch counters and patch_seq honest.
        # Bounded LRU — the publisher retries back-to-back, so even a
        # tiny window covers the at-least-once race with room to spare.
        self._patch_seen: "OrderedDict[str, dict]" = OrderedDict()
        self._patch_seen_lock = threading.Lock()
        self._latency = self.metrics.histogram(
            "serve_request_latency_seconds",
            "end-to-end /score latency (successful requests)",
        )
        # Per-stage latency waterfall (docs/serving.md §"Latency
        # waterfall"): one labeled summary, so p95 queue-wait vs p95
        # kernel is a single scrape, not a trace-file autopsy.
        self._stage_hist = self.metrics.histogram(
            "serve_stage_latency_seconds",
            "per-request stage waterfall: admission / queue_wait / "
            "batch_assembly / store_resolve / kernel / response "
            "(successful requests)",
        )
        self.metrics.gauge_fn(
            "serve_queue_depth", lambda: self.batcher.snapshot()["queued"],
            "requests waiting in the micro-batcher admission queue",
        )
        self.metrics.gauge_fn(
            "serve_batch_rows_mean",
            lambda: self.batcher.snapshot()["mean_batch_rows"],
            "mean coalesced micro-batch size",
        )
        self.metrics.gauge_fn(
            "serve_uptime_seconds", lambda: time.time() - self._started_at,
            "seconds since server start",
        )
        retrace.install_device_memory_gauges(self.metrics)
        # Startup registration of the recovery watermarks (gauge warm-up
        # audit, docs/observability.md): both read 0 ("never yet") from
        # the very first scrape instead of being absent until the first
        # swap/restart stamps them. recovery_snapshot still maps 0 →
        # None, so /healthz semantics are unchanged.
        for gname, ghelp in (
            ("swap_to_first_score_seconds",
             "seconds from a registry hot-swap publishing a version to "
             "its first completed scored batch"),
            ("restart_to_first_step_seconds",
             "seconds from process start to the restarted run's first "
             "completed step"),
        ):
            g = GLOBAL_REGISTRY.gauge(gname, ghelp)
            if not g.value():
                g.set(0.0)
        self._started_at = time.time()
        # Interval-rate state (satellite fix): lifetime requests/uptime
        # understates the current rate after any idle period, so each
        # snapshot also reports the rate over the window since the previous
        # snapshot/flush.
        self._rate_lock = threading.Lock()
        self._rate_prev_t = self._started_at
        self._rate_prev_requests = 0
        # Replication (docs/serving.md §"Replication"): a ReplicaTailer
        # attached via attach_replication surfaces its seq watermark + lag
        # on /healthz and the metrics snapshot — the staleness signal the
        # router weights traffic by.
        self.replication = None
        # Histogram batch autotuner (docs/serving.md §"Autotuned
        # batching"), attached by the front-line driver; /admin/tune
        # reports its current choice so operators see what the loop is
        # doing through the same surface they'd override it on.
        self.autotuner = None
        # Live fleet view: when set (serving driver, --telemetry-dir),
        # every metrics flush also exports the registry shard here so the
        # obs driver can aggregate this process BEFORE it exits.
        self.telemetry_shard_path: Optional[str] = None
        # Drain state (SIGTERM contract): the flag 503s requests arriving
        # on kept-alive connections after the listener closed; the
        # condition variable lets shutdown() wait for in-flight /score
        # handlers to finish before the batcher goes away.
        self._draining = False
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # route through PhotonLogger
                if server.logger is not None:
                    server.logger.debug("http: " + fmt, *args)

            def _reply(self, code: int, payload: dict, headers=()) -> None:
                body = json.dumps(payload).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _read_json(self) -> dict:
                if self.headers.get("Transfer-Encoding"):
                    # Only Content-Length bodies are read; silently scoring
                    # an empty row for a chunked body would be a wrong
                    # answer, not an error — refuse loudly instead. The
                    # unread chunk bytes would desync a kept-alive
                    # connection (parsed as the next request line), so
                    # this connection must close after the error reply.
                    self.close_connection = True
                    raise RequestError(
                        "chunked transfer encoding not supported; "
                        "send Content-Length"
                    )
                n = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(n) if n else b"{}"
                try:
                    return json.loads(raw or b"{}")
                except ValueError:
                    raise RequestError("request body is not valid JSON")

            def do_GET(self):
                path, _, query = self.path.partition("?")
                if path == "/metrics":
                    q = urllib.parse.parse_qs(query)
                    if q.get("format", ["json"])[0] in ("prom", "prometheus"):
                        # Prometheus text exposition: this server's registry
                        # merged with the process-global one (kernel
                        # retraces, device memory).
                        body = server.metrics.to_prometheus(
                            extra=GLOBAL_REGISTRY
                        ).encode("utf-8")
                        self.send_response(200)
                        self.send_header(
                            "Content-Type",
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    else:
                        self._reply(200, server.metrics_snapshot())
                    return
                if self.path == "/healthz":
                    v = server.registry.current
                    # Backend identity + restart/recovery counts ride every
                    # health reply (docs/robustness.md): an orchestrator —
                    # or the PR 6 gate's operator — can see at a glance
                    # WHICH backend is serving and whether the process has
                    # been limping through recoveries, not just alive/dead.
                    base = {
                        "model_version": v.version,
                        "backend": server.backend_name(),
                        "restarts": server.restart_counts(),
                        # Serving freshness (docs/online.md): swap + delta
                        # watermarks, so freshness SLOs are measurable
                        # whether or not an online trainer is attached.
                        "freshness": server.freshness(),
                        # Recovery latency watermarks + standby readiness
                        # (docs/robustness.md §"Recovery time").
                        "recovery": server.recovery_snapshot(),
                    }
                    if server.replication is not None:
                        # Seq watermark + lag (docs/serving.md
                        # §"Replication"): the router's staleness signal.
                        base["replication"] = server.replication.snapshot()
                    if not server.batcher.healthy:
                        self._reply(503, {
                            "status": "unhealthy",
                            "error": "batcher worker died: "
                                     f"{server.batcher.failed!r}",
                            "degraded": ["batcher_worker_dead"],
                            **base,
                        })
                        return
                    degraded = server.degraded_reasons(v)
                    self._reply(200, {
                        "status": "degraded" if degraded else "ok",
                        "degraded": degraded,
                        "model_dir": v.model_dir,
                        "uptime_s": round(
                            time.time() - server._started_at, 1),
                        **base,
                    })
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path == "/score":
                    self._score()
                elif self.path == "/admin/swap":
                    self._swap()
                elif self.path == "/admin/standby":
                    self._standby()
                elif self.path == "/admin/patch":
                    self._patch()
                elif self.path == "/admin/tune":
                    self._tune()
                elif self.path == "/admin/memory/shed":
                    self._memory_shed()
                elif self.path == "/admin/replication/restart":
                    self._replication_restart()
                else:
                    # Drain the unread body first: on a kept-alive
                    # connection it would otherwise be parsed as the next
                    # request line (same desync the chunked path closes).
                    n = int(self.headers.get("Content-Length") or 0)
                    if n:
                        self.rfile.read(n)
                    if self.headers.get("Transfer-Encoding"):
                        self.close_connection = True
                    self._reply(404, {"error": f"no route {self.path}"})

            def _score(self):
                # Drain gate (SIGTERM contract, docs/serving.md): once
                # shutdown began, the listener is closed — but a request
                # riding an already-open kept-alive connection could still
                # land here. Refuse it with the shed contract (503 +
                # Retry-After, connection closed) instead of racing the
                # batcher teardown; the router retries it on a live
                # replica.
                if server._draining:
                    n = int(self.headers.get("Content-Length") or 0)
                    if n:
                        self.rfile.read(n)
                    self.close_connection = True
                    self._reply(503, {"error": "server draining",
                                      "shed": True},
                                headers=(("Retry-After", "1"),))
                    return
                with server._inflight_cv:
                    server._inflight += 1
                try:
                    # Trace root: one trace id per request, attached to
                    # this thread for the admission spans and carried
                    # across the batcher boundary on the queue item
                    # (docs/observability.md). A client-supplied
                    # X-Photon-Trace-Id joins this server's spans to the
                    # CALLER's trace shard — the fleet merger renders the
                    # cross-process flow as one timeline
                    # (docs/observability.md §"Fleet view").
                    tid = (self.headers.get("X-Photon-Trace-Id")
                           or new_trace_id())
                    # Tail-based sampling (docs/observability.md §"Tail
                    # sampling"): register the request so its spans buffer
                    # in the ring; the verdict comes after the root span
                    # closes — promote on threshold breach or error,
                    # discard the boring majority.
                    tail = obs_trace.tail_sampler()
                    if tail is not None:
                        tail.begin(tid)
                    try:
                        with trace_context(tid), \
                                trace_span("serve.request",
                                           cat="serving") as req_span:
                            self._score_traced(req_span)
                    finally:
                        if tail is not None:
                            status = req_span.args.get("status")
                            tail.finish(
                                tid, req_span.seconds,
                                # Sheds are fast, loud, and counted — a
                                # shed flood must not flood the trace too.
                                error=status is None or (
                                    int(status) >= 500
                                    and not req_span.args.get("shed")),
                            )
                finally:
                    with server._inflight_cv:
                        server._inflight -= 1
                        server._inflight_cv.notify_all()

            def _score_traced(self, req_span):
                t0 = time.perf_counter()
                try:
                    with trace_span("serve.admission",
                                    cat="serving") as adm_span:
                        payload = self._read_json()
                        # Pressure-aware load shedding (docs/robustness.md
                        # §"Memory pressure"): past the critical device-
                        # memory watermark, admitting more rows only
                        # manufactures the next OOM — shed with the same
                        # 503 + Retry-After contract as a full queue. The
                        # body is read FIRST (an unread body would desync
                        # the kept-alive connection).
                        if server.shed_for_memory_pressure():
                            raise Overloaded(
                                "device memory watermark over critical; "
                                "shedding until pressure drains")
                        version = server.registry.current
                        row = version.scorer.parse_request(payload)
                        deadline = (
                            time.monotonic() + server.request_timeout_s
                        )
                        fut = server.batcher.submit(
                            version, row, deadline=deadline
                        )
                    # The batcher fails the future at the deadline; the
                    # +1s slack only covers a dead worker missed by the
                    # crash drain — a waiter must NEVER outlive its budget
                    # by more than that.
                    score = fut.result(
                        timeout=server.request_timeout_s + 1.0
                    )
                except RequestError as e:
                    server._count(errors=1)
                    req_span.set(status=400)
                    self._reply(400, {"error": str(e)})
                    return
                except Overloaded as e:
                    # Load shed: bounded queue full. 503 + Retry-After is
                    # the contract a client-side retry policy needs.
                    server._count(shed=1)
                    req_span.set(status=503, shed=True)
                    self._reply(503, {"error": str(e), "shed": True},
                                headers=(("Retry-After", "1"),))
                    return
                except (DeadlineExceeded, FuturesTimeout, TimeoutError):
                    server._count(expired=1)
                    req_span.set(status=503, expired=True)
                    self._reply(503, {"error": "request deadline exceeded"},
                                headers=(("Retry-After", "1"),))
                    return
                except Exception as e:  # noqa: BLE001 - a 500, not a crash
                    server._count(errors=1)
                    req_span.set(status=500)
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                    return
                total = time.perf_counter() - t0
                server.latency.observe(total)
                server._count(requests=1)
                req_span.set(status=200)
                # Stage waterfall (docs/serving.md §"Latency waterfall"):
                # admission measured here, queue_wait/batch_assembly/
                # store_resolve/kernel carried back on the ScoreResult,
                # response = everything the stage clock didn't cover
                # (future handoff, reply serialization).
                stages = {"admission": adm_span.seconds}
                stages.update(getattr(score, "stages", None) or {})
                stages["response"] = max(0.0, total - sum(stages.values()))
                for stage, sec in stages.items():
                    server._stage_hist.observe(sec, stage=stage)
                out = {"score": score, "model_version": version.version}
                degraded = getattr(score, "degraded", ())
                if degraded:
                    # Fixed-effect-only fallback behind an open store
                    # breaker: a usable score, but the client deserves to
                    # know which coordinates are missing.
                    server._count(degraded=1)
                    out["degraded"] = sorted(degraded)
                if "uid" in payload:
                    out["uid"] = payload["uid"]
                headers = ()
                if (self.headers.get("X-Photon-Timing") or "").lower() in (
                        "1", "true", "yes", "on"):
                    # Server-Timing-style opt-in breakdown on the response
                    # — durations in ms, stage order = waterfall order.
                    parts = [f"{st};dur={sec * 1e3:.3f}"
                             for st, sec in stages.items()]
                    parts.append(f"total;dur={total * 1e3:.3f}")
                    headers = (("X-Photon-Timing", ", ".join(parts)),)
                self._reply(200, out, headers=headers)

            def _swap(self):
                try:
                    payload = self._read_json()
                    if not isinstance(payload, dict):
                        raise RequestError(
                            "request body must be a JSON object")
                    model_dir = payload.get("model_dir")
                    if not model_dir:
                        raise RequestError("model_dir required")
                    v = server.registry.swap(model_dir)
                except RequestError as e:
                    self._reply(400, {"error": str(e)})
                    return
                except Exception as e:  # noqa: BLE001 - bad push, keep old
                    server._count(errors=1)
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                    return
                server._count(swaps=1)
                if server.logger is not None:
                    server.logger.info(
                        "hot-swapped to version %d (%s)", v.version, model_dir
                    )
                self._reply(200, {"model_version": v.version})

            def _standby(self):
                """Pre-warm the NEXT version (docs/robustness.md §"Recovery
                time"): build + warm model_dir off the hot path so the
                following /admin/swap to the same directory is a pointer
                move with zero scoring-kernel retraces."""
                try:
                    payload = self._read_json()
                    if not isinstance(payload, dict):
                        raise RequestError(
                            "request body must be a JSON object")
                    model_dir = payload.get("model_dir")
                    if not model_dir:
                        raise RequestError("model_dir required")
                    info = server.registry.prepare_standby(model_dir)
                except RequestError as e:
                    self._reply(400, {"error": str(e)})
                    return
                except Exception as e:  # noqa: BLE001 - bad dir, keep old
                    server._count(errors=1)
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                    return
                if server.logger is not None:
                    server.logger.info("standby prepared: %s", model_dir)
                self._reply(200, {"status": "prepared", **info})

            def _tune(self):
                """Hot-tune the micro-batcher (the control plane's damped
                autoscaling lever — docs/control.md §levers). Bounds are
                validated by ``MicroBatcher.reconfigure``; a bad value
                changes nothing."""
                try:
                    payload = self._read_json()
                    if not isinstance(payload, dict):
                        raise RequestError(
                            "request body must be a JSON object")
                    max_batch = payload.get("max_batch")
                    max_queue = payload.get("max_queue")
                    max_wait_ms = payload.get("max_wait_ms")
                    if (max_batch is None and max_queue is None
                            and max_wait_ms is None):
                        raise RequestError(
                            "max_batch, max_queue, or max_wait_ms required")
                    try:
                        cfg = server.batcher.reconfigure(
                            max_batch=(None if max_batch is None
                                       else int(max_batch)),
                            max_queue=(None if max_queue is None
                                       else int(max_queue)),
                            max_wait_ms=(None if max_wait_ms is None
                                         else float(max_wait_ms)),
                        )
                    except (TypeError, ValueError) as e:
                        raise RequestError(str(e)) from None
                except RequestError as e:
                    self._reply(400, {"error": str(e)})
                    return
                except Exception as e:  # noqa: BLE001 - keep old config
                    server._count(errors=1)
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                    return
                server._count(tunes=1)
                instant("serving.batcher_tuned", cat="serving", **cfg)
                if server.logger is not None:
                    server.logger.info(
                        "batcher tuned: max_batch=%d max_queue=%d "
                        "max_wait_ms=%.3f", cfg["max_batch"],
                        cfg["max_queue"], cfg["max_wait_ms"])
                # One actuation surface for the whole box: manual tunes
                # and the histogram autotuner act on the same batcher, so
                # the reply always reports the tuner's current choice.
                out = dict(cfg)
                out["autotune"] = (
                    server.autotuner.snapshot()
                    if server.autotuner is not None else {"enabled": False})
                self._reply(200, out)

            def _memory_shed(self):
                """Proactive device-memory shed (control plane's answer to
                a rising watermark, fired BEFORE the OOM ladder would).
                Spills every pinned sweep-cache byte — expendable by
                contract: spilled entries re-stream on next use."""
                n = int(self.headers.get("Content-Length") or 0)
                if n:
                    self.rfile.read(n)  # body carries nothing
                try:
                    out = server.shed_memory()
                except Exception as e:  # noqa: BLE001 - shed must not 500
                    server._count(errors=1)
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                    return
                server._count(memory_sheds=1)
                self._reply(200, out)

            def _replication_restart(self):
                """Journaled restart request for a dead replica tailer
                (the controller's ``replication_tailer_dead`` remediation;
                budget enforcement lives controller-side)."""
                n = int(self.headers.get("Content-Length") or 0)
                if n:
                    self.rfile.read(n)
                if server.replication is None:
                    self._reply(400, {
                        "error": "no replication tailer attached"})
                    return
                try:
                    out = server.replication.restart()
                except Exception as e:  # noqa: BLE001
                    server._count(errors=1)
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                    return
                if server.logger is not None:
                    server.logger.info(
                        "replication tailer restart requested "
                        "(restarted=%s)", out.get("restarted"))
                self._reply(200, out)

            def _patch(self):
                """Online model delta (docs/online.md §"Delta protocol"):
                changed-entity coefficient patches applied atomically to
                the current version's coefficient stores, device hot-set
                invalidated only for the patched entities. The publisher's
                X-Photon-Trace-Id (HttpPublisher attaches its publish
                span's id) carries through this handler's span and the
                serving.delta_applied instant, so the merged fleet
                timeline shows event→refresh→publish→apply as ONE flow."""
                tid = self.headers.get("X-Photon-Trace-Id")
                with trace_context(tid or new_trace_id()), \
                        trace_span("serve.patch", cat="serving"):
                    self._patch_traced()

            def _patch_traced(self):
                # At-least-once dedupe: HttpPublisher stamps each POST
                # with the delta's identity (seq + content digest); a
                # retry of a publish whose reply was lost replays the
                # FIRST application's result instead of double-applying —
                # patch_seq, patched_entities_total, and the
                # serving.delta_applied instant stay exactly-once. Keyed
                # on content, not bare seq: a restarted trainer
                # incarnation reuses low seqs for genuinely NEW deltas
                # (PR 16 replay contract), and those must apply.
                idem_key = self.headers.get("X-Photon-Idempotency-Key")
                if idem_key:
                    with server._patch_seen_lock:
                        cached = server._patch_seen.get(idem_key)
                        if cached is not None:
                            server._patch_seen.move_to_end(idem_key)
                    if cached is not None:
                        server._count(patch_duplicates=1)
                        if server.logger is not None:
                            server.logger.info(
                                "duplicate delta publish suppressed "
                                "(key=%s)", idem_key)
                        self._reply(200, {**cached, "duplicate": True})
                        return
                try:
                    payload = self._read_json()
                    from photon_tpu.online.delta import ModelDelta

                    try:
                        delta = ModelDelta.from_wire(payload)
                    except ValueError as e:
                        raise RequestError(str(e)) from None
                    if not delta.patches:
                        raise RequestError("delta has no patches")
                    result = server.registry.apply_delta(
                        delta.raw_patches(), seq=delta.seq,
                        event_horizon=delta.event_horizon,
                    )
                except RequestError as e:
                    server._count(errors=1)
                    self._reply(400, {"error": str(e)})
                    return
                except ValueError as e:
                    # Validation refused the delta (unknown coordinate,
                    # over-wide patch): the producer's bug, nothing applied.
                    server._count(errors=1)
                    self._reply(400, {"error": str(e)})
                    return
                except Exception as e:  # noqa: BLE001 - bad push, keep old
                    server._count(errors=1)
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                    return
                server._count(patches=1)
                if idem_key:
                    with server._patch_seen_lock:
                        server._patch_seen[idem_key] = result
                        while len(server._patch_seen) > 256:
                            server._patch_seen.popitem(last=False)
                if server.logger is not None:
                    server.logger.info(
                        "applied delta patch_seq=%d (%d entities)",
                        result["patch_seq"], result["patched"],
                    )
                self._reply(200, result)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self._loop_started = False
        self._serve_thread: Optional[threading.Thread] = None
        self._metrics_stop = threading.Event()
        self._metrics_thread: Optional[threading.Thread] = None
        # The flush loop runs for EITHER consumer: a JSONL path to append
        # to, or SLOs to judge on the flush cadence (an SLO-only server
        # must still evaluate periodically, not just at shutdown).
        if metrics_path or self.slo_config is not None:
            self._metrics_thread = threading.Thread(
                target=self._metrics_loop,
                args=(float(metrics_interval_s),),
                name="photon-serve-metrics",
                daemon=True,
            )
            self._metrics_thread.start()

    # ---------------------------------------------------------------- admin

    @property
    def address(self) -> tuple:
        return self.httpd.server_address[:2]

    def _count(self, **deltas) -> None:
        for k, d in deltas.items():
            self._counters[k].inc(d)

    @property
    def counters(self) -> dict:
        """Back-compat view of the old counter dict (registry-backed)."""
        return {k: int(c.value()) for k, c in self._counters.items()}

    def backend_name(self) -> str:
        """The backend serving this process's kernels, cached after first
        read (``jax.default_backend()`` is non-trivially costly under the
        tunnel backend and cannot change without a process restart)."""
        cached = getattr(self, "_backend_name", None)
        if cached is not None:
            return cached
        try:
            import jax

            self._backend_name = jax.default_backend()
        except Exception:  # noqa: BLE001 - health must answer regardless
            self._backend_name = "unknown"
        return self._backend_name

    def restart_counts(self) -> dict:
        """Process-wide restart/recovery counts by classified cause
        (``run_restarts_total`` + the scorer's kernel recoveries) for the
        health payload: ``{"total": N, "<cause>": n, ...}``."""
        out: dict = {"total": 0}
        for name in ("run_restarts_total", "serve_kernel_recoveries_total"):
            for labels, value in GLOBAL_REGISTRY.counter(name).collect():
                if not value:
                    continue
                out["total"] += int(value)
                key = labels.get("cause", "unclassified")
                out[key] = out.get(key, 0) + int(value)
        return out

    def freshness(self) -> dict:
        """Registry freshness watermarks (active version, last swap, last
        delta patch) for /healthz and the metrics snapshot."""
        try:
            return self.registry.freshness_snapshot()
        except Exception:  # noqa: BLE001 - harness fakes lack a registry
            return {}

    def memory_snapshot(self) -> dict:
        """Device-memory watchdog state (thresholds + last watermark) for
        the metrics snapshot (docs/robustness.md §"Memory pressure")."""
        try:
            from photon_tpu.runtime.memory_guard import guard

            return guard().snapshot()
        except Exception:  # noqa: BLE001 - metrics must answer regardless
            return {}

    def recovery_snapshot(self) -> dict:
        """Recovery-time watermarks for /healthz (docs/robustness.md
        §"Recovery time"): the two latency gauges the zero-recompile stack
        stamps (None until first stamped) and standby readiness."""
        out: dict = {
            "restart_to_first_step_seconds": None,
            "swap_to_first_score_seconds": None,
        }
        try:
            for name in out:
                v = GLOBAL_REGISTRY.gauge(name).value()
                out[name] = v if v > 0 else None
        except Exception:  # noqa: BLE001 - health must answer regardless
            pass
        try:
            out["standby"] = self.registry.standby_snapshot()
        except Exception:  # noqa: BLE001 - harness fakes lack a registry
            out["standby"] = {"ready": False}
        return out

    def shed_memory(self) -> dict:
        """Unconditional proactive shed (``POST /admin/memory/shed``):
        spill ALL pinned sweep-cache bytes and resample the watermark.
        Unlike ``MemoryGuard.check`` this does not wait for high water —
        the control plane fires it on a watermark TREND, before the OOM
        ladder would have to act reactively. Spilled entries re-stream on
        next use: throughput cost, never a wrong answer."""
        from photon_tpu.data.device_cache import shed_pins
        from photon_tpu.runtime.memory_guard import guard

        freed = shed_pins(1 << 62)
        g = guard()
        sample = g.sample(force=True)
        instant("serving.memory_shed", cat="serving",
                freed_bytes=int(freed),
                watermark=(None if sample is None
                           else round(sample["watermark"], 4)))
        if self.logger is not None:
            self.logger.info(
                "proactive memory shed freed %d bytes", freed)
        return {
            "freed_bytes": int(freed),
            "watermark": (None if sample is None
                          else round(sample["watermark"], 4)),
            "available": sample is not None,
        }

    def shed_for_memory_pressure(self) -> bool:
        """Admission gate: shed once the device-memory watermark crosses
        critical (``runtime/memory_guard``; throttled sample, so this is a
        cached-float compare per request, not a device call)."""
        try:
            from photon_tpu.runtime.memory_guard import guard

            return guard().should_shed()
        except Exception:  # noqa: BLE001 - shedding must never 500
            return False

    def degraded_reasons(self, version=None) -> list:
        """Why this (otherwise alive) server is serving worse answers:
        open/half-open circuit breakers (per-coordinate store breakers and
        the scorer's kernel breaker), device memory pressure over the
        high-water mark, and a dead or errored replication tailer (a
        replica whose state is permanently frozen must be drained by the
        router, not kept in rotation at an ever-staler watermark).
        Empty = fully healthy."""
        v = version if version is not None else self.registry.current
        reasons = []
        try:
            snap = v.scorer.breaker_snapshot()
        except Exception:  # noqa: BLE001 - harness fakes lack a scorer
            snap = {}
        for cid, s in sorted(snap.items()):
            if s.get("state") in ("open", "half_open"):
                kind = "kernel" if cid == "__kernel__" else f"store:{cid}"
                reasons.append(f"breaker_{s['state']}:{kind}")
        try:
            from photon_tpu.runtime.memory_guard import guard

            if guard().under_pressure():
                reasons.append("memory_pressure")
        except Exception:  # noqa: BLE001 - health must answer regardless
            pass
        rep = getattr(self, "replication", None)
        if rep is not None:
            try:
                rsnap = rep.snapshot()
                if rsnap.get("error"):
                    # Refused delta or follow-loop crash: the tailer
                    # refuses to advance, so the watermark is frozen.
                    reasons.append("replication_error")
                elif rsnap.get("started") and not rsnap.get("running"):
                    # start() was called but the thread is gone without a
                    # deliberate stop(): dead tailer, frozen state.
                    reasons.append("replication_tailer_dead")
            except Exception:  # noqa: BLE001 - health must answer
                reasons.append("replication_unknown")
        return reasons

    @property
    def latency(self):
        """The live latency histogram — resolved through the registry
        metric so a registry reset can never orphan the server's view."""
        return self._latency.histogram

    def metrics_snapshot(self, advance_interval: bool = False) -> dict:
        """Live metrics. ``advance_interval`` moves the interval-rate
        window forward; only the periodic JSONL flush passes True, so an
        external scraper polling ``GET /metrics`` cannot shrink the window
        the persisted interval rate covers — scrapes see the rate since
        the last flush, read-only."""
        v = self.registry.current
        now = time.time()
        elapsed = max(now - self._started_at, 1e-9)
        # Interval rate (deltas between flushes): the lifetime
        # requests/uptime figure understates the CURRENT rate after any
        # idle period — a server idle overnight then serving 1k rows/s
        # would report ~0. Both are reported; dashboards want the interval
        # figure, capacity ledgers the lifetime one. Counter reads happen
        # INSIDE the lock so two concurrent snapshots can never observe a
        # window whose request delta went backwards (negative rate).
        with self._rate_lock:
            counters = self.counters
            dt = now - self._rate_prev_t
            dreq = counters["requests"] - self._rate_prev_requests
            if advance_interval:
                self._rate_prev_t = now
                self._rate_prev_requests = counters["requests"]
        interval_rate = round(dreq / dt, 2) if dt > 1e-3 else None
        return {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "model_version": v.version,
            "latency": self.latency.snapshot(),
            "throughput_rows_per_sec": round(
                counters["requests"] / elapsed, 2),
            "throughput_interval_rows_per_sec": interval_rate,
            "interval_s": round(dt, 3),
            **counters,
            "freshness": self.freshness(),
            "memory": self.memory_snapshot(),
            "batcher": self.batcher.snapshot(),
            "coefficient_caches": v.scorer.cache_snapshot(),
            "breakers": v.scorer.breaker_snapshot(),
            "kernel_traces": retrace.traces(SCORE_KERNEL_NAME),
            "kernel_retraces_after_warmup": retrace.retraces_after_warmup(
                SCORE_KERNEL_NAME),
            # getattr: harness fakes build servers via __new__ and only
            # set what they exercise
            **({"replication": self.replication.snapshot()}
               if getattr(self, "replication", None) is not None else {}),
            **({"slo": self._slo_last.to_dict()}
               if getattr(self, "_slo_last", None) is not None else {}),
        }

    def _metrics_loop(self, interval_s: float) -> None:
        while not self._metrics_stop.wait(interval_s):
            self.flush_metrics()

    def check_slos(self, snapshot: Optional[dict] = None) -> Optional[dict]:
        """Judge the configured SLOs against ``snapshot`` (or a fresh one;
        called at every flush + shutdown, and directly by benches/tests).
        Returns the report dict, or None without a config."""
        if self.slo_config is None:
            return None
        if snapshot is None:
            snapshot = self.metrics_snapshot()
        self._slo_last = self.slo_config.evaluate(snapshot, where="serving")
        if not self._slo_last.ok and self.logger is not None:
            self.logger.warning(
                "serving SLO violations: %s",
                [r.name for r in self._slo_last.violations])
        return self._slo_last.to_dict()

    def flush_metrics(self) -> None:
        # SLO judgment happens on the flush cadence whether or not a JSONL
        # path is configured — the violation counter and trace instants
        # are the contract; the JSONL record is one more consumer. ONE
        # snapshot serves both, so the persisted record and the SLO values
        # written beside it can never disagree (and the interval window
        # only advances when a record is actually persisted).
        if (self.slo_config is None and not self.metrics_path
                and not self.telemetry_shard_path):
            return
        snap = self.metrics_snapshot(
            advance_interval=bool(self.metrics_path))
        slo = self.check_slos(snapshot=snap)
        if slo is not None:
            snap = {**snap, "slo": slo}
        if self.metrics_path:
            write_metrics_jsonl(self.metrics_path, [snap])
        if self.telemetry_shard_path:
            # Live fleet view (docs/observability.md §"Live fleet view"):
            # export the registry shard on the flush cadence, not only at
            # exit, so the obs driver's /fleet sees this replica's
            # counters WHILE it serves. Atomic write + idempotent
            # per-shard_id merge make the re-export safe; best-effort by
            # the telemetry contract.
            try:
                from photon_tpu.obs import fleet
                fleet.write_registry_shard(
                    self.telemetry_shard_path, registries=(self.metrics,))
            except Exception as e:  # noqa: BLE001 - evidence, never a failure
                if self.logger is not None:
                    self.logger.warning(
                        "live registry shard export failed: %s", e)

    def start(self) -> None:
        """Serve in a background thread (tests / embedded use)."""
        self._loop_started = True
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever,
            name="photon-serve-http",
            daemon=True,
        )
        self._serve_thread.start()

    def serve_forever(self) -> None:
        self._loop_started = True
        self.httpd.serve_forever()

    def attach_replication(self, tailer) -> None:
        """Expose a ``ReplicaTailer``'s watermark/lag on /healthz and the
        metrics snapshot (the serving driver's ``--delta-log`` replica
        mode wires this before serving starts)."""
        self.replication = tailer

    def shutdown(self, drain_timeout_s: float = 10.0) -> None:
        """Graceful drain (the SIGTERM contract, docs/serving.md):

        1. **Stop accepting** — the draining flag 503-sheds requests that
           arrive on already-open kept-alive connections, and the
           listening socket closes, so nothing new is admitted.
        2. **Finish in-flight batches** — wait (bounded by
           ``drain_timeout_s``) for every admitted /score handler to get
           its answer through the batcher before the worker goes away.
        3. **Close the batcher** — anything still queued past the
           deadline fails fast rather than hanging its waiter.
        4. **Flush telemetry** — the final metrics snapshot lands in the
           JSONL history (and SLOs are judged once more); the driver
           writes the registry telemetry shard right after this returns.
        """
        self._draining = True
        self._metrics_stop.set()
        if self._loop_started:
            # socketserver.shutdown() handshakes with serve_forever() and
            # would block forever if the loop never ran (build-only use).
            self.httpd.shutdown()
        self.httpd.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=5.0)
        # Handler threads are daemons (never joined by server_close), so
        # the in-flight wait below is the ONLY thing standing between an
        # admitted request and a batcher teardown under its feet.
        deadline = time.monotonic() + float(drain_timeout_s)
        with self._inflight_cv:
            while self._inflight > 0 and time.monotonic() < deadline:
                self._inflight_cv.wait(timeout=0.1)
            leftover = self._inflight
        if leftover and self.logger is not None:
            self.logger.warning(
                "shutdown drain timed out with %d request(s) in flight",
                leftover)
        self.batcher.close()
        self.flush_metrics()
