"""Concurrent JSON scoring server on stdlib ``ThreadingHTTPServer``.

Routes (docs/serving.md §schema):

* ``POST /score``       — one JSON row → ``{"score": .., "model_version"}``
  (plus ``"degraded": [..]`` when RE coordinates scored fixed-effect-only
  behind an open coefficient-store circuit breaker)
* ``GET  /healthz``     — liveness + current model version; 503 once the
  batcher worker has died
* ``GET  /metrics``     — latency histogram (p50/p95/p99), throughput +
  shed/expired counters, batcher + coefficient-cache + breaker stats,
  kernel compile count
* ``POST /admin/swap``  — ``{"model_dir": ..}`` → hot-swap; blocking,
  atomic, in-flight requests unaffected

Handler threads only parse and wait; all device work funnels through the
micro-batcher's single worker. Overload story (docs/robustness.md): a full
admission queue sheds the request with HTTP 503 + ``Retry-After`` instead
of queueing unboundedly, and each admitted request carries a deadline the
batcher honors — an expired row is dropped before the kernel runs and its
waiter gets 503, never a hang. Metrics snapshots append to the output
directory's ``serving-metrics.jsonl`` through ``utils/logging``'s JSONL
writer (periodically and at shutdown).
"""
from __future__ import annotations

import json
import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from photon_tpu.estimators.game_transformer import SCORE_KERNEL_STATS
from photon_tpu.serving.batcher import (
    DeadlineExceeded,
    MicroBatcher,
    Overloaded,
)
from photon_tpu.serving.registry import ModelRegistry
from photon_tpu.serving.scorer import RequestError
from photon_tpu.utils import LatencyHistogram, write_metrics_jsonl

_REQUEST_TIMEOUT_S = 30.0


class ScoringServer:
    """Owns the HTTP front-end + instrumentation around registry/batcher."""

    def __init__(
        self,
        registry: ModelRegistry,
        batcher: MicroBatcher,
        host: str = "127.0.0.1",
        port: int = 0,
        logger=None,
        metrics_path: Optional[str] = None,
        metrics_interval_s: float = 60.0,
        request_timeout_s: float = _REQUEST_TIMEOUT_S,
    ):
        self.registry = registry
        self.batcher = batcher
        self.logger = logger
        self.metrics_path = metrics_path
        self.request_timeout_s = float(request_timeout_s)
        self.latency = LatencyHistogram()
        self.counters = {
            "requests": 0, "errors": 0, "swaps": 0,
            "shed": 0, "expired": 0, "degraded": 0,
        }
        self._started_at = time.time()
        self._counters_lock = threading.Lock()
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # route through PhotonLogger
                if server.logger is not None:
                    server.logger.debug("http: " + fmt, *args)

            def _reply(self, code: int, payload: dict, headers=()) -> None:
                body = json.dumps(payload).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _read_json(self) -> dict:
                if self.headers.get("Transfer-Encoding"):
                    # Only Content-Length bodies are read; silently scoring
                    # an empty row for a chunked body would be a wrong
                    # answer, not an error — refuse loudly instead. The
                    # unread chunk bytes would desync a kept-alive
                    # connection (parsed as the next request line), so
                    # this connection must close after the error reply.
                    self.close_connection = True
                    raise RequestError(
                        "chunked transfer encoding not supported; "
                        "send Content-Length"
                    )
                n = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(n) if n else b"{}"
                try:
                    return json.loads(raw or b"{}")
                except ValueError:
                    raise RequestError("request body is not valid JSON")

            def do_GET(self):
                if self.path == "/healthz":
                    v = server.registry.current
                    if not server.batcher.healthy:
                        self._reply(503, {
                            "status": "unhealthy",
                            "error": "batcher worker died: "
                                     f"{server.batcher.failed!r}",
                            "model_version": v.version,
                        })
                        return
                    self._reply(200, {
                        "status": "ok",
                        "model_version": v.version,
                        "model_dir": v.model_dir,
                        "uptime_s": round(
                            time.time() - server._started_at, 1),
                    })
                elif self.path == "/metrics":
                    self._reply(200, server.metrics_snapshot())
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path == "/score":
                    self._score()
                elif self.path == "/admin/swap":
                    self._swap()
                else:
                    # Drain the unread body first: on a kept-alive
                    # connection it would otherwise be parsed as the next
                    # request line (same desync the chunked path closes).
                    n = int(self.headers.get("Content-Length") or 0)
                    if n:
                        self.rfile.read(n)
                    if self.headers.get("Transfer-Encoding"):
                        self.close_connection = True
                    self._reply(404, {"error": f"no route {self.path}"})

            def _score(self):
                t0 = time.perf_counter()
                try:
                    payload = self._read_json()
                    version = server.registry.current
                    row = version.scorer.parse_request(payload)
                    deadline = time.monotonic() + server.request_timeout_s
                    fut = server.batcher.submit(
                        version, row, deadline=deadline
                    )
                    # The batcher fails the future at the deadline; the
                    # +1s slack only covers a dead worker missed by the
                    # crash drain — a waiter must NEVER outlive its budget
                    # by more than that.
                    score = fut.result(
                        timeout=server.request_timeout_s + 1.0
                    )
                except RequestError as e:
                    server._count(errors=1)
                    self._reply(400, {"error": str(e)})
                    return
                except Overloaded as e:
                    # Load shed: bounded queue full. 503 + Retry-After is
                    # the contract a client-side retry policy needs.
                    server._count(shed=1)
                    self._reply(503, {"error": str(e), "shed": True},
                                headers=(("Retry-After", "1"),))
                    return
                except (DeadlineExceeded, FuturesTimeout, TimeoutError):
                    server._count(expired=1)
                    self._reply(503, {"error": "request deadline exceeded"},
                                headers=(("Retry-After", "1"),))
                    return
                except Exception as e:  # noqa: BLE001 - a 500, not a crash
                    server._count(errors=1)
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                    return
                server.latency.observe(time.perf_counter() - t0)
                server._count(requests=1)
                out = {"score": score, "model_version": version.version}
                degraded = getattr(score, "degraded", ())
                if degraded:
                    # Fixed-effect-only fallback behind an open store
                    # breaker: a usable score, but the client deserves to
                    # know which coordinates are missing.
                    server._count(degraded=1)
                    out["degraded"] = sorted(degraded)
                if "uid" in payload:
                    out["uid"] = payload["uid"]
                self._reply(200, out)

            def _swap(self):
                try:
                    payload = self._read_json()
                    if not isinstance(payload, dict):
                        raise RequestError(
                            "request body must be a JSON object")
                    model_dir = payload.get("model_dir")
                    if not model_dir:
                        raise RequestError("model_dir required")
                    v = server.registry.swap(model_dir)
                except RequestError as e:
                    self._reply(400, {"error": str(e)})
                    return
                except Exception as e:  # noqa: BLE001 - bad push, keep old
                    server._count(errors=1)
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})
                    return
                server._count(swaps=1)
                if server.logger is not None:
                    server.logger.info(
                        "hot-swapped to version %d (%s)", v.version, model_dir
                    )
                self._reply(200, {"model_version": v.version})

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self._loop_started = False
        self._serve_thread: Optional[threading.Thread] = None
        self._metrics_stop = threading.Event()
        self._metrics_thread: Optional[threading.Thread] = None
        if metrics_path:
            self._metrics_thread = threading.Thread(
                target=self._metrics_loop,
                args=(float(metrics_interval_s),),
                name="photon-serve-metrics",
                daemon=True,
            )
            self._metrics_thread.start()

    # ---------------------------------------------------------------- admin

    @property
    def address(self) -> tuple:
        return self.httpd.server_address[:2]

    def _count(self, **deltas) -> None:
        with self._counters_lock:
            for k, d in deltas.items():
                self.counters[k] += d

    def metrics_snapshot(self) -> dict:
        v = self.registry.current
        with self._counters_lock:
            counters = dict(self.counters)
        elapsed = max(time.time() - self._started_at, 1e-9)
        return {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "model_version": v.version,
            "latency": self.latency.snapshot(),
            "throughput_rows_per_sec": round(
                counters["requests"] / elapsed, 2),
            **counters,
            "batcher": self.batcher.snapshot(),
            "coefficient_caches": v.scorer.cache_snapshot(),
            "breakers": v.scorer.breaker_snapshot(),
            "kernel_traces": SCORE_KERNEL_STATS["traces"],
        }

    def _metrics_loop(self, interval_s: float) -> None:
        while not self._metrics_stop.wait(interval_s):
            self.flush_metrics()

    def flush_metrics(self) -> None:
        if self.metrics_path:
            write_metrics_jsonl(self.metrics_path, [self.metrics_snapshot()])

    def start(self) -> None:
        """Serve in a background thread (tests / embedded use)."""
        self._loop_started = True
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever,
            name="photon-serve-http",
            daemon=True,
        )
        self._serve_thread.start()

    def serve_forever(self) -> None:
        self._loop_started = True
        self.httpd.serve_forever()

    def shutdown(self) -> None:
        self._metrics_stop.set()
        if self._loop_started:
            # socketserver.shutdown() handshakes with serve_forever() and
            # would block forever if the loop never ran (build-only use).
            self.httpd.shutdown()
        self.httpd.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=5.0)
        self.batcher.close()
        self.flush_metrics()
