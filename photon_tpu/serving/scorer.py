"""Row scorer: request parsing + padded micro-batch assembly + the kernel.

One ``RowScorer`` per ``ModelVersion``. It owns the device-resident fixed
coefficients, the per-RE-coordinate ``CoefficientStore`` + LRU device
cache, and the stable-shape contract that keeps the shared jitted kernel
(``estimators.game_transformer.additive_score_rows``) from ever
recompiling after warmup:

* row counts pad to the next power of two, capped at ``max_batch`` — a
  fixed ladder of bucket shapes, all compiled by ``warmup()``;
* per-shard feature width is the FIXED ``max_row_nnz`` (requests beyond it
  are rejected with a client error, never silently truncated);
* the RE subspace width is fixed per version by the coefficient store's
  widest entity; LRU staging rewrites table rows without changing shapes.
"""
from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from photon_tpu.estimators.config import (
    FixedEffectDataConfig,
    RandomEffectDataConfig,
)
from photon_tpu.estimators.game_transformer import (
    SCORE_KERNEL_NAME,
    additive_score_rows,
)
from photon_tpu.faults import fault_point
from photon_tpu.game.coordinates import FixedEffectModel
from photon_tpu.obs import retrace, trace_span
from photon_tpu.game.random_effect import RandomEffectModel
from photon_tpu.serving.circuit import CircuitBreaker
from photon_tpu.serving.coefficient_store import (
    CoefficientStore,
    DeviceCoefficientCache,
    _next_pow2,
)


class RequestError(ValueError):
    """Client-side problem with one request (HTTP 400, not 500)."""


@dataclasses.dataclass
class ParsedRow:
    """One request after feature-index resolution (host numpy only)."""

    shard_idx: Mapping[str, np.ndarray]   # shard -> [K] int32 (ghost = dim)
    shard_val: Mapping[str, np.ndarray]   # shard -> [K] float32
    offset: float
    entity_keys: Mapping[str, Optional[str]]  # RE coordinate id -> key


class RowScorer:
    def __init__(self, model, data_configs, index_maps, shard_configs, config):
        self.model = model
        self.data_configs = dict(data_configs)
        self.index_maps = dict(index_maps)
        self.shard_configs = dict(shard_configs)
        self.config = config
        self._intercepts = {
            s: im.intercept_index
            for s, im in index_maps.items()
            if shard_configs[s].add_intercept
            and im.intercept_index is not None
        }

        fixed_parts, re_parts = [], []
        self._fixed_ws, self._caches = {}, {}
        for cid, dcfg in self.data_configs.items():
            m = model[cid]
            if isinstance(dcfg, FixedEffectDataConfig):
                if not isinstance(m, FixedEffectModel):
                    raise TypeError(
                        f"{cid!r}: fixed-effect config, {type(m)} model"
                    )
                w = m.model.coefficients.means.astype(jnp.float32)
                self._fixed_ws[cid] = jnp.concatenate(
                    [w, jnp.zeros((1,), w.dtype)]
                )
                fixed_parts.append((cid, dcfg.feature_shard))
            elif isinstance(dcfg, RandomEffectDataConfig):
                if not isinstance(m, RandomEffectModel):
                    raise TypeError(
                        f"{cid!r}: random-effect config, {type(m)} model"
                    )
                store = CoefficientStore.from_model(m)
                breaker = None
                if getattr(config, "breaker_failures", 0) > 0:
                    breaker = CircuitBreaker(
                        failure_threshold=config.breaker_failures,
                        cooldown_s=config.breaker_cooldown_s,
                        slow_call_s=config.breaker_slow_call_s or None,
                    )
                self._caches[cid] = DeviceCoefficientCache(
                    store,
                    # Floor at max_batch: batch slot resolution pins its
                    # own slots against eviction, which needs one slot per
                    # distinct in-batch entity in the worst case.
                    capacity=max(config.cache_entities, config.max_batch),
                    breaker=breaker,
                )
                re_parts.append((cid, dcfg.feature_shard))
            else:  # pragma: no cover - union is closed
                raise TypeError(f"unknown data config {type(dcfg)}")
        self.fixed_parts = tuple(fixed_parts)
        self.re_parts = tuple(re_parts)
        self._re_types = {
            cid: self.data_configs[cid].re_type for cid, _ in re_parts
        }
        self._shards_used = sorted(
            {shard for _, shard in fixed_parts + re_parts}
        )
        # Kernel-path circuit breaker (docs/robustness.md §"Backend-failure
        # resilience"): the store breakers above degrade a sick coefficient
        # STORE; this one bounds re-initialization attempts when the KERNEL
        # itself fails on a classified device loss. Closed: a device-lost
        # kernel error triggers one clear-caches + re-run recovery. Open
        # (repeated failures): recovery is skipped and the error fast-fails
        # to the batcher — scoring latency must not absorb doomed re-inits.
        # breaker_failures=0 disables it (same contract as the store
        # breakers): kernel errors then surface unrecovered, the pre-guard
        # behavior.
        kernel_failures = getattr(config, "breaker_failures", 5)
        self.kernel_breaker = (
            CircuitBreaker(
                failure_threshold=max(1, int(kernel_failures)),
                cooldown_s=getattr(config, "breaker_cooldown_s", 2.0) or 2.0,
            )
            if kernel_failures > 0 else None
        )
        # Effective micro-batch cap under the OOM degradation ladder
        # (docs/robustness.md §"Memory pressure"): an oom-classified
        # kernel failure halves it to the next-smaller WARMED bucket shape
        # (the power-of-two ladder warmup() compiles), sticky for the
        # RUN — the cap is seeded from the process-wide sticky plan, so a
        # registry hot-swap's fresh scorer starts at the proven-fitting
        # cap instead of re-OOMing its way back down (and re-burning the
        # shared downshift budget). The stable-shape no-recompile contract
        # is preserved: every downshifted shape is on the warmup ladder.
        cap = int(config.max_batch)
        from photon_tpu.runtime.memory_guard import sticky_plan

        sticky = sticky_plan("serving.kernel")
        if sticky and sticky.get("max_batch"):
            cap = max(1, min(cap, int(sticky["max_batch"])))
        self._max_batch_cap = cap
        self._warming = False

    # -------------------------------------------------------------- parsing

    def parse_request(self, payload: dict) -> ParsedRow:
        """JSON request → index-resolved row (docs/serving.md §schema).

        Feature lists live under the shard's feature-bag keys (same record
        fields the training data used); entity ids under ``entities`` (or a
        top-level field named like the RE type, mirroring the reader's
        metadataMap fallback). Unindexed features drop, like the reader;
        unknown entities keep the row and fall back to fixed-effect-only.
        """
        if not isinstance(payload, dict):
            raise RequestError("request body must be a JSON object")
        k_cap = self.config.max_row_nnz
        shard_idx, shard_val = {}, {}
        for shard in self._shards_used:
            imap = self.index_maps[shard]
            cfg = self.shard_configs[shard]
            dim = len(imap)
            idxs, vals = [], []
            icpt = self._intercepts.get(shard)
            if icpt is not None:
                idxs.append(icpt)
                vals.append(1.0)
            for bag in cfg.feature_bags:
                feats = payload.get(bag)
                if feats is None:
                    continue
                if not isinstance(feats, (list, tuple)):
                    raise RequestError(f"feature bag {bag!r} must be a list")
                for feat in feats:
                    try:
                        i = imap.get_index(feat["name"], feat.get("term"))
                        v = float(feat["value"])
                    except (TypeError, KeyError, ValueError) as e:
                        raise RequestError(
                            f"bad feature entry in bag {bag!r}: {e}"
                        ) from None
                    if i >= 0:  # unindexed features dropped, as the reader
                        idxs.append(i)
                        vals.append(v)
            if len(idxs) > k_cap:
                raise RequestError(
                    f"row has {len(idxs)} features in shard {shard!r}; "
                    f"serving caps rows at max_row_nnz={k_cap} "
                    "(raise the knob, don't truncate)"
                )
            row_i = np.full(k_cap, dim, np.int32)
            row_v = np.zeros(k_cap, np.float32)
            row_i[: len(idxs)] = idxs
            row_v[: len(vals)] = vals
            shard_idx[shard] = row_i
            shard_val[shard] = row_v

        entities = payload.get("entities") or {}
        if not isinstance(entities, dict):
            raise RequestError('"entities" must be a map of RE type -> id')
        entity_keys = {}
        for cid, re_type in self._re_types.items():
            key = entities.get(re_type)
            if key is None:
                key = payload.get(re_type)  # top-level fallback, as reader
            entity_keys[cid] = None if key is None else str(key)
        try:
            offset = float(payload.get("offset") or 0.0)
        except (TypeError, ValueError):
            raise RequestError("offset must be a number") from None
        return ParsedRow(
            shard_idx=shard_idx,
            shard_val=shard_val,
            offset=offset,
            entity_keys=entity_keys,
        )

    # -------------------------------------------------------------- scoring

    def _bucket(self, n: int) -> int:
        return min(_next_pow2(n), self._max_batch_cap)

    def score_rows(self, rows: Sequence[ParsedRow]) -> np.ndarray:
        """Scores for up to ``max_batch`` rows as ONE padded kernel call;
        longer sequences score in max_batch-sized chunks."""
        return self.score_rows_flagged(rows)[0]

    def arm_swap_clock(self, t0: Optional[float] = None) -> None:
        """Start the hot-swap→first-score clock (the registry arms it the
        moment this scorer's version is published). The first SERVED batch
        after arming stamps ``swap_to_first_score_seconds`` — on the
        standby path this collapses to the pointer-swap + one dispatch,
        which is the whole point (docs/robustness.md §"Recovery time")."""
        import time as _time

        self._swap_armed_t0 = _time.monotonic() if t0 is None else t0

    def _note_swap_first_score(self) -> None:
        # dict.pop is atomic under the GIL: exactly one serving thread
        # claims the armed clock, the rest see a no-op.
        t0 = self.__dict__.pop("_swap_armed_t0", None)
        if t0 is None:
            return
        import time as _time

        from photon_tpu.obs import instant
        from photon_tpu.obs.metrics import REGISTRY

        seconds = _time.monotonic() - t0
        REGISTRY.gauge(
            "swap_to_first_score_seconds",
            "seconds from a registry hot-swap publishing a version to its "
            "first completed scored batch (docs/robustness.md §recovery "
            "time)",
        ).set(round(seconds, 4))
        instant("recovery.swap_first_score", cat="recovery",
                seconds=round(seconds, 4))

    def score_rows_flagged(
        self, rows: Sequence[ParsedRow],
        stage_sink: Optional[dict] = None,
    ) -> tuple[np.ndarray, list]:
        """``(scores, flags)``: ``flags[i]`` is the tuple of RE coordinate
        ids whose contribution row ``i`` LOST to an open coefficient-store
        circuit breaker (fixed-effect-only degradation, docs/robustness.md);
        empty for fully-scored rows.

        ``stage_sink``, when given, accumulates the per-stage latency
        waterfall in seconds (``batch_assembly`` / ``store_resolve`` /
        ``kernel``) across every chunk — including downshift retries, so
        the waterfall prices what the batch actually cost, not what a
        clean pass would have.

        An ``oom``-classified kernel failure is absorbed by the bounded
        max-batch downshift (``_absorb_kernel_oom``): only the failed
        chunk onward re-scores at the smaller cap (already-completed
        chunks and their store resolves are kept — no extra device work
        under exactly the pressure that caused the OOM) — the waiters see
        a slower answer, never a 500, until the downshift budget (or the
        kernel breaker) says the device is truly out of room."""
        out, flags = [], []
        lo = 0
        downshifted = False
        while lo < len(rows):
            chunk = rows[lo: lo + self._max_batch_cap]
            try:
                if downshifted:
                    with retrace.expected_compiles():
                        s, f = self._score_chunk(chunk, stage_sink)
                else:
                    s, f = self._score_chunk(chunk, stage_sink)
            except Exception as e:  # noqa: BLE001 - classified below
                if not self._absorb_kernel_oom(e):
                    raise
                downshifted = True
                continue  # retry THIS chunk's rows at the smaller cap
            out.append(s)
            flags.extend(f)
            lo += len(chunk)
            # Only the retried chunk's dispatch is "expected": the shapes
            # at the smaller cap are warmed, so later chunks must keep
            # the retrace sentinel armed.
            downshifted = False
        if rows:
            self._note_swap_first_score()
        return (
            np.concatenate(out) if out else np.zeros(0, np.float32),
            flags,
        )

    def _absorb_kernel_oom(self, err) -> bool:
        """May the scoring path retry ``err`` at a halved micro-batch?

        The kernel CircuitBreaker treats repeated OOM like device errors —
        every OOM records a failure, and an OPEN breaker short-circuits
        the downshift into fast failures — but the ladder runs FIRST:
        halving to the next-smaller warmed power-of-two shape (floor 1
        row) usually fits, and shedding throughput beats shedding
        requests. Bounded by ``PHOTON_OOM_MAX_DOWNSHIFTS``; each
        downshift is journaled + counted (``runtime/memory_guard``) and
        sticky for this scorer."""
        from photon_tpu.runtime import memory_guard as _mg

        if not _mg.is_oom(err):
            return False
        if self.kernel_breaker is not None:
            self.kernel_breaker.record_failure()
            if not self.kernel_breaker.allow():
                _mg.journal_event(
                    "oom_exhausted", site="serving.kernel", cause="oom",
                    plan=f"max_batch={self._max_batch_cap}",
                    reason="kernel breaker open")
                return False
        cap = self._max_batch_cap
        half = cap // 2
        if half < 1:
            _mg.journal_event(
                "oom_exhausted", site="serving.kernel", cause="oom",
                plan="max_batch=1", reason="no smaller batch shape")
            return False
        new_cap = 1 << (half.bit_length() - 1)  # largest warmed pow2 <= half
        if not _mg.downshifter("serving.kernel").absorb(
                err, before=f"max_batch={cap}",
                after=f"max_batch={new_cap}"):
            return False
        self._max_batch_cap = new_cap
        # Process-sticky: the next hot-swap's scorer starts here too.
        _mg.set_sticky_plan("serving.kernel", {"max_batch": new_cap})
        return True

    def _score_chunk(
        self, rows: Sequence[ParsedRow],
        stage_sink: Optional[dict] = None,
    ) -> tuple[np.ndarray, list]:
        b = len(rows)
        with trace_span("serve.batch_assembly", cat="serving",
                        rows=b) as assembly_span:
            bp = self._bucket(b)
            k = self.config.max_row_nnz
            shard_idx, shard_val = {}, {}
            for shard in self._shards_used:
                dim = len(self.index_maps[shard])
                mi = np.full((bp, k), dim, np.int32)
                mv = np.zeros((bp, k), np.float32)
                for r, row in enumerate(rows):
                    mi[r] = row.shard_idx[shard]
                    mv[r] = row.shard_val[shard]
                shard_idx[shard] = jnp.asarray(mi)
                shard_val[shard] = jnp.asarray(mv)
            offsets = np.zeros(bp, np.float32)
            for r, row in enumerate(rows):
                offsets[r] = row.offset

        resolve_seconds = 0.0
        re_proj, re_coef = {}, {}
        degraded_rows: list[list[str]] = [[] for _ in range(b)]
        for cid, _ in self.re_parts:
            cache = self._caches[cid]
            keys = [row.entity_keys[cid] for row in rows]
            keys += [None] * (bp - b)  # pad rows → fallback zero row
            with trace_span("serve.store_resolve", cat="serving",
                            coordinate=cid, keys=b) as resolve_span:
                slots, degraded = cache.resolve(keys)
            resolve_seconds += resolve_span.seconds
            if degraded.any():
                for r in np.flatnonzero(degraded[:b]):
                    degraded_rows[int(r)].append(cid)
            re_proj[cid], re_coef[cid] = cache.gather(slots)

        def run_kernel() -> np.ndarray:
            # Chaos hook: error="device_lost" exercises the breaker-gated
            # re-init + retry below without a real device loss. Quiet
            # during warmup so a plan's `after` counts only served batches.
            if not self._warming:
                fault_point("serving.kernel", rows=b, bucket=bp)
            # First compile of a bucket shape is recorded in the AOT
            # compile store so a restarted serving process (or a standby
            # scorer) pre-warms the whole ladder instead of re-tracing.
            from photon_tpu.runtime.compile_store import dispatch_recorded

            scores = dispatch_recorded(
                SCORE_KERNEL_NAME, additive_score_rows,
                (jnp.asarray(offsets), shard_idx, shard_val,
                 self._fixed_ws, re_proj, re_coef),
                {"fixed_parts": self.fixed_parts,
                 "re_parts": self.re_parts})
            # The D2H fetch below is the sync point; inside the span so the
            # kernel span reports completed compute, not async dispatch.
            return np.asarray(scores)

        with trace_span("serve.kernel", cat="serving", rows=b,
                        bucket=bp) as kernel_span:
            try:
                host_scores = run_kernel()
                if self.kernel_breaker is not None:
                    self.kernel_breaker.record_success()
            except Exception as e:  # noqa: BLE001 - classified below
                host_scores = self._recover_kernel(e, run_kernel)
        if stage_sink is not None:
            # Accumulate (not assign): a downshift retry re-runs the chunk
            # and the waterfall must price both passes.
            for stage, sec in (("batch_assembly", assembly_span.seconds),
                               ("store_resolve", resolve_seconds),
                               ("kernel", kernel_span.seconds)):
                stage_sink[stage] = stage_sink.get(stage, 0.0) + sec
        return host_scores[:b], [tuple(d) for d in degraded_rows]

    def _recover_kernel(self, err: Exception, run_kernel) -> np.ndarray:
        """Kernel device-loss recovery, bounded by the kernel breaker:
        clear the executable caches (+ warm marks, so the retry's recompile
        is expected) and re-run the batch ONCE. ONLY a classified
        device_lost is recoverable this way — a deterministic kernel error
        (bad lowering, shape bug) would fail the retry identically, and
        purging every compiled serving shape for it would break the
        stable-shape latency contract for nothing. The breaker counts every
        failure; once open, recovery is short-circuited and the error
        fast-fails every waiter in the batch until the cooldown's half-open
        probe — a dead device must degrade to fast 500s, not a re-init
        storm."""
        from photon_tpu.obs.metrics import REGISTRY
        from photon_tpu.runtime.backend_guard import (
            classify_backend_error,
            is_device_lost,
        )

        cause = classify_backend_error(err)
        REGISTRY.counter(
            "serve_kernel_errors_total",
            "scoring-kernel failures by classified cause",
        ).inc(cause=cause)
        if self.kernel_breaker is None or not is_device_lost(err):
            raise err
        self.kernel_breaker.record_failure()
        if not self.kernel_breaker.allow():
            raise err
        from photon_tpu.obs import instant
        from photon_tpu.supervisor import clear_executable_caches

        instant("recovery.kernel_reinit", cat="recovery", cause=cause,
                error=f"{type(err).__name__}: {str(err)[:200]}")
        clear_executable_caches(f"serving kernel recovery [{cause}]")
        try:
            with retrace.expected_compiles():
                host_scores = run_kernel()
        except Exception:
            self.kernel_breaker.record_failure()
            raise
        self.kernel_breaker.record_success()
        REGISTRY.counter(
            "serve_kernel_recoveries_total",
            "scoring batches recovered by kernel re-initialization",
        ).inc(cause=cause)
        # The cache clear dropped EVERY bucket shape's executable and the
        # warm mark with them; re-warm the full ladder now (a closed set,
        # one-time cost on a rare recovery) so the stable-shape
        # no-recompile contract — and its retrace sentinel — re-arms.
        self.warmup()
        return host_scores

    def warmup(self) -> int:
        """Compile every row-bucket shape once (empty rows, fallback
        entities) so no request ever waits on XLA. Returns the number of
        buckets warmed."""
        dummy = ParsedRow(
            shard_idx={
                s: np.full(
                    self.config.max_row_nnz,
                    len(self.index_maps[s]),
                    np.int32,
                )
                for s in self._shards_used
            },
            shard_val={
                s: np.zeros(self.config.max_row_nnz, np.float32)
                for s in self._shards_used
            },
            offset=0.0,
            entity_keys={cid: None for cid, _ in self.re_parts},
        )
        sizes, b = [], 1
        # Ladder tops out at the EFFECTIVE cap: under a sticky OOM
        # downshift the shapes above it are unreachable (_bucket clamps),
        # and warming them would dispatch more rows than the cap admits.
        while b < self._max_batch_cap:
            sizes.append(b)
            b <<= 1
        sizes.append(self._max_batch_cap)  # reachable even when not pow2
        # A NEW version's warmup legitimately compiles new shapes (hot swap
        # to different max_batch/nnz). Suppress the sentinel for THIS
        # thread only: the old version keeps serving during a swap, and a
        # genuine retrace on a serving thread must still warn.
        self._warming = True
        try:
            with retrace.expected_compiles():
                for size in sizes:
                    self._score_chunk([dummy] * size)
        finally:
            self._warming = False
        # Shape ladder fully compiled: from here on, any further trace of
        # the scoring kernel is a hot-path retrace — the sentinel counts it
        # and warns (log + trace event + Prometheus counter).
        retrace.mark_warm(SCORE_KERNEL_NAME)
        return len(sizes)

    def validate_delta(self, coordinate: str, patches) -> None:
        """Validate one coordinate's entity patches WITHOUT applying:
        coordinate exists, every patch fits the device-cache row width,
        and the store accepts the column layout. The registry calls this
        for EVERY coordinate before the first apply, so a bad coordinate
        in a multi-coordinate delta can never leave another's patches
        half-published."""
        cache = self._caches.get(coordinate)
        if cache is None:
            raise ValueError(
                f"unknown random-effect coordinate {coordinate!r}; "
                f"patchable: {sorted(self._caches)}"
            )
        for key, (cols, _vals) in patches.items():
            if len(cols) > cache.width:
                raise ValueError(
                    f"patch for {coordinate!r}/{key!r} has {len(cols)} "
                    f"coefficients but the device cache width is "
                    f"{cache.width}; widen the serving config or shrink "
                    "the online subspace (max_event_nnz x window bounds it)"
                )
        cache.store.validate_patches(patches)

    def apply_delta(self, coordinate: str, patches) -> dict:
        """Apply one coordinate's entity patches (docs/online.md §"Delta
        protocol"): validate (atomicity — a delta either applies whole or
        not at all), overlay the store in one reference swap, then
        invalidate exactly the patched entities in the device hot-set.
        ``patches`` maps entity key → ``(cols, vals)``."""
        self.validate_delta(coordinate, patches)
        cache = self._caches[coordinate]
        patched = cache.store.apply_patches(patches)
        invalidated = cache.invalidate(list(patches))
        return {"patched": patched, "invalidated": invalidated}

    def cache_snapshot(self) -> dict:
        return {cid: c.snapshot() for cid, c in self._caches.items()}

    def breaker_snapshot(self) -> dict:
        """Per-RE-coordinate store breakers + the kernel breaker (for
        /metrics and /healthz degradation reporting). The kernel breaker
        rides under the reserved ``__kernel__`` key — coordinate ids come
        from user config and can never start with a dunder."""
        out = {
            cid: c.breaker.snapshot()
            for cid, c in self._caches.items()
            if c.breaker is not None
        }
        if self.kernel_breaker is not None:
            out["__kernel__"] = self.kernel_breaker.snapshot()
        return out
