"""Histogram-autotuned micro-batching (docs/serving.md §"Autotuned batching").

The micro-batcher's two knobs — ``max_batch`` (coalescing cap) and
``max_wait_ms`` (coalescing deadline) — were flags until PR 19. This
module chooses them **continuously from live telemetry** instead: every
tick it diffs the ``serve_stage_latency_seconds`` labeled-child states
(the PR 18 waterfall — the same mergeable histogram state the fleet
aggregator consumes) and the batcher's own fill counters, then nudges the
knobs along the scorer's WARMED power-of-two bucket ladder. Staying on
the ladder is load-bearing: every shape the autotuner can choose was
compiled by ``warmup()``, so autotuning never causes a scoring-kernel
retrace (the PR 19 acceptance gate).

Damping reuses the PR 17 autoscaler's discipline (control/policy.py):

* **hysteresis bands** — scale up only above ``queue_high`` occupancy,
  down only below ``queue_low``; between the bands the tuner holds;
* **min_run** — a direction must persist for N consecutive ticks before
  it acts (one bursty tick is noise, not a regime);
* **per-lever cooldown shared by both directions** — after an action the
  lever freezes, so an up/down flap inside the cooldown is impossible by
  construction.

The tuner reports its current choice and reasoning via :meth:`snapshot`,
which ``/admin/tune`` exposes (the control plane keeps one actuation
surface — satellite task, ISSUE 19).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from photon_tpu.utils.logging import LatencyHistogram


def _delta_hist(prev: Optional[dict], cur: dict) -> LatencyHistogram:
    """Histogram of ONLY the samples observed since ``prev`` (bin-wise
    state subtraction — exact, same contract the fleet merger relies on)."""
    if prev is None or len(prev["counts"]) != len(cur["counts"]):
        return LatencyHistogram.from_state(cur)
    return LatencyHistogram.from_state({
        "lo_ms": cur["lo_ms"],
        "bins_per_decade": cur.get("bins_per_decade", 20),
        "counts": [max(0, c - p) for c, p in
                   zip(cur["counts"], prev["counts"])],
        "sum": max(0.0, cur["sum"] - prev["sum"]),
        "max": cur["max"],
        "n": max(0, cur["n"] - prev["n"]),
    })


def _pow2_ladder(top: int) -> list[int]:
    """The warmed bucket ladder: powers of two below ``top``, plus ``top``
    itself (warmup() compiles exactly this set)."""
    sizes, b = [], 1
    while b < top:
        sizes.append(b)
        b <<= 1
    sizes.append(int(top))
    return sizes


class BatchAutotuner:
    """Drives ``MicroBatcher.reconfigure`` from live stage-latency state.

    ``ladder_max`` is the scorer's warmed batch cap (``ServingConfig
    .max_batch``); ``cap_fn`` optionally reports the scorer's CURRENT
    effective cap (the OOM downshift ladder may have lowered it) so the
    tuner never proposes an unreachable shape.
    """

    def __init__(
        self,
        batcher,
        stage_hist,
        *,
        ladder_max: int,
        cap_fn: Optional[Callable[[], int]] = None,
        tick_s: float = 1.0,
        min_run: int = 3,
        cooldown_s: float = 10.0,
        queue_high: float = 0.5,
        queue_low: float = 0.05,
        knee_latency_ms: float = 50.0,
        wait_bounds_ms: tuple = (0.25, 8.0),
        min_samples: int = 16,
        logger=None,
    ):
        self.batcher = batcher
        self.stage_hist = stage_hist
        self.ladder_max = int(ladder_max)
        self.cap_fn = cap_fn
        self.tick_s = float(tick_s)
        self.min_run = int(min_run)
        self.cooldown_s = float(cooldown_s)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.knee_latency_ms = float(knee_latency_ms)
        self.wait_bounds_ms = (float(wait_bounds_ms[0]),
                               float(wait_bounds_ms[1]))
        self.min_samples = int(min_samples)
        self.logger = logger
        self._prev_states: dict = {}
        self._prev_stats: dict = {}
        self._streak: dict = {"batch": 0, "wait": 0}
        self._cooldown_until: dict = {"batch": 0.0, "wait": 0.0}
        self._suppressed = {"cooldown": 0, "min_run": 0, "idle": 0}
        self._actions: deque = deque(maxlen=16)
        self._basis: dict = {}
        self._ticks = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        from photon_tpu.obs.metrics import REGISTRY

        self._action_counter = REGISTRY.counter(
            "serve_autotune_actions_total",
            "autotuner knob movements by lever and direction",
        )
        self._choice_gauge = REGISTRY.gauge(
            "serve_autotune_choice",
            "autotuned micro-batcher knobs (lever -> current value)",
        )
        self._publish_choice()

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="photon-serve-autotune", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.tick_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - a sick tuner must not kill serving
                if self.logger is not None:
                    self.logger.exception("autotune tick failed")

    # ------------------------------------------------------------------ tick

    def _stage_delta(self, stage: str) -> LatencyHistogram:
        cur = self.stage_hist.child(stage=stage).state()
        d = _delta_hist(self._prev_states.get(stage), cur)
        self._prev_states[stage] = cur
        return d

    def tick(self, now: Optional[float] = None) -> Optional[dict]:
        """One observation + (maybe) one actuation. Returns the action
        applied this tick, or None. Synchronous and side-effect-complete:
        tests drive it directly with synthetic histogram states."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return self._tick_locked(now)

    def _tick_locked(self, now: float) -> Optional[dict]:
        self._ticks += 1
        snap = self.batcher.snapshot()
        queue_frac = snap["queued"] / max(1, snap["max_queue"])
        d_batches = snap["batches"] - self._prev_stats.get("batches", 0)
        d_rows = snap["rows"] - self._prev_stats.get("rows", 0)
        self._prev_stats = {"batches": snap["batches"],
                            "rows": snap["rows"]}
        kernel = self._stage_delta("kernel")
        queue_wait = self._stage_delta("queue_wait")
        kernel_p95 = kernel.quantile_ms(0.95)
        kernel_p50 = kernel.quantile_ms(0.50)
        queue_p95 = queue_wait.quantile_ms(0.95)
        fill = (d_rows / d_batches / max(1, snap["max_batch"])
                if d_batches else 0.0)
        self._basis = {
            "queue_frac": round(queue_frac, 4),
            "kernel_p50_ms": round(kernel_p50, 3),
            "kernel_p95_ms": round(kernel_p95, 3),
            "queue_wait_p95_ms": round(queue_p95, 3),
            "batch_fill": round(fill, 3),
            "delta_rows": d_rows,
            "delta_samples": kernel._n,
        }
        if d_batches == 0 and queue_frac == 0.0:
            # Idle box: no evidence either way — hold everything. (An idle
            # tuner that shrank knobs would greet the next burst mistuned.)
            self._streak["batch"] = 0
            self._streak["wait"] = 0
            self._suppressed["idle"] += 1
            return None
        action = self._tune_batch(now, queue_frac, kernel_p95, fill, d_rows)
        if action is None:
            action = self._tune_wait(now, kernel, kernel_p50)
        return action

    # ---------------------------------------------------------------- levers

    def _ladder(self) -> list[int]:
        top = self.ladder_max
        if self.cap_fn is not None:
            try:
                top = max(1, min(top, int(self.cap_fn())))
            except Exception:  # noqa: BLE001 - cap probe must not stop tuning
                pass
        return _pow2_ladder(top)

    def _act(self, lever: str, direction: str, now: float,
             **changes) -> dict:
        cfg = self.batcher.reconfigure(**changes)
        self._cooldown_until[lever] = now + self.cooldown_s
        self._streak[lever] = 0
        self._action_counter.inc(lever=lever, direction=direction)
        action = {"lever": lever, "direction": direction, "at": time.time(),
                  "applied": changes, "basis": dict(self._basis)}
        self._actions.append(action)
        self._publish_choice()
        if self.logger is not None:
            self.logger.info(
                "autotune: %s %s -> %s  [%s]", lever, direction, changes,
                ", ".join(f"{k}={v}" for k, v in self._basis.items()))
        return {"config": cfg, **action}

    def _gate(self, lever: str, want: int, now: float) -> bool:
        """Hysteresis + cooldown shared by both directions (PR 17
        discipline). ``want`` is -1/0/+1; returns True when the lever may
        act NOW."""
        if want == 0:
            self._streak[lever] = 0
            return False
        streak = self._streak[lever]
        streak = streak + want if (streak == 0 or (streak > 0) == (want > 0)) \
            else want
        self._streak[lever] = streak
        if abs(streak) < self.min_run:
            self._suppressed["min_run"] += 1
            return False
        if now < self._cooldown_until[lever]:
            self._suppressed["cooldown"] += 1
            return False
        return True

    def _tune_batch(self, now, queue_frac, kernel_p95, fill,
                    d_rows) -> Optional[dict]:
        ladder = self._ladder()
        cur = self.batcher.max_batch
        # Snap onto the ladder (an operator /admin/tune may have set an
        # off-ladder value): the largest warmed size <= cur.
        at = max(i for i, s in enumerate(ladder) if s <= cur) \
            if cur >= ladder[0] else 0
        want = 0
        if (queue_frac >= self.queue_high
                and kernel_p95 <= self.knee_latency_ms
                and at + 1 < len(ladder)):
            # Queue is backing up while the kernel still has headroom:
            # bigger batches drain more rows per dispatch.
            want = +1
        elif (queue_frac <= self.queue_low and fill > 0
                and fill <= 0.25 and at > 0 and d_rows > 0):
            # Mostly-empty batches at a quiet queue: a smaller cap wastes
            # less padded compute per dispatch.
            want = -1
        if not self._gate("batch", want, now):
            return None
        new = ladder[at + want]
        if new == cur:
            return None
        return self._act("batch", "up" if want > 0 else "down", now,
                         max_batch=new)

    def _tune_wait(self, now, kernel: LatencyHistogram,
                   kernel_p50: float) -> Optional[dict]:
        if kernel._n < self.min_samples:
            self._streak["wait"] = 0
            return None
        # The coalescing deadline should cost about what one dispatch
        # costs: waiting much longer adds latency a bigger batch can't
        # repay; much shorter and concurrent rows miss the bus and pay a
        # whole extra kernel.
        lo, hi = self.wait_bounds_ms
        target = min(max(0.5 * kernel_p50, lo), hi)
        cur = self.batcher.max_wait_s * 1e3
        if cur <= 0:
            cur = lo
        ratio = target / cur
        want = +1 if ratio > 1.25 else (-1 if ratio < 0.8 else 0)
        if not self._gate("wait", want, now):
            return None
        return self._act("wait", "up" if want > 0 else "down", now,
                         max_wait_ms=round(target, 4))

    # ------------------------------------------------------------- reporting

    def _publish_choice(self) -> None:
        self._choice_gauge.set(float(self.batcher.max_batch),
                               lever="max_batch")
        self._choice_gauge.set(round(self.batcher.max_wait_s * 1e3, 4),
                               lever="max_wait_ms")

    def snapshot(self) -> dict:
        """Current choice + decision basis, reported via /admin/tune."""
        with self._lock:
            return {
                "enabled": True,
                "ticks": self._ticks,
                "current": {
                    "max_batch": self.batcher.max_batch,
                    "max_wait_ms": round(self.batcher.max_wait_s * 1e3, 4),
                },
                "ladder": self._ladder(),
                "basis": dict(self._basis),
                "suppressed": dict(self._suppressed),
                "actions": list(self._actions),
                "policy": {
                    "queue_high": self.queue_high,
                    "queue_low": self.queue_low,
                    "knee_latency_ms": self.knee_latency_ms,
                    "min_run": self.min_run,
                    "cooldown_s": self.cooldown_s,
                    "wait_bounds_ms": list(self.wait_bounds_ms),
                },
            }
