"""Request micro-batcher: coalesce concurrent single rows into one kernel call.

The serve-path economics: one padded kernel dispatch costs roughly the
same for 1 row as for 64 (the device work is tiny; dispatch dominates), so
under concurrency the batcher turns N in-flight single-row requests into
ceil(N / max_batch) dispatches. A single idle request pays at most
``max_wait_ms`` of coalescing latency.

One worker thread owns all device interaction (LRU staging + kernel
dispatch), which keeps the coefficient-cache mutation single-threaded by
construction. Each queue item carries its ``ModelVersion`` reference: a
batch only ever contains rows of ONE version, so a hot-swap mid-stream
simply splits a batch — in-flight requests finish on the version they
captured, new ones ride the new version, none are dropped.

Overload and failure story (docs/robustness.md):

* ADMISSION is bounded: at most ``max_queue`` requests may wait. Beyond
  that ``submit`` raises :class:`Overloaded` immediately — the server turns
  it into HTTP 503 + ``Retry-After`` (load shedding) instead of letting the
  queue, and every queued request's latency, grow without bound.
* DEADLINES propagate into the worker: a request whose deadline passed
  while it sat in the queue is failed with :class:`DeadlineExceeded`
  *before* the jitted kernel runs — the waiter already gave up, so burning
  device time on its row would only add latency to live requests behind it.
* A WORKER CRASH (exception escaping the loop itself, not a per-batch
  scoring error) fails every pending future immediately and marks the
  batcher unhealthy (``/healthz`` goes 503) — queued waiters must not sit
  out the full request timeout against a dead worker.
"""
from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Optional

from photon_tpu.faults import fault_point
from photon_tpu.obs import trace as obs_trace
from photon_tpu.obs.trace import current_trace_id, trace_span


class Overloaded(RuntimeError):
    """Admission queue full; the caller should shed this request (503)."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before its row reached the kernel."""


class _Pending:
    __slots__ = ("version", "row", "deadline", "future", "trace_id",
                 "enqueued_at")

    def __init__(self, version, row, deadline=None):
        self.version = version
        self.row = row
        self.deadline = deadline  # time.monotonic() value, or None
        self.future: Future = Future()
        # Trace propagation across the thread boundary (Dapper-style): the
        # submitting request's trace id rides the queue item so the worker
        # can correlate queue wait + kernel time back to the request.
        self.trace_id = current_trace_id()
        self.enqueued_at = time.perf_counter()


class MicroBatcher:
    def __init__(
        self,
        max_batch: int = 64,
        max_wait_ms: float = 2.0,
        max_queue: int = 1024,
        start: bool = True,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.max_queue = int(max_queue)
        # A bounded stdlib queue IS the admission control: put_nowait past
        # maxsize raises queue.Full, which submit turns into Overloaded.
        self._q: queue.Queue = queue.Queue(maxsize=self.max_queue)
        self._carry: list = []  # other-version items deferred one round
        self._inflight: list = []  # items the worker holds this round
        self._stop = threading.Event()
        self.failed: Optional[BaseException] = None
        # Serializes submit vs close/crash: a submit that passed the checks
        # must finish its put before a drain runs, or the item's future
        # would sit unresolved until the request timeout.
        self._submit_lock = threading.Lock()
        self.stats = {
            "batches": 0, "rows": 0, "max_batch_rows": 0,
            "shed": 0, "expired": 0,
        }
        self._thread = threading.Thread(
            target=self._loop, name="photon-serve-batcher", daemon=True
        )
        if start:
            self._thread.start()

    def start(self) -> None:
        if not self._thread.is_alive():
            self._thread.start()

    @property
    def healthy(self) -> bool:
        """False once the worker has died from an unexpected exception."""
        return self.failed is None

    def submit(self, version, row, deadline: Optional[float] = None) -> Future:
        """Enqueue one parsed row against ``version``; resolves to its
        score (or the scoring exception). ``deadline`` is a
        ``time.monotonic()`` value after which the row is dropped unscored
        (future fails with :class:`DeadlineExceeded`). Raises
        :class:`Overloaded` when the admission queue is full."""
        with self._submit_lock:
            if self.failed is not None:
                raise RuntimeError(
                    "batcher worker died"
                ) from self.failed
            if self._stop.is_set():
                raise RuntimeError("batcher is shut down")
            item = _Pending(version, row, deadline)
            try:
                self._q.put_nowait(item)
            except queue.Full:
                self.stats["shed"] += 1
                raise Overloaded(
                    f"admission queue full ({self.max_queue} waiting)"
                ) from None
        return item.future

    def close(self) -> None:
        with self._submit_lock:
            self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
        # Fail anything still queued rather than hanging its waiter.
        self._fail_pending(RuntimeError("scoring server shut down"))

    # ------------------------------------------------------------ internals

    def _take(self, timeout: Optional[float]) -> _Pending:
        """Pop one queued item (worker thread / final drain)."""
        if timeout is not None:
            return self._q.get(timeout=timeout)
        return self._q.get_nowait()

    def _fail_pending(self, error: BaseException) -> None:
        # _inflight first: items the worker had already dequeued when it
        # died would otherwise be invisible to the drain below and leave
        # their waiters hanging the full request timeout.
        leftovers = list(self._inflight) + list(self._carry)
        self._inflight = []
        self._carry = []
        while True:
            try:
                leftovers.append(self._take(None))
            except queue.Empty:
                break
        for item in leftovers:
            if not item.future.done():
                item.future.set_exception(error)

    def _loop(self) -> None:
        try:
            self._run()
        except BaseException as e:  # noqa: BLE001 - worker death, not a batch error
            # Mark failure UNDER the submit lock: any submit that has not
            # yet enqueued will now raise, and everything already enqueued
            # is drained below — no future can slip through unresolved.
            with self._submit_lock:
                self.failed = e
            self._fail_pending(
                RuntimeError(f"batcher worker died: {type(e).__name__}: {e}")
            )

    def _run(self) -> None:
        while not self._stop.is_set():
            items = self._carry
            self._carry = []
            if not items:
                try:
                    items = [self._take(timeout=0.1)]
                except queue.Empty:
                    continue
            deadline = time.monotonic() + self.max_wait_s
            while len(items) < self.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    items.append(self._take(timeout=remaining))
                except queue.Empty:
                    break
            # Drain anything already queued (no extra waiting).
            while len(items) < self.max_batch:
                try:
                    items.append(self._take(None))
                except queue.Empty:
                    break
            # Deadline-expired rows are dropped BEFORE the kernel runs:
            # their waiters have (or are about to) time out, and scoring
            # them would only delay the live rows behind them.
            now = time.monotonic()
            live = []
            for it in items:
                if it.deadline is not None and now >= it.deadline:
                    self.stats["expired"] += 1
                    if not it.future.done():
                        it.future.set_exception(DeadlineExceeded(
                            "request deadline passed before scoring"
                        ))
                else:
                    live.append(it)
            if not live:
                continue
            items = live
            self._inflight = items  # crash drain covers dequeued items
            fault_point("serving.batcher_batch", rows=len(items))
            v0 = items[0].version
            batch = [it for it in items if it.version is v0]
            self._carry = [it for it in items if it.version is not v0]
            picked = time.perf_counter()
            col = obs_trace.active_collector()
            if col is not None:
                # Queue-wait spans, one per admitted row, stamped with the
                # ORIGINATING request's trace id: the span starts at submit
                # time (producer thread) and ends here (worker thread) —
                # exactly the cross-thread hop the timeline must bridge.
                for it in batch:
                    col.complete(
                        "serve.queue_wait", "serving", it.enqueued_at,
                        picked - it.enqueued_at,
                        {"trace_id": it.trace_id} if it.trace_id else {},
                    )
            # Per-batch stage clock (docs/serving.md §"Latency waterfall"):
            # the scorer accumulates batch_assembly / store_resolve /
            # kernel seconds into the sink; queue_wait is per-row. The
            # whole dict rides each ScoreResult back across the thread
            # boundary so the server can expose the waterfall.
            stage_sink: dict = {}
            try:
                with trace_span(
                    "serve.batch", cat="serving", rows=len(batch),
                    trace_ids=[it.trace_id for it in batch
                               if it.trace_id is not None] or None,
                ):
                    scores, flags = v0.scorer.score_rows_flagged(
                        [it.row for it in batch], stage_sink=stage_sink
                    )
                for it, s, fl in zip(batch, scores, flags):
                    it.future.set_result(ScoreResult(
                        float(s), fl,
                        {"queue_wait": picked - it.enqueued_at,
                         **stage_sink}))
            except Exception as e:  # noqa: BLE001 - routed to the waiter
                for it in batch:
                    if not it.future.done():
                        it.future.set_exception(e)
            self._inflight = []
            self.stats["batches"] += 1
            self.stats["rows"] += len(batch)
            self.stats["max_batch_rows"] = max(
                self.stats["max_batch_rows"], len(batch)
            )

    def reconfigure(self, max_batch: Optional[int] = None,
                    max_queue: Optional[int] = None,
                    max_wait_ms: Optional[float] = None) -> dict:
        """Hot-tune batch/queue/deadline limits (the control plane's
        damped autoscaling lever, ``POST /admin/tune`` — and the
        histogram autotuner's actuation surface, docs/serving.md
        §"Autotuned batching").

        The worker reads ``self.max_batch`` / ``self.max_wait_s`` fresh
        at every assembly round and ``Queue.maxsize`` is consulted under
        the queue's own mutex on each ``put_nowait``, so all changes take
        effect at the next admission/dispatch without pausing the worker.
        Shrinking ``max_queue`` below the current depth never drops
        queued waiters — the bound only gates NEW admissions. Returns the
        active config."""
        with self._submit_lock:
            if max_batch is not None:
                if int(max_batch) < 1:
                    raise ValueError(
                        f"max_batch must be >= 1, got {max_batch}")
                self.max_batch = int(max_batch)
            if max_queue is not None:
                if int(max_queue) < 1:
                    raise ValueError(
                        f"max_queue must be >= 1, got {max_queue}")
                self.max_queue = int(max_queue)
                self._q.maxsize = self.max_queue
            if max_wait_ms is not None:
                if float(max_wait_ms) < 0:
                    raise ValueError(
                        f"max_wait_ms must be >= 0, got {max_wait_ms}")
                self.max_wait_s = float(max_wait_ms) / 1e3
            return {"max_batch": self.max_batch,
                    "max_queue": self.max_queue,
                    "max_wait_ms": round(self.max_wait_s * 1e3, 4)}

    def snapshot(self) -> dict:
        s = dict(self.stats)
        s["mean_batch_rows"] = round(
            s["rows"] / s["batches"], 2) if s["batches"] else 0.0
        s["queued"] = self._q.qsize()
        s["max_batch"] = self.max_batch
        s["max_queue"] = self.max_queue
        s["healthy"] = self.healthy
        return s


class ScoreResult(float):
    """A score that IS a float (full arithmetic/JSON compatibility) plus the
    degradation flags: which RE coordinates scored fixed-effect-only because
    their coefficient-store circuit breaker was open — and the per-stage
    latency waterfall (``stages``: stage name → seconds) the batcher
    measured for this row's batch."""

    __slots__ = ("degraded", "stages")

    def __new__(cls, value: float, degraded=(), stages=None):
        obj = super().__new__(cls, value)
        obj.degraded = tuple(degraded)
        obj.stages = stages or {}
        return obj
