"""Request micro-batcher: coalesce concurrent single rows into one kernel call.

The serve-path economics: one padded kernel dispatch costs roughly the
same for 1 row as for 64 (the device work is tiny; dispatch dominates), so
under concurrency the batcher turns N in-flight single-row requests into
ceil(N / max_batch) dispatches. A single idle request pays at most
``max_wait_ms`` of coalescing latency.

One worker thread owns all device interaction (LRU staging + kernel
dispatch), which keeps the coefficient-cache mutation single-threaded by
construction. Each queue item carries its ``ModelVersion`` reference: a
batch only ever contains rows of ONE version, so a hot-swap mid-stream
simply splits a batch — in-flight requests finish on the version they
captured, new ones ride the new version, none are dropped.
"""
from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future


class _Pending:
    __slots__ = ("version", "row", "future")

    def __init__(self, version, row):
        self.version = version
        self.row = row
        self.future: Future = Future()


class MicroBatcher:
    def __init__(
        self,
        max_batch: int = 64,
        max_wait_ms: float = 2.0,
        start: bool = True,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._carry: list = []  # other-version items deferred one round
        self._stop = threading.Event()
        # Serializes submit vs close: a submit that passed the stop check
        # must finish its put before close drains, or the item's future
        # would sit unresolved until the request timeout.
        self._submit_lock = threading.Lock()
        self.stats = {"batches": 0, "rows": 0, "max_batch_rows": 0}
        self._thread = threading.Thread(
            target=self._loop, name="photon-serve-batcher", daemon=True
        )
        if start:
            self._thread.start()

    def start(self) -> None:
        if not self._thread.is_alive():
            self._thread.start()

    def submit(self, version, row) -> Future:
        """Enqueue one parsed row against ``version``; resolves to its
        float score (or the scoring exception)."""
        with self._submit_lock:
            if self._stop.is_set():
                raise RuntimeError("batcher is shut down")
            item = _Pending(version, row)
            self._q.put(item)
        return item.future

    def close(self) -> None:
        with self._submit_lock:
            self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
        # Fail anything still queued rather than hanging its waiter.
        leftovers = list(self._carry)
        self._carry = []
        while True:
            try:
                leftovers.append(self._q.get_nowait())
            except queue.Empty:
                break
        for item in leftovers:
            if not item.future.done():
                item.future.set_exception(
                    RuntimeError("scoring server shut down")
                )

    # ------------------------------------------------------------ internals

    def _loop(self) -> None:
        while not self._stop.is_set():
            items = self._carry
            self._carry = []
            if not items:
                try:
                    items = [self._q.get(timeout=0.1)]
                except queue.Empty:
                    continue
            deadline = time.monotonic() + self.max_wait_s
            while len(items) < self.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    items.append(self._q.get(timeout=remaining))
                except queue.Empty:
                    break
            # Drain anything already queued (no extra waiting).
            while len(items) < self.max_batch:
                try:
                    items.append(self._q.get_nowait())
                except queue.Empty:
                    break
            v0 = items[0].version
            batch = [it for it in items if it.version is v0]
            self._carry = [it for it in items if it.version is not v0]
            try:
                scores = v0.scorer.score_rows([it.row for it in batch])
                for it, s in zip(batch, scores):
                    it.future.set_result(float(s))
            except Exception as e:  # noqa: BLE001 - routed to the waiter
                for it in batch:
                    if not it.future.done():
                        it.future.set_exception(e)
            self.stats["batches"] += 1
            self.stats["rows"] += len(batch)
            self.stats["max_batch_rows"] = max(
                self.stats["max_batch_rows"], len(batch)
            )

    def snapshot(self) -> dict:
        s = dict(self.stats)
        s["mean_batch_rows"] = round(
            s["rows"] / s["batches"], 2) if s["batches"] else 0.0
        return s
