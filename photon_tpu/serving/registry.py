"""Versioned model registry with atomic hot-swap for online scoring.

A registry owns the serving-side view of one training-driver output
directory: the ``MmapIndexMap``s the model was trained with, the loaded
``GameModel``, per-coordinate data configs reconstructed from
``game-metadata.json`` (the same reconstruction the batch scoring driver
does), and a warmed ``RowScorer``.

Hot-swap contract: ``swap(model_dir)`` builds and WARMS the new version
entirely in the calling thread (typically an admin request handler or a
background poller) while traffic keeps flowing against the current
version; only then does the current-version pointer move, under a lock, in
one reference assignment. Requests capture a version reference at submit
time and score against it even if a swap lands mid-flight — nothing is
ever torn down under an in-flight request (old versions are garbage-
collected when the last request referencing them completes).
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Optional

from photon_tpu.estimators import (
    FixedEffectDataConfig,
    RandomEffectDataConfig,
)
from photon_tpu.index.index_map import MmapIndexMap
from photon_tpu.io.data_reader import FeatureShardConfig
from photon_tpu.io.model_io import default_index_root, load_game_model
from photon_tpu.serving.scorer import RowScorer


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Operational knobs (docs/serving.md §knobs)."""

    max_batch: int = 64          # micro-batch row cap (pow2 recommended)
    max_wait_ms: float = 2.0     # batcher coalescing window
    cache_entities: int = 4096   # LRU device hot-set capacity per RE coord
    max_row_nnz: int = 128       # per-shard padded feature width per row
    default_bags: tuple = ("features",)  # pre-metadata models only
    # Robustness knobs (docs/robustness.md): bounded admission queue
    # (beyond it requests shed with HTTP 503 + Retry-After), per-request
    # deadline propagated into the batcher, and the coefficient-store
    # circuit breaker (0 breaker_failures disables; when open, RE lookups
    # degrade to fixed-effect-only scoring, flagged in the response).
    max_queue: int = 1024        # admission-queue bound (load shedding)
    request_timeout_s: float = 30.0  # per-request deadline
    breaker_failures: int = 5    # consecutive store failures to open
    breaker_cooldown_s: float = 2.0  # open-state duration before a probe
    breaker_slow_call_s: float = 0.0  # store-lookup latency SLO (0 = off)


@dataclasses.dataclass(frozen=True)
class ModelVersion:
    """One immutable, fully-warmed serving snapshot of a model directory."""

    version: int
    model_dir: str
    meta: dict
    scorer: RowScorer
    loaded_at: float

    @property
    def coordinates(self) -> dict:
        return self.meta["coordinates"]


def _model_dir_stamp(model_dir: str) -> tuple:
    """Content stamp of a model directory's top-level files
    ((name, mtime_ns, size) per entry, sorted): a re-push between
    ``prepare_standby`` and ``swap`` changes it, so a warmed-but-stale
    standby is detected and rebuilt instead of silently published."""
    out = []
    try:
        for name in sorted(os.listdir(model_dir)):
            try:
                st = os.stat(os.path.join(model_dir, name))
            except OSError:
                continue
            out.append((name, st.st_mtime_ns, st.st_size))
    except OSError:
        pass
    return tuple(out)


def _build_version(
    version: int, model_dir: str, config: ServingConfig,
    index_dir: Optional[str] = None,
) -> ModelVersion:
    with open(os.path.join(model_dir, "game-metadata.json")) as f:
        meta = json.load(f)
    shards = {info["feature_shard"] for info in meta["coordinates"].values()}
    index_root = index_dir or default_index_root(model_dir)
    index_maps = {
        s: MmapIndexMap(os.path.join(index_root, s)) for s in sorted(shards)
    }
    for im in index_maps.values():
        # Touch every partition now: lazy mmap loads must not land on the
        # first request's latency.
        im.preload()
    model, meta = load_game_model(model_dir, index_maps)

    data_configs = {}
    for cid, info in meta["coordinates"].items():
        if info["type"] == "fixed":
            data_configs[cid] = FixedEffectDataConfig(info["feature_shard"])
        else:
            data_configs[cid] = RandomEffectDataConfig(
                re_type=info["re_type"], feature_shard=info["feature_shard"]
            )
    saved_shards = meta.get("feature_shards", {})
    shard_configs = {
        s: (
            FeatureShardConfig(
                feature_bags=tuple(saved_shards[s]["feature_bags"]),
                add_intercept=saved_shards[s]["add_intercept"],
            )
            if s in saved_shards
            else FeatureShardConfig(feature_bags=tuple(config.default_bags))
        )
        for s in index_maps
    }
    scorer = RowScorer(model, data_configs, index_maps, shard_configs, config)
    scorer.warmup()
    return ModelVersion(
        version=version,
        model_dir=model_dir,
        meta=meta,
        scorer=scorer,
        loaded_at=time.time(),
    )


class ModelRegistry:
    """Holds the current ModelVersion; ``swap`` replaces it atomically."""

    # Class-level default so partially constructed registries (tests build
    # bare instances via __new__ to isolate apply_delta) can still bump it.
    _store_generation = 0

    def __init__(
        self,
        model_dir: str,
        config: ServingConfig = ServingConfig(),
        index_dir: Optional[str] = None,
    ):
        self.config = config
        self._index_dir = index_dir
        self._lock = threading.Lock()
        self._swap_lock = threading.Lock()  # serializes concurrent swaps
        self._next_version = 1
        self._current: Optional[ModelVersion] = None
        # Warm standby (docs/robustness.md §"Recovery time"): a fully
        # built + warmed next version held aside so the swap that publishes
        # it collapses to a pointer move (prepare_standby / swap). The
        # directory stamp detects a re-push between prepare and swap.
        self._standby: Optional[ModelVersion] = None
        self._standby_prepared_at: Optional[float] = None
        self._standby_stamp: Optional[tuple] = None
        # Online-delta freshness bookkeeping (docs/online.md): patch_seq /
        # timestamps survive hot-swaps so /healthz freshness is measurable
        # with or without a trainer attached.
        self._patch_state = {
            "patch_seq": 0,
            "last_patch_ts": None,
            "last_patch_entities": 0,
            "patched_entities_total": 0,
            "last_event_horizon": None,
        }
        # Coefficient-visibility generation (docs/serving.md §"Front
        # line"): bumped on every swap AND every applied delta. Front-end
        # workers stamp the generation of their read-only store export on
        # each wire frame; the scorer only honors worker-verified entity
        # misses when the generations still match, so worker store
        # staleness can never change a score.
        self._store_generation = 0
        self.swap(model_dir)

    @property
    def current(self) -> ModelVersion:
        with self._lock:
            return self._current

    def prepare_standby(self, model_dir: str) -> dict:
        """Build + fully WARM ``model_dir`` as a standby version NOW —
        index preload, coefficient store, and the scorer's whole
        compiled-shape ladder — without publishing it. The next
        :meth:`swap` to the same directory then collapses to a pointer
        move: no load, no warmup, zero scoring-kernel retraces on the
        serving threads (docs/robustness.md §"Recovery time").

        Serialized against swaps (same lock), invisible to traffic. A
        failed build leaves any previous standby intact."""
        with self._swap_lock:
            stamp = _model_dir_stamp(model_dir)
            version = _build_version(
                self._next_version, model_dir, self.config, self._index_dir
            )
            with self._lock:
                self._standby = version
                self._standby_prepared_at = time.time()
                self._standby_stamp = stamp
        from photon_tpu.obs import instant

        instant("serving.standby_prepared", cat="serving",
                model_dir=model_dir)
        return {"model_dir": model_dir, "prepared_at": time.time(),
                "warmed": True}

    def standby_snapshot(self) -> dict:
        """Standby state for /healthz: is a pre-warmed next version ready,
        and for which model directory."""
        with self._lock:
            sb, at = self._standby, self._standby_prepared_at
        return {
            "ready": sb is not None,
            "model_dir": None if sb is None else sb.model_dir,
            "prepared_at": at,
        }

    def swap(self, model_dir: str) -> ModelVersion:
        """Load + warm ``model_dir`` as a new version, then publish it.

        Blocking for the caller; invisible to in-flight traffic until the
        final pointer assignment. Raises (and keeps the current version)
        if the new directory fails to load — a bad push can't take the
        server down.

        When :meth:`prepare_standby` already warmed this directory, the
        build + warmup are skipped entirely and the swap IS the pointer
        assignment — the ``swap_to_first_score_seconds`` the scorer stamps
        then measures one dispatch, not a model load. Either way the
        published scorer's swap clock is armed at publish time.
        """
        with self._swap_lock:
            with self._lock:
                standby = self._standby
                stamp = self._standby_stamp
                if standby is not None and standby.model_dir == model_dir:
                    self._standby = None
                    self._standby_prepared_at = None
                    self._standby_stamp = None
                else:
                    standby = None
            if standby is not None and stamp != _model_dir_stamp(model_dir):
                # The directory was re-pushed after prepare_standby: the
                # warmed snapshot no longer matches what's on disk.
                # Publishing it would silently serve OUTDATED coefficients
                # under the new version number — discard it and take the
                # build path (a slower swap, never a stale one).
                from photon_tpu.obs import instant

                instant("serving.standby_stale", cat="serving",
                        model_dir=model_dir)
                standby = None
            from_standby = standby is not None
            if from_standby:
                version = dataclasses.replace(
                    standby, version=self._next_version,
                    loaded_at=time.time())
            else:
                version = _build_version(
                    self._next_version, model_dir, self.config,
                    self._index_dir
                )
            with self._lock:
                hot = self._current is not None
                self._current = version
                self._next_version += 1
                self._store_generation += 1
            if hot:
                # Swap→first-score clock (docs/robustness.md §recovery
                # time): armed at the pointer move, closed by the first
                # served batch. Not armed for the registry's initial load —
                # "time since construction" is startup, not a swap.
                version.scorer.arm_swap_clock()
        if hot:
            from photon_tpu.obs import instant

            instant("serving.hot_swap", cat="serving",
                    version=version.version, from_standby=from_standby)
        return version

    def apply_delta(self, patches_by_coordinate, seq: Optional[int] = None,
                    event_horizon: Optional[int] = None) -> dict:
        """Apply an online model delta to the CURRENT version, atomically
        per coordinate (docs/online.md §"Delta protocol").

        ``patches_by_coordinate`` maps coordinate id → {entity key →
        ``(cols, vals)``}. Runs under the swap lock so a delta and a
        hot-swap serialize: a delta never lands half on an outgoing
        version; in-flight requests that captured the version pre-apply
        score consistent pre-delta coefficients (the store overlay swap is
        itself atomic). Validation failures (unknown coordinate, over-wide
        patch, unsorted cols) apply NOTHING.
        """
        with self._swap_lock:
            version = self.current
            # Validate EVERYTHING across EVERY coordinate before the first
            # apply — unknown coordinate, over-wide patch, bad column
            # layout anywhere refuses the whole delta with no coordinate
            # half-published (tested: a multi-coordinate delta with one
            # poisoned coordinate applies nothing).
            for cid, patches in patches_by_coordinate.items():
                version.scorer.validate_delta(cid, patches)
            applied = {}
            total = 0
            for cid, patches in patches_by_coordinate.items():
                applied[cid] = version.scorer.apply_delta(cid, patches)
                total += applied[cid]["patched"]
            with self._lock:
                st = self._patch_state
                st["patch_seq"] += 1
                st["last_patch_ts"] = time.time()
                st["last_patch_entities"] = total
                st["patched_entities_total"] += total
                if event_horizon is not None:
                    st["last_event_horizon"] = int(event_horizon)
                patch_seq = st["patch_seq"]
                self._store_generation += 1
        from photon_tpu.obs import instant

        instant("serving.delta_applied", cat="serving", patch_seq=patch_seq,
                entities=total, trainer_seq=seq)
        return {
            "model_version": version.version,
            "patch_seq": patch_seq,
            "patched": total,
            "coordinates": applied,
        }

    @property
    def store_generation(self) -> int:
        with self._lock:
            return self._store_generation

    def export_frontline(self, runtime_dir: str) -> dict:
        """Write everything an accelerator-free front-end worker needs to
        parse + pre-resolve requests (docs/serving.md §"Front line"): the
        per-RE-coordinate ``CoefficientStore`` saved in its mmap-able flat
        layout, plus a ``frontline.json`` manifest carrying the parse
        config (feature bags, intercepts, row width), index-map locations,
        and the store generation at export time. Returns the manifest."""
        v = self.current
        scorer = v.scorer
        os.makedirs(runtime_dir, exist_ok=True)
        index_root = self._index_dir or default_index_root(v.model_dir)
        res = {}
        for cid, _shard in scorer.re_parts:
            store_dir = os.path.join(runtime_dir, "stores", cid)
            scorer._caches[cid].store.save(store_dir)
            res[cid] = {
                "re_type": scorer._re_types[cid],
                "feature_shard": scorer.data_configs[cid].feature_shard,
                "store_dir": store_dir,
            }
        shards = {}
        for s in scorer._shards_used:
            cfg = scorer.shard_configs[s]
            shards[s] = {
                "feature_bags": list(cfg.feature_bags),
                "add_intercept": bool(cfg.add_intercept),
                "dim": len(scorer.index_maps[s]),
                "intercept_index": scorer._intercepts.get(s),
                "index_dir": os.path.join(index_root, s),
            }
        manifest = {
            "generation": self.store_generation,
            "model_version": v.version,
            "model_dir": v.model_dir,
            "max_row_nnz": int(self.config.max_row_nnz),
            "request_timeout_s": float(self.config.request_timeout_s),
            "shards": shards,
            "re_coordinates": res,
        }
        path = os.path.join(runtime_dir, "frontline.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=2)
        os.replace(tmp, path)
        return manifest

    def freshness_snapshot(self) -> dict:
        """Serving freshness for /healthz and /metrics (measurable without
        the trainer attached): active version, when it was swapped in, and
        the delta-patch watermark."""
        v = self.current
        with self._lock:
            st = dict(self._patch_state)
        return {
            "model_version": v.version,
            "last_swap_ts": v.loaded_at,
            "seconds_since_swap": round(time.time() - v.loaded_at, 1),
            "patch_seq": st["patch_seq"],
            "last_patch_ts": st["last_patch_ts"],
            "seconds_since_patch": (
                round(time.time() - st["last_patch_ts"], 1)
                if st["last_patch_ts"] else None
            ),
            "last_patch_entities": st["last_patch_entities"],
            "patched_entities_total": st["patched_entities_total"],
            "last_event_horizon": st["last_event_horizon"],
        }
