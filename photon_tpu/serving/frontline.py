"""Scorer-side front line: the IPC service + worker supervision.

PR 19 (docs/serving.md §"Front line") splits the serving box into N
accelerator-free async front-end workers and ONE device-owning scorer
process. This module is the scorer's half:

* :class:`FrontLine` exports the registry's coefficient stores + parse
  manifest for the workers (``ModelRegistry.export_frontline``), creates
  one IPC channel per worker (lock-free shm rings when the box has POSIX
  shared memory, unix-socket fallback otherwise), spawns + supervises the
  worker processes (liveness via heartbeats, bounded journaled restarts),
  and answers their wire frames;
* per-link service threads decode :mod:`wire` score requests into
  ``ParsedRow``s and feed the EXISTING micro-batcher — warm standby, the
  circuit breakers, OOM downshift, pressure shedding, and graceful drain
  all apply to front-line traffic exactly as they do to the threaded
  server's, because it is literally the same batcher and registry;
* responses carry the scorer-side stage waterfall (queue_wait /
  batch_assembly / store_resolve / kernel) and the scorer's tail-sampling
  verdict, so the worker can stamp a full cross-process waterfall and
  force-promote its half of the trace chain.

Metric ownership is partitioned by process to keep the fleet merge
honest: the scorer observes ONLY the scorer-side stages into
``serve_stage_latency_seconds`` (the autotuner's live signal); workers
observe only worker-side stages (admission / parse / ipc / response).
Merged across shards, each stage of the box-level waterfall is counted
exactly once.
"""
from __future__ import annotations

import json
import os
import secrets
import signal
import subprocess
import sys
import threading
import time
from typing import Optional

import numpy as np

from photon_tpu.obs import trace as obs_trace
from photon_tpu.obs.trace import trace_context
from photon_tpu.serving import ipc, wire
from photon_tpu.serving.batcher import DeadlineExceeded, Overloaded
from photon_tpu.serving.scorer import ParsedRow

_HEARTBEAT_STALE_S = 3.0
_RESTART_WINDOW_S = 60.0
_MAX_RESTARTS_PER_WINDOW = 3


def pick_port(host: str = "127.0.0.1") -> int:
    """A currently-free TCP port. The front line needs ONE concrete port
    shared by every worker (SO_REUSEPORT); 'bind 0 and see' per worker
    would scatter them."""
    import socket as _socket

    with _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


class _WorkerLink:
    """Supervisor-side state for one front-end worker."""

    def __init__(self, worker_id: int, channel):
        self.worker_id = worker_id
        self.channel = channel
        self.proc: Optional[subprocess.Popen] = None
        self.pid: Optional[int] = None
        self.state = "starting"      # starting | live | dead | restarting
        self.last_seen = time.monotonic()
        self.hello = threading.Event()
        self.served = 0
        self.errors = 0
        self.restarts: list = []     # monotonic restart timestamps
        self.log_path: Optional[str] = None

    def snapshot(self) -> dict:
        return {
            "worker_id": self.worker_id,
            "pid": self.pid,
            "state": self.state,
            "seconds_since_seen": round(
                time.monotonic() - self.last_seen, 2),
            "served": self.served,
            "errors": self.errors,
            "restarts": len(self.restarts),
        }


class FrontLine:
    """Runs the multi-process serving box around an existing
    :class:`ScoringServer` (which keeps serving its own port as the box's
    admin plane — /admin/swap, /admin/patch, /metrics all stay there;
    scoring traffic enters through the workers' shared port)."""

    def __init__(
        self,
        server,
        *,
        workers: int = 2,
        host: str = "127.0.0.1",
        port: int = 8080,
        runtime_dir: str,
        transport: str = "auto",   # auto | shm | socket
        autotuner=None,
        telemetry_dir: Optional[str] = None,
        journal=None,
        logger=None,
        ring_bytes: int = ipc.DEFAULT_RING_BYTES,
    ):
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        self.server = server
        self.registry = server.registry
        self.batcher = server.batcher
        self.n_workers = int(workers)
        self.host = host
        self.port = int(port)
        self.runtime_dir = runtime_dir
        self.telemetry_dir = telemetry_dir
        self.journal = journal
        self.logger = logger
        self.autotuner = autotuner
        self.ring_bytes = int(ring_bytes)
        self.token = secrets.token_hex(4)
        if transport == "auto":
            transport = "shm" if ipc.shm_available() else "socket"
        if transport not in ("shm", "socket"):
            raise ValueError(f"unknown transport {transport!r}")
        self.transport = transport
        self._listener: Optional[ipc.SocketListener] = None
        self._links: dict[int, _WorkerLink] = {}
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._started = False
        self.manifest: Optional[dict] = None
        from photon_tpu.obs.metrics import REGISTRY

        self._ipc_requests = REGISTRY.counter(
            "serve_frontline_requests_total",
            "wire score requests handled by the scorer IPC service, "
            "by outcome")
        self._known_miss_skips = REGISTRY.counter(
            "serve_frontline_known_miss_skips_total",
            "entity-store lookups skipped because a worker verified the "
            "key absent at a matching store generation")
        self._restart_counter = REGISTRY.counter(
            "serve_frontline_worker_restarts_total",
            "front-end worker processes restarted by the supervisor")

    # ------------------------------------------------------------- lifecycle

    @property
    def address(self) -> tuple:
        return (self.host, self.port)

    def start(self, ready_timeout_s: float = 30.0) -> None:
        os.makedirs(self.runtime_dir, exist_ok=True)
        self.manifest = self.registry.export_frontline(self.runtime_dir)
        if self.transport == "socket":
            self._listener = ipc.SocketListener(
                os.path.join(self.runtime_dir, "frontline.sock"))
            accept_t = threading.Thread(
                target=self._accept_loop, name="photon-fl-accept",
                daemon=True)
            accept_t.start()
            self._threads.append(accept_t)
        for i in range(self.n_workers):
            link = _WorkerLink(i, None)
            if self.transport == "shm":
                link.channel = ipc.create_worker_rings(
                    self.token, i, capacity=self.ring_bytes)
            self._links[i] = link
            self._spawn(link)
            if link.channel is not None:
                self._start_link_thread(link)
        deadline = time.monotonic() + ready_timeout_s
        for link in self._links.values():
            remaining = deadline - time.monotonic()
            if not link.hello.wait(timeout=max(0.1, remaining)):
                tail = self._log_tail(link)
                self.stop(drain=False)
                raise RuntimeError(
                    f"front-end worker {link.worker_id} (pid {link.pid}) "
                    f"never reported ready within {ready_timeout_s:.0f}s"
                    + (f"; last log lines:\n{tail}" if tail else "")
                )
        monitor = threading.Thread(
            target=self._monitor_loop, name="photon-fl-monitor", daemon=True)
        monitor.start()
        self._threads.append(monitor)
        if self.autotuner is not None:
            self.autotuner.start()
        self._started = True
        if self.logger is not None:
            self.logger.info(
                "front line up: %d worker(s) on http://%s:%d over %s "
                "(runtime %s, store generation %d)",
                self.n_workers, self.host, self.port, self.transport,
                self.runtime_dir, self.manifest["generation"])

    def stop(self, drain: bool = True, timeout_s: float = 10.0) -> None:
        self._stop.set()
        if self.autotuner is not None:
            self.autotuner.stop()
        for link in self._links.values():
            if link.proc is not None and link.proc.poll() is None:
                try:
                    link.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + timeout_s
        for link in self._links.values():
            if link.proc is None:
                continue
            try:
                link.proc.wait(timeout=max(0.1,
                                           deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                link.proc.kill()
                link.proc.wait(timeout=5.0)
            link.state = "dead"
        for link in self._links.values():
            if link.channel is not None:
                link.channel.close()
        if self._listener is not None:
            self._listener.close()

    # --------------------------------------------------------------- workers

    def _worker_cmd(self, link: _WorkerLink) -> list:
        if self.transport == "shm":
            spec = f"shm:{self.token}"
        else:
            spec = f"sock:{self._listener.path}"
        cmd = [
            sys.executable, "-m", "photon_tpu.serving.async_frontend",
            "--manifest", os.path.join(self.runtime_dir, "frontline.json"),
            "--worker-id", str(link.worker_id),
            "--host", self.host,
            "--port", str(self.port),
            "--ipc", spec,
        ]
        if self.telemetry_dir:
            cmd += ["--telemetry-dir", self.telemetry_dir]
        return cmd

    def _spawn(self, link: _WorkerLink) -> None:
        link.log_path = os.path.join(
            self.runtime_dir, f"worker-{link.worker_id}.log")
        log = open(link.log_path, "ab")
        try:
            link.proc = subprocess.Popen(
                self._worker_cmd(link), stdout=log, stderr=log,
                env=dict(os.environ))
        finally:
            log.close()
        link.pid = link.proc.pid
        link.state = "starting"
        link.last_seen = time.monotonic()
        self._write_worker_table()

    def _log_tail(self, link: _WorkerLink, n: int = 15) -> str:
        try:
            with open(link.log_path, "r", errors="replace") as f:
                return "".join(f.readlines()[-n:])
        except OSError:
            return ""

    def _write_worker_table(self) -> None:
        """``frontline-workers.json`` next to the manifest: pids + states
        for operators and the chaos drill (which needs a pid to SIGKILL)."""
        path = os.path.join(self.runtime_dir, "frontline-workers.json")
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"port": self.port,
                           "scorer_pid": os.getpid(),
                           "workers": [l.snapshot()
                                       for l in self._links.values()]},
                          f, indent=2)
            os.replace(tmp, path)
        except OSError:
            pass

    def _accept_loop(self) -> None:
        """Socket fallback: workers connect and introduce themselves with
        a hello control frame carrying their worker id."""
        while not self._stop.is_set():
            ch = self._listener.accept()
            if ch is None:
                return
            try:
                frame = ch.recv(timeout=5.0)
                kind, req_id, payload = wire.decode_control(frame)
                wid = int(payload["worker_id"])
                link = self._links[wid]
            except Exception:  # noqa: BLE001 - a bad client must not kill accept
                ch.close()
                continue
            link.channel = ch
            self._handle_control(link, req_id, payload)
            self._start_link_thread(link)

    def _start_link_thread(self, link: _WorkerLink) -> None:
        t = threading.Thread(
            target=self._serve_link, args=(link,),
            name=f"photon-fl-w{link.worker_id}", daemon=True)
        t.start()
        self._threads.append(t)

    def _monitor_loop(self) -> None:
        while not self._stop.wait(0.25):
            for link in list(self._links.values()):
                if link.proc is None:
                    continue
                exited = link.proc.poll() is not None
                stale = (time.monotonic() - link.last_seen
                         > _HEARTBEAT_STALE_S)
                if link.state == "live" and (exited or stale) and exited:
                    self._on_worker_death(link)

    def _on_worker_death(self, link: _WorkerLink) -> None:
        rc = link.proc.returncode
        link.state = "dead"
        if self.logger is not None:
            self.logger.warning(
                "front-end worker %d (pid %s) died (rc=%s)",
                link.worker_id, link.pid, rc)
        if self.journal is not None:
            self.journal.record(
                "frontline_worker_exit", worker_id=link.worker_id,
                pid=link.pid, returncode=rc)
        now = time.monotonic()
        link.restarts = [t for t in link.restarts
                         if now - t < _RESTART_WINDOW_S]
        if self._stop.is_set():
            return
        if len(link.restarts) >= _MAX_RESTARTS_PER_WINDOW:
            if self.logger is not None:
                self.logger.error(
                    "worker %d exceeded %d restarts in %.0fs; leaving it "
                    "down (surviving workers keep the port)",
                    link.worker_id, _MAX_RESTARTS_PER_WINDOW,
                    _RESTART_WINDOW_S)
            self._write_worker_table()
            return
        link.restarts.append(now)
        link.hello.clear()
        link.state = "restarting"
        # shm rings survive a worker death (the scorer owns them); a
        # restarted worker re-attaches to the same segments. Any frames
        # the dead worker left half-consumed are bounded by the ring and
        # drained by the link thread as usual.
        self._restart_counter.inc()
        if self.journal is not None:
            self.journal.record(
                "frontline_worker_restart", worker_id=link.worker_id)
        self._spawn(link)

    # ------------------------------------------------------------ link serve

    def _serve_link(self, link: _WorkerLink) -> None:
        while not self._stop.is_set():
            try:
                frame = link.channel.recv(timeout=0.5)
            except ipc.TransportClosed:
                return
            if frame is None:
                continue
            link.last_seen = time.monotonic()
            try:
                kind, req_id = wire.frame_kind(frame)
            except wire.WireError:
                link.errors += 1
                continue
            try:
                if kind == wire.KIND_SCORE_REQ:
                    self._handle_score(link, frame)
                elif kind in (wire.KIND_CTL_REQ, wire.KIND_HEARTBEAT):
                    _, _, payload = wire.decode_control(frame)
                    self._handle_control(link, req_id, payload)
            except ipc.TransportClosed:
                return
            except Exception as e:  # noqa: BLE001 - one bad frame, not the link
                link.errors += 1
                try:
                    link.channel.send(wire.encode_score_response(
                        req_id, status=wire.STATUS_INTERNAL,
                        error=f"{type(e).__name__}: {e}"))
                except Exception:  # noqa: BLE001 - peer may be gone
                    pass

    # --------------------------------------------------------------- scoring

    def _wire_to_parsed(self, req: wire.ScoreRequest, scorer) -> list:
        """Validate + convert wire rows to ``ParsedRow``s. The arrays come
        pre-resolved and pre-padded; the scorer still bounds-checks every
        index (a worker — or a binary-edge client — is trusted for
        EFFORT, never for MEMORY SAFETY: a bad column id would gather
        garbage coefficients)."""
        k = scorer.config.max_row_nnz
        gen_match = (req.store_generation
                     == self.registry.store_generation)
        rows = []
        for row in req.rows:
            shard_idx, shard_val = {}, {}
            for shard in scorer._shards_used:
                idx = row.shard_idx.get(shard)
                val = row.shard_val.get(shard)
                if idx is None or val is None:
                    raise wire.WireError(
                        f"frame is missing feature shard {shard!r}")
                if idx.shape[0] != k:
                    raise wire.WireError(
                        f"shard {shard!r} row width {idx.shape[0]} != "
                        f"serving max_row_nnz {k}")
                dim = len(scorer.index_maps[shard])
                if idx.min(initial=0) < 0 or idx.max(initial=0) > dim:
                    raise wire.WireError(
                        f"feature index out of range for shard {shard!r} "
                        f"(dim {dim})")
                shard_idx[shard] = idx
                shard_val[shard] = val
            keys = {}
            for cid in scorer._re_types:
                key = row.entity_keys.get(cid)
                if key is not None and gen_match and cid in row.known_miss:
                    # Worker verified the key absent at this generation:
                    # skip the store lookup, go straight to the
                    # fixed-effect fallback (same score either way).
                    self._known_miss_skips.inc()
                    key = None
                keys[cid] = key
            rows.append(ParsedRow(
                shard_idx=shard_idx, shard_val=shard_val,
                offset=row.offset, entity_keys=keys))
        return rows

    def _handle_score(self, link: _WorkerLink, frame: bytes) -> None:
        t0 = time.perf_counter()
        server = self.server
        req = wire.decode_score_request(frame)
        tid = req.trace_id or None
        if server._draining:
            link.channel.send(wire.encode_score_response(
                req.req_id, status=wire.STATUS_DRAINING,
                error="server draining", retry_after_s=1.0))
            return
        tail = obs_trace.tail_sampler()
        if tail is not None and tid:
            tail.begin(tid)
        version = self.registry.current
        try:
            if server.shed_for_memory_pressure():
                raise Overloaded(
                    "device memory watermark over critical; shedding "
                    "until pressure drains")
            rows = self._wire_to_parsed(req, version.scorer)
            timeout_s = (req.deadline_ms / 1e3 if req.deadline_ms > 0
                         else server.request_timeout_s)
            deadline = time.monotonic() + timeout_s
            with server._inflight_cv:
                server._inflight += len(rows)
            futs = []
            try:
                with trace_context(tid):
                    for row in rows:
                        futs.append(self.batcher.submit(
                            version, row, deadline=deadline))
            except BaseException:
                # Never cancel() a submitted future — the batcher worker
                # set_results unconditionally and a cancelled future would
                # poison its whole batch. Let already-submitted rows score
                # and release their inflight slot on completion.
                with server._inflight_cv:
                    server._inflight -= len(rows) - len(futs)
                    server._inflight_cv.notify_all()

                def _release(_f):
                    with server._inflight_cv:
                        server._inflight -= 1
                        server._inflight_cv.notify_all()

                for f in futs:
                    f.add_done_callback(_release)
                raise
        except wire.WireError as e:
            self._finish_tail(tail, tid, t0, error=False)
            self._respond_error(link, req.req_id, wire.STATUS_BAD_REQUEST,
                                str(e))
            return
        except Overloaded as e:
            server._count(shed=1)
            self._finish_tail(tail, tid, t0, error=False)
            self._respond_error(link, req.req_id, wire.STATUS_OVERLOADED,
                                str(e), retry_after_s=1.0)
            return
        except Exception as e:  # noqa: BLE001 - a 500-class reply, not a crash
            server._count(errors=1)
            self._finish_tail(tail, tid, t0, error=True)
            self._respond_error(link, req.req_id, wire.STATUS_INTERNAL,
                                f"{type(e).__name__}: {e}")
            return
        pending = _PendingScore(self, link, req, version, futs, t0, tail)
        for f in futs:
            f.add_done_callback(pending.one_done)

    def _respond_error(self, link, req_id, status, error,
                       retry_after_s: float = 0.0) -> None:
        link.errors += 1
        self._ipc_requests.inc(outcome=_OUTCOMES.get(status, "error"))
        try:
            link.channel.send(wire.encode_score_response(
                req_id, status=status, error=error,
                retry_after_s=retry_after_s))
        except (ipc.TransportClosed, ipc.RingFull):
            pass

    def _finish_tail(self, tail, tid, t0, error: bool) -> bool:
        if tail is None or not tid:
            return False
        return tail.finish(tid, time.perf_counter() - t0, error=error)

    # --------------------------------------------------------------- control

    def workers_snapshot(self) -> list:
        return [link.snapshot() for link in self._links.values()]

    def _box_health(self) -> dict:
        """The scorer-side health block workers embed in their /healthz:
        the single-process /healthz fields PLUS the worker table, so ANY
        worker can report a degraded sibling (SO_REUSEPORT means the
        caller cannot choose which worker answers)."""
        server = self.server
        v = self.registry.current
        degraded = server.degraded_reasons(v)
        workers = self.workers_snapshot()
        for w in workers:
            if w["state"] != "live":
                degraded = list(degraded) + [
                    f"frontline_worker_{w['worker_id']}_{w['state']}"]
        return {
            "status": ("unhealthy" if not self.batcher.healthy
                       else "degraded" if degraded else "ok"),
            "degraded": degraded,
            "draining": server._draining,
            "model_version": v.version,
            "model_dir": v.model_dir,
            "backend": server.backend_name(),
            "store_generation": self.registry.store_generation,
            "freshness": server.freshness(),
            "recovery": server.recovery_snapshot(),
            "batcher": self.batcher.snapshot(),
            "workers": workers,
        }

    def _handle_control(self, link: _WorkerLink, req_id: int,
                        payload: dict) -> None:
        op = payload.get("op")
        if op == "hello":
            link.pid = payload.get("pid", link.pid)
            link.state = "live"
            link.hello.set()
            self._write_worker_table()
            if self.journal is not None:
                self.journal.record("frontline_worker_joined",
                                    worker_id=link.worker_id, pid=link.pid)
            reply = {"ok": True,
                     "generation": self.registry.store_generation,
                     "model_version": self.registry.current.version}
        elif op == "heartbeat":
            link.served = int(payload.get("served", link.served))
            if link.state == "starting":
                link.state = "live"
            reply = {"ok": True,
                     "draining": self.server._draining,
                     "generation": self.registry.store_generation,
                     "health": self._box_health()}
        elif op == "healthz":
            reply = self._box_health()
        elif op == "tune":
            reply = self._ctl_tune(payload)
        else:
            reply = {"error": f"unknown control op {op!r}"}
        try:
            link.channel.send(
                wire.encode_control(wire.KIND_CTL_RESP, req_id, reply))
        except (ipc.TransportClosed, ipc.RingFull):
            pass

    def _ctl_tune(self, payload: dict) -> dict:
        """The /admin/tune proxy target (ISSUE 19 satellite): ONE
        actuation surface for the whole box — a worker forwards the HTTP
        body here, the scorer's batcher applies it, and the reply reports
        the autotuner's current choice alongside."""
        try:
            cfg = self.batcher.reconfigure(
                max_batch=(None if payload.get("max_batch") is None
                           else int(payload["max_batch"])),
                max_queue=(None if payload.get("max_queue") is None
                           else int(payload["max_queue"])),
                max_wait_ms=(None if payload.get("max_wait_ms") is None
                             else float(payload["max_wait_ms"])),
            )
        except (TypeError, ValueError) as e:
            return {"error": str(e), "bad_request": True}
        self.server._count(tunes=1)
        from photon_tpu.obs import instant

        instant("serving.batcher_tuned", cat="serving", **cfg)
        return {
            **cfg,
            "autotune": (self.autotuner.snapshot()
                         if self.autotuner is not None
                         else {"enabled": False}),
        }


_OUTCOMES = {
    wire.STATUS_OK: "ok",
    wire.STATUS_BAD_REQUEST: "bad_request",
    wire.STATUS_OVERLOADED: "shed",
    wire.STATUS_DEADLINE: "expired",
    wire.STATUS_INTERNAL: "error",
    wire.STATUS_DRAINING: "draining",
}


class _PendingScore:
    """Gathers one wire request's row futures; the LAST completion builds
    and sends the response (on the batcher worker thread — response
    encoding is microseconds, cheaper than a handoff to yet another
    thread would be)."""

    __slots__ = ("fl", "link", "req", "version", "futs", "t0", "tail",
                 "_remaining", "_lock")

    def __init__(self, fl, link, req, version, futs, t0, tail):
        self.fl = fl
        self.link = link
        self.req = req
        self.version = version
        self.futs = futs
        self.t0 = t0
        self.tail = tail
        self._remaining = len(futs)
        self._lock = threading.Lock()

    def one_done(self, _fut) -> None:
        with self._lock:
            self._remaining -= 1
            if self._remaining:
                return
        try:
            self._complete()
        finally:
            server = self.fl.server
            with server._inflight_cv:
                server._inflight -= len(self.futs)
                server._inflight_cv.notify_all()

    def _complete(self) -> None:
        fl, link, req = self.fl, self.link, self.req
        server = fl.server
        scores, degraded, stages = [], [], {}
        status, error, retry_after = wire.STATUS_OK, "", 0.0
        for f in self.futs:
            exc = f.exception()
            if exc is None:
                score = f.result()
                scores.append(float(score))
                degraded.append(tuple(getattr(score, "degraded", ())))
                for st, sec in (getattr(score, "stages", None)
                                or {}).items():
                    # Rows of one request overwhelmingly share a batch;
                    # max() reports the batch's stage cost once instead
                    # of summing the same kernel N times.
                    stages[st] = max(stages.get(st, 0.0), float(sec))
            elif isinstance(exc, Overloaded):
                status, error = wire.STATUS_OVERLOADED, str(exc)
                retry_after = 1.0
            elif isinstance(exc, DeadlineExceeded):
                status, error = wire.STATUS_DEADLINE, str(exc)
            else:
                status = wire.STATUS_INTERNAL
                error = f"{type(exc).__name__}: {exc}"
        total = time.perf_counter() - self.t0
        promoted = False
        if status == wire.STATUS_OK:
            link.served += len(scores)
            server._count(requests=1)  # scorer owns serve_* counters box-wide
            for st, sec in stages.items():
                server._stage_hist.observe(sec, stage=st)
            server.latency.observe(total)
            if any(degraded):
                server._count(degraded=1)
            promoted = fl._finish_tail(self.tail, req.trace_id or None,
                                       self.t0, error=False)
        else:
            if status == wire.STATUS_DEADLINE:
                server._count(expired=1)
            elif status == wire.STATUS_INTERNAL:
                server._count(errors=1)
            promoted = fl._finish_tail(
                self.tail, req.trace_id or None, self.t0,
                error=status == wire.STATUS_INTERNAL)
        fl._ipc_requests.inc(outcome=_OUTCOMES.get(status, "error"))
        flags = wire.RESP_FLAG_TRACE_PROMOTED if promoted else 0
        try:
            link.channel.send(wire.encode_score_response(
                req.req_id, status=status, error=error,
                retry_after_s=retry_after,
                model_version=self.version.version, flags=flags,
                scores=np.asarray(scores, np.float32),
                degraded=degraded, stages=stages))
        except (ipc.TransportClosed, ipc.RingFull):
            link.errors += 1
