"""Worker↔scorer IPC transports for the serving front line.

Two interchangeable transports carry :mod:`photon_tpu.serving.wire`
frames between a front-end worker process and the device-owning scorer
process (docs/serving.md §"Front line"):

* :class:`ShmRing` — a **lock-free SPSC byte ring** over
  ``multiprocessing.shared_memory``. Head/tail are monotonically
  increasing u64 byte counters at fixed 8-byte-aligned offsets; the
  producer only writes the tail, the consumer only writes the head, so
  there is no cross-process lock anywhere on the hot path. (CPython
  writes an aligned 8-byte slice with a single ``memcpy``, which is
  atomic on every platform this project targets; the socket transport
  below is the fallback for anything more exotic.) Monotonic counters
  sidestep the classic empty-vs-full ambiguity: ``tail - head`` is the
  exact number of unread bytes.
* :class:`SocketChannel` — a connected ``AF_UNIX`` stream socket with
  the same u32-length framing. Slightly higher per-frame cost (two
  syscalls) but zero shared-memory assumptions; it is also the accept
  path workers use to introduce themselves when rings are disabled.

Both expose the same three calls — ``send(frame)``, ``recv(timeout)``,
``close()`` — so the frontline service and the workers are transport-
agnostic. ``send`` is thread-safe (the scorer's response path has two
producers: the batcher callback and the control plane); ``recv`` assumes
a single reader, which both sides guarantee by construction.

jax-free by design: workers import this at boot.
"""
from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Optional

_U64 = struct.Struct("<Q")
_LEN = struct.Struct("<I")
_RING_HEADER = 16  # [0:8) head (consumer-owned), [8:16) tail (producer-owned)

DEFAULT_RING_BYTES = 1 << 20  # 1 MiB per direction per worker


class RingFull(RuntimeError):
    """Producer timed out waiting for ring space (backpressure signal)."""


class TransportClosed(RuntimeError):
    """The peer went away (worker exit / scorer exit)."""


def _sleep_backoff(spins: int) -> None:
    # Adaptive wait: burn a few polls for sub-µs latency, then yield with
    # escalating sleeps so an idle ring costs ~nothing.
    if spins < 64:
        return
    if spins < 256:
        time.sleep(0)
    elif spins < 1024:
        time.sleep(50e-6)
    else:
        time.sleep(500e-6)


class ShmRing:
    """One direction of a shared-memory frame ring (SPSC, lock-free)."""

    def __init__(self, shm, *, owner: bool):
        self._shm = shm
        self._buf = shm.buf
        self._cap = shm.size - _RING_HEADER
        self._owner = owner
        self._send_lock = threading.Lock()  # in-process producers only
        self._closed = False

    # -- construction ------------------------------------------------------

    @classmethod
    def create(cls, name: str, capacity: int = DEFAULT_RING_BYTES):
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(
            name=name, create=True, size=capacity + _RING_HEADER)
        shm.buf[:_RING_HEADER] = b"\x00" * _RING_HEADER
        return cls(shm, owner=True)

    @classmethod
    def attach(cls, name: str):
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(name=name)
        # The attaching process must NOT let its resource tracker unlink
        # the segment at exit — the creator owns the lifetime. (The
        # tracker auto-registers on attach in CPython's implementation.)
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:  # noqa: BLE001 - tracker details vary by version
            pass
        return cls(shm, owner=False)

    # -- counters ----------------------------------------------------------

    def _head(self) -> int:
        return _U64.unpack_from(self._buf, 0)[0]

    def _tail(self) -> int:
        return _U64.unpack_from(self._buf, 8)[0]

    def _set_head(self, v: int) -> None:
        _U64.pack_into(self._buf, 0, v)

    def _set_tail(self, v: int) -> None:
        _U64.pack_into(self._buf, 8, v)

    # -- data movement -----------------------------------------------------

    def _write_at(self, pos: int, data: bytes) -> None:
        off = pos % self._cap
        first = min(len(data), self._cap - off)
        base = _RING_HEADER
        self._buf[base + off: base + off + first] = data[:first]
        if first < len(data):
            self._buf[base: base + len(data) - first] = data[first:]

    def _read_at(self, pos: int, n: int) -> bytes:
        off = pos % self._cap
        first = min(n, self._cap - off)
        base = _RING_HEADER
        out = bytes(self._buf[base + off: base + off + first])
        if first < n:
            out += bytes(self._buf[base: base + (n - first)])
        return out

    def send(self, frame: bytes, timeout: Optional[float] = 5.0) -> None:
        need = _LEN.size + len(frame)
        if need > self._cap:
            raise ValueError(
                f"frame of {len(frame)} bytes exceeds ring capacity "
                f"{self._cap}; raise the ring size"
            )
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._send_lock:
            spins = 0
            while True:
                if self._closed:
                    raise TransportClosed("ring closed")
                tail = self._tail()
                if self._cap - (tail - self._head()) >= need:
                    break
                if deadline is not None and time.monotonic() >= deadline:
                    raise RingFull(
                        f"ring full for {timeout:.1f}s "
                        f"({tail - self._head()} unread bytes)"
                    )
                spins += 1
                _sleep_backoff(spins)
            self._write_at(tail, _LEN.pack(len(frame)))
            self._write_at(tail + _LEN.size, frame)
            # Publish AFTER the payload bytes are in place: the consumer
            # only looks past `tail`, so a torn frame is never visible.
            self._set_tail(tail + need)

    def recv(self, timeout: Optional[float] = None) -> Optional[bytes]:
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        while True:
            if self._closed:
                return None
            head = self._head()
            if self._tail() != head:
                break
            if deadline is not None and time.monotonic() >= deadline:
                return None
            spins += 1
            _sleep_backoff(spins)
        n = _LEN.unpack(self._read_at(head, _LEN.size))[0]
        frame = self._read_at(head + _LEN.size, n)
        self._set_head(head + _LEN.size + n)
        return frame

    def pending_bytes(self) -> int:
        return self._tail() - self._head()

    def close(self) -> None:
        self._closed = True
        try:
            # Release the memoryview before closing the mapping or CPython
            # refuses to close the shm (exported pointers).
            self._buf = None
            self._shm.close()
            if self._owner:
                self._shm.unlink()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass


class RingChannel:
    """Duplex frame channel from two one-direction rings."""

    def __init__(self, send_ring: ShmRing, recv_ring: ShmRing):
        self._send = send_ring
        self._recv = recv_ring

    def send(self, frame: bytes, timeout: Optional[float] = 5.0) -> None:
        self._send.send(frame, timeout=timeout)

    def recv(self, timeout: Optional[float] = None) -> Optional[bytes]:
        return self._recv.recv(timeout=timeout)

    def close(self) -> None:
        self._send.close()
        self._recv.close()


def ring_names(token: str, worker_id: int) -> tuple[str, str]:
    """Shared-memory segment names for one worker's (request, response)
    rings. Short and unique per box: shm names live in a global
    namespace."""
    return (f"ph-{token}-w{worker_id}q", f"ph-{token}-w{worker_id}r")


def create_worker_rings(
    token: str, worker_id: int, capacity: int = DEFAULT_RING_BYTES,
) -> RingChannel:
    """Scorer side: create both rings; returns the SCORER's view (sends
    responses, receives requests)."""
    req_name, resp_name = ring_names(token, worker_id)
    req = ShmRing.create(req_name, capacity)
    resp = ShmRing.create(resp_name, capacity)
    return RingChannel(send_ring=resp, recv_ring=req)


def attach_worker_rings(token: str, worker_id: int) -> RingChannel:
    """Worker side: attach to rings the scorer created; returns the
    WORKER's view (sends requests, receives responses)."""
    req_name, resp_name = ring_names(token, worker_id)
    req = ShmRing.attach(req_name)
    resp = ShmRing.attach(resp_name)
    return RingChannel(send_ring=req, recv_ring=resp)


class SocketChannel:
    """u32-length-framed duplex channel over a connected stream socket."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._send_lock = threading.Lock()
        self._recv_buf = b""
        self._closed = False
        sock.setblocking(True)

    @classmethod
    def connect(cls, path: str, timeout: float = 5.0) -> "SocketChannel":
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(timeout)
        s.connect(path)
        return cls(s)

    def send(self, frame: bytes, timeout: Optional[float] = 5.0) -> None:
        with self._send_lock:
            if self._closed:
                raise TransportClosed("socket closed")
            try:
                self._sock.settimeout(timeout)
                self._sock.sendall(_LEN.pack(len(frame)) + frame)
            except (BrokenPipeError, ConnectionError, OSError) as e:
                raise TransportClosed(f"peer gone: {e}") from None

    def _read_exact(self, n: int, deadline: Optional[float]) -> Optional[bytes]:
        while len(self._recv_buf) < n:
            if self._closed:
                return None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._sock.settimeout(min(remaining, 0.5))
            else:
                self._sock.settimeout(0.5)
            try:
                chunk = self._sock.recv(1 << 16)
            except socket.timeout:
                continue
            except OSError as e:
                raise TransportClosed(f"peer gone: {e}") from None
            if not chunk:
                raise TransportClosed("peer closed the connection")
            self._recv_buf += chunk
        out = self._recv_buf[:n]
        self._recv_buf = self._recv_buf[n:]
        return out

    def recv(self, timeout: Optional[float] = None) -> Optional[bytes]:
        deadline = None if timeout is None else time.monotonic() + timeout
        hdr = self._read_exact(_LEN.size, deadline)
        if hdr is None:
            return None
        n = _LEN.unpack(hdr)[0]
        # The length prefix is committed; finish the frame even if the
        # caller's timeout elapsed mid-frame (partial reads would desync).
        return self._read_exact(n, None)

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class SocketListener:
    """Scorer-side accept loop companion for the socket fallback."""

    def __init__(self, path: str):
        self.path = path
        if os.path.exists(path):
            os.unlink(path)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(path)
        self._sock.listen(64)
        self._sock.settimeout(0.5)
        self._closed = False

    def accept(self) -> Optional[SocketChannel]:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
                return SocketChannel(conn)
            except socket.timeout:
                continue
            except OSError:
                return None
        return None

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass


def shm_available() -> bool:
    """Can this box create POSIX shared memory? (Containers sometimes
    mount /dev/shm noexec-tiny or not at all — fall back to sockets.)"""
    try:
        ring = ShmRing.create(f"ph-probe-{os.getpid()}", 4096)
        ring.close()
        return True
    except Exception:  # noqa: BLE001 - any failure means "use sockets"
        return False
