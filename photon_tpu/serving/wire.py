"""Binary wire encoding for the serving front line (docs/serving.md §wire).

One frame format serves two boundaries:

* the **IPC hop** between a front-end worker and the device-owning scorer
  process (every request crosses it, so parse cost is the front line's
  per-row CPU floor), and
* the **HTTP edge**, where a trusted co-located client (the bench, a
  router on the same box) may POST a pre-encoded frame with
  ``Content-Type: application/x-photon-wire`` instead of JSON — JSON is
  still accepted everywhere, the binary path is an opt-in fast lane.

Design rules:

* little-endian throughout; a fixed 16-byte header carries magic,
  version, frame kind, flags, and a request id — decoders REFUSE unknown
  magic/version loudly (``WireError``) instead of guessing;
* feature arrays travel **pre-resolved and pre-padded**: ``int32`` column
  ids + ``float32`` values at the serving row width ``k`` (=
  ``max_row_nnz``), straight ``ndarray.tobytes()`` / ``np.frombuffer``
  with zero per-feature marshalling — the decode cost of a row is two
  buffer views, not a JSON tree walk;
* entity keys ride as flagged UTF-8 strings; a worker that verified a key
  MISSING in its read-only mmap store marks it ``KNOWN_MISS`` with the
  store generation it checked, so the scorer can skip the dead lookup when
  the generation still matches (deltas bump it — correctness never
  depends on worker store freshness);
* control traffic (tune/healthz/drain/hello/heartbeat) is framed the same
  way but carries JSON — it is not the hot path, and keeping it schemaless
  lets the admin surface grow without a wire version bump.

This module is deliberately **jax-free**: front-end workers import it at
boot and must never pay (or depend on) an accelerator runtime.
"""
from __future__ import annotations

import dataclasses
import json
import struct
from typing import Mapping, Optional, Sequence

import numpy as np

MAGIC = b"PhW1"
VERSION = 1

# Frame kinds.
KIND_SCORE_REQ = 1
KIND_SCORE_RESP = 2
KIND_CTL_REQ = 3
KIND_CTL_RESP = 4
KIND_HEARTBEAT = 5

# Response status codes (mirror the HTTP edge contract).
STATUS_OK = 0
STATUS_BAD_REQUEST = 1    # HTTP 400
STATUS_OVERLOADED = 2     # HTTP 503 + Retry-After (shed)
STATUS_DEADLINE = 3       # HTTP 503 (expired)
STATUS_INTERNAL = 4       # HTTP 500
STATUS_DRAINING = 5       # HTTP 503 + Retry-After (drain)

# Response flag bits.
RESP_FLAG_TRACE_PROMOTED = 0x01  # scorer's tail sampler kept this chain

# Per-(row, coordinate) entity flags.
ENT_NONE = 0         # no key: fixed-effect-only row by request
ENT_KEY = 1          # key attached, scorer resolves it
ENT_KNOWN_MISS = 3   # key attached but worker-verified absent from the
#                      store at the frame's store_generation

_HEADER = struct.Struct("<4sHBBQ")  # magic, version, kind, flags, req_id
HEADER_SIZE = _HEADER.size

WIRE_CONTENT_TYPE = "application/x-photon-wire"


class WireError(ValueError):
    """Malformed, truncated, or wrong-version frame (client error)."""


@dataclasses.dataclass
class WireRow:
    """One pre-resolved scoring row (structurally a scorer ``ParsedRow``).

    ``known_miss`` lists RE coordinate ids whose key the ENCODING side
    verified absent from its (read-only, possibly stale) coefficient
    store; the decoder surfaces them so the scorer can skip the lookup
    when store generations match.
    """

    shard_idx: Mapping[str, np.ndarray]      # shard -> [K] int32
    shard_val: Mapping[str, np.ndarray]      # shard -> [K] float32
    offset: float
    entity_keys: Mapping[str, Optional[str]]  # RE coordinate -> key
    known_miss: frozenset = frozenset()


@dataclasses.dataclass
class ScoreRequest:
    req_id: int
    trace_id: str
    deadline_ms: float        # 0 = server default timeout
    store_generation: int
    rows: Sequence[WireRow]


@dataclasses.dataclass
class ScoreResponse:
    req_id: int
    status: int = STATUS_OK
    error: str = ""
    retry_after_s: float = 0.0
    model_version: int = 0
    flags: int = 0
    scores: np.ndarray = None
    degraded: Sequence[tuple] = ()        # per row: tuple of RE coord ids
    stages: Mapping[str, float] = None    # stage -> seconds (f64)

    @property
    def trace_promoted(self) -> bool:
        return bool(self.flags & RESP_FLAG_TRACE_PROMOTED)


class _Writer:
    __slots__ = ("buf",)

    def __init__(self):
        self.buf = bytearray()

    def raw(self, b) -> None:
        self.buf += b

    def pack(self, fmt: str, *vals) -> None:
        self.buf += struct.pack(fmt, *vals)

    def str8(self, s: str) -> None:
        b = s.encode("utf-8")
        if len(b) > 0xFF:
            raise WireError(f"string too long for u8 length: {len(b)}")
        self.buf += struct.pack("<B", len(b))
        self.buf += b

    def str16(self, s: str) -> None:
        b = s.encode("utf-8")
        if len(b) > 0xFFFF:
            raise WireError(f"string too long for u16 length: {len(b)}")
        self.buf += struct.pack("<H", len(b))
        self.buf += b


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise WireError(
                f"truncated frame: need {n} bytes at offset {self.pos}, "
                f"have {len(self.buf) - self.pos}"
            )
        out = self.buf[self.pos: self.pos + n]
        self.pos += n
        return out

    def unpack(self, fmt: str):
        s = struct.Struct(fmt)
        vals = s.unpack(self.take(s.size))
        return vals if len(vals) > 1 else vals[0]

    def str8(self) -> str:
        n = self.unpack("<B")
        return self.take(n).decode("utf-8")

    def str16(self) -> str:
        n = self.unpack("<H")
        return self.take(n).decode("utf-8")

    def array(self, dtype, count: int) -> np.ndarray:
        it = np.dtype(dtype).itemsize
        raw = self.take(it * count)
        return np.frombuffer(raw, dtype=dtype, count=count)


def _header(kind: int, req_id: int, flags: int = 0) -> bytes:
    return _HEADER.pack(MAGIC, VERSION, kind, flags, req_id)


def frame_kind(buf: bytes) -> tuple[int, int]:
    """Peek ``(kind, req_id)`` after validating magic + version."""
    if len(buf) < HEADER_SIZE:
        raise WireError(f"frame shorter than header: {len(buf)} bytes")
    magic, version, kind, _flags, req_id = _HEADER.unpack_from(buf, 0)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r} (want {MAGIC!r})")
    if version != VERSION:
        raise WireError(
            f"unsupported wire version {version} (this build speaks "
            f"{VERSION})"
        )
    return kind, req_id


def is_wire(body: bytes) -> bool:
    """Cheap sniff for the HTTP edge: does this body claim to be a frame?"""
    return len(body) >= 4 and body[:4] == MAGIC


# ------------------------------------------------------------- score request


def encode_score_request(
    rows: Sequence[WireRow],
    *,
    req_id: int = 0,
    trace_id: str = "",
    deadline_ms: float = 0.0,
    store_generation: int = 0,
) -> bytes:
    if not rows:
        raise WireError("a score request must carry at least one row")
    if len(rows) > 0xFFFF:
        raise WireError(f"too many rows in one frame: {len(rows)}")
    shards = sorted(rows[0].shard_idx)
    res = sorted(rows[0].entity_keys)
    if len(shards) > 0xFF or len(res) > 0xFF:
        raise WireError("too many shards / RE coordinates for the frame")
    k = int(rows[0].shard_idx[shards[0]].shape[0]) if shards else 0
    w = _Writer()
    w.raw(_header(KIND_SCORE_REQ, req_id))
    w.pack("<If", store_generation, deadline_ms)
    w.str8(trace_id)
    w.pack("<HHBB", len(rows), k, len(shards), len(res))
    for s in shards:
        w.str8(s)
    for cid in res:
        w.str8(cid)
    for s in shards:
        mi = np.empty((len(rows), k), np.int32)
        mv = np.empty((len(rows), k), np.float32)
        for r, row in enumerate(rows):
            mi[r] = row.shard_idx[s]
            mv[r] = row.shard_val[s]
        w.raw(mi.tobytes())
        w.raw(mv.tobytes())
    w.raw(np.asarray([row.offset for row in rows], np.float32).tobytes())
    for row in rows:
        for cid in res:
            key = row.entity_keys.get(cid)
            if key is None:
                w.pack("<B", ENT_NONE)
            else:
                flag = (ENT_KNOWN_MISS if cid in row.known_miss
                        else ENT_KEY)
                w.pack("<B", flag)
                w.str16(str(key))
    return bytes(w.buf)


def decode_score_request(buf: bytes) -> ScoreRequest:
    kind, req_id = frame_kind(buf)
    if kind != KIND_SCORE_REQ:
        raise WireError(f"expected score request, got frame kind {kind}")
    r = _Reader(buf, HEADER_SIZE)
    store_generation, deadline_ms = r.unpack("<If")
    trace_id = r.str8()
    n_rows, k, n_shards, n_re = r.unpack("<HHBB")
    shards = [r.str8() for _ in range(n_shards)]
    res = [r.str8() for _ in range(n_re)]
    per_shard = {}
    for s in shards:
        mi = r.array(np.int32, n_rows * k).reshape(n_rows, k)
        mv = r.array(np.float32, n_rows * k).reshape(n_rows, k)
        per_shard[s] = (mi, mv)
    offsets = r.array(np.float32, n_rows)
    rows = []
    for i in range(n_rows):
        keys, miss = {}, set()
        for cid in res:
            flag = r.unpack("<B")
            if flag == ENT_NONE:
                keys[cid] = None
            elif flag in (ENT_KEY, ENT_KNOWN_MISS):
                keys[cid] = r.str16()
                if flag == ENT_KNOWN_MISS:
                    miss.add(cid)
            else:
                raise WireError(f"unknown entity flag {flag}")
        rows.append(WireRow(
            shard_idx={s: per_shard[s][0][i] for s in shards},
            shard_val={s: per_shard[s][1][i] for s in shards},
            offset=float(offsets[i]),
            entity_keys=keys,
            known_miss=frozenset(miss),
        ))
    return ScoreRequest(
        req_id=req_id,
        trace_id=trace_id,
        deadline_ms=float(deadline_ms),
        store_generation=int(store_generation),
        rows=rows,
    )


# ------------------------------------------------------------ score response


def encode_score_response(
    req_id: int,
    *,
    status: int = STATUS_OK,
    error: str = "",
    retry_after_s: float = 0.0,
    model_version: int = 0,
    flags: int = 0,
    scores: Optional[np.ndarray] = None,
    degraded: Sequence[Sequence[str]] = (),
    stages: Optional[Mapping[str, float]] = None,
) -> bytes:
    w = _Writer()
    w.raw(_header(KIND_SCORE_RESP, req_id, flags))
    w.pack("<B", status)
    w.str16(error[:2000])
    w.pack("<fI", retry_after_s, model_version)
    sc = (np.asarray(scores, np.float32)
          if scores is not None else np.zeros(0, np.float32))
    w.pack("<H", len(sc))
    w.raw(sc.tobytes())
    # Degraded coordinates as a per-row bitmask over a shared name table:
    # 16 bits bounds the RE coordinate count per model, which the serving
    # config bounds far lower in practice.
    names = sorted({c for row in degraded for c in row})
    if len(names) > 16:
        raise WireError(f"too many degraded coordinates: {len(names)}")
    w.pack("<B", len(names))
    for n in names:
        w.str8(n)
    if names:
        at = {n: i for i, n in enumerate(names)}
        for i in range(len(sc)):
            row = degraded[i] if i < len(degraded) else ()
            mask = 0
            for c in row:
                mask |= 1 << at[c]
            w.pack("<H", mask)
    st = stages or {}
    if len(st) > 0xFF:
        raise WireError("too many stages")
    w.pack("<B", len(st))
    for name, sec in st.items():
        w.str8(name)
        w.pack("<d", float(sec))
    return bytes(w.buf)


def decode_score_response(buf: bytes) -> ScoreResponse:
    kind, req_id = frame_kind(buf)
    if kind != KIND_SCORE_RESP:
        raise WireError(f"expected score response, got frame kind {kind}")
    flags = _HEADER.unpack_from(buf, 0)[3]
    r = _Reader(buf, HEADER_SIZE)
    status = r.unpack("<B")
    error = r.str16()
    retry_after_s, model_version = r.unpack("<fI")
    n = r.unpack("<H")
    scores = r.array(np.float32, n)
    n_names = r.unpack("<B")
    names = [r.str8() for _ in range(n_names)]
    degraded: list[tuple] = []
    if names:
        for _ in range(n):
            mask = r.unpack("<H")
            degraded.append(tuple(
                nm for b, nm in enumerate(names) if mask & (1 << b)))
    else:
        degraded = [()] * n
    n_stages = r.unpack("<B")
    stages = {}
    for _ in range(n_stages):
        name = r.str8()
        stages[name] = r.unpack("<d")
    return ScoreResponse(
        req_id=req_id,
        status=status,
        error=error,
        retry_after_s=float(retry_after_s),
        model_version=int(model_version),
        flags=flags,
        scores=scores,
        degraded=degraded,
        stages=stages,
    )


# ----------------------------------------------------------------- control


def encode_control(kind: int, req_id: int, payload: dict) -> bytes:
    """Control frame (tune / healthz / drain / hello / heartbeat): JSON
    body behind the binary header — schemaless on purpose, see module
    docstring."""
    if kind not in (KIND_CTL_REQ, KIND_CTL_RESP, KIND_HEARTBEAT):
        raise WireError(f"not a control frame kind: {kind}")
    body = json.dumps(payload).encode("utf-8")
    w = _Writer()
    w.raw(_header(kind, req_id))
    w.pack("<I", len(body))
    w.raw(body)
    return bytes(w.buf)


def decode_control(buf: bytes) -> tuple[int, int, dict]:
    """``(kind, req_id, payload)`` for any control-family frame."""
    kind, req_id = frame_kind(buf)
    if kind not in (KIND_CTL_REQ, KIND_CTL_RESP, KIND_HEARTBEAT):
        raise WireError(f"not a control frame kind: {kind}")
    r = _Reader(buf, HEADER_SIZE)
    n = r.unpack("<I")
    try:
        payload = json.loads(r.take(n).decode("utf-8"))
    except ValueError as e:
        raise WireError(f"bad control payload: {e}") from None
    if not isinstance(payload, dict):
        raise WireError("control payload must be a JSON object")
    return kind, req_id, payload
