"""Optimization problems: optimizer + objective + regularization + variances.

Parity: reference ⟦photon-api/.../optimization/GeneralizedLinearOptimizationProblem,
DistributedOptimizationProblem, SingleNodeOptimizationProblem⟧ and
``VarianceComputationType`` (SURVEY.md §2.2).

TPU-first: the distributed/single-node split disappears — one
``GLMOptimizationProblem.run`` is the whole solve as a pure jittable function.
Distribution is a property of how the *batch* is sharded (parallel/), not of
the problem class; the per-entity variant is this same function under ``vmap``
(random effects).
"""
from __future__ import annotations

import dataclasses
import enum
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from photon_tpu.data.batch import LabeledBatch
from photon_tpu.data.normalization import NormalizationContext
from photon_tpu.functions.objective import GLMObjective
from photon_tpu.functions.prior import PriorDistribution
from photon_tpu.models.coefficients import Coefficients
from photon_tpu.models.glm import GeneralizedLinearModel
from photon_tpu.ops.losses import loss_for_task
from photon_tpu.optim import (
    LBFGS,
    OWLQN,
    TRON,
    OptimizerConfig,
    OptimizerResult,
    OptimizerType,
    RegularizationContext,
)
from photon_tpu.types import TaskType

Array = jax.Array


class VarianceComputationType(enum.Enum):
    """Reference ⟦VarianceComputationType⟧: NONE / SIMPLE (1/diag H) /
    FULL (diag H⁻¹)."""

    NONE = "NONE"
    SIMPLE = "SIMPLE"
    FULL = "FULL"


# FULL variance cap: 16384² f32 ≈ 1 GB Hessian — the largest that is still
# plainly a "moderate-D fixed effect" use. Beyond this, refuse with guidance
# instead of letting XLA OOM (VERDICT round-2 weak #6).
FULL_VARIANCE_MAX_DIM = 16384


@dataclasses.dataclass(frozen=True)
class GLMOptimizationProblem:
    """Binds task, optimizer choice, regularization, and variance mode.

    ``run(batch, w0)`` returns the trained model + optimizer state history.
    Static configuration only — instances close cleanly into jit.
    """

    task: TaskType
    optimizer_type: OptimizerType = OptimizerType.LBFGS
    optimizer_config: OptimizerConfig = OptimizerConfig()
    regularization: RegularizationContext = RegularizationContext()
    reg_weight: float = 0.0
    variance_type: VarianceComputationType = VarianceComputationType.NONE
    reg_mask: Optional[Array] = None
    # Incremental-training prior (array-valued, stripped from the jit key
    # like reg_mask). Reference ⟦PriorDistribution⟧.
    prior: Optional["PriorDistribution"] = None

    def objective(
        self,
        reg_mask: Optional[Array] = None,
        prior: Optional["PriorDistribution"] = None,
        reg_weight=None,
    ) -> GLMObjective:
        rw = self.reg_weight if reg_weight is None else reg_weight
        return GLMObjective(
            loss=loss_for_task(self.task),
            l2_weight=self.regularization.l2_weight(rw),
            reg_mask=self.reg_mask if reg_mask is None else reg_mask,
            prior=self.prior if prior is None else prior,
        )

    def fit(
        self,
        batch: LabeledBatch,
        w0: Array,
        reg_mask: Optional[Array] = None,
        normalization: Optional["NormalizationContext"] = None,
        prior: Optional["PriorDistribution"] = None,
    ) -> tuple[GeneralizedLinearModel, OptimizerResult]:
        """Jitted ``run`` with a process-wide compilation cache.

        The problem (minus array-valued ``reg_mask``/``prior``, which are
        passed as dynamic arguments) is the static jit key, so repeated fits
        with the same config and shapes — every coordinate-descent step —
        reuse one XLA executable instead of re-tracing a fresh
        ``jax.jit(problem.run)``.
        """
        mask = reg_mask if reg_mask is not None else self.reg_mask
        pr = prior if prior is not None else self.prior
        # reg_weight is dynamic too: a λ-grid sweep reuses ONE executable
        # instead of recompiling per grid point. The static key keeps only
        # the weight's sign (the L1-routing guard in ``run`` needs it).
        key = dataclasses.replace(
            self, reg_mask=None, prior=None,
            reg_weight=1.0 if self.reg_weight > 0 else 0.0,
        )
        rw = jnp.asarray(self.reg_weight, w0.dtype)
        from photon_tpu.obs import trace_span

        # Optimizer-layer span (docs/observability.md): one per GLM solve,
        # covering dispatch on the cached executable (compiles show up as
        # outsized first spans; the sentinel counts them per kernel).
        with trace_span("optim.glm_fit", cat="optim", rows=batch.n_rows,
                        dim=batch.dim,
                        optimizer=self.optimizer_type.name):
            # First compile of this signature lands in the AOT compile
            # store (runtime/compile_store.py) so a restart or device-loss
            # recovery pre-warms it instead of re-tracing.
            from photon_tpu.runtime.compile_store import dispatch_recorded

            return dispatch_recorded(
                "glm_fit", _fit_jitted,
                (key, batch, w0, mask, pr, normalization, rw))

    def run(
        self,
        batch: LabeledBatch,
        w0: Array,
        reg_mask: Optional[Array] = None,
        normalization: Optional["NormalizationContext"] = None,
        prior: Optional["PriorDistribution"] = None,
        reg_weight=None,
    ) -> tuple[GeneralizedLinearModel, OptimizerResult]:
        """Full solve. ``reg_mask`` overrides the static ``self.reg_mask`` —
        used by random effects, where each vmapped entity solve carries its
        own projected per-feature penalty mask.

        With a non-identity ``normalization``, the optimizer runs in the
        transformed feature space (regularization applies there, as in the
        reference — SURVEY.md §7 hard-part #5) against the *raw* sparse
        features, and the returned model is mapped back to original space.
        """
        obj = self.objective(reg_mask, prior, reg_weight)
        norm = normalization if normalization is not None and not normalization.is_identity else None
        if norm is None:
            vg = obj.bind(batch)
        else:
            # Data term evaluated through the coefficient-space map; the L2
            # term applies directly to the transformed-space coefficients.
            data_obj = dataclasses.replace(obj, l2_weight=0.0)
            inner = norm.wrap_value_and_grad(data_obj.bind(batch))

            def vg(wp: Array) -> tuple[Array, Array]:
                v, g = inner(wp)
                lam = obj._l2_vec(wp)
                return v + 0.5 * jnp.sum(lam * wp * wp), g + lam * wp

            w0 = norm.coef_to_transformed(w0)

        # Reference parity: L1 (and the L1 part of elastic net) is only
        # handled by OWL-QN; pairing it with a smooth optimizer would
        # silently train unregularized. The guard needs a CONCRETE weight:
        # a concrete override wins; a traced override (the ``fit`` path)
        # falls back to ``self.reg_weight``, which ``fit`` sets to a
        # sign-preserving sentinel — either way the decision matches the
        # effective weight's sign.
        guard_weight = (
            reg_weight
            if isinstance(reg_weight, (int, float))
            else self.reg_weight
        )
        if (
            self.optimizer_type != OptimizerType.OWLQN
            and self.regularization.l1_weight(guard_weight) > 0.0
        ):
            raise ValueError(
                f"{self.regularization.reg_type.name} regularization requires "
                f"OptimizerType.OWLQN, got {self.optimizer_type.name}"
            )

        if self.optimizer_type == OptimizerType.LBFGS:
            if norm is None:
                # Incremental-score path: line-search probes are elementwise
                # over maintained margins; one matvec + one rmatvec per
                # iteration (vs one fused pass per probe). Identical math.
                result = LBFGS(self.optimizer_config).optimize_scored(
                    obj.score_space(batch), w0
                )
            else:
                result = LBFGS(self.optimizer_config).optimize(vg, w0)
        elif self.optimizer_type == OptimizerType.OWLQN:
            l1 = self.regularization.l1_weight(
                self.reg_weight if reg_weight is None else reg_weight
            )
            mask = obj.reg_mask if obj.reg_mask is not None else jnp.ones_like(w0)
            result = OWLQN(self.optimizer_config).optimize(vg, w0, l1 * mask)
        elif self.optimizer_type == OptimizerType.TRON:
            if norm is None:
                hvp_at = obj.bind_hvp_at(batch)
            else:
                data_obj = dataclasses.replace(obj, l2_weight=0.0)
                inner_at = norm.wrap_hvp_at(data_obj.bind_hvp_at(batch))

                def hvp_at(wp: Array):
                    hv = inner_at(wp)
                    return lambda vp: hv(vp) + obj._l2_vec(vp) * vp

            result = TRON(self.optimizer_config).optimize(vg, w0, hvp_at)
        else:  # pragma: no cover - enum is closed
            raise ValueError(f"unknown optimizer {self.optimizer_type}")

        x = result.x if norm is None else norm.coef_to_original(result.x)
        # Variances are reported for the original-space coefficients, from
        # the curvature of the objective actually minimized.
        variances = self._variances(obj, x, batch, norm)
        model = GeneralizedLinearModel(
            Coefficients(means=x, variances=variances), self.task
        )
        return model, result

    def _variances(
        self,
        obj: GLMObjective,
        w: Array,
        batch: LabeledBatch,
        norm: Optional[NormalizationContext] = None,
    ) -> Optional[Array]:
        if self.variance_type == VarianceComputationType.NONE:
            return None
        # Under normalization the minimized objective's L2 term is λ‖w'‖²
        # with w'_j = w_j / f_j, i.e. an effective per-coefficient penalty
        # λ/f_j² in original space (the intercept is shift-corrected but
        # normally reg-masked). Use that effective penalty so the reported
        # curvature matches the trained objective.
        data_obj = dataclasses.replace(obj, l2_weight=0.0)
        lam = obj._l2_vec(w)
        if norm is not None and norm.factors is not None:
            f, _ = norm._effective()
            lam = lam / (f * f)
        if self.variance_type == VarianceComputationType.SIMPLE:
            diag = data_obj.hessian_diagonal(w, batch) + lam
            return 1.0 / jnp.maximum(diag, 1e-12)
        # FULL: materialize H column-by-column via HVPs and invert. Only
        # sensible for moderate D (same caveat as the reference's full
        # Hessian inverse). Refuse absurd D outright: a 10M-feature shard
        # would allocate a D×D Hessian (400 TB) and HBM-OOM deep inside XLA
        # with no actionable message (VERDICT round-2 weak #6).
        d = int(w.shape[0])
        if d > FULL_VARIANCE_MAX_DIM:
            itemsize = jnp.dtype(w.dtype).itemsize
            raise ValueError(
                f"FULL variance materializes a {d}x{d} Hessian "
                f"({d * d * itemsize / 1e9:.1f} GB at {jnp.dtype(w.dtype).name}), "
                f"over the {FULL_VARIANCE_MAX_DIM}-feature cap; use "
                "VarianceComputationType.SIMPLE for wide models"
            )
        eye = jnp.eye(w.shape[0], dtype=w.dtype)
        h = jax.vmap(lambda v: data_obj.hessian_vector(w, v, batch))(eye)
        h = 0.5 * (h + h.T) + jnp.diag(lam)
        return jnp.diag(jnp.linalg.inv(h + 1e-12 * eye))


@partial(jax.jit, static_argnums=0)
def _fit_jitted(problem: GLMOptimizationProblem, batch, w0, reg_mask, prior,
                normalization, reg_weight):
    from photon_tpu.obs import retrace

    retrace.note_trace("glm_fit")  # 1 trace == 1 XLA compile
    return problem.run(batch, w0, reg_mask, normalization, prior, reg_weight)
