"""GLM objective functions over batches: value, gradient, HVP, Hessian diag.

Parity: reference ⟦photon-api/.../function/DistributedGLMLossFunction.scala⟧ +
⟦photon-lib/.../function/SingleNodeGLMLossFunction.scala⟧ and the aggregators
⟦ValueAndGradientAggregator, HessianVectorAggregator, HessianDiagonalAggregator⟧
(SURVEY.md §2.1/§2.2).

TPU-first: there is ONE objective implementation. The reference needed separate
distributed (treeAggregate) and single-node (Breeze loop) objective stacks; here
the same pure function serves both — run it on one chip, under ``vmap`` for
per-entity solves, or on a sharded batch where XLA turns the row-sum into an
AllReduce over ICI (see parallel/). Gradients come from autodiff (the
aggregators' hand-rolled sums fall out of the vjp of matvec), and Hessian-vector
products from forward-over-reverse ``jax.jvp``.

Conventions (reference parity, SURVEY.md §7 hard-part #6):
  * total loss = Σᵢ wᵢ ℓ(zᵢ, yᵢ) with zᵢ = xᵢᵀβ + offsetᵢ  (no 1/N scaling),
  * L2 term = λ/2 ‖β_masked‖² where the mask excludes the intercept,
  * L1 is never part of the smooth objective (OWL-QN handles it).
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from photon_tpu.data.batch import LabeledBatch
from photon_tpu.functions.prior import PriorDistribution
from photon_tpu.ops.losses import PointwiseLoss

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class GLMObjective:
    """Smooth GLM objective bound to a loss; batch is passed per call.

    ``reg_mask`` (None = all ones) excludes coefficients (e.g. the intercept)
    from the L2 term. All methods are pure and jit/vmap/shard_map-safe.
    """

    loss: PointwiseLoss
    l2_weight: float = 0.0
    reg_mask: Optional[Array] = None
    # Gaussian prior from a previous model (incremental training); its terms
    # add to value/grad/HVP/diag. Reference ⟦PriorDistributionDiff⟧ mixes the
    # same terms into the diff function.
    prior: Optional["PriorDistribution"] = None

    # -- core --------------------------------------------------------------

    def _l2_vec(self, like: Array) -> Array:
        """Per-coefficient L2 penalty λᵢ = λ·maskᵢ. The mask is a per-feature
        penalty weight (binary in the reference: 0 on the intercept)."""
        if self.reg_mask is None:
            return jnp.full_like(like, self.l2_weight)
        return self.l2_weight * self.reg_mask.astype(like.dtype)

    def value(self, w: Array, batch: LabeledBatch) -> Array:
        z = batch.features.matvec(w) + batch.offsets
        data_term = jnp.sum(batch.weights * self.loss.loss(z, batch.labels))
        out = data_term + 0.5 * jnp.sum(self._l2_vec(w) * w * w)
        if self.prior is not None:
            out = out + self.prior.value(w)
        return out

    def value_and_grad(self, w: Array, batch: LabeledBatch) -> tuple[Array, Array]:
        """Hand-fused single pass: z → (ℓ, dℓ/dz) → Xᵀ(w·dz) + L2 terms.

        Equivalent to ``jax.value_and_grad(self.value)`` but computes the loss
        and its margin-derivative together (the reference's
        ``ValueAndGradientAggregator`` seqOp) so one data pass serves both.
        """
        z = batch.features.matvec(w) + batch.offsets
        lv = jnp.sum(batch.weights * self.loss.loss(z, batch.labels))
        dz = batch.weights * self.loss.d1(z, batch.labels)
        g = batch.features.rmatvec(dz)
        lam = self._l2_vec(w)
        lv = lv + 0.5 * jnp.sum(lam * w * w)
        g = g + lam * w
        if self.prior is not None:
            lv = lv + self.prior.value(w)
            g = g + self.prior.gradient(w)
        return lv, g

    def hessian_vector(self, w: Array, v: Array, batch: LabeledBatch) -> Array:
        """H·v in one pass: Xᵀ(diag(w·d2)·Xv) + λ·v_masked.

        Reference ⟦HessianVectorAggregator⟧; on TPU this is two fused
        matvecs — no separate aggregation job.
        """
        z = batch.features.matvec(w) + batch.offsets
        d2 = batch.weights * self.loss.d2(z, batch.labels)
        hv = batch.features.rmatvec(d2 * batch.features.matvec(v))
        hv = hv + self._l2_vec(v) * v
        if self.prior is not None:
            hv = hv + self.prior.hessian_vector(v)
        return hv

    def hessian_diagonal(self, w: Array, batch: LabeledBatch) -> Array:
        """diag(H) = Σᵢ wᵢ d2ᵢ xᵢⱼ² + λ·mask — reference ⟦HessianDiagonalAggregator⟧."""
        z = batch.features.matvec(w) + batch.offsets
        d2 = batch.weights * self.loss.d2(z, batch.labels)
        diag = batch.features.sq_rmatvec(d2)
        diag = diag + self._l2_vec(w)
        if self.prior is not None:
            diag = diag + self.prior.hessian_diagonal()
        return diag

    # -- score-space interface (incremental-z optimizers) --------------------

    def value_from_scores(self, z: Array, w: Array, batch: LabeledBatch) -> Array:
        """Objective value given precomputed margins z = Xw + offsets.

        Lets an optimizer that maintains z incrementally (z ← z + t·Xp per
        accepted step) price line-search probes with pure elementwise work —
        no data pass. See ``LBFGS.optimize_scored``.
        """
        lv = jnp.sum(batch.weights * self.loss.loss(z, batch.labels))
        lv = lv + 0.5 * jnp.sum(self._l2_vec(w) * w * w)
        if self.prior is not None:
            lv = lv + self.prior.value(w)
        return lv

    def grad_from_scores(self, z: Array, w: Array, batch: LabeledBatch) -> Array:
        """Gradient given margins: Xᵀ(weights·ℓ'(z)) + L2/prior terms —
        exactly one rmatvec pass."""
        dz = batch.weights * self.loss.d1(z, batch.labels)
        g = batch.features.rmatvec(dz) + self._l2_vec(w) * w
        if self.prior is not None:
            g = g + self.prior.gradient(w)
        return g

    def score_space(self, batch: LabeledBatch) -> "ScoreSpaceObjective":
        """Bundle of score-space callables for ``LBFGS.optimize_scored``."""
        return ScoreSpaceObjective(
            score=lambda w: batch.features.matvec(w) + batch.offsets,
            score_delta=lambda p: batch.features.matvec(p),
            value_from_scores=lambda z, w: self.value_from_scores(z, w, batch),
            grad_from_scores=lambda z, w: self.grad_from_scores(z, w, batch),
        )

    # -- closure builders for the optimizers --------------------------------

    def bind(self, batch: LabeledBatch) -> Callable[[Array], tuple[Array, Array]]:
        """Close over a batch → ``w ↦ (value, grad)`` for Optimizer.optimize."""
        return lambda w: self.value_and_grad(w, batch)

    def bind_hvp(self, batch: LabeledBatch) -> Callable[[Array, Array], Array]:
        return lambda w, v: self.hessian_vector(w, v, batch)

    def bind_hvp_at(
        self, batch: LabeledBatch
    ) -> Callable[[Array], Callable[[Array], Array]]:
        """``w ↦ (v ↦ H(w)·v)`` with the margins z (and the loss curvature d2)
        computed ONCE at w. Inside TRON's inner CG loop, where w is fixed, this
        hoists the z matvec explicitly: each H·v then costs exactly 2 data
        passes (Xv matvec + rmatvec) instead of 3 — and the optimizer's
        ``data_passes`` accounting matches the program XLA actually runs
        (rather than hoping loop-invariant code motion fires).
        """

        def at(w: Array) -> Callable[[Array], Array]:
            z = batch.features.matvec(w) + batch.offsets
            d2 = batch.weights * self.loss.d2(z, batch.labels)

            def hv(v: Array) -> Array:
                out = batch.features.rmatvec(d2 * batch.features.matvec(v))
                out = out + self._l2_vec(v) * v
                if self.prior is not None:
                    out = out + self.prior.hessian_vector(v)
                return out

            return hv

        return at


@dataclasses.dataclass(frozen=True)
class ScoreSpaceObjective:
    """Callables an incremental-score optimizer needs (SURVEY.md §3.4: the
    reference pays one cluster job per line-search probe; here probes are
    elementwise over z, and a full iteration is 1 matvec + 1 rmatvec)."""

    score: Callable[[Array], Array]               # w ↦ z = Xw + offsets
    score_delta: Callable[[Array], Array]         # p ↦ Xp  (no offsets)
    value_from_scores: Callable[[Array, Array], Array]   # (z, w) ↦ f
    grad_from_scores: Callable[[Array, Array], Array]    # (z, w) ↦ ∇f


def intercept_reg_mask(dim: int, intercept_index: Optional[int]) -> Optional[Array]:
    """1s everywhere except the intercept column (reference convention)."""
    if intercept_index is None:
        return None
    return jnp.ones((dim,), jnp.float32).at[intercept_index].set(0.0)
