"""Gaussian prior from a previous model posterior — incremental training.

Parity: reference ⟦photon-lib/.../function/PriorDistribution.scala,
PriorDistributionDiff⟧ (SURVEY.md §2.1 "Prior/warm-start", §5.4): retraining
on new data penalizes deviation from the previous model's posterior,
per-coefficient:

    P(w) = (λ_inc / 2) Σⱼ (wⱼ − μⱼ)² / σⱼ²

where (μ, σ²) are the previous coefficients' means and variances (variance
defaults to 1 where the previous run computed none) and λ_inc is the
incremental-training weight. Value/gradient/HVP/diagonal terms add directly
to the smooth objective — unlike L1, a Gaussian prior is smooth, so every
optimizer supports it.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PriorDistribution:
    """Per-coefficient Gaussian prior: ``precisions`` already folds in the
    incremental weight (λ_inc/σ²), so the penalty is
    ½ Σ precⱼ (wⱼ − μⱼ)². Zero precision ⇒ no prior on that coefficient
    (used for ghost/padding slots in projected per-entity priors)."""

    means: Array        # [D]
    precisions: Array   # [D]

    @staticmethod
    def from_model(
        means: Array,
        variances: Optional[Array],
        incremental_weight: float = 1.0,
        min_variance: float = 1e-12,
    ) -> "PriorDistribution":
        """Reference ⟦PriorDistribution.apply⟧: previous posterior → prior;
        missing variances default to 1 (unit-variance prior)."""
        means = jnp.asarray(means)
        if variances is None:
            var = jnp.ones_like(means)
        else:
            var = jnp.maximum(jnp.asarray(variances), min_variance)
        return PriorDistribution(
            means=means, precisions=incremental_weight / var
        )

    def value(self, w: Array) -> Array:
        d = w - self.means
        return 0.5 * jnp.sum(self.precisions * d * d)

    def gradient(self, w: Array) -> Array:
        return self.precisions * (w - self.means)

    def hessian_vector(self, v: Array) -> Array:
        return self.precisions * v

    def hessian_diagonal(self) -> Array:
        return self.precisions
