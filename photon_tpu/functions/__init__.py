"""Objective functions and optimization problems."""
from photon_tpu.functions.objective import GLMObjective, intercept_reg_mask  # noqa: F401
from photon_tpu.functions.problem import (  # noqa: F401
    GLMOptimizationProblem,
    VarianceComputationType,
)
