"""Memory-pressure resilience: the OOM degradation ladder + device watchdog.

Why this module exists: PR 8 classified ``oom`` as a first-class failure
cause (``backend_guard.CAUSE_OOM``) but every recovery path treated it like
a transient — ``RunSupervisor`` restarted the attempt with IDENTICAL
shapes, which deterministically re-OOMs until the restart budget is gone.
Restarts cannot fix resource exhaustion; only a *smaller plan* can.
Upstream photon-ml never met this wall because Spark spills per-partition
work to disk (PAPER.md §0 ``treeAggregate``/``mapPartitions``); the
TPU-native analogue is a degradation ladder that trades throughput for
survival (docs/robustness.md §"Memory pressure"):

* **Classified-OOM retry-with-downshift** — when a solve raises an
  ``oom``-classified error, the failing site retries at the next-cheaper
  plan instead of escalating: RE bucket solves drop one blessed chunk
  tier (PR 4's chunked==full equivalence keeps the result unchanged),
  then fall to the vmapped/streamed path; out-of-core solvers halve
  ``chunk_rows``; the online trainer halves ``refresh_batch``; the
  serving micro-batcher halves its effective max batch (already a warmed
  padded shape). Each downshift is bounded per site
  (``PHOTON_OOM_MAX_DOWNSHIFTS``, default 3), journaled as a
  ``recovery.oom_downshift`` row/instant with the before→after plan,
  counted in ``oom_downshifts_total{site,cause}``, and STICKY for the
  rest of the run (re-promotion only via a fresh run's cost-table race).
* **Device-memory watchdog** — :class:`MemoryGuard` samples the live jax
  device memory stats (riding the PR 2 heartbeat), exports the
  ``device_memory_{bytes_in_use,bytes_limit,watermark}`` gauges,
  proactively asks ``DeviceSweepCache`` to spill LRU pins above the
  high-water fraction BEFORE XLA ever OOMs, and clamps the default
  sweep-cache budget to the live device limit instead of the static MB
  constant (:func:`effective_sweep_budget`).
* **Pressure-aware load shedding** — serving admission sheds (503 +
  Retry-After) once the watermark crosses critical, and ``/healthz``
  reports ``degraded: ["memory_pressure"]`` while above high water.
* **Supervisor policy** — an OOM-caused restart is attempted at most
  once, pre-degraded (:func:`pre_degrade_for_restart` shrinks the
  sweep-cache budget and caps the RE chunk ladder for the next attempt),
  and never burns backoff sleep (a deterministic failure does not heal
  with time — ``supervisor.py``).

Everything degrades honestly: on a backend with no ``memory_stats()``
(CPU) the watchdog reports unavailable and sheds nothing, while the
classified-OOM ladder still works — which is what makes the whole ladder
chaos-testable on CPU via the injected ``device_oom`` fault.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

from photon_tpu.obs import instant
from photon_tpu.obs.metrics import REGISTRY

__all__ = [
    "MemoryGuard",
    "OomDownshifter",
    "downshifter",
    "effective_sweep_budget",
    "guard",
    "is_oom",
    "journal_event",
    "max_oom_downshifts",
    "pre_degrade_for_restart",
    "reset_state",
    "set_journal",
    "set_sticky_plan",
    "sticky_plan",
]

logger = logging.getLogger("photon_tpu.memory_guard")

_OOM_DOWNSHIFTS = REGISTRY.counter(
    "oom_downshifts_total",
    "OOM-classified failures absorbed by downshifting to a cheaper plan, "
    "by site (docs/robustness.md §memory pressure)",
)
_PRESSURE_SPILLS = REGISTRY.counter(
    "memory_pressure_spills_total",
    "proactive sweep-cache spills triggered by the device-memory watchdog",
)
_PRESSURE_SHEDS = REGISTRY.counter(
    "memory_pressure_sheds_total",
    "serving requests shed because the device-memory watermark crossed "
    "critical",
)
_MEM_IN_USE = REGISTRY.gauge(
    "device_memory_bytes_in_use",
    "live device bytes in use (max across local devices with stats)",
)
_MEM_LIMIT = REGISTRY.gauge(
    "device_memory_bytes_limit",
    "device memory capacity (bytes_limit of the most-loaded local device)",
)
_MEM_WATERMARK = REGISTRY.gauge(
    "device_memory_watermark",
    "bytes_in_use / bytes_limit of the most-loaded local device (0 when "
    "the backend exposes no memory stats)",
)


def max_oom_downshifts(default: int = 3) -> int:
    """Per-site bound on OOM downshifts (``PHOTON_OOM_MAX_DOWNSHIFTS``);
    past it the original error escalates (journaled exhaustion)."""
    try:
        return max(0, int(os.environ.get(
            "PHOTON_OOM_MAX_DOWNSHIFTS", default)))
    except (TypeError, ValueError):
        return int(default)


def is_oom(err) -> bool:
    """Is this failure the one cause the downshift ladder may absorb?"""
    from photon_tpu.runtime.backend_guard import (
        CAUSE_OOM,
        classify_backend_error,
    )

    return classify_backend_error(err) == CAUSE_OOM


# ------------------------------------------------------------ journal hook
#
# Downshifts happen deep inside solves, far from the RunSupervisor that
# owns the recovery journal. The supervisor (and the drivers) register
# their journal here for the duration of a run, so in-run OOM events land
# as real journal rows next to the restart story; without one, the trace
# instant alone is the record (same contract as the device-loss recovery).

_journal_lock = threading.Lock()
_JOURNAL = None


def set_journal(journal):
    """Register the active :class:`~photon_tpu.supervisor.RecoveryJournal`
    (or None to detach). Downshift/exhaustion/pre-degrade events then
    write journal rows; the ``recovery.*`` trace instant is emitted either
    way. Returns the PREVIOUSLY registered journal so a scoped caller
    (the supervisor) can restore it instead of detaching an outer one."""
    global _JOURNAL
    with _journal_lock:
        prev = _JOURNAL
        _JOURNAL = journal
        return prev


def journal_event(event: str, **fields) -> None:
    """One recovery event: a journal row when a journal is registered
    (``RecoveryJournal.record`` mirrors the trace instant), else the
    ``recovery.<event>`` instant alone."""
    with _journal_lock:
        j = _JOURNAL
    if j is not None:
        try:
            j.record(event, **fields)
            return
        except Exception:  # noqa: BLE001 - evidence, never a failure mode
            pass
    instant(f"recovery.{event}", cat="recovery", **fields)


# ------------------------------------------------------------ sticky plans
#
# A downshift is sticky for the rest of the run: the OOM proved the bigger
# plan does not fit, and flapping back up would re-OOM on the next sweep.
# Sites record their surviving plan here; re-promotion happens only on a
# fresh run (the PR 4 cost-table race, or a new process).

_sticky_lock = threading.Lock()
_STICKY: dict = {}


def sticky_plan(site: str) -> Optional[dict]:
    """The sticky degraded plan for ``site`` (e.g. ``{"chunk": 1024}`` for
    ``re.solve``), or None when the site runs at full plan."""
    with _sticky_lock:
        p = _STICKY.get(site)
        return dict(p) if p is not None else None


def set_sticky_plan(site: str, plan: dict) -> None:
    with _sticky_lock:
        _STICKY[site] = dict(plan)


class OomDownshifter:
    """Bounded absorber of OOM-classified failures at one site.

    ``absorb(err, before=..., after=...)`` returns True when the caller
    may retry at the cheaper plan (the downshift is journaled + counted);
    False once the per-site bound is spent (the exhaustion is journaled
    and the caller must re-raise — a classified escalation, not a loop).
    Thread-safe: serving worker threads share one per-site instance.
    """

    def __init__(self, site: str):
        self.site = site
        self.count = 0
        self._lock = threading.Lock()

    def absorb(self, err, before=None, after=None, **ctx) -> bool:
        from photon_tpu.runtime.backend_guard import classify_backend_error

        cause = classify_backend_error(err)
        with self._lock:
            if self.count >= max_oom_downshifts():
                journal_event(
                    "oom_exhausted", site=self.site, cause=cause,
                    downshifts=self.count,
                    error=f"{type(err).__name__}: {str(err)[:200]}", **ctx)
                logger.error(
                    "OOM at %s with the downshift budget spent (%d/%d) — "
                    "escalating: %s", self.site, self.count,
                    max_oom_downshifts(), err)
                return False
            self.count += 1
            n = self.count
        _OOM_DOWNSHIFTS.inc(site=self.site, cause=cause)
        journal_event(
            "oom_downshift", site=self.site, cause=cause, downshift=n,
            before=before, after=after,
            error=f"{type(err).__name__}: {str(err)[:200]}", **ctx)
        logger.warning(
            "OOM at %s (%s: %s) — downshifting %s -> %s (%d/%d; sticky for "
            "this run)", self.site, type(err).__name__, err, before, after,
            n, max_oom_downshifts())
        return True


_shifter_lock = threading.Lock()
_SHIFTERS: dict = {}


def downshifter(site: str) -> OomDownshifter:
    """The process-global downshifter for ``site`` (bound shared across
    every solve at that site — the budget is per run, not per bucket)."""
    with _shifter_lock:
        s = _SHIFTERS.get(site)
        if s is None:
            s = _SHIFTERS[site] = OomDownshifter(site)
        return s


# --------------------------------------------------------- memory watchdog


def _default_stats() -> Optional[dict]:
    """``{bytes_in_use, bytes_limit, watermark}`` of the MOST-LOADED local
    device, or None when no device exposes memory stats (CPU)."""
    try:
        import jax

        worst = None
        for d in jax.local_devices():
            stats = d.memory_stats()
            if not stats:
                continue
            in_use = float(stats.get("bytes_in_use", 0.0))
            limit = float(stats.get("bytes_limit", 0.0))
            if limit <= 0:
                continue
            frac = in_use / limit
            if worst is None or frac > worst["watermark"]:
                worst = {"bytes_in_use": in_use, "bytes_limit": limit,
                         "watermark": frac}
        return worst
    except Exception:  # noqa: BLE001 - a sick backend must not break callers
        return None


def _env_fraction(name: str, default: float) -> float:
    try:
        v = float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default
    return v if 0.0 < v <= 1.0 else default


class MemoryGuard:
    """Device-memory watchdog: sample, export, spill, shed.

    One instance per process (:func:`guard`). ``stats_fn`` is the test/
    chaos seam — drills substitute a fake returning any watermark, so the
    spill and shed paths run for real on CPU. Samples are throttled
    (``min_sample_interval_s``) so the serving admission path can consult
    :meth:`should_shed` per request without a per-request device call.

    Thresholds (fractions of ``bytes_limit``):

    * ``high_water`` (``PHOTON_MEM_HIGH_WATER``, default 0.85) — above it
      :meth:`check` proactively spills sweep-cache pins and ``/healthz``
      reports ``memory_pressure``;
    * ``critical`` (``PHOTON_MEM_CRITICAL``, default 0.95) — above it
      serving admission sheds (503 + Retry-After).
    """

    def __init__(
        self,
        high_water: Optional[float] = None,
        critical: Optional[float] = None,
        stats_fn: Optional[Callable[[], Optional[dict]]] = None,
        min_sample_interval_s: float = 0.5,
    ):
        self.high_water = (
            _env_fraction("PHOTON_MEM_HIGH_WATER", 0.85)
            if high_water is None else float(high_water))
        self.critical = (
            _env_fraction("PHOTON_MEM_CRITICAL", 0.95)
            if critical is None else float(critical))
        self.stats_fn = stats_fn if stats_fn is not None else _default_stats
        self.min_sample_interval_s = float(min_sample_interval_s)
        self._lock = threading.Lock()
        self._last_sample: Optional[dict] = None
        self._last_sample_t = float("-inf")
        self._spills = 0

    def sample(self, force: bool = False) -> Optional[dict]:
        """Latest ``{bytes_in_use, bytes_limit, watermark}`` (throttled;
        ``force`` bypasses the throttle), or None when the backend exposes
        no memory stats. Sets the ``device_memory_*`` gauges."""
        now = time.monotonic()
        with self._lock:
            if (not force
                    and now - self._last_sample_t
                    < self.min_sample_interval_s):
                return self._last_sample
        s = self.stats_fn()
        with self._lock:
            self._last_sample = s
            self._last_sample_t = now
        if s is not None:
            _MEM_IN_USE.set(s["bytes_in_use"])
            _MEM_LIMIT.set(s["bytes_limit"])
            _MEM_WATERMARK.set(round(s["watermark"], 4))
        else:
            _MEM_WATERMARK.set(0.0)
        return s

    def watermark(self) -> Optional[float]:
        s = self.sample()
        return None if s is None else s["watermark"]

    def under_pressure(self) -> bool:
        """Watermark at or above high water (the /healthz degraded gate)."""
        w = self.watermark()
        return w is not None and w >= self.high_water

    def should_shed(self) -> bool:
        """Watermark at or above critical (the admission-control gate);
        counts the shed decision so the drill is metric-visible."""
        w = self.watermark()
        if w is None or w < self.critical:
            return False
        _PRESSURE_SHEDS.inc()
        return True

    def check(self) -> dict:
        """One watchdog pass (rides the heartbeat loop): fresh sample +
        proactive sweep-cache spill when above high water. Returns
        ``{available, watermark, spilled_bytes}``."""
        s = self.sample(force=True)
        if s is None:
            return {"available": False, "watermark": None,
                    "spilled_bytes": 0}
        freed = 0
        if s["watermark"] >= self.high_water:
            # Free enough pinned bytes to get back under the high-water
            # line. The sweep cache is the one device consumer whose
            # contents are EXPENDABLE by contract (a spilled entry
            # re-streams next pass — a throughput regression, never a
            # wrong answer), so it is the pressure valve.
            target = int(s["bytes_in_use"]
                         - self.high_water * s["bytes_limit"])
            from photon_tpu.data.device_cache import shed_pins

            freed = shed_pins(max(0, target))
            if freed:
                self._spills += 1
                _PRESSURE_SPILLS.inc()
                instant("memory.pressure_spill", cat="recovery",
                        watermark=round(s["watermark"], 4),
                        freed_bytes=int(freed))
                logger.warning(
                    "device memory watermark %.2f >= high water %.2f — "
                    "spilled %d sweep-cache bytes (next pass re-streams "
                    "them)", s["watermark"], self.high_water, freed)
        return {"available": True,
                "watermark": round(s["watermark"], 4),
                "spilled_bytes": int(freed)}

    def snapshot(self) -> dict:
        s = self._last_sample
        return {
            "high_water": self.high_water,
            "critical": self.critical,
            "watermark": None if s is None else round(s["watermark"], 4),
            "spills": self._spills,
        }


_guard_lock = threading.Lock()
_GUARD: Optional[MemoryGuard] = None


def guard() -> MemoryGuard:
    """The process-global :class:`MemoryGuard` (created on first use)."""
    global _GUARD
    with _guard_lock:
        if _GUARD is None:
            _GUARD = MemoryGuard()
        return _GUARD


# ----------------------------------------------- sweep-cache budget policy

_budget_lock = threading.Lock()
_BUDGET_SCALE = 1.0
_clamp_warned = False


def sweep_budget_scale() -> float:
    """Run-wide degradation multiplier on sweep-cache budgets (halved by
    each :func:`pre_degrade_for_restart`)."""
    with _budget_lock:
        return _BUDGET_SCALE


def effective_sweep_budget(requested_bytes: int) -> int:
    """The PER-DEVICE budget a ``DeviceSweepCache`` actually gets (the
    cache multiplies by its mesh's entity-axis device count — its pins are
    sharded, so each device carries 1/n of the total):

    * scaled by the run's degradation multiplier (an OOM-pre-degraded
      restart must not re-pin the budget that just killed the attempt);
    * clamped to ``PHOTON_SWEEP_CACHE_DEVICE_FRACTION`` (default 0.5) of
      the LIVE device ``bytes_limit`` when the backend reports one — the
      static 2048 MB default can exceed the whole device on small parts,
      and a budget the device cannot hold is an OOM schedule, not a
      cache. One-time warning when the clamp fires; backends with no
      memory stats (CPU) keep the requested budget.
    """
    global _clamp_warned
    b = int(requested_bytes * sweep_budget_scale())
    if b <= 0:
        return 0
    s = guard().sample()
    if s is None or s["bytes_limit"] <= 0:
        return b
    frac = _env_fraction("PHOTON_SWEEP_CACHE_DEVICE_FRACTION", 0.5)
    cap = int(s["bytes_limit"] * frac)
    if b > cap:
        with _budget_lock:
            warn = not _clamp_warned
            _clamp_warned = True
        if warn:
            logger.warning(
                "sweep-cache budget %d bytes exceeds %.0f%% of the live "
                "device limit (%d bytes) — clamping to %d. Set "
                "PHOTON_SWEEP_CACHE_MB (or PHOTON_SWEEP_CACHE_DEVICE_"
                "FRACTION) to size the cache to this part.",
                b, 100.0 * frac, int(s["bytes_limit"]), cap)
        return cap
    return b


def pre_degrade_for_restart(reason: str = "supervised OOM restart") -> dict:
    """Shrink the NEXT attempt's memory plan after an OOM-caused attempt
    failure (the supervisor's one pre-degraded restart): halve the
    sweep-cache budget scale and cap the RE chunk ladder one blessed tier
    below its current cap. Journaled so the degraded plan the next attempt
    runs under is in the recovery record. Returns the plan."""
    global _BUDGET_SCALE
    with _budget_lock:
        _BUDGET_SCALE *= 0.5
        scale = _BUDGET_SCALE
    from photon_tpu.game.newton_re import chunk_ladder

    ladder = chunk_ladder()
    cur = sticky_plan("re.solve") or {}
    eff = cur.get("chunk") or ladder[-1] + 1
    smaller = [c for c in ladder if c < eff]
    new_chunk = max(smaller) if smaller else ladder[0]
    set_sticky_plan("re.solve", {**cur, "chunk": new_chunk})
    plan = {
        "sweep_cache_budget_scale": scale,
        "re_chunk_cap": new_chunk,
        "reason": reason,
    }
    journal_event("oom_predegrade", **plan)
    logger.warning(
        "pre-degrading the next attempt after OOM: sweep-cache budget "
        "scale %.3f, RE chunk cap %d (%s)", scale, new_chunk, reason)
    return plan


def reset_state() -> None:
    """Test hook: forget sticky plans, downshift counts, budget scale,
    the journal hook, and the guard singleton."""
    global _GUARD, _BUDGET_SCALE, _clamp_warned
    with _sticky_lock:
        _STICKY.clear()
    with _shifter_lock:
        _SHIFTERS.clear()
    with _budget_lock:
        _BUDGET_SCALE = 1.0
        _clamp_warned = False
    with _guard_lock:
        _GUARD = None
    set_journal(None)
