"""AOT compile-artifact store: zero-recompile restarts and hot-swaps.

Why this module exists: every recovery path PR 8 built — supervised
checkpoint-resume restarts, in-run device-loss recovery, serving kernel
re-warmup — pays a full XLA retrace on re-entry, because
``supervisor.clear_executable_caches`` and process restarts drop every
compiled executable (PR 4 measured 3.5 s compile + 6.4 s calibration at the
100K bucket shape, and TPU_RECOVERY.jsonl shows restart storms where that
cost recurs per attempt). Upstream photon-ml never had this failure mode —
Spark re-JITs Scala closures for free — so the rebuild's recovery-time
story is only honest once compilation stops being the dominant term in
MTTR (ROADMAP item 4).

The store has two layers:

* **Artifact bytes** — JAX's persistent compilation cache
  (``jax_compilation_cache_dir``): every XLA executable serializes to disk
  keyed by its HLO digest, so a re-compile after a cache clear or a process
  restart is a disk LOAD, not an XLA compile. The store forces the cache on
  (under ``<root>/xla`` when the driver didn't wire its own dir) with a
  zero min-compile-time floor — recovery cares about every kernel in the
  closed set, not just the slow ones.
* **The manifest** (``<root>/manifest.json`` + one pickled abstract
  signature per entry) — the piece the raw cache lacks: an enumerable
  record of every (kernel, abstract shapes, dtype, static config, backend,
  code fingerprint) a run compiled, so a PRE-WARM pass can replay
  ``jit(...).lower(*abstract_args).compile()`` for the whole closed kernel
  set *before* an attempt goes live. ``lower().compile()`` shares the jit
  dispatch cache (verified: the subsequent real call neither re-traces nor
  re-compiles), so a pre-warmed attempt starts solving in milliseconds.

The closed kernel set (the only record sites): the blessed chunk-ladder RE
solvers (``fit_bucket_newton``, ``fit_bucket_newton_dual``,
``fit_bucket_vmapped``), ``glm_fit``, and ``additive_score_rows``.
Recording is best-effort by contract — a signature that will not pickle is
skipped with a debug log, never an error in the training path.

Wired through the recovery stack (docs/robustness.md §"Recovery time"):

* :class:`~photon_tpu.supervisor.RunSupervisor` pre-warms the next attempt
  between restarts and journals a ``prewarm`` row (mirrored once as a
  ``recovery.prewarm`` trace instant, emitted here) with compile-vs-load
  seconds;
* :func:`~photon_tpu.runtime.backend_guard.recover_from_device_loss`
  repopulates from the store right after ``clear_executable_caches`` so the
  in-run recovery re-step loads instead of recompiling cold;
* checkpoints stamp :func:`manifest_ref_if_active` into their metadata so a
  checkpoint-resume restart knows exactly which artifacts to pre-warm
  (:func:`prewarm_from_checkpoint`);
* ``game/descent.py`` stamps :func:`note_first_step` after its first
  committed step, closing the ``restart_to_first_step_seconds`` clock the
  supervisor arms per attempt.

Compile-vs-load accounting rides ``jax.monitoring``: each compile request
either MISSES the persistent cache (the ``backend_compile_duration`` is XLA
time) or HITS it (the duration is artifact-load I/O). The split feeds the
``xla_compile_seconds_total`` / ``xla_cache_load_seconds_total`` counters
and the CI assertion that a warm restart's XLA share sits below its I/O
share.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import threading
import time
from typing import Optional, Sequence

__all__ = [
    "CompileStore",
    "active",
    "arm_first_step_clock",
    "compile_split",
    "configure",
    "deactivate",
    "install_accounting",
    "manifest_ref_if_active",
    "note_compilation",
    "note_first_step",
    "prewarm_from_checkpoint",
    "prewarm_if_active",
    "process_has_compiled",
    "record_if_active",
]

logger = logging.getLogger("photon_tpu.runtime")

MANIFEST_NAME = "manifest.json"
MANIFEST_VERSION = 1

# ------------------------------------------------------ compile/load split
#
# jax.monitoring event stream, observed per compile request:
#   miss: .../compile_requests_use_cache, .../cache_misses,
#         backend_compile_duration          -> XLA compile time
#   hit:  .../compile_requests_use_cache, .../cache_hits,
#         cache_retrieval_time_sec, backend_compile_duration
#                                            -> artifact-load I/O time
# The marker event and the duration arrive on the same thread in order, so
# a thread-local "last marker" attributes each duration correctly.

_acc_lock = threading.Lock()
_acc_installed = False
_acc_available: Optional[bool] = None  # None until first install attempt
_acc_tls = threading.local()


def accounting_available() -> bool:
    """Did the compile-vs-load listeners actually install? Pre-warm uses
    this to classify honestly: with no accounting, an entry that silently
    paid a cold compile must never be reported as a load."""
    install_accounting()
    return bool(_acc_available)


def install_accounting() -> bool:
    """Install the process-wide XLA compile-vs-load listeners (idempotent).

    Returns False when ``jax.monitoring`` is unavailable — the counters
    then stay at zero and :class:`compile_split` reports empty deltas, but
    nothing in the store's record/prewarm contract breaks."""
    global _acc_installed, _acc_available
    with _acc_lock:
        if _acc_installed:
            return bool(_acc_available)
        try:
            from jax._src import monitoring
        except Exception as e:  # noqa: BLE001 - version-dependent API
            logger.debug("compile accounting unavailable: %s", e)
            _acc_installed = True
            _acc_available = False
            return False
        from photon_tpu.obs.metrics import REGISTRY

        hits = REGISTRY.counter(
            "xla_cache_hits_total",
            "compile requests served from the persistent compilation cache "
            "(artifact load, not an XLA compile)",
        )
        misses = REGISTRY.counter(
            "xla_cache_misses_total",
            "compile requests that paid a real XLA backend compile",
        )
        xla_s = REGISTRY.counter(
            "xla_compile_seconds_total",
            "wall seconds inside XLA backend compiles (cache misses)",
        )
        io_s = REGISTRY.counter(
            "xla_cache_load_seconds_total",
            "wall seconds loading compiled executables from the persistent "
            "cache (cache hits)",
        )

        def on_event(name: str, **kw) -> None:
            if name.endswith("/cache_hits"):
                _acc_tls.last = "hit"
                hits.inc()
            elif name.endswith("/cache_misses"):
                _acc_tls.last = "miss"
                misses.inc()

        def on_duration(name: str, secs: float, **kw) -> None:
            if name.endswith("backend_compile_duration"):
                # No marker (cache disabled / unknown) counts as a miss:
                # without a persistent cache every compile IS XLA time.
                if getattr(_acc_tls, "last", "miss") == "hit":
                    io_s.inc(max(float(secs), 0.0))
                else:
                    xla_s.inc(max(float(secs), 0.0))
                _acc_tls.last = "miss"  # marker consumed

        monitoring.register_event_listener(on_event)
        monitoring.register_event_duration_secs_listener(on_duration)
        _acc_installed = True
        _acc_available = True
        return True


class compile_split:
    """``with compile_split() as cs: ...`` — per-block deltas of the XLA
    compile-vs-load accounting: ``cs.hits``/``cs.misses`` (compile requests
    by outcome) and ``cs.xla_seconds``/``cs.io_seconds``."""

    def __init__(self) -> None:
        self.hits = 0
        self.misses = 0
        self.xla_seconds = 0.0
        self.io_seconds = 0.0
        self.available = False  # did the jax.monitoring listeners install?

    def _values(self) -> tuple:
        from photon_tpu.obs.metrics import REGISTRY

        return (
            REGISTRY.counter("xla_cache_hits_total").value(),
            REGISTRY.counter("xla_cache_misses_total").value(),
            REGISTRY.counter("xla_compile_seconds_total").value(),
            REGISTRY.counter("xla_cache_load_seconds_total").value(),
        )

    def __enter__(self) -> "compile_split":
        self.available = install_accounting()
        self._before = self._values()
        return self

    def __exit__(self, *exc) -> None:
        h, m, x, i = self._values()
        b = self._before
        self.hits = int(h - b[0])
        self.misses = int(m - b[1])
        self.xla_seconds = max(0.0, x - b[2])
        self.io_seconds = max(0.0, i - b[3])


# ------------------------------------------------------- signature helpers


# Any process-wide compilation (registered kernels bump this via
# obs.retrace.note_trace) — the "already compiled" detector behind the
# enable_compilation_cache late-call guard (cli/params.py).
_compiled_flag = threading.Event()


def note_compilation() -> None:
    _compiled_flag.set()


def process_has_compiled() -> bool:
    """Best-effort "this process already compiled something": any watched
    kernel traced (retrace sentinel), or the flag was set directly."""
    if _compiled_flag.is_set():
        return True
    try:
        from photon_tpu.obs import retrace

        return any(v > 0 for v in retrace.all_traces().values())
    except Exception:  # noqa: BLE001 - detector, never a failure mode
        return False


def _abstractify(x):
    """Array-likes → ShapeDtypeStruct; everything else (statics: problem
    configs, ints, part tuples) passes through to the pickle."""
    import jax
    import numpy as np

    if isinstance(x, (jax.Array, np.ndarray)):
        return jax.ShapeDtypeStruct(tuple(x.shape), np.dtype(x.dtype))
    return x


_fp_cache: dict = {}


def _code_fingerprint(fn) -> str:
    """Digest of the kernel's defining module source — a changed kernel
    invalidates its entries (the executable they name no longer matches the
    code that would be traced)."""
    import sys

    mod = getattr(fn, "__module__", None) or ""
    cached = _fp_cache.get(mod)
    if cached is not None:
        return cached
    digest = "unknown"
    try:
        path = getattr(sys.modules.get(mod), "__file__", None)
        if path:
            with open(path, "rb") as f:
                digest = hashlib.sha256(f.read()).hexdigest()[:16]
    except OSError:
        pass
    _fp_cache[mod] = digest
    return digest


def _import_fn(ref: str):
    """``"module:qualname"`` → the (jitted) callable."""
    import importlib

    mod_name, _, qual = ref.partition(":")
    obj = importlib.import_module(mod_name)
    for part in qual.split("."):
        obj = getattr(obj, part)
    return obj


def _default_backend() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:  # noqa: BLE001 - no backend => no store entry
        return "unknown"


# --------------------------------------------------------------- the store


class CompileStore:
    """Manifest-backed AOT compile-artifact store (module doc).

    One directory per store: ``manifest.json`` (entry metadata keyed by
    signature digest) plus one ``<key>.sig`` pickle per entry holding the
    exact ``(args, kwargs)`` tuple — statics verbatim, traced arrays as
    ``ShapeDtypeStruct`` — that :meth:`prewarm` replays through
    ``fn.lower(...).compile()``. Thread-safe; manifest writes are atomic
    (tmp + ``os.replace``) so a reader never sees a torn manifest.
    """

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()
        self._entries: dict = {}
        self._load_manifest()
        install_accounting()

    # ------------------------------------------------------------ manifest

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.root, MANIFEST_NAME)

    def _load_manifest(self) -> None:
        try:
            with open(self.manifest_path) as f:
                data = json.load(f)
            self._entries = dict(data.get("entries", {}))
        except FileNotFoundError:
            self._entries = {}
        except (OSError, ValueError) as e:
            # A corrupt manifest must degrade to "empty store" (recompiles),
            # never take a recovery path down with it.
            logger.warning("compile store manifest unreadable (%s); "
                           "starting empty: %s", self.manifest_path, e)
            self._entries = {}

    def _write_manifest(self) -> None:
        # Caller holds self._lock.
        tmp = f"{self.manifest_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"version": MANIFEST_VERSION, "entries": self._entries},
                      f, indent=1, sort_keys=True)
        os.replace(tmp, self.manifest_path)

    def entries(self) -> dict:
        with self._lock:
            return dict(self._entries)

    def manifest_digest(self) -> str:
        with self._lock:
            keys = sorted(self._entries)
        return hashlib.sha256("|".join(keys).encode()).hexdigest()[:16]

    def manifest_ref(self) -> dict:
        """Checkpoint-embeddable reference: enough for a resumed restart to
        find and pre-warm exactly this artifact set."""
        return {
            "root": self.root,
            "digest": self.manifest_digest(),
            "entries": len(self._entries),
        }

    # -------------------------------------------------------------- record

    def record(self, kernel: str, fn, args: Sequence = (),
               kwargs: Optional[dict] = None) -> bool:
        """Record one compiled signature of ``kernel`` (best-effort).

        ``args``/``kwargs`` are the EXACT call arguments of the jitted
        ``fn`` — arrays are abstracted to shape/dtype structs, statics are
        pickled verbatim so the pre-warm replay traces the identical HLO.
        Returns True when a NEW entry landed; False for duplicates or any
        recording failure (never raises into a training path)."""
        note_compilation()
        try:
            import jax

            sig = jax.tree.map(_abstractify, (tuple(args), dict(kwargs or {})))
            blob = pickle.dumps(sig, protocol=pickle.HIGHEST_PROTOCOL)
            fn_ref = f"{fn.__module__}:{fn.__qualname__}"
            backend = _default_backend()
            fp = _code_fingerprint(fn)
            key = hashlib.sha256(
                f"{kernel}|{fn_ref}|{fp}|{backend}|{jax.__version__}|".encode()
                + blob
            ).hexdigest()[:24]
        except Exception as e:  # noqa: BLE001 - recording is best-effort
            logger.debug("compile store: signature for %s not recordable "
                         "(%s: %s)", kernel, type(e).__name__, e)
            return False
        with self._lock:
            if key in self._entries:
                return False
        try:
            sig_path = os.path.join(self.root, f"{key}.sig")
            tmp = f"{sig_path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, sig_path)
            with self._lock:
                self._entries[key] = {
                    "kernel": kernel,
                    "fn": fn_ref,
                    "backend": backend,
                    "jax_version": jax.__version__,
                    "code_fingerprint": fp,
                    "created_at": time.strftime(
                        "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                }
                self._write_manifest()
        except OSError as e:
            logger.debug("compile store: entry write failed (%s)", e)
            return False
        from photon_tpu.obs.metrics import REGISTRY

        REGISTRY.counter(
            "compile_store_entries_total",
            "AOT compile-store manifest entries recorded, by kernel",
        ).inc(kernel=kernel)
        return True

    # ------------------------------------------------------------- prewarm

    def prewarm(self, kernels: Optional[Sequence[str]] = None,
                logger_=None, reason: str = "") -> dict:
        """Replay every matching manifest entry through
        ``fn.lower(*abstract_args).compile()`` so the executables are live
        BEFORE the run/swap goes hot.

        With the persistent cache populated each replay is an artifact
        LOAD; a cold store (fresh machine, new code fingerprint upstream)
        compiles — and thereby populates the cache for the next restart.
        Entries for another backend/jax version/code fingerprint are
        skipped, as is anything that fails to import, unpickle, or lower —
        pre-warm can degrade to "nothing warmed", never to a new failure.

        Returns ``{entries, loaded, compiled, skipped, load_seconds,
        xla_seconds, seconds}`` and emits ONE ``recovery.prewarm`` trace
        instant (callers journaling a row pass ``_mirror=False``)."""
        from photon_tpu.obs import instant, retrace
        from photon_tpu.obs.metrics import REGISTRY

        log = logger_ or logger
        t0 = time.perf_counter()
        backend = _default_backend()
        try:
            import jax

            jax_version = jax.__version__
        except Exception:  # noqa: BLE001
            jax_version = "unknown"
        loaded = compiled = 0
        skipped: list = []
        load_s = xla_s = 0.0
        for key, meta in sorted(self.entries().items()):
            if kernels is not None and meta.get("kernel") not in kernels:
                continue
            if (meta.get("backend") != backend
                    or meta.get("jax_version") != jax_version):
                skipped.append((key, "backend/jax mismatch"))
                continue
            try:
                fn = _import_fn(meta["fn"])
                if meta.get("code_fingerprint") != _code_fingerprint(fn):
                    skipped.append((key, "stale code fingerprint"))
                    continue
                with open(os.path.join(self.root, f"{key}.sig"), "rb") as f:
                    args, kw = pickle.load(f)
            except Exception as e:  # noqa: BLE001 - entry-level isolation
                skipped.append((key, f"{type(e).__name__}: {e}"))
                continue
            try:
                # Expected compiles: a prewarm trace must never fire the
                # retrace-after-warmup alarm — it IS the warmup.
                with compile_split() as cs, retrace.expected_compiles():
                    fn.lower(*args, **kw).compile()
            except Exception as e:  # noqa: BLE001 - entry-level isolation
                skipped.append((key, f"{type(e).__name__}: {e}"))
                continue
            # Honest classification: without the monitoring listeners we
            # cannot distinguish a cache load from a cold compile, and a
            # silently-cold entry reported as "loaded" would turn the CI
            # warm-restart assertion false-green — count it as compiled.
            if cs.misses > 0 or not cs.available:
                compiled += 1
            else:
                loaded += 1
            load_s += cs.io_seconds
            xla_s += cs.xla_seconds
        took = time.perf_counter() - t0
        summary = {
            "entries": len(self._entries),
            "loaded": loaded,
            "compiled": compiled,
            "skipped": len(skipped),
            "load_seconds": round(load_s, 4),
            "xla_seconds": round(xla_s, 4),
            "seconds": round(took, 4),
            "accounting": accounting_available(),
        }
        REGISTRY.counter(
            "compile_store_prewarm_loads_total",
            "prewarmed executables that LOADED from the persistent cache",
        ).inc(loaded)
        REGISTRY.counter(
            "compile_store_prewarm_compiles_total",
            "prewarmed executables that paid a cold XLA compile",
        ).inc(compiled)
        instant("recovery.prewarm", cat="recovery", reason=reason, **summary)
        if log is not None:
            log.info(
                "compile store prewarm%s: %d loaded, %d compiled, %d skipped "
                "(load %.3fs, xla %.3fs)",
                f" ({reason})" if reason else "", loaded, compiled,
                len(skipped), load_s, xla_s)
            for key, why in skipped[:5]:
                log.debug("compile store prewarm skipped %s: %s", key, why)
        return summary


# ------------------------------------------------- process default store

_active_lock = threading.Lock()
_ACTIVE: Optional[CompileStore] = None
_DISABLED = False  # explicit opt-out pins OFF even with the env var set


def configure(root: str, enable_xla_cache: bool = True) -> CompileStore:
    """Make ``root`` this process's active compile store. When no
    persistent compilation cache is wired yet (``jax_compilation_cache_dir``
    unset) and ``enable_xla_cache``, the store supplies one —
    ``$PHOTON_XLA_CACHE_DIR`` or ``<root>/xla`` — with a zero
    min-compile-time floor (recovery needs EVERY kernel in the closed set
    persisted, not just the slow ones)."""
    global _ACTIVE, _DISABLED
    store = CompileStore(root)
    if enable_xla_cache:
        _ensure_persistent_cache(store)
    with _active_lock:
        _ACTIVE = store
        _DISABLED = False  # an explicit configure overrides a prior opt-out
    return store


def _ensure_persistent_cache(store: CompileStore) -> None:
    try:
        import jax

        # The store's floor wins either way: with a compile store active,
        # recovery needs EVERY kernel in the closed set persisted — the
        # cache-only default of 1.0s (enable_compilation_cache) would drop
        # exactly the sub-second kernels a warm restart then recompiles
        # cold while the prewarm journal claims the store is working.
        min_secs = float(os.environ.get("PHOTON_XLA_CACHE_MIN_SECS", "0.0"))
        if jax.config.jax_compilation_cache_dir:
            # Driver already wired its own dir; layer on it, floor lowered.
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", min_secs)
            return
        path = (os.environ.get("PHOTON_XLA_CACHE_DIR")
                or os.path.join(store.root, "xla"))
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_secs)
        _reset_jax_cache_handle()
    except Exception as e:  # noqa: BLE001 - cache layer is best-effort
        logger.warning("compile store: persistent cache unavailable (%s); "
                       "prewarm will compile instead of load", e)


def _reset_jax_cache_handle() -> None:
    """Drop jax's memoized persistent-cache handle so a cache dir set
    AFTER this process's first compile still takes effect. jax initializes
    the cache lazily at the first compile and memoizes the result — with
    no dir configured at that moment, every later ``jax_compilation_cache_
    dir`` update is silently ignored (the late-call no-op the
    enable_compilation_cache guard warns about). Private API, so failure
    degrades to the old behavior (warn-only)."""
    try:
        from jax._src import compilation_cache

        compilation_cache.reset_cache()
    except Exception as e:  # noqa: BLE001 - version-dependent private API
        logger.debug("jax compilation-cache reset unavailable: %s", e)


def active() -> Optional[CompileStore]:
    """The process's active store: configured explicitly, or lazily from
    ``$PHOTON_COMPILE_STORE``; None when neither names one or when
    :func:`disable` pinned the explicit opt-out."""
    global _ACTIVE
    with _active_lock:
        if _ACTIVE is not None:
            return _ACTIVE
        if _DISABLED:
            return None
    root = os.environ.get("PHOTON_COMPILE_STORE")
    if root:
        return configure(root)
    return None


def disable() -> None:
    """Pin the store OFF process-wide (``--compile-store off``): without
    this, a fleet-wide ``$PHOTON_COMPILE_STORE`` export would lazily
    re-activate the store — and repoint the persistent cache — on the
    first kernel compile, overriding the operator's explicit opt-out."""
    global _ACTIVE, _DISABLED
    with _active_lock:
        _ACTIVE = None
        _DISABLED = True


def deactivate() -> None:
    """Forget the active store AND any opt-out pin (tests)."""
    global _ACTIVE, _DISABLED
    with _active_lock:
        _ACTIVE = None
        _DISABLED = False


def dispatch_recorded(kernel: str, fn, args: Sequence = (),
                      kwargs: Optional[dict] = None):
    """Dispatch ``fn(*args, **kwargs)`` under a retrace compile watch and,
    when THIS dispatch compiled, record the signature into the active
    store — the shared record-site shim (``problem.fit``, the serving
    scorer, ``transform_rows``). Costs two counter reads per call when
    nothing compiles."""
    from photon_tpu.obs.retrace import compile_watch

    with compile_watch(kernels=(kernel,)) as cw:
        out = fn(*args, **(kwargs or {}))
    if cw.compiled:
        record_if_active(kernel, fn, args, kwargs)
    return out


def record_if_active(kernel: str, fn, args: Sequence = (),
                     kwargs: Optional[dict] = None) -> bool:
    """``CompileStore.record`` against the active store; no-op without one.
    Also feeds the already-compiled detector either way."""
    note_compilation()
    store = active()
    if store is None:
        return False
    return store.record(kernel, fn, args, kwargs)


def prewarm_if_active(reason: str = "", kernels=None,
                      logger_=None) -> Optional[dict]:
    """``CompileStore.prewarm`` against the active store; None without one.
    Never raises — recovery paths call this between clearing the executable
    caches and re-entering the solve."""
    store = active()
    if store is None:
        return None
    try:
        return store.prewarm(kernels=kernels, logger_=logger_, reason=reason)
    except Exception as e:  # noqa: BLE001 - prewarm must not break recovery
        (logger_ or logger).warning(
            "compile store prewarm failed (%s: %s); recovery proceeds cold",
            type(e).__name__, e)
        return None


def manifest_ref_if_active() -> Optional[dict]:
    store = active()
    return None if store is None else store.manifest_ref()


def prewarm_from_checkpoint(payload: Optional[dict],
                            logger_=None) -> Optional[dict]:
    """Pre-warm from the compile-store reference a checkpoint carries
    (``meta["compile_store"]``, stamped by ``CheckpointManager.save``), so
    a checkpoint-resume restart starts solving in milliseconds. Falls back
    to the active store when the referenced root is gone; returns None when
    neither exists."""
    ref = ((payload or {}).get("meta") or {}).get("compile_store") or {}
    root = ref.get("root")
    store = active()
    if root and os.path.isdir(root) and (store is None
                                         or store.root != os.path.abspath(root)):
        # The checkpoint's store is authoritative for ITS kernel set; warm
        # it without stealing the process's active-store slot.
        store = CompileStore(root)
    if store is None:
        return None
    try:
        return store.prewarm(logger_=logger_, reason="checkpoint-resume")
    except Exception as e:  # noqa: BLE001 - resume must not fail on this
        (logger_ or logger).warning(
            "checkpoint prewarm failed (%s: %s); resume proceeds cold",
            type(e).__name__, e)
        return None


# --------------------------------------------- restart-to-first-step clock

_clock_lock = threading.Lock()
_first_step: Optional[dict] = None


def arm_first_step_clock(attempt: int = 0, journal=None) -> None:
    """Start the restart→first-step clock (the supervisor arms one per
    attempt). The next :func:`note_first_step` stamps the elapsed seconds
    into the ``restart_to_first_step_seconds`` gauge, a
    ``recovery.first_step`` trace instant, and — when ``journal`` is a
    :class:`~photon_tpu.supervisor.RecoveryJournal` — a ``first_step``
    journal row."""
    global _first_step
    with _clock_lock:
        _first_step = {
            "t0": time.monotonic(),
            "attempt": int(attempt),
            "journal": journal,
        }


def first_step_clock_armed() -> bool:
    with _clock_lock:
        return _first_step is not None


def disarm_first_step_clock() -> None:
    """Drop an armed clock without stamping (the supervised run ended —
    success or final failure — before any step committed; a later
    unrelated step must not stamp a stale span)."""
    global _first_step
    with _clock_lock:
        _first_step = None


def note_first_step(phase: str) -> Optional[float]:
    """Close the armed clock (no-op when disarmed — callers stamp
    unconditionally after every committed step; only the first one after
    arming lands). Returns the measured seconds when it fired."""
    global _first_step
    with _clock_lock:
        st = _first_step
        _first_step = None
    if st is None:
        return None
    seconds = time.monotonic() - st["t0"]
    from photon_tpu.obs import instant
    from photon_tpu.obs.metrics import REGISTRY

    REGISTRY.gauge(
        "restart_to_first_step_seconds",
        "seconds from (re)start of the latest supervised attempt to its "
        "first committed training step (docs/robustness.md §recovery time)",
    ).set(round(seconds, 4))
    instant("recovery.first_step", cat="recovery", phase=phase,
            attempt=st["attempt"], seconds=round(seconds, 4))
    journal = st["journal"]
    if journal is not None:
        try:
            journal.record(
                "first_step", _mirror=False, attempt=st["attempt"],
                phase=phase,
                restart_to_first_step_seconds=round(seconds, 4))
        except Exception:  # noqa: BLE001 - journal is evidence, not a dep
            pass
    return seconds
