"""Runtime-layer services: backend health, failure policy, recovery.

The reference inherited its runtime resilience from Spark (a lost executor
is rescheduled, lineage replays the partition — SURVEY.md §5.3); the
rebuild's runtime is a JAX backend client whose failure modes — init hangs,
compile errors, device loss, OOM — previously surfaced as unclassified
exceptions or, worse, 25-minute silent hangs (TPU_RECOVERY.jsonl).
``backend_guard`` makes backend failure a first-class, tested contract:
fail fast under a hard deadline, classify the cause, and recover under an
explicit policy (docs/robustness.md §"Backend-failure resilience").
``compile_store`` makes recovery CHEAP: an AOT compile-artifact store +
manifest so restarts, device-loss re-steps, and serving hot-swaps load
compiled executables instead of re-paying XLA (docs/robustness.md
§"Recovery time").
"""
from photon_tpu.runtime.backend_guard import (
    BACKEND_POLICIES,
    BackendProbeResult,
    BackendUnusable,
    backend_init_timeout_s,
    classify_backend_error,
    ensure_backend,
    guard_snapshot,
    is_device_lost,
    max_inrun_recoveries,
    probe_backend,
    recover_from_device_loss,
)
from photon_tpu.runtime.compile_store import (
    CompileStore,
    compile_split,
)
from photon_tpu.runtime.memory_guard import (
    MemoryGuard,
    OomDownshifter,
    is_oom,
    max_oom_downshifts,
)

__all__ = [
    "CompileStore",
    "compile_split",
    "MemoryGuard",
    "OomDownshifter",
    "is_oom",
    "max_oom_downshifts",
    "BACKEND_POLICIES",
    "BackendProbeResult",
    "BackendUnusable",
    "backend_init_timeout_s",
    "classify_backend_error",
    "ensure_backend",
    "guard_snapshot",
    "is_device_lost",
    "max_inrun_recoveries",
    "probe_backend",
    "recover_from_device_loss",
]
