"""Fail-fast backend health probe, error classification, failure policy.

Why this module exists (the operational record, not a hypothetical):
``TPU_RECOVERY.jsonl`` logs seven consecutive runs each burning ~1500 s
inside ``Unable to initialize backend: UNAVAILABLE`` before dying, and the
ROADMAP bench-trajectory caveat notes rounds r3/r5 silently fell back to
CPU, poisoning cross-round comparisons until the PR 6 gate started
refusing them. Upstream photon-ml never had this problem class — Spark
re-schedules a lost executor and lineage replays its partition — so the
rebuild needs an explicit contract where the reference had a runtime.

Three pieces:

* :func:`probe_backend` — a SUBPROCESS-isolated backend init with a hard
  deadline (``PHOTON_BACKEND_INIT_TIMEOUT_S``, default 120 s). A wedged
  device grant blocks ``jax.devices()`` forever *in C++*; no in-process
  timeout can interrupt it, so the probe must be a child process the
  parent can kill. SIGTERM first (a hard-killed client that later receives
  the grant can wedge it for every subsequent process), SIGKILL as the
  backstop.
* :func:`classify_backend_error` — maps backend failures onto the four
  causes the recovery layers act on: ``init_unavailable`` (the 1500 s
  class: grant wedged / UNAVAILABLE / init hang), ``compile_error``,
  ``device_lost`` (mid-run loss: the only in-run-recoverable cause), and
  ``oom``. Everything else is ``unknown`` — never guessed.
* :func:`ensure_backend` — the ``--backend-policy`` contract shared by
  bench.py and every CLI driver:

  ========== ==============================================================
  policy     on probe failure
  ========== ==============================================================
  strict     raise :class:`BackendUnusable` (classified cause; driver
             exits nonzero) — the default: never silently train on the
             wrong hardware
  failover   re-enter on the next available backend (CPU), stamping the
             swap into :func:`guard_snapshot` so bench provenance (and
             the PR 6 gate) can never mistake a failover round for an
             accelerator number
  cpu-only   pin the CPU backend up front; no probe, no accelerator
  ========== ==============================================================

In-run recovery (device loss mid-sweep) lives here too —
:func:`recover_from_device_loss` is the shared checkpoint-then-clear-then-
resume step ``game/descent.py`` and ``optim/out_of_core.py`` call; see
docs/robustness.md for the full ladder.
"""
from __future__ import annotations

import dataclasses
import os
import re
import time
from typing import Optional

__all__ = [
    "BACKEND_POLICIES",
    "CAUSE_INIT_UNAVAILABLE",
    "CAUSE_COMPILE_ERROR",
    "CAUSE_DEVICE_LOST",
    "CAUSE_HOST_LOST",
    "CAUSE_OOM",
    "CAUSE_UNKNOWN",
    "BackendProbeResult",
    "BackendUnusable",
    "backend_init_timeout_s",
    "classify_backend_error",
    "ensure_backend",
    "guard_snapshot",
    "is_device_lost",
    "max_inrun_recoveries",
    "probe_backend",
    "record_failover",
    "recover_from_device_loss",
    "reset_guard",
    "try_claim_lock",
    "wait_claim_lock",
]

BACKEND_POLICIES = ("strict", "failover", "cpu-only")

CAUSE_INIT_UNAVAILABLE = "init_unavailable"
CAUSE_COMPILE_ERROR = "compile_error"
CAUSE_DEVICE_LOST = "device_lost"
CAUSE_HOST_LOST = "host_lost"
CAUSE_OOM = "oom"
CAUSE_UNKNOWN = "unknown"


def backend_init_timeout_s(default: float = 120.0) -> float:
    """Hard deadline for backend init (``PHOTON_BACKEND_INIT_TIMEOUT_S``).

    The default kills the observed ~25-minute init hangs at 2 minutes — a
    healthy accelerator grant completes in seconds, so anything past this
    is the wedge, not a slow success. Malformed/negative values fall back
    to ``default`` (a typo'd override must degrade the deadline, never
    disable fail-fast)."""
    try:
        v = float(os.environ.get("PHOTON_BACKEND_INIT_TIMEOUT_S", default))
    except (TypeError, ValueError):
        return float(default)
    return v if v > 0 else float(default)


def max_inrun_recoveries(default: int = 2) -> int:
    """Bound on in-run device-loss recoveries per scope
    (``PHOTON_DEVICE_LOST_MAX_RECOVERIES``): past it the error escalates to
    the :class:`~photon_tpu.supervisor.RunSupervisor` restart path."""
    try:
        return max(0, int(os.environ.get(
            "PHOTON_DEVICE_LOST_MAX_RECOVERIES", default)))
    except (TypeError, ValueError):
        return int(default)


# Ordered classification: FIRST match wins, so the ordering is part of the
# contract. ``init_unavailable`` outranks ``compile_error`` because the
# recovery-log failure signature is literally "UNAVAILABLE: TPU backend
# setup/compile error" — an init-phase failure that merely mentions
# compilation, and restart-with-backoff (not a code change) is its remedy.
_CAUSE_PATTERNS: tuple = (
    # ``host_lost`` first: a dead PEER HOST often surfaces through the same
    # transport noise a dead local device does ("connection reset" from the
    # coordinator, a collective that never completes) — when the message
    # names a peer host / missed beacon / mesh barrier, the whole-host
    # protocol (mesh shrink, parallel/distributed.MeshMembership) owns the
    # recovery, not the single-device ``recover_from_device_loss`` path.
    (CAUSE_HOST_LOST, re.compile(
        r"peer host|host\W{0,3}(was\s+)?lost|missed beacon"
        r"|beacon.{0,30}stale|mesh barrier.{0,30}(timed? ?out|timeout)"
        r"|collective.{0,40}waiting for host",
        re.IGNORECASE)),
    (CAUSE_OOM, re.compile(
        r"RESOURCE_EXHAUSTED|out of memory|\bOOM\b|hbm.{0,20}exhausted",
        re.IGNORECASE)),
    (CAUSE_DEVICE_LOST, re.compile(
        r"device\W{0,3}(was\s+)?lost|DEVICE_LOST|device is in an invalid"
        r"|socket closed|connection reset|broken pipe.{0,40}device"
        r"|tunnel.{0,30}(closed|dropped|reset)",
        re.IGNORECASE)),
    (CAUSE_INIT_UNAVAILABLE, re.compile(
        r"UNAVAILABLE|[Uu]nable to initialize backend"
        r"|[Ff]ailed to initialize|[Nn]o visible device"
        r"|backend init.{0,30}(timed? ?out|deadline)"
        r"|probe hung|wedged device grant",
    )),
    (CAUSE_COMPILE_ERROR, re.compile(
        r"XlaCompile|compilation (error|failure|failed)"
        r"|compile (error|failed)|lowering (error|failed)|Mosaic failed",
        re.IGNORECASE)),
)


def classify_backend_error(err) -> str:
    """One of the cause constants for an exception (or message text).

    Exception *types* outrank message text: an injected
    :class:`~photon_tpu.faults.DeviceLostError` or a real ``MemoryError``
    classifies by what it is, not what it says."""
    text = err if isinstance(err, str) else f"{type(err).__name__}: {err}"
    if not isinstance(err, str):
        from photon_tpu.faults import DeviceLostError, DeviceOomError

        if isinstance(err, DeviceLostError):
            return CAUSE_DEVICE_LOST
        if isinstance(err, (MemoryError, DeviceOomError)):
            return CAUSE_OOM
        if isinstance(err, (OSError, ConnectionError)):
            # A plain I/O error whose MESSAGE happens to say "connection
            # reset" / "socket closed" (an NFS hiccup, a dropped HTTP
            # peer) is NOT a device loss: it must take the io-retry /
            # supervisor path, never the in-run recovery's
            # executable-cache purge. Real tunnel losses surface as
            # XlaRuntimeError (a RuntimeError), which still classifies by
            # text below.
            return CAUSE_UNKNOWN
    for cause, pattern in _CAUSE_PATTERNS:
        if pattern.search(text):
            return cause
    return CAUSE_UNKNOWN


def is_device_lost(err) -> bool:
    """Is this the one cause the in-run recovery path may absorb?"""
    return classify_backend_error(err) == CAUSE_DEVICE_LOST


class BackendUnusable(RuntimeError):
    """The backend failed its health probe under ``--backend-policy
    strict``: carries the classified ``cause`` and the probe's ``reason``
    so the driver's nonzero exit is diagnosable from the one-line error."""

    def __init__(self, cause: str, reason: str):
        self.cause = cause
        self.reason = reason
        super().__init__(f"backend unusable [{cause}]: {reason}")


@dataclasses.dataclass(frozen=True)
class BackendProbeResult:
    """Outcome of one (possibly multi-attempt) subprocess probe."""

    ok: bool
    backend: str             # jax.default_backend() seen by the probe child
    seconds: float           # wall time of the LAST attempt
    attempts: int
    cause: Optional[str] = None
    reason: Optional[str] = None

    def to_dict(self) -> dict:
        out = dataclasses.asdict(self)
        return {k: v for k, v in out.items() if v is not None}


_PROBE_MARK = "PHOTON_BACKEND="
_DEFAULT_PROBE_CODE = (
    "import jax, jax.numpy as jnp; "
    "jnp.ones((8,)).sum().block_until_ready(); "
    f"print('{_PROBE_MARK}' + jax.default_backend())"
)

# Machine-wide single-TPU-claimant lock, shared with bench.py and
# scripts/tpu_claimant.py: the axon tunnel grants ONE client at a time and
# overlapping clients can wedge it — the operational record's ~25-minute
# failure mode. EVERY tunnel client (claimants, bench, and now the
# drivers' probes) must hold this flock before touching the tunnel. The
# per-uid fallback keeps self-exclusion working on a shared sticky /tmp
# where another user owns the shared path.
TPU_CLAIM_LOCK = "/tmp/tpu_claimant.lock"
_CLAIM_LOCK_HANDLE = None  # held for the process lifetime once acquired


def try_claim_lock() -> bool:
    """Acquire the claim lock; False if another tunnel client holds it
    (do NOT touch the tunnel), True once held (kept until process exit —
    the caller IS the tunnel client from here on)."""
    global _CLAIM_LOCK_HANDLE
    if _CLAIM_LOCK_HANDLE is not None:
        return True
    import fcntl

    for path in (TPU_CLAIM_LOCK, f"{TPU_CLAIM_LOCK}.{os.getuid()}"):
        try:
            f = open(path, "a")
        except OSError:
            continue  # foreign-owned path on sticky /tmp: per-uid fallback
        try:
            fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            f.close()
            return False  # a claimant is active
        _CLAIM_LOCK_HANDLE = f
        return True
    return True  # no lockable path: don't block the run over it


def wait_claim_lock(timeout_s: float, poll_s: float = 5.0) -> bool:
    """Poll for the claim lock up to ``timeout_s`` (0 = one try)."""
    deadline = time.monotonic() + timeout_s
    while True:
        if try_claim_lock():
            return True
        if time.monotonic() >= deadline:
            return False
        time.sleep(poll_s)


def _probe_once(code: str, timeout_s: float) -> BackendProbeResult:
    import subprocess
    import sys

    t0 = time.monotonic()
    # Popen + SIGTERM grace, not subprocess.run's SIGKILL: a hard-killed
    # client that later receives the device grant can wedge it for every
    # subsequent process (the exact failure this probe exists to catch).
    p = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        out, err = p.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        p.terminate()
        try:
            p.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()
            p.communicate()
        took = time.monotonic() - t0
        return BackendProbeResult(
            ok=False, backend="", seconds=took, attempts=1,
            cause=CAUSE_INIT_UNAVAILABLE,
            reason=(f"backend init timed out after {timeout_s:.0f}s "
                    "deadline (wedged device grant?) — probe child killed"),
        )
    took = time.monotonic() - t0
    backend = ""
    for line in (out or "").splitlines():
        if line.startswith(_PROBE_MARK):
            backend = line[len(_PROBE_MARK):].strip()
    if p.returncode == 0 and backend:
        return BackendProbeResult(
            ok=True, backend=backend, seconds=took, attempts=1)
    tail = (err or out or "").strip()[-400:]
    reason = f"probe exited {p.returncode}: {tail}" if tail else (
        f"probe exited {p.returncode} with no output")
    return BackendProbeResult(
        ok=False, backend=backend, seconds=took, attempts=1,
        cause=classify_backend_error(tail or reason), reason=reason,
    )


def probe_backend(
    timeout_s: Optional[float] = None,
    attempts: Optional[int] = None,
    probe_code: Optional[str] = None,
    claim_lock: bool = True,
) -> BackendProbeResult:
    """Subprocess-isolated backend health check under a hard deadline.

    ``probe_code`` is the test/chaos seam: recovery drills substitute a
    child that hangs or prints a canned UNAVAILABLE traceback, and the
    deadline-kill + classification path runs for real without a chip.
    ``attempts`` (``PHOTON_BACKEND_PROBE_ATTEMPTS``, default 1) retries
    the probe; attempt counts are stamped into provenance either way.

    A REAL probe (no ``probe_code``) is a tunnel client, so it first takes
    the machine-wide claim lock (``PHOTON_BACKEND_LOCK_WAIT``, default
    60 s): probing while a recovery claimant is mid-claim would be a
    second concurrent client — the wedge trigger this layer exists to
    prevent. A held lock reports as a classified failure (transient;
    strict policy fails fast, failover re-enters on CPU) instead of
    risking the wedge. ``claim_lock=False`` is for callers that already
    manage the lock themselves (bench.py — flock by the same process on a
    second fd would self-conflict)."""
    deadline = backend_init_timeout_s() if timeout_s is None else timeout_s
    if attempts is None:
        try:
            attempts = max(1, int(os.environ.get(
                "PHOTON_BACKEND_PROBE_ATTEMPTS", "1")))
        except (TypeError, ValueError):
            attempts = 1
    if probe_code is None and claim_lock:
        try:
            lock_wait = float(os.environ.get(
                "PHOTON_BACKEND_LOCK_WAIT", "60"))
        except (TypeError, ValueError):
            lock_wait = 60.0
        if not wait_claim_lock(lock_wait):
            return BackendProbeResult(
                ok=False, backend="", seconds=0.0, attempts=0,
                cause=CAUSE_INIT_UNAVAILABLE,
                reason=("TPU claim lock held by another client (recovery "
                        f"claimant?) through the {lock_wait:.0f}s wait "
                        "window; not probing — a second concurrent tunnel "
                        "client is the wedge trigger"),
            )
    code = probe_code or _DEFAULT_PROBE_CODE
    last = None
    for i in range(attempts):
        last = _probe_once(code, deadline)
        if last.ok:
            return dataclasses.replace(last, attempts=i + 1)
    return dataclasses.replace(last, attempts=attempts)


# ------------------------------------------------------------- guard state
#
# One guard decision per process (the probe is an up-front gate, not a
# recurring cost); bench provenance and /healthz read the snapshot.

_STATE: Optional[dict] = None
_PROBED_OK = False  # per-process probe memo: one subprocess, not one per run()


def guard_snapshot() -> Optional[dict]:
    """The guard's decision record for provenance stamping, or None when
    no guard ran in this process: ``{policy, backend, backend_init_seconds,
    probe_attempts, failover}``."""
    return None if _STATE is None else dict(_STATE)


def reset_guard() -> None:
    """Test hook: forget the per-process guard decision + probe memo."""
    global _STATE, _PROBED_OK
    _STATE = None
    _PROBED_OK = False


def _jax_initialized() -> bool:
    """True when THIS process already has a live jax backend — probing a
    subprocess then proves nothing the parent doesn't already know, and
    costs seconds per driver run (tests call drivers dozens of times)."""
    import sys

    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge as xb

        return bool(getattr(xb, "_backends", None))
    except Exception:  # noqa: BLE001 - private API; absence = not provable
        return False


def _pin_cpu() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")


def ensure_backend(
    policy: str = "strict",
    timeout_s: Optional[float] = None,
    logger=None,
    probe_code: Optional[str] = None,
) -> dict:
    """Enforce the backend policy before any in-process jax backend init.

    Returns the guard snapshot (also kept module-global for provenance).
    Under ``strict`` a failed probe raises :class:`BackendUnusable`; under
    ``failover`` the process re-enters on CPU with the swap recorded (a
    ``backend_failovers_total{cause=...}`` counter bump + a
    ``recovery.backend_failover`` trace instant + the snapshot stamp);
    ``cpu-only`` pins CPU and never touches the accelerator tunnel."""
    global _STATE, _PROBED_OK
    if policy not in BACKEND_POLICIES:
        raise ValueError(
            f"unknown backend policy {policy!r}; known: {BACKEND_POLICIES}")
    if policy == "cpu-only":
        _pin_cpu()
        _STATE = {"policy": policy, "backend": "cpu",
                  "backend_init_seconds": 0.0, "probe_attempts": 0,
                  "failover": None}
        return dict(_STATE)

    if probe_code is None and (
            _PROBED_OK or _jax_initialized()
            or os.environ.get("PHOTON_BACKEND_PROBE") == "0"):
        # Backend already proven live in-process (or probing disabled):
        # keep/refresh the snapshot without burning a subprocess.
        backend = None
        try:
            import sys

            jax = sys.modules.get("jax")
            if jax is not None and _jax_initialized():
                backend = jax.default_backend()
        except Exception:  # noqa: BLE001 - snapshot detail, never fatal
            pass
        if _STATE is None or _STATE.get("backend") is None:
            _STATE = {"policy": policy, "backend": backend,
                      "backend_init_seconds": 0.0, "probe_attempts": 0,
                      "failover": None}
        else:
            _STATE["policy"] = policy
            if backend is not None:
                _STATE["backend"] = backend
        return dict(_STATE)

    probe = probe_backend(timeout_s=timeout_s, probe_code=probe_code)
    if probe.ok:
        _PROBED_OK = True
        _STATE = {"policy": policy, "backend": probe.backend,
                  "backend_init_seconds": round(probe.seconds, 3),
                  "probe_attempts": probe.attempts, "failover": None}
        return dict(_STATE)

    from photon_tpu.obs import instant
    from photon_tpu.obs.metrics import REGISTRY

    REGISTRY.counter(
        "backend_probe_failures_total",
        "health-probe failures by classified cause (runtime/backend_guard)",
    ).inc(cause=probe.cause or CAUSE_UNKNOWN)
    instant("recovery.backend_probe_failed", cat="recovery",
            cause=probe.cause, reason=probe.reason,
            seconds=round(probe.seconds, 3), policy=policy)
    if logger is not None:
        logger.warning(
            "backend probe failed [%s] after %.1fs (attempt %d): %s",
            probe.cause, probe.seconds, probe.attempts, probe.reason)
    if policy == "strict":
        raise BackendUnusable(probe.cause or CAUSE_UNKNOWN,
                              probe.reason or "probe failed")
    return record_failover(probe, logger=logger, policy=policy)


def record_failover(
    probe: BackendProbeResult, logger=None, policy: str = "failover",
) -> dict:
    """Re-enter on the next available backend and stamp the swap.

    CPU is always initializable in-process, so it is the universal next
    rung; the swap lands in the guard snapshot (→ bench provenance), the
    ``backend_failovers_total{cause=...}`` counter, and a
    ``recovery.backend_failover`` trace instant — so a failover round can
    NEVER masquerade as an accelerator number (PR 6 per-metric backend
    provenance refuses the cross-backend comparison). Shared by
    :func:`ensure_backend` and the :class:`~photon_tpu.supervisor.
    RunSupervisor` between-attempts path."""
    global _STATE
    from photon_tpu.obs import instant
    from photon_tpu.obs.metrics import REGISTRY

    _pin_cpu()
    failover = {
        "to": "cpu",
        "cause": probe.cause or CAUSE_UNKNOWN,
        "reason": probe.reason,
        "probe_seconds": round(probe.seconds, 3),
    }
    REGISTRY.counter(
        "backend_failovers_total",
        "policy-driven backend failovers by classified cause",
    ).inc(cause=failover["cause"])
    instant("recovery.backend_failover", cat="recovery", **failover)
    if logger is not None:
        logger.warning(
            "backend policy 'failover': re-entering on CPU [%s] — artifacts "
            "will stamp backend=cpu (not comparable to accelerator rounds)",
            failover["cause"])
    _STATE = {"policy": policy, "backend": "cpu",
              "backend_init_seconds": round(probe.seconds, 3),
              "probe_attempts": probe.attempts, "failover": failover}
    return dict(_STATE)


# --------------------------------------------------------- in-run recovery


def recover_from_device_loss(
    reason: str,
    device_cache=None,
    logger=None,
    reinit_client: bool = False,
) -> dict:
    """The shared mid-run recovery step (descent / out-of-core / scorer):

    1. drop jax's compiled-executable caches AND the retrace sentinel's
       warm marks (``supervisor.clear_executable_caches`` — the recompiles
       that follow are expected, not alarms);
    2. release device-resident sweep-cache pins (``device_cache`` when the
       caller owns one, else every live ``DeviceSweepCache`` in the
       process) — their device buffers died with the device;
    3. optionally re-initialize the backend client (``reinit_client``) —
       ONLY for callers holding no live device arrays (the supervisor's
       between-attempt path); in-run callers keep their host-checkpointed
       state and re-enter through fresh uploads.

    The caller checkpoints BEFORE calling this (checkpoint → clear →
    re-init → resume is the drill order the chaos suite asserts). Emits
    ``recovery.device_lost`` + ``recovery.backend_reinit`` trace instants
    and bumps ``run_restarts_total{cause="device_lost"}`` so the recovery
    is visible in metrics and the trace timeline."""
    from photon_tpu.obs import instant
    from photon_tpu.obs.metrics import REGISTRY

    instant("recovery.device_lost", cat="recovery", reason=reason)
    REGISTRY.counter(
        "run_restarts_total",
        "training restarts/recoveries by classified cause "
        "(docs/robustness.md §recovery journal)",
    ).inc(cause=CAUSE_DEVICE_LOST)

    from photon_tpu.supervisor import clear_executable_caches

    clear_executable_caches(f"device-loss recovery: {reason}")

    released = 0
    if device_cache is not None:
        device_cache.release()
        released = 1
    else:
        from photon_tpu.data.device_cache import release_all_caches

        released = release_all_caches()

    reinit = False
    if reinit_client:
        try:
            from jax.extend.backend import clear_backends

            clear_backends()
            reinit = True
        except Exception as e:  # noqa: BLE001 - version-dependent API
            if logger is not None:
                logger.warning("backend client re-init unavailable (%s: %s); "
                               "executable caches cleared only",
                               type(e).__name__, e)

    # Repopulate from the AOT compile store (runtime/compile_store.py):
    # every executable the purge dropped loads back from the persistent
    # cache BEFORE the caller re-enters its step, so the recovery re-step
    # dispatches warm instead of recompiling the whole kernel set cold.
    # AFTER the optional client re-init on purpose — clear_backends drops
    # the client the pre-warmed executables would live in, so warming
    # first would waste the whole pass and lie in the telemetry. prewarm
    # emits its own recovery.prewarm instant; a missing/failed store
    # degrades to the pre-store behavior (recompile on dispatch).
    from photon_tpu.runtime import compile_store as _cs

    prewarm = _cs.prewarm_if_active(reason=f"device-loss recovery: {reason}",
                                    logger_=logger)
    instant("recovery.backend_reinit", cat="recovery", reason=reason,
            caches_released=released, client_reinit=reinit,
            prewarm_loaded=None if prewarm is None else prewarm["loaded"])
    if logger is not None:
        logger.warning(
            "device-loss recovery (%s): executable caches cleared, %d sweep "
            "cache(s) released%s%s — resuming from checkpointed state",
            reason, released, ", backend client re-initialized"
            if reinit else "",
            "" if prewarm is None else
            f", {prewarm['loaded']} executable(s) pre-warmed from the "
            f"compile store ({prewarm['load_seconds']:.3f}s load, "
            f"{prewarm['xla_seconds']:.3f}s xla)")
    return {"caches_released": released, "client_reinit": reinit,
            "prewarm": prewarm}
