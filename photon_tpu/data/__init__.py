"""Data layer: batches, datasets, normalization, validation, sampling."""
from photon_tpu.data.batch import (  # noqa: F401
    DenseFeatures,
    Features,
    LabeledBatch,
    SparseFeatures,
    ell_from_rows,
    make_dense_batch,
)
from photon_tpu.data.normalization import (  # noqa: F401
    NormalizationContext,
    NormalizationType,
    context_from_statistics,
    identity_context,
)
from photon_tpu.data.sampling import (  # noqa: F401
    BinaryClassificationDownSampler,
    DownSampler,
    down_sampler_for_task,
)
from photon_tpu.data.statistics import (  # noqa: F401
    FeatureDataStatistics,
    compute_feature_statistics,
)
from photon_tpu.data.validators import (  # noqa: F401
    DataValidationError,
    DataValidationType,
    sanity_check_data,
)
