"""Data layer: batches, datasets, normalization, validation, sampling."""
from photon_tpu.data.batch import (  # noqa: F401
    DenseFeatures,
    Features,
    LabeledBatch,
    SparseFeatures,
    ell_from_rows,
    make_dense_batch,
)
