"""Random-effect datasets: per-entity data, bucketed and padded for vmap.

Parity: reference ⟦photon-api/.../data/RandomEffectDataset.scala⟧ +
``LocalDataset`` + ⟦.../projector/LinearSubspaceProjector⟧ and the
sample-count-balancing ⟦RandomEffectDatasetPartitioner⟧ (SURVEY.md §2.2,
§3.5, §2.6 P2/P6).

TPU-first layout: instead of an ``RDD[(REId, LocalDataset)]`` with one Breeze
solve per entity inside ``mapPartitions``, entities are grouped host-side and
packed into **buckets** of identical padded shape ``[E, S, K]`` (entities x
max-samples x max-nnz). Within a bucket every per-entity solve is one lane of
a ``vmap``; buckets shard over the mesh's entity axis. Shapes are quantized
to powers of two so the number of distinct XLA compilations stays O(log² of
the size range) — the TPU analog of the reference's skew-balancing
partitioner.

Feature projection: each entity sees only the feature columns present in its
own rows (the reference's ``LinearSubspaceProjector``). Global ELL indices are
remapped to a compact per-entity local space ``[0, P)``; ``proj[e, p]`` maps
local slot p back to the global column (or ``global_dim`` for unused pad
slots, which is the global ghost column). Scoring and model export gather
through ``proj``.

Active/passive split: rows beyond ``active_bound`` per entity keep weight for
scoring (``weights``) but carry 0 in ``train_weights`` — the reference's
passive data, scored but not trained on.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _next_pow2(n: int) -> int:
    return 1 << max(0, int(n - 1).bit_length())


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class EntityBucket:
    """One padded bucket of entities with identical [E, S, K, P] shapes.

    ``idx``/``val`` are per-entity ELL in *local* feature space (ghost column
    = P). ``proj`` maps local→global columns (ghost slots hold
    ``global_dim``). ``row_ids`` maps each (entity, sample) slot back to the
    global row it came from (padding slots hold the global row count N, a
    ghost row). ``weights`` masks valid rows; ``train_weights`` additionally
    zeroes passive rows. ``entity_ids`` are dense REIds (padding: -1).
    """

    idx: Array            # [E, S, K] int32, local column ids
    val: Array            # [E, S, K]
    labels: Array         # [E, S]
    weights: Array        # [E, S] — 0 marks padded rows
    train_weights: Array  # [E, S] — 0 marks padded AND passive rows
    row_ids: Array        # [E, S] int32 into the global sample order; N = pad
    proj: Array           # [E, P] int32 local→global column map; dim = pad
    entity_ids: Array     # [E] int32 dense entity ids; -1 = padded entity

    @property
    def n_entities(self) -> int:
        return self.idx.shape[0]

    @property
    def max_samples(self) -> int:
        return self.idx.shape[1]

    @property
    def local_dim(self) -> int:
        return self.proj.shape[1]

    def local_batches(self, global_offsets: Array):
        """Per-entity LabeledBatch pytree stacked on axis 0 (for vmap), using
        offsets gathered from the global per-sample offset vector."""
        from photon_tpu.data.batch import LabeledBatch, SparseFeatures

        global_offsets = global_offsets.astype(self.val.dtype)
        # Ghost row offset 0: extend then gather (row_ids padding == n).
        ext = jnp.concatenate([global_offsets, jnp.zeros((1,), global_offsets.dtype)])
        offsets = ext[self.row_ids]
        return LabeledBatch(
            features=SparseFeatures(idx=self.idx, val=self.val, dim=self.local_dim),
            labels=self.labels,
            offsets=offsets,
            weights=self.train_weights,
        )

    def scores(self, coefs: Array) -> Array:
        """Per-slot scores [E, S] from per-entity coefficients [E, P]
        (offsets NOT included — GAME composes scores additively)."""
        ext = jnp.concatenate([coefs, jnp.zeros_like(coefs[:, :1])], axis=1)

        def one(w_ext, idx, val):
            return jnp.sum(w_ext[idx] * val, axis=-1)

        return jax.vmap(one)(ext, self.idx, self.val)


@dataclasses.dataclass(frozen=True)
class RandomEffectDataset:
    """All buckets for one random-effect coordinate + host-side entity index.

    ``entity_to_slot`` maps entity key → (bucket_index, lane); ``n_rows`` is
    the global sample count the ``row_ids`` refer to.
    """

    re_type: str                      # entity column name, e.g. "userId"
    buckets: Sequence[EntityBucket]
    entity_keys: Sequence             # dense REId -> original key
    entity_to_slot: dict              # dense REId -> (bucket, lane)
    n_rows: int
    global_dim: int

    @property
    def n_entities(self) -> int:
        return len(self.entity_keys)

    def scatter_scores(self, per_bucket_scores: Sequence[Array]) -> Array:
        """Assemble a global [n_rows] score vector from per-bucket [E, S]
        scores (padding slots point at the ghost row and are dropped)."""
        out = jnp.zeros((self.n_rows + 1,), per_bucket_scores[0].dtype)
        for b, s in zip(self.buckets, per_bucket_scores):
            out = out.at[b.row_ids.ravel()].set(s.ravel())
        return out[: self.n_rows]


def down_sample_dataset(
    dataset: RandomEffectDataset, sampler, key
) -> RandomEffectDataset:
    """Down-sample training weights per entity bucket (reference: per-config
    down-sampling applies to random-effect coordinates too). Only
    ``train_weights`` change — scoring weights and the active/passive split
    are untouched, and already-zero (padded/passive) slots stay zero."""
    import jax as _jax

    new_buckets = []
    for i, b in enumerate(dataset.buckets):
        k = _jax.random.fold_in(key, i)
        tw = sampler.down_sample_weights(k, b.labels, b.train_weights)
        new_buckets.append(dataclasses.replace(b, train_weights=tw))
    return dataclasses.replace(dataset, buckets=tuple(new_buckets))


def pearson_scores(
    local: np.ndarray,
    vals: np.ndarray,
    labels_e: np.ndarray,
    n_cols: int,
) -> np.ndarray:
    """|Pearson correlation| of each local feature column with the label over
    one entity's rows, treating absent entries as 0 (sparse semantics —
    reference ⟦LocalDataset.filterFeaturesByPearsonCorrelationScore⟧).
    Assumes each row indexes a column at most once (squares accumulate
    per-entry, so duplicate (row, col) entries would skew the variance).

    Zero-variance columns score 0 (the intercept is force-kept by the
    caller, not through its score).
    """
    s = len(labels_e)
    flat = local.ravel()
    keep = flat < n_cols
    cols = flat[keep]
    v = vals.ravel()[keep]
    y_rep = np.repeat(labels_e, local.shape[1])[keep]
    sum_x = np.bincount(cols, weights=v, minlength=n_cols)
    sum_x2 = np.bincount(cols, weights=v * v, minlength=n_cols)
    sum_xy = np.bincount(cols, weights=v * y_rep, minlength=n_cols)
    sum_y = labels_e.sum()
    sum_y2 = (labels_e * labels_e).sum()
    num = s * sum_xy - sum_x * sum_y
    var_x = s * sum_x2 - sum_x * sum_x
    var_y = s * sum_y2 - sum_y * sum_y
    denom = np.sqrt(np.maximum(var_x, 0.0) * max(var_y, 0.0))
    with np.errstate(invalid="ignore", divide="ignore"):
        corr = np.where(denom > 0, np.abs(num) / np.maximum(denom, 1e-30), 0.0)
    return corr


def build_random_effect_dataset(
    re_type: str,
    entity_keys_per_row: np.ndarray,
    idx: np.ndarray,
    val: np.ndarray,
    labels: np.ndarray,
    global_dim: int,
    weights: Optional[np.ndarray] = None,
    active_bound: Optional[int] = None,
    min_entity_rows: int = 1,
    intercept_index: Optional[int] = None,
    dtype=np.float32,
    max_features_per_entity: Optional[int] = None,
    max_bucket_entities: Optional[int] = None,
    host_resident: bool = False,
) -> RandomEffectDataset:
    """Host-side builder: group rows by entity, project features, bucket+pad.

    Inputs are global ELL arrays (``idx[N, K]`` with ghost == ``global_dim``)
    plus one entity key per row. Entities with fewer than ``min_entity_rows``
    rows are dropped (reference: ``numActiveDataPointsLowerBound``).
    ``intercept_index``, when given, is force-included in every entity's
    subspace so each per-entity model can carry an intercept.

    ``max_features_per_entity`` enables Pearson-correlation feature filtering
    (reference ⟦LocalDataset.filterFeaturesByPearsonCorrelationScore⟧,
    SURVEY.md §2.2): each entity keeps only its ``m`` features most
    |correlated| with the label (ties broken by lower column id; the
    intercept always kept on top of ``m``), shrinking per-entity subspaces
    and bucket padding on wide shards.

    Fully vectorized over entities (VERDICT round-2 weak #7): per-entity
    subspaces come from ONE ``unique`` over (entity, column) pair keys, the
    local remap is ONE ``searchsorted`` against those keys, Pearson sums are
    global ``bincount``s, and bucket packing is flat fancy-index writes —
    no per-entity Python. ``_build_reference_loop`` keeps the original
    entity-at-a-time implementation as the oracle for the equivalence test.

    Scale controls (SURVEY.md §2.6 P6): ``max_bucket_entities`` splits each
    size-class bucket into slices of at most that many entities, and
    ``host_resident=True`` keeps bucket arrays as host numpy — the RE
    trainer then transfers ONE bucket at a time, so peak device residency
    is a single bucket instead of the whole grouped dataset (the knob that
    bounds HBM for config-5-scale GAME).
    """
    if max_bucket_entities is not None and max_bucket_entities < 1:
        raise ValueError(
            f"max_bucket_entities must be >= 1, got {max_bucket_entities}"
        )
    n, k = idx.shape
    idx = np.asarray(idx)
    val = np.asarray(val)
    labels = np.asarray(labels, dtype)
    weights = np.ones(n, dtype) if weights is None else np.asarray(weights, dtype)

    keys, inv = _sorted_factorize(entity_keys_per_row)
    counts_all = np.bincount(inv, minlength=len(keys))
    kept = np.flatnonzero(counts_all >= min_entity_rows)
    e_count = len(kept)
    if e_count == 0:
        return RandomEffectDataset(
            re_type=re_type, buckets=(), entity_keys=[], entity_to_slot={},
            n_rows=n, global_dim=global_dim,
        )
    new_id = np.full(len(keys), -1, np.int64)
    new_id[kept] = np.arange(e_count)
    dense_e = new_id[inv]                       # [n] dense entity id, -1 dropped
    row_kept = dense_e >= 0
    counts = counts_all[kept]                   # [E] rows per kept entity

    # ---- per-entity column subspaces. The DISTINCT (entity, column) pair
    # count is small (≈ E × per-entity support), but a single np.unique
    # with return_inverse over all N·K entry keys materializes ~4 int64
    # arrays of N·K (keys, sort permutation, sorted copy, inverse) — ~20 GB
    # of temporaries at the 50M×13 rehearsal shape, the RE build's RSS
    # peak (VERDICT r4 weak #4). Instead: chunked uniques (each bounded by
    # the chunk), one final unique over the concatenated smalls, then a
    # chunked searchsorted for each entry's pair rank — peak extra memory
    # is one chunk's worth plus the distinct-pair table.
    stride = global_dim + 1
    ent_of_row = dense_e                         # [n], -1 = dropped row
    chunk_rows_ = max(1, min(n, 1 << 22))
    uniq_parts = []
    if intercept_index is not None:
        uniq_parts.append(
            np.arange(e_count, dtype=np.int64) * stride + intercept_index)
    nz_per_ent = np.zeros(e_count, np.int64)
    for lo in range(0, n, chunk_rows_):
        hi = min(lo + chunk_rows_, n)
        ee_c = np.repeat(ent_of_row[lo:hi], k)
        fi_c = idx[lo:hi].ravel().astype(np.int64)
        ok_c = (ee_c >= 0) & (fi_c < global_dim)
        pairs_c = ee_c[ok_c] * stride + fi_c[ok_c]
        uniq_parts.append(np.unique(pairs_c))
        if intercept_index is None:  # counts only feed the empty-entity fix
            nz_per_ent += np.bincount(ee_c[ok_c], minlength=e_count)
    if intercept_index is None:
        # entities with no real entries still need a 1-column subspace ([0])
        empty = np.flatnonzero(nz_per_ent == 0)
        if len(empty):
            uniq_parts.append(empty.astype(np.int64) * stride)
    upairs = np.unique(np.concatenate(uniq_parts))
    del uniq_parts
    ent_of_col = upairs // stride

    # entry_pos: each ok entry's rank in upairs, chunked searchsorted.
    entry_pos_parts = []
    ok_parts = []
    for lo in range(0, n, chunk_rows_):
        hi = min(lo + chunk_rows_, n)
        ee_c = np.repeat(ent_of_row[lo:hi], k)
        fi_c = idx[lo:hi].ravel().astype(np.int64)
        ok_c = (ee_c >= 0) & (fi_c < global_dim)
        pairs_c = ee_c[ok_c] * stride + fi_c[ok_c]
        entry_pos_parts.append(
            np.searchsorted(upairs, pairs_c).astype(np.int32))
        ok_parts.append(ok_c)
    entry_pos = np.concatenate(entry_pos_parts) if entry_pos_parts else \
        np.zeros(0, np.int32)
    entry_ok = np.concatenate(ok_parts) if ok_parts else np.zeros(0, bool)
    del entry_pos_parts, ok_parts
    # int32 throughout: these are the N·K-sized survivors, and at the 50M
    # rehearsal shape every int64 copy here is 5.2 GB of RSS.
    ee = np.repeat(ent_of_row.astype(np.int32), k)  # entity per ELL entry

    if max_features_per_entity is not None:
        chosen = _choose_pairs_by_pearson(
            upairs, ent_of_col, stride, entry_pos, entry_ok,
            val.ravel(), labels, dense_e, counts, e_count,
            max_features_per_entity, intercept_index,
        )
        # remap surviving pair ids to their rank in the filtered set
        new_pos = np.cumsum(chosen, dtype=np.int64) - 1
        survived = chosen[entry_pos]
        entry_pos = np.where(survived, new_pos[entry_pos], -1)
        upairs, ent_of_col = upairs[chosen], ent_of_col[chosen]
    ncols = np.bincount(ent_of_col, minlength=e_count).astype(np.int64)
    col_off = np.zeros(e_count + 1, np.int64)
    np.cumsum(ncols, out=col_off[1:])

    # ---- local remap straight from the unique inverse (no searchsorted)
    ee_safe = np.maximum(ee, 0)
    local_flat = ncols[ee_safe].astype(np.int32)     # default: local ghost
    ok_ix = np.flatnonzero(entry_ok)
    hit_ok = entry_pos >= 0
    local_flat[ok_ix[hit_ok]] = (
        entry_pos[hit_ok] - col_off[ee[ok_ix[hit_ok]]]
    ).astype(np.int32)
    local = local_flat.reshape(n, k)
    hit = np.zeros(n * k, bool)
    hit[ok_ix[hit_ok]] = True
    val_eff = np.where(hit.reshape(n, k), val, 0.0).astype(val.dtype)

    # ---- bucket by (pow2 samples, pow2 local dim); dense ids in the same
    # (bucket-sorted, then ascending-entity) order as the reference loop
    s_pad_e = _next_pow2_vec(counts)
    p_pad_e = _next_pow2_vec(ncols)
    ent_sort = np.lexsort((np.arange(e_count), p_pad_e, s_pad_e))
    dense_of = np.empty(e_count, np.int64)
    dense_of[ent_sort] = np.arange(e_count)          # entity -> dense id

    # group boundaries of (s_pad, p_pad) buckets over the sorted entities
    sp_sorted = np.stack([s_pad_e[ent_sort], p_pad_e[ent_sort]], axis=1)
    bucket_break = np.any(np.diff(sp_sorted, axis=0) != 0, axis=1)
    bucket_starts = np.concatenate([[0], np.flatnonzero(bucket_break) + 1, [e_count]])

    # rows re-sorted by dense id (stable keeps original row order per entity)
    row_dense = np.where(row_kept, dense_of[np.maximum(dense_e, 0)], e_count)
    row_order = np.argsort(row_dense, kind="stable")[: int(row_kept.sum())]
    rcounts = counts[ent_sort]                        # rows per dense id
    rstarts = np.zeros(e_count + 1, np.int64)
    np.cumsum(rcounts, out=rstarts[1:])
    within_row = np.arange(len(row_order)) - rstarts[row_dense[row_order]]

    # column entries re-sorted by dense id
    col_dense = dense_of[ent_of_col]
    col_order = np.argsort(col_dense, kind="stable")
    ccounts = ncols[ent_sort]
    cstarts = np.zeros(e_count + 1, np.int64)
    np.cumsum(ccounts, out=cstarts[1:])
    within_col = np.arange(len(col_order)) - cstarts[col_dense[col_order]]
    cols_flat = upairs % stride

    buckets = []
    entity_keys_out = list(keys[kept][ent_sort])
    entity_to_slot = {}
    for b, (mb, me) in enumerate(zip(bucket_starts[:-1], bucket_starts[1:])):
        ecount = int(me - mb)
        s_pad = int(sp_sorted[mb, 0])
        p_pad = int(sp_sorted[mb, 1])
        b_idx = np.full((ecount, s_pad, k), p_pad, np.int32)
        b_val = np.zeros((ecount, s_pad, k), dtype)
        b_lab = np.zeros((ecount, s_pad), dtype)
        b_w = np.zeros((ecount, s_pad), dtype)
        b_tw = np.zeros((ecount, s_pad), dtype)
        b_rows = np.full((ecount, s_pad), n, np.int32)
        b_proj = np.full((ecount, p_pad), global_dim, np.int32)

        rsl = slice(rstarts[mb], rstarts[me])
        rows_b = row_order[rsl]                       # original row ids
        lane_r = row_dense[rows_b] - mb
        wr = within_row[rsl]
        b_idx[lane_r, wr] = local[rows_b]
        b_val[lane_r, wr] = val_eff[rows_b]
        b_lab[lane_r, wr] = labels[rows_b]
        b_w[lane_r, wr] = weights[rows_b]
        tw = weights[rows_b].copy()
        if active_bound is not None:
            tw[wr >= active_bound] = 0.0              # passive rows
        b_tw[lane_r, wr] = tw
        b_rows[lane_r, wr] = rows_b

        csl = slice(cstarts[mb], cstarts[me])
        centries = col_order[csl]
        b_proj[col_dense[centries] - mb, within_col[csl]] = cols_flat[centries]

        conv = (lambda a: a) if host_resident else jnp.asarray
        cap = max_bucket_entities or ecount
        for lo in range(0, ecount, cap):
            hi = min(lo + cap, ecount)
            bi = len(buckets)
            for lane in range(lo, hi):
                entity_to_slot[int(mb + lane)] = (bi, lane - lo)
            buckets.append(EntityBucket(
                idx=conv(b_idx[lo:hi]), val=conv(b_val[lo:hi]),
                labels=conv(b_lab[lo:hi]), weights=conv(b_w[lo:hi]),
                train_weights=conv(b_tw[lo:hi]),
                row_ids=conv(b_rows[lo:hi]),
                proj=conv(b_proj[lo:hi]),
                entity_ids=conv(
                    np.arange(mb + lo, mb + hi, dtype=np.int32)
                ),
            ))

    return RandomEffectDataset(
        re_type=re_type,
        buckets=tuple(buckets),
        entity_keys=entity_keys_out,
        entity_to_slot=entity_to_slot,
        n_rows=n,
        global_dim=global_dim,
    )


def _next_pow2_vec(x: np.ndarray) -> np.ndarray:
    x = np.maximum(np.asarray(x, np.int64), 1)
    return 1 << np.ceil(np.log2(x)).astype(np.int64)


def _sorted_factorize(keys_per_row: np.ndarray):
    """(sorted unique keys, inverse) — np.unique semantics, hash-based speed.

    np.unique comparison-sorts the raw key column; for millions of object
    strings that sort IS the old builder's profile hot spot. pandas'
    hash-based factorize + a sort of the (small) unique set is ~5x faster
    and produces the identical (sorted-unique, inverse) pair."""
    try:
        import pandas as pd
    except ImportError:  # pragma: no cover - pandas ships in the image
        return np.unique(keys_per_row, return_inverse=True)
    codes, uniq = pd.factorize(keys_per_row, sort=True)
    if (codes < 0).any():
        # pd.factorize drops NaN/None (code -1); np.unique keeps them as
        # keys — fall back so missing-key behavior matches.
        return np.unique(keys_per_row, return_inverse=True)
    return np.asarray(uniq), codes.astype(np.int64)


def _choose_pairs_by_pearson(
    upairs, ent_of_col, stride, entry_pos, entry_ok, flat_val,
    labels, dense_e, counts, e_count, max_features, intercept_index,
):
    """Vectorized Pearson top-m per entity over the (entity, col) pair keys;
    returns the keep mask over ``upairs``.

    Matches ``pearson_scores`` semantics (absent entries are zeros) with
    global bincounts instead of per-entity passes; entities at or under the
    cap keep their full subspace, ties break toward lower column ids, and
    the intercept is force-kept on top of ``m``.
    """
    pos = entry_pos
    v_raw = flat_val[entry_ok]                       # source dtype, like
    v = np.asarray(v_raw, np.float64)                # pearson_scores' v
    y_row = np.asarray(labels, np.float64)
    k = len(entry_ok) // dense_e.shape[0]
    y_ent = np.repeat(y_row, k)[entry_ok]            # label of each entry's row
    npairs = len(upairs)
    sum_x = np.bincount(pos, weights=v, minlength=npairs)
    # v*v in the SOURCE dtype (f32 upstream) so scores are bit-identical to
    # pearson_scores — exact ties must break the same way in both builders.
    sum_x2 = np.bincount(pos, weights=np.asarray(v_raw * v_raw, np.float64),
                         minlength=npairs)
    sum_xy = np.bincount(pos, weights=v * y_ent, minlength=npairs)
    row_of_kept = dense_e >= 0
    sum_y_e = np.bincount(dense_e[row_of_kept], weights=y_row[row_of_kept],
                          minlength=e_count)
    sum_y2_e = np.bincount(dense_e[row_of_kept],
                           weights=y_row[row_of_kept] ** 2, minlength=e_count)
    s_e = counts.astype(np.float64)
    s, sy, sy2 = s_e[ent_of_col], sum_y_e[ent_of_col], sum_y2_e[ent_of_col]
    num = s * sum_xy - sum_x * sy
    var_x = s * sum_x2 - sum_x * sum_x
    var_y = s * sy2 - sy * sy
    denom = np.sqrt(np.maximum(var_x, 0.0) * np.maximum(var_y, 0.0))
    with np.errstate(invalid="ignore", divide="ignore"):
        score = np.where(denom > 0, np.abs(num) / np.maximum(denom, 1e-30), 0.0)

    cols = upairs % stride
    rank_order = np.lexsort((cols, -score, ent_of_col))
    off = np.zeros(e_count + 1, np.int64)
    np.cumsum(np.bincount(ent_of_col, minlength=e_count), out=off[1:])
    rank = np.empty(npairs, np.int64)
    rank[rank_order] = np.arange(npairs) - off[ent_of_col[rank_order]]
    over_cap = (off[1:] - off[:-1]) > max_features     # per entity
    chosen = ~over_cap[ent_of_col] | (rank < max_features)
    if intercept_index is not None:
        chosen |= cols == intercept_index
    return chosen


def _build_reference_loop(
    re_type: str,
    entity_keys_per_row: np.ndarray,
    idx: np.ndarray,
    val: np.ndarray,
    labels: np.ndarray,
    global_dim: int,
    weights: Optional[np.ndarray] = None,
    active_bound: Optional[int] = None,
    min_entity_rows: int = 1,
    intercept_index: Optional[int] = None,
    dtype=np.float32,
    max_features_per_entity: Optional[int] = None,
) -> RandomEffectDataset:
    """Original entity-at-a-time builder, kept as the oracle for the
    vectorized path's equivalence test (tests/test_random_effect.py)."""
    n, k = idx.shape
    labels = np.asarray(labels, dtype)
    weights = np.ones(n, dtype) if weights is None else np.asarray(weights, dtype)

    keys, inv = np.unique(entity_keys_per_row, return_inverse=True)
    order = np.argsort(inv, kind="stable")
    counts = np.bincount(inv, minlength=len(keys))

    # Per-entity row lists in original order; drop tiny entities.
    starts = np.zeros(len(keys) + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    kept = [e for e in range(len(keys)) if counts[e] >= min_entity_rows]

    # Build per-entity projections + local data (numpy, then bucketed).
    entities = []
    for e in kept:
        rows = order[starts[e]:starts[e + 1]]
        e_idx = idx[rows]             # [s, k] global ids (ghost == global_dim)
        e_val = val[rows]
        cols = np.unique(e_idx[e_idx < global_dim])
        if intercept_index is not None and intercept_index not in cols:
            cols = np.sort(np.append(cols, intercept_index))
        if len(cols) == 0:
            cols = np.asarray([0], np.int64)
        # local remap: ghost -> len(cols) (local ghost)
        local = np.searchsorted(cols, np.minimum(e_idx, global_dim - 1)).astype(np.int32)
        local = np.where(e_idx >= global_dim, len(cols), local)

        if (
            max_features_per_entity is not None
            and len(cols) > max_features_per_entity
        ):
            scores = pearson_scores(
                local, e_val, np.asarray(labels[rows], np.float64), len(cols)
            )
            # Top-m by |corr|, ties to the lower column id (deterministic);
            # the intercept is force-kept regardless of its (zero) score.
            order_by_score = np.lexsort((np.arange(len(cols)), -scores))
            chosen = np.zeros(len(cols), bool)
            chosen[order_by_score[:max_features_per_entity]] = True
            if intercept_index is not None:
                at = int(np.searchsorted(cols, intercept_index))
                if at < len(cols) and cols[at] == intercept_index:
                    chosen[at] = True
            cols = cols[chosen]
            in_kept = np.isin(e_idx, cols)
            local = np.searchsorted(
                cols, np.minimum(e_idx, global_dim - 1)
            ).astype(np.int32)
            local = np.where(in_kept, local, len(cols))
            e_val = np.where(in_kept, e_val, 0.0)
        entities.append((e, rows, cols, local, e_val))

    # Bucket by (pow2 samples, pow2 local dim).
    bucket_map: dict[tuple[int, int], list] = {}
    for ent in entities:
        s_cap = len(ent[1])
        p_cap = len(ent[2])
        key = (_next_pow2(s_cap), _next_pow2(p_cap))
        bucket_map.setdefault(key, []).append(ent)

    buckets = []
    entity_keys_out = []
    entity_to_slot = {}
    for (s_pad, p_pad), members in sorted(bucket_map.items()):
        ecount = len(members)
        b_idx = np.full((ecount, s_pad, k), p_pad, np.int32)   # local ghost
        b_val = np.zeros((ecount, s_pad, k), dtype)
        b_lab = np.zeros((ecount, s_pad), dtype)
        b_w = np.zeros((ecount, s_pad), dtype)
        b_tw = np.zeros((ecount, s_pad), dtype)
        b_rows = np.full((ecount, s_pad), n, np.int32)         # global ghost row
        b_proj = np.full((ecount, p_pad), global_dim, np.int32)
        b_eids = np.full((ecount,), -1, np.int32)
        for lane, (e, rows, cols, local, vals) in enumerate(members):
            s = len(rows)
            b_idx[lane, :s] = local
            b_val[lane, :s] = vals
            b_lab[lane, :s] = labels[rows]
            b_w[lane, :s] = weights[rows]
            tw = weights[rows].copy()
            if active_bound is not None and s > active_bound:
                tw[active_bound:] = 0.0      # passive rows: scored, not trained
            b_tw[lane, :s] = tw
            b_rows[lane, :s] = rows
            b_proj[lane, : len(cols)] = cols
            dense_id = len(entity_keys_out)
            b_eids[lane] = dense_id
            entity_keys_out.append(keys[e])
            entity_to_slot[dense_id] = (len(buckets), lane)
        buckets.append(EntityBucket(
            idx=jnp.asarray(b_idx), val=jnp.asarray(b_val),
            labels=jnp.asarray(b_lab), weights=jnp.asarray(b_w),
            train_weights=jnp.asarray(b_tw), row_ids=jnp.asarray(b_rows),
            proj=jnp.asarray(b_proj), entity_ids=jnp.asarray(b_eids),
        ))

    return RandomEffectDataset(
        re_type=re_type,
        buckets=tuple(buckets),
        entity_keys=list(entity_keys_out),
        entity_to_slot=entity_to_slot,
        n_rows=n,
        global_dim=global_dim,
    )
