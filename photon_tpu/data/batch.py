"""Batched training data as fixed-shape device pytrees.

Parity: reference ⟦photon-api/.../data/GameDatum.scala⟧ / ``LabeledPoint(label,
features, offset, weight)`` — but instead of an RDD of per-example records,
data lives as structure-of-arrays batches with **static shapes**, the form XLA
tiles onto the MXU (SURVEY.md §7 design stance).

Feature representations:

* ``DenseFeatures`` — ``x[N, D]``; right for small/mid feature spaces where the
  score is one big matmul.
* ``SparseFeatures`` — padded ELL format: ``idx[N, K] int32`` / ``val[N, K]``
  with K = max nnz per row; padding slots point at column ``D`` (a zero
  "ghost" column) with value 0. This is the TPU-native replacement for the
  reference's Breeze ``SparseVector`` rows: gathers/segment-sums over fixed
  [N, K] tiles instead of per-row pointer chasing, so a 10M-feature space
  never materializes densely (SURVEY.md §7 "hard parts" #2).

Both support ``matvec`` (scores), ``rmatvec`` (gradient accumulation — the
transpose action), and ``sq_rmatvec`` (Hessian-diagonal accumulation).
Autodiff of ``matvec`` produces exactly ``rmatvec`` (gather ↔ scatter-add), so
objectives can be plain differentiated functions.

A ``padded_rows`` mask supports static-shape batching: rows beyond the true
sample count carry weight 0 and contribute nothing (the equivalent of the
reference's per-partition iteration just not seeing absent rows).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Union

import jax
import jax.numpy as jnp

from photon_tpu.ops import pass_counter
from photon_tpu.types import REAL_ACCELERATOR_BACKENDS

Array = jax.Array

_WARNED_PALLAS_F64 = False


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DenseFeatures:
    """Row-major dense design matrix ``x[N, D]``."""

    x: Array

    @property
    def n_rows(self) -> int:
        return self.x.shape[0]

    @property
    def dim(self) -> int:
        return self.x.shape[1]

    def matvec(self, w: Array) -> Array:
        pass_counter.record("matvec")
        return self.x @ w

    def rmatvec(self, v: Array) -> Array:
        """Xᵀv — accumulate per-row coefficients ``v`` into feature space."""
        pass_counter.record("rmatvec")
        return self.x.T @ v

    def sq_rmatvec(self, v: Array) -> Array:
        """(X∘X)ᵀv — for Hessian diagonals: Σᵢ vᵢ·xᵢⱼ²."""
        pass_counter.record("sq_rmatvec")
        return (self.x * self.x).T @ v

    def row_slice(self, start: int, size: int) -> "DenseFeatures":
        return DenseFeatures(jax.lax.dynamic_slice_in_dim(self.x, start, size, 0))


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SparseFeatures:
    """Padded ELL sparse matrix: per-row index/value lists of width K.

    ``idx[N, K]`` holds column ids in [0, D]; id == D marks padding (its value
    must be 0). ``dim`` (static) is the true feature dimension D.

    ``fast`` (optional, see ``ops/fast_sparse.py``) carries precomputed
    MXU-friendly layouts; when present, matvec/rmatvec take the fast path
    (row-slice gather + one-hot reduce) instead of XLA's slow generic
    gather/scatter lowering. Attach with ``with_fast_path()``.

    ``pallas`` (optional, see ``ops/pallas_sparse.py``) carries the Pallas
    slot tables; on a TPU backend (f32 data) matvec/rmatvec then run as
    hand-written kernels — hardware dynamic-gather + fused one-hot MXU
    reduce, no 128-wide gather blow-up. Attach with ``with_pallas_path()``;
    off-TPU the XLA paths are used (set ``PHOTON_PALLAS_INTERPRET=1`` to
    force the kernels through the Pallas interpreter, tests only).
    """

    idx: Array
    val: Array
    dim: int = dataclasses.field(metadata=dict(static=True))
    fast: Optional[object] = None
    pallas: Optional[object] = None

    @property
    def n_rows(self) -> int:
        return self.idx.shape[0]

    @property
    def max_nnz(self) -> int:
        return self.idx.shape[1]

    def with_fast_path(self, q_capacity: int = 2048) -> "SparseFeatures":
        """Build the fast-path layouts (host-side, once) and attach them."""
        from photon_tpu.ops.fast_sparse import build_fast_aux

        if self.fast is not None:
            return self
        aux = build_fast_aux(
            jax.device_get(self.idx), jax.device_get(self.val), self.dim,
            q_capacity=q_capacity,
        )
        if jnp.dtype(self.val.dtype).itemsize < 4:
            # Values were already narrowed (with_value_dtype before attach):
            # the column table must match or the rmatvec half of the
            # bandwidth saving silently evaporates (builder emits f32).
            # Only narrow-dtype casts: f64 runs keep the f32 table (the
            # builder already truncated through f32, so widening would
            # double its memory for zero precision).
            aux = dataclasses.replace(
                aux, cs_val=aux.cs_val.astype(self.val.dtype)
            )
        return dataclasses.replace(self, fast=aux)

    def with_pallas_path(self) -> "SparseFeatures":
        """Build the Pallas slot tables (host-side, once) and attach them,
        plus the XLA fast path as the off-TPU fallback. Large datasets chunk
        (512K-row / 256K-feature table slices); no-op (XLA fast path only)
        if the packed tables would blow the device-memory budget."""
        from photon_tpu.ops.pallas_sparse import build_pallas_aux

        out = self.with_fast_path()
        if out.pallas is not None:
            return out
        try:
            aux = build_pallas_aux(
                jax.device_get(self.idx), jax.device_get(self.val), self.dim
            )
        except ValueError:  # over the table-memory budget
            return out
        return dataclasses.replace(out, pallas=aux)

    def with_accelerator_paths(self) -> "SparseFeatures":
        """Attach the MXU-friendly layouts where they can actually win:
        accelerator backend + unsharded features (row-sharding drops them —
        the column-sorted tables are not partitionable along rows). The
        estimator/transformer call this so driver-trained models run the
        fast formulations on TPU without callers knowing about layouts;
        off-accelerator this is a no-op (XLA's plain CPU lowerings beat the
        fast-path formulations there, and the host-side table builds are
        pure overhead). float64 operands attach only the XLA fast path
        (the Pallas kernels are f32-only)."""
        import os

        import jax

        if jax.default_backend() not in REAL_ACCELERATOR_BACKENDS:
            return self
        if os.environ.get("PHOTON_DISABLE_ACCEL_PATHS") == "1":
            # Operator kill switch: the fast path's one-hot MXU program is
            # a heavy compile, and on a degraded tunnel heavy remote
            # compiles have wedged the device grant (2026-07-31, 2-for-2).
            # Disables every AUTO-attach (drivers/estimators route through
            # here); code that calls with_fast_path()/with_pallas_path()
            # explicitly — e.g. bench.py's sparse race — honors the same
            # variable at its own call site, keeping explicit requests
            # explicit.
            return self
        # HBM guard: the layouts cost ~20 bytes/entry on device on top of
        # the 8 bytes/entry ELL data. At config-5 scale (1.3e9 entries)
        # they would crowd out the batch itself; past the budget the solve
        # keeps the plain formulation (and P3/row sharding remain the
        # intended scale paths). Tunable: PHOTON_ACCEL_AUX_BUDGET_GB.
        entries = int(self.idx.shape[0]) * int(self.idx.shape[1])
        budget_gb = float(os.environ.get("PHOTON_ACCEL_AUX_BUDGET_GB", "4"))
        if 20 * entries > budget_gb * 1e9:
            return self
        vd = os.environ.get("PHOTON_VALUE_DTYPE")
        if vd is not None and jnp.dtype(vd) != jnp.dtype(self.val.dtype):
            # Opt-in narrow value storage (e.g. PHOTON_VALUE_DTYPE=bfloat16):
            # ~27% less hot-loop HBM traffic; see with_value_dtype. Tables
            # build in f32 first, then storage casts (Pallas is f32-only
            # and is skipped).
            return self.with_fast_path().with_value_dtype(vd)
        if jnp.dtype(self.val.dtype) != jnp.float32:
            return self.with_fast_path()
        return self.with_pallas_path()

    def with_value_dtype(self, dtype) -> "SparseFeatures":
        """Store feature VALUES in a narrower dtype (e.g. ``jnp.bfloat16``).

        The fused GLM pass is HBM-bound and values are 8 B of its 15 B
        per-entry stream (with int16 digit splits; 19 B at int32), so
        bfloat16 storage cuts hot-loop traffic ~27% on TPU; the ops upcast
        on load and accumulate in the operand precision, so only storage
        narrows. One-hot / binary / small-integer features are EXACT in
        bfloat16; continuous features round to 8 mantissa bits — opting in
        accepts that quantization. The Pallas tables are f32-only and are
        dropped; the XLA fast path's column table is re-cast to match.
        """
        dt = jnp.dtype(dtype)
        if jnp.dtype(self.val.dtype) == dt:
            return self
        out = dataclasses.replace(self, val=self.val.astype(dt))
        if out.fast is not None:
            out = dataclasses.replace(
                out,
                fast=dataclasses.replace(
                    out.fast, cs_val=out.fast.cs_val.astype(dt)
                ),
            )
        if out.pallas is not None and dt != jnp.float32:
            out = dataclasses.replace(out, pallas=None)
        return out

    def without_fast_path(self) -> "SparseFeatures":
        """Drop the fast/pallas layouts (e.g. before row-sharding: the
        column-sorted tables are not partitionable along the row axis)."""
        if self.fast is None and self.pallas is None:
            return self
        return dataclasses.replace(self, fast=None, pallas=None)

    def _pallas_mode(self, dtype) -> Optional[bool]:
        """None = don't use the kernels; else the ``interpret`` flag."""
        import os

        if self.pallas is None:
            return None
        if jnp.dtype(dtype) != jnp.float32:
            # The slot-table kernels are f32-only; --dtype float64 runs must
            # not silently think they are on the Pallas path (VERDICT r3
            # weak #5) — say so once, then use the XLA fast path.
            global _WARNED_PALLAS_F64
            if not _WARNED_PALLAS_F64:
                _WARNED_PALLAS_F64 = True
                import logging

                # warning, not info: without a configured handler INFO is
                # dropped and the downgrade would stay silent for direct
                # estimator-API users.
                logging.getLogger("photon_tpu.ops").warning(
                    "Pallas tables attached but operand dtype is %s; the "
                    "kernels are float32-only — using the XLA fast path",
                    jnp.dtype(dtype),
                )
            return None
        if os.environ.get("PHOTON_PALLAS_INTERPRET") == "1":
            return True
        return (False if jax.default_backend() in REAL_ACCELERATOR_BACKENDS
                else None)

    def _use_pallas(self, dtype) -> bool:
        return self._pallas_mode(dtype) is not None

    def matvec(self, w: Array) -> Array:
        pass_counter.record("matvec")
        interp = self._pallas_mode(w.dtype)
        if interp is not None:
            from photon_tpu.ops.pallas_sparse import matvec_pallas

            return matvec_pallas(self.pallas, w, interpret=interp)
        if self.fast is not None:
            from photon_tpu.ops.fast_sparse import matvec_fast

            return matvec_fast(self.fast, self.val, w, self.dim)
        # Gather through an extended vector with a zero ghost column so
        # padding indices read 0 — no masking needed in the hot loop.
        w_ext = jnp.concatenate([w, jnp.zeros((1,), w.dtype)])
        return jnp.sum(w_ext[self.idx] * self.val, axis=-1)

    def rmatvec(self, v: Array) -> Array:
        pass_counter.record("rmatvec")
        interp = self._pallas_mode(v.dtype)
        if interp is not None:
            from photon_tpu.ops.pallas_sparse import rmatvec_pallas

            return rmatvec_pallas(self.pallas, v, interpret=interp)
        if self.fast is not None:
            from photon_tpu.ops.fast_sparse import rmatvec_fast

            return rmatvec_fast(self.fast, v, self.dim)
        contrib = (v[:, None] * self.val).ravel()
        out = jax.ops.segment_sum(
            contrib, self.idx.ravel(), num_segments=self.dim + 1
        )
        return out[: self.dim]

    def sq_rmatvec(self, v: Array) -> Array:
        pass_counter.record("sq_rmatvec")
        interp = self._pallas_mode(v.dtype)
        if interp is not None:
            from photon_tpu.ops.pallas_sparse import rmatvec_pallas

            return rmatvec_pallas(self.pallas, v, square_vals=True,
                                  interpret=interp)
        if self.fast is not None:
            from photon_tpu.ops.fast_sparse import rmatvec_fast

            return rmatvec_fast(self.fast, v, self.dim, square_vals=True)
        contrib = (v[:, None] * self.val * self.val).ravel()
        out = jax.ops.segment_sum(
            contrib, self.idx.ravel(), num_segments=self.dim + 1
        )
        return out[: self.dim]

    def row_slice(self, start: int, size: int) -> "SparseFeatures":
        return SparseFeatures(
            idx=jax.lax.dynamic_slice_in_dim(self.idx, start, size, 0),
            val=jax.lax.dynamic_slice_in_dim(self.val, start, size, 0),
            dim=self.dim,
        )


Features = Union[DenseFeatures, SparseFeatures]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class LabeledBatch:
    """A batch of labeled examples: the SoA form of the reference's
    ``RDD[(UniqueSampleId, LabeledPoint)]`` for one feature shard.

    ``weights`` doubles as the validity mask: padded rows carry weight 0.
    """

    features: Features
    labels: Array               # [N]
    offsets: Array              # [N]
    weights: Array              # [N]

    @property
    def n_rows(self) -> int:
        return self.labels.shape[0]

    @property
    def dim(self) -> int:
        return self.features.dim

    def with_offsets(self, offsets: Array) -> "LabeledBatch":
        return dataclasses.replace(self, offsets=offsets)

    def add_to_offsets(self, scores: Array) -> "LabeledBatch":
        return dataclasses.replace(self, offsets=self.offsets + scores)

    def with_accelerator_paths(self, cache: Optional[dict] = None) -> "LabeledBatch":
        """Sparse features gain the MXU layouts (see
        ``SparseFeatures.with_accelerator_paths``); dense features no-op.
        ``cache`` (id(features) -> attached features) lets config sweeps
        reuse one host-side table build per distinct feature object."""
        feats = self.features
        if not hasattr(feats, "with_accelerator_paths"):
            return self
        if cache is not None and id(feats) in cache:
            attached = cache[id(feats)]
        else:
            attached = feats.with_accelerator_paths()
            if cache is not None:
                cache[id(feats)] = attached
        if attached is feats:
            return self
        return dataclasses.replace(self, features=attached)


def make_dense_batch(
    x,
    labels,
    offsets=None,
    weights=None,
    dtype=jnp.float32,
) -> LabeledBatch:
    x = jnp.asarray(x, dtype)
    n = x.shape[0]
    return LabeledBatch(
        features=DenseFeatures(x),
        labels=jnp.asarray(labels, dtype),
        offsets=jnp.zeros((n,), dtype) if offsets is None else jnp.asarray(offsets, dtype),
        weights=jnp.ones((n,), dtype) if weights is None else jnp.asarray(weights, dtype),
    )


def ell_from_rows(
    rows: list[tuple],
    dim: int,
    max_nnz: Optional[int] = None,
    dtype=jnp.float32,
) -> SparseFeatures:
    """Pack per-row (indices, values) pairs into padded ELL arrays (host-side)."""
    import numpy as np

    n = len(rows)
    k = max_nnz or max((len(r[0]) for r in rows), default=1)
    k = max(k, 1)
    idx = np.full((n, k), dim, dtype=np.int32)
    val = np.zeros((n, k), dtype=np.dtype(dtype))
    for i, (ri, rv) in enumerate(rows):
        if len(ri) > k:
            raise ValueError(
                f"row {i} has {len(ri)} nonzeros > max_nnz={k}; raise max_nnz "
                "(silent truncation would corrupt features)"
            )
        idx[i, : len(ri)] = np.asarray(ri)
        val[i, : len(rv)] = np.asarray(rv)
    return SparseFeatures(idx=jnp.asarray(idx), val=jnp.asarray(val, dtype), dim=dim)
