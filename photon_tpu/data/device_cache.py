"""Device-resident sweep cache: stop re-uploading the dataset every sweep.

Multi-sweep GAME training re-enters every coordinate once per sweep, and the
host-resident data paths paid host→device transfer each time: a
``host_resident=True`` random-effect dataset re-uploaded every bucket per
sweep, and the out-of-core fixed-effect solver re-streamed its ELL chunks on
every optimizer pass. The reference never had this problem — Spark RDDs
persist across ``CoordinateDescent`` iterations (``.persist()`` on the
per-coordinate datasets) — and ROADMAP item 4 names the fix: pin the
dataset on device after sweep 0.

:class:`DeviceSweepCache` is that pin, with a **memory budget**: entries are
device-array pytrees keyed by the host object they mirror; once the budget
(``PHOTON_SWEEP_CACHE_MB``, default 2048, ``0`` disables) would be
exceeded, further datasets SPILL — the build still runs (this sweep's
transfer happens either way) but nothing is retained, so the next sweep
streams again, exactly the pre-cache behavior. Budget pressure is therefore
a throughput regression, never an OOM. Residency and spill are
gauge-reported (``sweep_cache_*``) so a bench artifact or /metrics scrape
shows whether the cache actually held.

Identity matters for random effects: ``RandomEffectCoordinate`` compares
``proj`` arrays BY IDENTITY to detect "model trained on this dataset", so
the cached device mirror of a dataset must be the SAME object every sweep.
``dataset_mirror`` guarantees that: one mirror per source dataset for the
cache's lifetime (spilled datasets return the original host-backed object,
whose identity is equally stable).
"""
from __future__ import annotations

import dataclasses
import os
import threading
from typing import Callable, Optional

import numpy as np

from photon_tpu.obs import trace_span
from photon_tpu.obs.metrics import REGISTRY

__all__ = ["DeviceSweepCache", "default_budget_bytes", "release_all_caches",
           "shed_pins"]

# Live-instance registry (weak: the cache's own lifetime is unchanged) so
# device-loss recovery (runtime/backend_guard.recover_from_device_loss)
# can drop EVERY process-wide pin at once — after a device loss the pinned
# buffers are dead weight at best and poison at worst, and the recovery
# path has no handle on the estimator that owns each cache.
import weakref

_LIVE_CACHES: "weakref.WeakSet" = weakref.WeakSet()


def release_all_caches() -> int:
    """Release every live :class:`DeviceSweepCache`; returns how many."""
    caches = list(_LIVE_CACHES)
    for c in caches:
        c.release()
    return len(caches)


def shed_pins(max_bytes: int) -> int:
    """Spill up to ``max_bytes`` of pinned chunk entries across every live
    cache (oldest pins first) — the device-memory watchdog's pressure
    valve (``runtime/memory_guard.MemoryGuard.check``). Returns the bytes
    freed. Spilled entries re-stream on their next use, exactly the
    budget-spill behavior, so this trades throughput for headroom, never
    correctness."""
    freed = 0
    for c in list(_LIVE_CACHES):
        if freed >= max_bytes:
            break
        freed += c.shed(max_bytes - freed)
    return freed

_CACHE_BYTES = REGISTRY.gauge(
    "sweep_cache_bytes",
    "Device bytes currently pinned by DeviceSweepCache instances",
)
_CACHE_ENTRIES = REGISTRY.gauge(
    "sweep_cache_entries",
    "Entries currently resident across DeviceSweepCache instances",
)
_CACHE_HITS = REGISTRY.counter(
    "sweep_cache_hits_total",
    "Sweep-cache lookups served from device-resident arrays",
)
_CACHE_MISSES = REGISTRY.counter(
    "sweep_cache_misses_total",
    "Sweep-cache lookups that had to upload (first touch)",
)
_CACHE_SPILLED = REGISTRY.gauge(
    "sweep_cache_spilled_bytes",
    "Bytes that did NOT fit the sweep-cache budget and re-stream per sweep",
)


def default_budget_bytes() -> int:
    """``PHOTON_SWEEP_CACHE_MB`` (default 2048 MB; 0 disables caching).

    PER-DEVICE: a mesh-attached cache multiplies by the entity-axis device
    count, because its pins are sharded — each device holds 1/n of every
    pinned array, so the budget the operator sizes against one device's
    HBM scales with the mesh instead of silently confining an 8-device
    rig to one device's allowance (and the PR 13
    ``PHOTON_SWEEP_CACHE_DEVICE_FRACTION`` clamp applies per device too —
    ``memory_guard.effective_sweep_budget`` sees the per-device figure)."""
    try:
        mb = float(os.environ.get("PHOTON_SWEEP_CACHE_MB", "2048"))
    except ValueError:
        mb = 2048.0
    return max(0, int(mb * 1e6))


def _tree_nbytes(tree) -> int:
    import jax

    return sum(
        int(getattr(leaf, "nbytes", 0)) for leaf in jax.tree.leaves(tree)
    )


class DeviceSweepCache:
    """Budgeted pin of host training data on device across sweeps.

    One instance per fit/estimator (the estimator shares it across a
    λ-sweep's configurations — same data, one upload). ``release()`` drops
    every pin and rolls the process-wide gauges back; a cache that simply
    goes out of scope releases via ``__del__`` as a backstop.
    """

    def __init__(self, budget_bytes: Optional[int] = None, mesh=None,
                 entity_axis="data"):
        requested = (
            default_budget_bytes() if budget_bytes is None
            else max(0, int(budget_bytes))
        )
        if requested:
            # Live-device clamp + run-wide degradation scale
            # (runtime/memory_guard): the static 2048 MB default can
            # exceed the whole device on small parts, and an
            # OOM-pre-degraded restart must not re-pin the budget that
            # just killed the attempt. Backends with no memory stats
            # (CPU) keep the requested budget. BOTH the requested budget
            # and the clamp are PER-DEVICE figures; the mesh multiplier
            # below converts to the cache-wide total.
            from photon_tpu.runtime.memory_guard import (
                effective_sweep_budget,
            )

            requested = effective_sweep_budget(requested)
        self.mesh = mesh
        self.entity_axis = entity_axis
        if mesh is not None:
            from photon_tpu.parallel.mesh import axes_size

            self.n_devices = axes_size(mesh, entity_axis)
        else:
            self.n_devices = 1
        # Per-device figure; ``budget_bytes`` (the cache-wide total) is a
        # property so the run's sticky shard degradation shrinks it live.
        self._per_device_budget = requested
        # key -> (device pytree, nbytes, retained-host-referent). The
        # referent is whatever object the KEY was derived from (an id());
        # retaining it pins the id, so a freed-and-recycled address can
        # never alias a different object onto a stale device entry.
        self._entries: dict = {}
        self._mirrors: dict = {}
        # key -> (retained host referent, nbytes), same id-pinning rule as
        # _entries: spill accounting is once-per-key, so a freed-and-
        # recycled id matching a stale spill key would silently skip a NEW
        # key's bytes; nbytes lets discard() roll the accounting back.
        self._spilled_keys: dict = {}
        self._bytes = 0
        self._spilled = 0
        self._labels = None
        # key -> device labels the entry's bytes were credited to (None =
        # construction-mesh default); removal must credit the same series.
        self._entry_labels: dict = {}
        self._lock = threading.Lock()
        _LIVE_CACHES.add(self)

    @staticmethod
    def _labels_for(mesh) -> list:
        """Device-id labels for the per-device ``sweep_cache_bytes``
        series: the given mesh's devices or the default device."""
        try:
            if mesh is not None:
                devs = list(np.asarray(mesh.devices).flat)
            else:
                import jax

                devs = [jax.devices()[0]]
            return [str(getattr(d, "id", i)) for i, d in enumerate(devs)]
        except Exception:  # noqa: BLE001 - labels are telemetry only
            return ["0"]

    def _device_labels(self) -> list:
        """Construction-mesh labels, memoized. Lazy — reading
        jax.devices() at construction would initialize the backend before
        the owner wants it."""
        if self._labels is None:
            self._labels = self._labels_for(self.mesh)
        return self._labels

    def effective_devices(self) -> int:
        """The entity-axis device count pins actually shard over NOW: the
        construction mesh size, shrunk by the run's sticky shard-loss
        degradation (docs/robustness.md §"Shard loss")."""
        if self.mesh is None or self.n_devices <= 1:
            return self.n_devices
        try:
            from photon_tpu.runtime import memory_guard as _mg

            m = int((_mg.sticky_plan("re.shard") or {}).get("shards") or 0)
        except Exception:  # noqa: BLE001 - degradation lookup is advisory
            m = 0
        return m if 0 < m < self.n_devices else self.n_devices

    @property
    def budget_bytes(self) -> int:
        """Cache-wide total: per-device budget × the EFFECTIVE device
        count. After a shard loss the total shrinks with the surviving
        mesh, so survivors are never loaded past the per-device allowance
        the operator (and the memory_guard clamp) sized."""
        return self._per_device_budget * max(1, self.effective_devices())

    def _bytes_gauge(self, delta: float, labels=None) -> None:
        """Move the resident-bytes gauge: the unlabelled TOTAL (existing
        consumers — descent residency instants, bench artifacts — keep
        their series) plus a per-device-labelled series splitting the
        delta across the devices THIS pin shards over (callers pass the
        labels recorded at put time, so removal credits the same series
        even after the effective mesh changed)."""
        _CACHE_BYTES.inc(delta)
        labels = labels or self._device_labels()
        share = delta / len(labels)
        for lbl in labels:
            _CACHE_BYTES.inc(share, device=lbl)

    # -- core --------------------------------------------------------------

    @property
    def resident_bytes(self) -> int:
        return self._bytes

    @property
    def spilled_bytes(self) -> int:
        return self._spilled

    @property
    def enabled(self) -> bool:
        return self.budget_bytes > 0

    def stats(self) -> dict:
        return {
            "budget_bytes": self.budget_bytes,
            "resident_bytes": self._bytes,
            "spilled_bytes": self._spilled,
            "entries": len(self._entries),
        }

    def get_or_put(self, key, nbytes: int, build: Callable, retain=None):
        """Device pytree for ``key``: cached on hit; on miss ``build()``
        runs (the upload) and the result is RETAINED only when ``nbytes``
        fits the remaining budget — else it is returned un-pinned (spill:
        this use still works, the next sweep re-uploads; spilled bytes are
        counted ONCE per key, not per re-miss, so the gauge reads dataset
        size, not dataset × passes). ``retain`` pins the host object the
        key was derived from (see ``_entries``)."""
        with self._lock:
            hit = self._entries.get(key)
            # Once spilled, a key stays spilled (and its bytes stay counted
            # once): budget pressure or a watchdog shed proved it doesn't
            # fit, and re-pinning it later would both flap the residency
            # and double-count the spill accounting.
            spilled = key in self._spilled_keys
        if hit is not None:
            _CACHE_HITS.inc()
            return hit[0]
        _CACHE_MISSES.inc()
        fits = (self.enabled and not spilled
                and self._bytes + nbytes <= self.budget_bytes)
        with trace_span("ingest.device_put", cat="ingest",
                        bytes=int(nbytes), cached=bool(fits)):
            built = build()
        if not fits:
            with self._lock:
                if key not in self._spilled_keys:
                    self._spilled_keys[key] = (retain, int(nbytes))
                    self._spilled += int(nbytes)
                    _CACHE_SPILLED.inc(int(nbytes))
            return built
        with self._lock:
            if key not in self._entries:
                self._entries[key] = (built, int(nbytes), retain)
                self._bytes += int(nbytes)
                self._bytes_gauge(int(nbytes))
                _CACHE_ENTRIES.inc()
        return built

    def discard(self, key) -> None:
        """Forget one key whose host referent was replaced (the pin — or
        its once-per-key spill accounting — can never be hit again); no-op
        for unknown keys. Rolls byte accounting back so a replaced-then-
        re-fed chunk is not double-counted."""
        with self._lock:
            entry = self._entries.pop(key, None)
            labels = self._entry_labels.pop(key, None)
            spilled = self._spilled_keys.pop(key, None)
            if entry is not None:
                self._bytes -= entry[1]
            if spilled is not None:
                self._spilled -= spilled[1]
        if entry is not None:
            self._bytes_gauge(-entry[1], labels)
            _CACHE_ENTRIES.inc(-1)
        if spilled is not None:
            _CACHE_SPILLED.inc(-spilled[1])

    def shed(self, max_bytes: int) -> int:
        """Spill up to ``max_bytes`` of pinned CHUNK entries, oldest pin
        first, marking them spilled (sticky: they re-stream every later
        pass instead of re-pinning — memory pressure proved they don't
        fit). Dataset mirrors are exempt: their device arrays must stay
        the same object for the cache's lifetime (identity contract,
        module doc), so converting one back to streaming mid-run is not an
        option. Returns the bytes freed."""
        if max_bytes <= 0:
            return 0
        freed = entries = newly_spilled = 0
        freed_series: list = []
        with self._lock:
            for key in list(self._entries):
                if freed >= max_bytes:
                    break
                if key in self._mirrors:
                    continue
                _built, nbytes, retain = self._entries.pop(key)
                freed_series.append((nbytes, self._entry_labels.pop(key,
                                                                    None)))
                self._bytes -= nbytes
                freed += nbytes
                entries += 1
                if key not in self._spilled_keys:
                    self._spilled_keys[key] = (retain, nbytes)
                    self._spilled += nbytes
                    newly_spilled += nbytes
        if freed:
            for nbytes, labels in freed_series:
                self._bytes_gauge(-nbytes, labels)
            _CACHE_ENTRIES.inc(-entries)
        if newly_spilled:
            _CACHE_SPILLED.inc(newly_spilled)
        return freed

    def release(self) -> None:
        """Drop every pinned entry (device memory frees once consumers drop
        their own references) and roll the process gauges back."""
        with self._lock:
            freed = self._bytes
            n = len(self._entries)
            spilled = self._spilled
            freed_series = [
                (nb, self._entry_labels.get(k))
                for k, (_b, nb, _r) in self._entries.items()
            ]
            self._entries.clear()
            self._mirrors.clear()
            self._spilled_keys.clear()
            self._entry_labels.clear()
            self._bytes = 0
            self._spilled = 0
        if freed:
            for nb, labels in freed_series:
                self._bytes_gauge(-nb, labels)
        if n:
            _CACHE_ENTRIES.inc(-n)
        if spilled:
            _CACHE_SPILLED.inc(-spilled)

    def __del__(self):  # pragma: no cover - GC backstop
        try:
            self.release()
        except Exception:
            pass

    # -- typed helpers -----------------------------------------------------

    def dataset_mirror(self, dataset):
        """Device-resident mirror of a ``RandomEffectDataset`` whose buckets
        are host numpy (``host_resident=True`` builds). The SAME mirror
        object returns for the cache's lifetime (score/train identity
        checks — see module doc). Datasets already device-backed, or busting
        the budget, return the ORIGINAL object (streaming re-upload path).
        """
        if not self.enabled:
            # Disabled cache (budget 0): pure pass-through, like the OOC
            # chunk path — no mirror bookkeeping, no "spill" telemetry for
            # a cache the operator explicitly turned off.
            return dataset
        key = ("re_dataset", id(dataset))
        with self._lock:
            hit = self._mirrors.get(key)
        if hit is not None:
            # A spilled dataset's "mirror" is the host original: every
            # lookup still re-uploads downstream, so it counts as a MISS —
            # the hit counter must only ever mean "device-resident served".
            (_CACHE_MISSES if key in self._spilled_keys
             else _CACHE_HITS).inc()
            return hit
        buckets = getattr(dataset, "buckets", ())
        if not buckets or not isinstance(buckets[0].idx, np.ndarray):
            # Already device-backed (the default build): nothing to pin.
            return dataset
        import jax

        nbytes = sum(_tree_nbytes(b) for b in buckets)
        fits = self.enabled and self._bytes + nbytes <= self.budget_bytes
        if not fits:
            # Spill: the ORIGINAL host-backed object is the (identity-
            # stable) mirror — every sweep re-uploads, as before the cache.
            _CACHE_MISSES.inc()
            with self._lock:
                if key not in self._mirrors:
                    self._mirrors[key] = dataset
                    self._spilled_keys[key] = (dataset, int(nbytes))
                    self._spilled += int(nbytes)
                    _CACHE_SPILLED.inc(int(nbytes))
            return dataset
        _CACHE_MISSES.inc()
        with trace_span("ingest.device_put", cat="ingest",
                        bytes=int(nbytes), cached=True,
                        what=f"re_dataset:{dataset.re_type}"):
            if self.mesh is not None:
                # Per-shard pins: each bucket's entity axis is padded to
                # the mesh multiple (the same inert-lane convention the
                # solve would apply) and device_put row-sharded over the
                # entity axis — every device holds 1/n of the pin instead
                # of device 0 holding everything, and the mesh solve's
                # per-bucket placement becomes a no-op re-put. Consumers
                # always read THIS mirror (train and score), so the padded
                # lanes (zero coefs, ghost rows) stay invisible. The mesh
                # resolves through the run's sticky shard degradation at
                # PUT time, not construction time: after a real shard loss
                # the recovery releases every mirror, and the rebuild here
                # must land on the SURVIVING devices — re-putting onto the
                # construction-time mesh would re-raise device_lost outside
                # the solve's shard-loss catch (docs/robustness.md §"Shard
                # loss": later sweeps start degraded, never re-fail).
                from photon_tpu.game.random_effect import (
                    _effective_mesh,
                    _pad_bucket,
                )
                from photon_tpu.parallel.mesh import axes_size, batch_sharding

                mesh, axis = _effective_mesh(self.mesh, self.entity_axis)
                n_dev = axes_size(mesh, axis)
                labels = self._labels_for(mesh)
                sharding = batch_sharding(mesh, axis)
                dev_buckets = tuple(
                    jax.tree.map(
                        lambda leaf: jax.device_put(leaf, sharding),
                        _pad_bucket(b, n_dev, dataset.n_rows,
                                    dataset.global_dim),
                    )
                    for b in buckets
                )
                nbytes = sum(_tree_nbytes(b) for b in dev_buckets)
            else:
                labels = None
                dev_buckets = tuple(
                    jax.tree.map(jax.numpy.asarray, b) for b in buckets
                )
        mirror = dataclasses.replace(dataset, buckets=dev_buckets)
        with self._lock:
            if key not in self._mirrors:
                self._mirrors[key] = mirror
                self._entries[key] = (dev_buckets, int(nbytes), dataset)
                if labels is not None:
                    self._entry_labels[key] = labels
                self._bytes += int(nbytes)
                self._bytes_gauge(int(nbytes), labels)
                _CACHE_ENTRIES.inc()
            mirror = self._mirrors[key]
        return mirror
