"""Pre-training data sanity checks.

Parity: reference ⟦photon-api/.../data/DataValidators.scala⟧ +
``DataValidationType`` (SURVEY.md §2.2 "Data validation"): finite features /
offsets / weights, task-specific label checks (binary for logistic &
smoothed-hinge SVM, finite for linear, non-negative for Poisson), with
VALIDATE_FULL / VALIDATE_SAMPLE / VALIDATE_DISABLED modes.

TPU-first: each check is one jitted reduction over the fixed-shape batch —
all checks fuse into a single device pass returning a small vector of
violation counts; only that vector crosses to the host, where failures raise
``DataValidationError`` listing every failed check (the reference logs and
aggregates all failures before throwing, so callers see the full list).
Padded rows (weight == 0) are skipped. SAMPLE mode validates a deterministic
row slice, standing in for the reference's RDD sample.
"""
from __future__ import annotations

import enum
from functools import partial

import jax
import jax.numpy as jnp

from photon_tpu.data.batch import DenseFeatures, LabeledBatch, SparseFeatures
from photon_tpu.types import TaskType

Array = jax.Array

# Rows checked under VALIDATE_SAMPLE. Shared with callers that pre-slice
# host-side before the device transfer (the out-of-core driver) so the two
# --data-validation contracts cannot silently diverge.
SAMPLE_ROWS_DEFAULT = 1024


class DataValidationType(enum.Enum):
    """Reference ⟦DataValidationType⟧."""

    VALIDATE_FULL = "VALIDATE_FULL"
    VALIDATE_SAMPLE = "VALIDATE_SAMPLE"
    VALIDATE_DISABLED = "VALIDATE_DISABLED"

    @classmethod
    def parse(cls, s: str) -> "DataValidationType":
        return cls(s.strip().upper())


class DataValidationError(ValueError):
    """Raised with the complete list of failed checks."""

    def __init__(self, failures: list[str]):
        self.failures = failures
        super().__init__("data validation failed: " + "; ".join(failures))


_CHECKS = (
    "features are not all finite",
    "offsets are not all finite",
    "weights are not all finite and non-negative",
    "labels are not all finite",
    "labels are not all binary (0/1) as required by the task",
    "labels are not all non-negative as required by Poisson regression",
)


@partial(jax.jit, static_argnums=1)
def _violation_counts(batch: LabeledBatch, task: TaskType) -> Array:
    """[len(_CHECKS)] counts of violating rows (0 where check passes/skipped)."""
    mask = batch.weights != 0

    feats = batch.features
    if isinstance(feats, DenseFeatures):
        row_finite = jnp.all(jnp.isfinite(feats.x), axis=-1)
    elif isinstance(feats, SparseFeatures):
        row_finite = jnp.all(jnp.isfinite(feats.val), axis=-1)
    else:  # pragma: no cover - Features union is closed
        raise TypeError(f"unknown feature container {type(feats)}")

    def count(bad: Array) -> Array:
        return jnp.sum(jnp.where(mask, bad, False).astype(jnp.int32))

    binary_tasks = (
        TaskType.LOGISTIC_REGRESSION,
        TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
    )
    zero = jnp.zeros((), jnp.int32)
    return jnp.stack(
        [
            count(~row_finite),
            count(~jnp.isfinite(batch.offsets)),
            jnp.sum(
                (~jnp.isfinite(batch.weights) | (batch.weights < 0)).astype(jnp.int32)
            ),
            count(~jnp.isfinite(batch.labels)),
            count((batch.labels != 0) & (batch.labels != 1))
            if task in binary_tasks
            else zero,
            count(batch.labels < 0)
            if task == TaskType.POISSON_REGRESSION
            else zero,
        ]
    )


def sanity_check_data(
    batch: LabeledBatch,
    task: TaskType,
    validation_type: DataValidationType = DataValidationType.VALIDATE_FULL,
    sample_rows: int = SAMPLE_ROWS_DEFAULT,
) -> None:
    """Raise ``DataValidationError`` listing every failed check.

    Reference ⟦DataValidators.sanityCheckDataFrameForTraining⟧ semantics:
    run all applicable checks, aggregate, throw once with the full list.
    """
    if validation_type == DataValidationType.VALIDATE_DISABLED:
        return
    if validation_type == DataValidationType.VALIDATE_SAMPLE:
        n = min(sample_rows, batch.n_rows)
        batch = LabeledBatch(
            features=batch.features.row_slice(0, n),
            labels=batch.labels[:n],
            offsets=batch.offsets[:n],
            weights=batch.weights[:n],
        )
    counts = jax.device_get(_violation_counts(batch, task))
    failures = [
        f"{msg} ({int(c)} rows)" for msg, c in zip(_CHECKS, counts) if int(c) > 0
    ]
    if failures:
        raise DataValidationError(failures)
