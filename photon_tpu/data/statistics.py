"""Per-feature summary statistics for normalization and summarization output.

Parity: reference ⟦photon-api/.../stat/FeatureDataStatistics.scala⟧ /
``BasicStatisticalSummary`` (wraps Spark's ``MultivariateStatisticalSummary``;
SURVEY.md §2.2 "Statistics"). Mean / variance / min / max / nnz per feature
column, computed over all examples of a feature shard.

TPU-first: one jitted pass over the fixed-shape batch. Sparse (ELL) columns
get exact moments including implicit zeros — Σx and Σx² come from
``segment_sum`` over the index arrays, and the zero-count correction adjusts
min/max/variance, mirroring what Spark's summarizer does streaming-wise.
Padded rows (weight == 0) are excluded, matching the reference iterating only
real examples.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from photon_tpu.data.batch import DenseFeatures, LabeledBatch, SparseFeatures

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class FeatureDataStatistics:
    """Summary over one feature shard. All arrays are [D]."""

    mean: Array
    variance: Array
    min: Array
    max: Array
    num_nonzeros: Array   # float counts (jit-friendly)
    count: Array          # scalar: number of (unpadded) examples

    @property
    def dim(self) -> int:
        return self.mean.shape[-1]

    def std(self) -> Array:
        return jnp.sqrt(jnp.maximum(self.variance, 0.0))

    def max_magnitude(self) -> Array:
        return jnp.maximum(jnp.abs(self.min), jnp.abs(self.max))


@jax.jit
def compute_feature_statistics(batch: LabeledBatch) -> FeatureDataStatistics:
    """One-pass per-feature summary; mask = rows with weight > 0."""
    mask = (batch.weights > 0).astype(jnp.float32)
    n = jnp.sum(mask)
    n_safe = jnp.maximum(n, 1.0)
    feats = batch.features

    if isinstance(feats, DenseFeatures):
        x = feats.x * mask[:, None]
        s1 = jnp.sum(x, axis=0)
        s2 = jnp.sum(x * x, axis=0)
        # Masked-out rows read as +inf/-inf so they never win min/max.
        big = jnp.inf
        xm = jnp.where(mask[:, None] > 0, feats.x, big)
        xM = jnp.where(mask[:, None] > 0, feats.x, -big)
        mn = jnp.min(xm, axis=0)
        mx = jnp.max(xM, axis=0)
        # All rows masked out → no observations; report 0 like the sparse path.
        mn = jnp.where(jnp.isinf(mn), 0.0, mn)
        mx = jnp.where(jnp.isinf(mx), 0.0, mx)
        nnz = jnp.sum((feats.x != 0) & (mask[:, None] > 0), axis=0).astype(jnp.float32)
    elif isinstance(feats, SparseFeatures):
        d = feats.dim
        w_row = mask[:, None]
        vals = feats.val * w_row
        flat_idx = feats.idx.ravel()
        s1 = jax.ops.segment_sum(vals.ravel(), flat_idx, num_segments=d + 1)[:d]
        s2 = jax.ops.segment_sum((vals * feats.val).ravel(), flat_idx, num_segments=d + 1)[:d]
        present = ((feats.val != 0) & (w_row > 0)).astype(jnp.float32)
        nnz = jax.ops.segment_sum(present.ravel(), flat_idx, num_segments=d + 1)[:d]
        # Min/max over explicit values; padding/masked slots neutralized.
        big = jnp.float32(jnp.inf)
        vm = jnp.where(present > 0, feats.val, big).ravel()
        vM = jnp.where(present > 0, feats.val, -big).ravel()
        mn = jax.ops.segment_min(vm, flat_idx, num_segments=d + 1)[:d]
        mx = jax.ops.segment_max(vM, flat_idx, num_segments=d + 1)[:d]
        # Implicit zeros: any column with fewer explicit nonzeros than rows
        # also contains 0.
        has_zero = nnz < n
        mn = jnp.where(has_zero, jnp.minimum(mn, 0.0), mn)
        mx = jnp.where(has_zero, jnp.maximum(mx, 0.0), mx)
        # Columns never touched at all: min=max=0.
        mn = jnp.where(jnp.isinf(mn), 0.0, mn)
        mx = jnp.where(jnp.isinf(mx), 0.0, mx)
    else:  # pragma: no cover - Features union is closed
        raise TypeError(f"unknown feature container {type(feats)}")

    mean = s1 / n_safe
    # Sample variance with Bessel correction, as Spark's summarizer reports.
    var = jnp.maximum(s2 - n * mean * mean, 0.0) / jnp.maximum(n - 1.0, 1.0)
    return FeatureDataStatistics(
        mean=mean, variance=var, min=mn, max=mx, num_nonzeros=nnz, count=n
    )
