"""Down-sampling for class imbalance, per coordinate.

Parity: reference ⟦photon-api/.../sampling/DownSampler.scala,
BinaryClassificationDownSampler, DefaultDownSampler⟧ (SURVEY.md §2.2
"Down-sampling"): the fixed-effect coordinate may down-sample its training
data per optimization config; dropped examples' weight mass is restored by
re-scaling kept examples by 1/rate so the objective stays an unbiased
estimate. The binary-classification variant keeps every positive and
down-samples only negatives.

TPU-first: shapes under jit are static, so "dropping" a row means zeroing its
weight (weight 0 ≡ the row does not exist for loss/grad/Hessian — exactly the
padded-row convention of ``LabeledBatch``) and the mask is drawn with
``jax.random`` on-device. This keeps down-sampling inside the jitted training
step with zero host round-trips. For genuine memory savings a host-side
``compact`` helper physically repacks the kept rows into a smaller batch
(bucketed to limit recompilation), which is what the reference's RDD filter
achieves.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.data.batch import DenseFeatures, LabeledBatch, SparseFeatures
from photon_tpu.types import TaskType

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class DownSampler:
    """Uniform down-sampling at ``rate`` ∈ (0, 1]; weight rescale 1/rate.

    Reference ⟦DefaultDownSampler⟧.
    """

    rate: float

    def __post_init__(self):
        if not (0.0 < self.rate <= 1.0):
            raise ValueError(f"down-sampling rate must be in (0, 1], got {self.rate}")

    def down_sample_weights(
        self, key: Array, labels: Array, weights: Array
    ) -> Array:
        """Weight-level core (any shape): zero dropped rows, rescale kept.
        Shared by the fixed-effect batch path and the per-entity
        random-effect train-weight path."""
        keep = jax.random.uniform(key, labels.shape) < self.rate
        return jnp.where(keep, weights / self.rate, 0.0)

    def down_sample(self, key: Array, batch: LabeledBatch) -> LabeledBatch:
        """Jit-safe: zero dropped rows' weights, rescale kept rows."""
        new_w = self.down_sample_weights(key, batch.labels, batch.weights)
        return dataclasses.replace(batch, weights=new_w)


@dataclasses.dataclass(frozen=True)
class BinaryClassificationDownSampler(DownSampler):
    """Keep all positives; down-sample negatives at ``rate``, re-weighting
    kept negatives by 1/rate. Reference ⟦BinaryClassificationDownSampler⟧."""

    def down_sample_weights(
        self, key: Array, labels: Array, weights: Array
    ) -> Array:
        keep_draw = jax.random.uniform(key, labels.shape) < self.rate
        is_pos = labels > 0
        keep = is_pos | keep_draw
        scale = jnp.where(is_pos, 1.0, 1.0 / self.rate)
        return jnp.where(keep, weights * scale, 0.0)

    def down_sample(self, key: Array, batch: LabeledBatch) -> LabeledBatch:
        new_w = self.down_sample_weights(key, batch.labels, batch.weights)
        return dataclasses.replace(batch, weights=new_w)


def down_sampler_for_task(task: TaskType, rate: float) -> DownSampler:
    """Reference ⟦DownSampler.apply⟧: binary tasks get the class-aware
    sampler, everything else the default."""
    if task in (TaskType.LOGISTIC_REGRESSION, TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM):
        return BinaryClassificationDownSampler(rate)
    return DownSampler(rate)


def compact(batch: LabeledBatch, row_multiple: int = 128) -> LabeledBatch:
    """Host-side repack: physically drop weight-0 rows, pad up to a multiple
    of ``row_multiple`` (bounds the number of distinct compiled shapes)."""
    w = np.asarray(jax.device_get(batch.weights))
    keep = np.nonzero(w != 0)[0]
    n = max(int(len(keep)), 1)
    n_pad = -n % row_multiple
    total = n + n_pad

    def take(arr):
        a = np.asarray(jax.device_get(arr))
        out = np.zeros((total,) + a.shape[1:], a.dtype)
        out[: len(keep)] = a[keep]
        return jnp.asarray(out)

    feats = batch.features
    if isinstance(feats, DenseFeatures):
        new_feats = DenseFeatures(take(feats.x))
    elif isinstance(feats, SparseFeatures):
        idx = np.asarray(jax.device_get(feats.idx))
        pad_idx = np.full((total, idx.shape[1]), feats.dim, idx.dtype)
        pad_idx[: len(keep)] = idx[keep]
        new_feats = SparseFeatures(
            idx=jnp.asarray(pad_idx), val=take(feats.val), dim=feats.dim
        )
    else:  # pragma: no cover - Features union is closed
        raise TypeError(f"unknown feature container {type(feats)}")

    return LabeledBatch(
        features=new_feats,
        labels=take(batch.labels),
        offsets=take(batch.offsets),
        weights=take(batch.weights),
    )
