"""Feature normalization contexts: optimize in a scaled space, report back.

Parity: reference ⟦photon-api/.../normalization/NormalizationType.scala,
NormalizationContext.scala⟧ (SURVEY.md §2.2 "Normalization", §7 hard-part #5):

* ``NONE`` — identity.
* ``SCALE_WITH_STANDARD_DEVIATION`` — factor 1/σⱼ, no shift.
* ``SCALE_WITH_MAX_MAGNITUDE`` — factor 1/max|xⱼ|, no shift.
* ``STANDARDIZATION`` — factor 1/σⱼ AND shift μⱼ (requires an intercept).

The reference's key trick is preserved: **data is never transformed** (that
would densify sparse features). Instead the coefficient vector is mapped
between spaces around each margin computation. With transformed features
x' = (x − s)∘f, a transformed-space model (w', b') scores

    z = w'ᵀx' + b' = (w'∘f)ᵀ x + (b' − (w'∘f)ᵀ s)

so the original-space equivalents are w = w'∘f and b = b' − (w'∘f)ᵀs — a
linear map applied to coefficients once per objective evaluation, while the
sparse matvec runs on the raw features. The intercept is excluded from both
factor and shift (its factor is 1, shift 0), and shifts are only legal when an
intercept exists to absorb them — both reference invariants, enforced here.

Regularization applies to transformed-space coefficients (what the optimizer
sees), again matching the reference.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from photon_tpu.data.statistics import FeatureDataStatistics

Array = jax.Array


class NormalizationType(enum.Enum):
    """Reference ⟦NormalizationType⟧."""

    NONE = "NONE"
    SCALE_WITH_STANDARD_DEVIATION = "SCALE_WITH_STANDARD_DEVIATION"
    SCALE_WITH_MAX_MAGNITUDE = "SCALE_WITH_MAX_MAGNITUDE"
    STANDARDIZATION = "STANDARDIZATION"

    @classmethod
    def parse(cls, s: str) -> "NormalizationType":
        return cls(s.strip().upper())


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class NormalizationContext:
    """factors[D] / shifts[D] (either may be None = identity).

    ``intercept_index`` is static; factor there is forced to 1 and shift to 0.
    """

    factors: Optional[Array]
    shifts: Optional[Array]
    intercept_index: Optional[int] = dataclasses.field(
        default=None, metadata=dict(static=True)
    )

    def __post_init__(self):
        if self.shifts is not None and self.intercept_index is None:
            raise ValueError(
                "shifts require an intercept to absorb them (reference "
                "NormalizationContext invariant)"
            )

    @property
    def is_identity(self) -> bool:
        return self.factors is None and self.shifts is None

    # -- coefficient-space maps (see module docstring for the algebra) ------

    def _effective(self) -> tuple[Optional[Array], Optional[Array]]:
        """Factors/shifts with the intercept slot forced to (1, 0).

        ``context_from_statistics`` already sanitizes these, but a directly
        constructed context must obey the same invariant or the two coef maps
        stop being inverses; forcing here is tracer-safe (a value check in
        ``__post_init__`` would fail under jit)."""
        f, s = self.factors, self.shifts
        if self.intercept_index is not None:
            if f is not None:
                f = f.at[self.intercept_index].set(1.0)
            if s is not None:
                s = s.at[self.intercept_index].set(0.0)
        return f, s

    def coef_to_original(self, w: Array) -> Array:
        """Transformed-space model → original-space model (w = w'∘f; intercept
        absorbs −(w'∘f)ᵀs)."""
        f, s = self._effective()
        out = w if f is None else w * f
        if s is not None:
            corr = jnp.sum(out * s)
            out = out.at[self.intercept_index].add(-corr)
        return out

    def coef_to_transformed(self, w: Array) -> Array:
        """Original-space model → transformed-space model (inverse map)."""
        f, s = self._effective()
        out = w
        if s is not None:
            corr = jnp.sum(out * s)
            out = out.at[self.intercept_index].add(corr)
        if f is not None:
            out = out / f
        return out

    def wrap_value_and_grad(
        self, vg: Callable[[Array], tuple[Array, Array]]
    ) -> Callable[[Array], tuple[Array, Array]]:
        """Lift an original-space (value, grad) closure to transformed space.

        The chain rule through the linear map ``coef_to_original`` is its
        transpose, obtained exactly via ``jax.vjp`` — no hand-derived
        adjoint to get silently wrong (SURVEY.md §7 hard-part #5).
        """
        if self.is_identity:
            return vg

        def wrapped(wp: Array) -> tuple[Array, Array]:
            w, pullback = jax.vjp(self.coef_to_original, wp)
            v, g = vg(w)
            return v, pullback(g)[0]

        return wrapped

    def wrap_hvp(
        self, hvp: Callable[[Array, Array], Array]
    ) -> Callable[[Array, Array], Array]:
        """Transformed-space HVP: H' = Aᵀ H A for the linear map A."""
        if self.is_identity:
            return hvp

        def wrapped(wp: Array, vp: Array) -> Array:
            w = self.coef_to_original(wp)
            av = self.coef_to_original(vp)  # A is linear: A·v
            _, pullback = jax.vjp(self.coef_to_original, wp)
            return pullback(hvp(w, av))[0]

        return wrapped

    def wrap_hvp_at(
        self, hvp_at: Callable[[Array], Callable[[Array], Array]]
    ) -> Callable[[Array], Callable[[Array], Array]]:
        """Factory form of ``wrap_hvp``: the original-space point and its
        pullback are computed once per x, preserving the inner factory's
        hoisting (TRON's CG loop calls only the returned ``v ↦ H'v``)."""
        if self.is_identity:
            return hvp_at

        def wrapped(wp: Array) -> Callable[[Array], Array]:
            w = self.coef_to_original(wp)
            _, pullback = jax.vjp(self.coef_to_original, wp)
            hv = hvp_at(w)
            return lambda vp: pullback(hv(self.coef_to_original(vp)))[0]

        return wrapped


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class LocalNormalizationContext:
    """Per-entity-subspace normalization for random effects (vmappable).

    The reference applies one NormalizationContext per feature shard to BOTH
    the fixed effect and every per-entity random-effect solve. Each entity
    sees only its projected feature subspace, so the shard-level factors /
    shifts are gathered through the entity's local→global projection, and the
    intercept position — which varies per entity — is carried as a one-hot
    vector instead of a static index so the whole context batches under
    ``vmap`` (leaves ``[E, P]`` → per-lane ``[P]``).

    Same coefficient-space algebra as ``NormalizationContext`` with the
    one-hot h replacing indexed updates: w = w'∘f − h·(w'∘f)ᵀs.
    Ghost slots (projection padding) carry factor 1 / shift 0, so they stay
    inert. Instances are only built for non-identity shard contexts.
    """

    factors: Optional[Array]          # [P] (or [E, P] before vmap)
    shifts: Optional[Array]
    intercept_onehot: Optional[Array]

    @property
    def is_identity(self) -> bool:
        return self.factors is None and self.shifts is None

    def _effective(self) -> tuple[Optional[Array], Optional[Array]]:
        # Sanitized at construction (project_context); nothing to force.
        return self.factors, self.shifts

    def coef_to_original(self, w: Array) -> Array:
        f, s = self.factors, self.shifts
        out = w if f is None else w * f
        if s is not None:
            out = out - self.intercept_onehot * jnp.sum(out * s)
        return out

    def coef_to_transformed(self, w: Array) -> Array:
        f, s = self.factors, self.shifts
        out = w
        if s is not None:
            out = out + self.intercept_onehot * jnp.sum(out * s)
        if f is not None:
            out = out / f
        return out

    # Same lifting as NormalizationContext (duck-typed in problem.run).
    wrap_value_and_grad = NormalizationContext.wrap_value_and_grad
    wrap_hvp = NormalizationContext.wrap_hvp
    wrap_hvp_at = NormalizationContext.wrap_hvp_at


def project_context(
    ctx: NormalizationContext,
    proj: Array,
    global_dim: int,
) -> Optional[LocalNormalizationContext]:
    """Gather a shard-level context into local subspace(s) through ``proj``
    (``[..., P]`` local→global column map; ghost slots hold ``global_dim``).

    Returns None for identity contexts. The shard context's intercept column
    (if any) becomes a one-hot over local slots.
    """
    if ctx.is_identity:
        return None
    f, s = ctx._effective()
    for vec in (f, s):
        if vec is not None and vec.shape[-1] != global_dim:
            raise ValueError(
                f"normalization context is {vec.shape[-1]}-dim but the "
                f"projection's global feature space is {global_dim}-dim"
            )

    def gather(vec: Optional[Array], ghost_fill: float) -> Optional[Array]:
        if vec is None:
            return None
        ext = jnp.concatenate(
            [vec, jnp.full((1,), ghost_fill, vec.dtype)]
        )
        return ext[proj]

    onehot = None
    if ctx.intercept_index is not None:
        onehot = (proj == ctx.intercept_index).astype(
            f.dtype if f is not None else s.dtype
        )
    if s is not None and onehot is None:  # pragma: no cover - ctx invariant
        raise ValueError("shifts require an intercept (NormalizationContext)")
    return LocalNormalizationContext(
        factors=gather(f, 1.0), shifts=gather(s, 0.0), intercept_onehot=onehot
    )


def identity_context(intercept_index: Optional[int] = None) -> NormalizationContext:
    return NormalizationContext(factors=None, shifts=None, intercept_index=intercept_index)


def context_from_statistics(
    stats: FeatureDataStatistics,
    ntype: NormalizationType,
    intercept_index: Optional[int] = None,
) -> NormalizationContext:
    """Build a context the way the reference's driver does from the feature
    summary (⟦NormalizationContext.apply(normalizationType, summary,
    interceptIdOpt)⟧). Zero-σ / zero-magnitude columns get factor 1."""
    if ntype == NormalizationType.NONE:
        return identity_context(intercept_index)

    def safe_inv(x: Array) -> Array:
        return jnp.where(x > 0, 1.0 / jnp.where(x > 0, x, 1.0), 1.0)

    factors = shifts = None
    if ntype == NormalizationType.SCALE_WITH_STANDARD_DEVIATION:
        factors = safe_inv(stats.std())
    elif ntype == NormalizationType.SCALE_WITH_MAX_MAGNITUDE:
        factors = safe_inv(stats.max_magnitude())
    elif ntype == NormalizationType.STANDARDIZATION:
        if intercept_index is None:
            raise ValueError(
                "STANDARDIZATION shifts features and therefore requires an "
                "intercept column (reference invariant)"
            )
        factors = safe_inv(stats.std())
        shifts = stats.mean
    else:  # pragma: no cover - enum is closed
        raise ValueError(f"unknown normalization type {ntype}")

    if intercept_index is not None:
        factors = factors.at[intercept_index].set(1.0)
        if shifts is not None:
            shifts = shifts.at[intercept_index].set(0.0)
    return NormalizationContext(
        factors=factors, shifts=shifts, intercept_index=intercept_index
    )
