// Native Avro block decoder for the streaming ingest path.
//
// The reference reads training data with spark-avro executors-wide
// (SURVEY.md §2.3 AvroDataReader); this library is the TPU rebuild's
// host-side equivalent: it decodes Avro *block payloads* (the container
// framing, codec inflate, and chunk assembly stay in Python —
// photon_tpu/io/streaming.py) straight into columnar buffers with zero
// per-record Python objects.
//
// Design:
//  * The Python side compiles the writer schema + reader config into
//    (a) a flattened pre-order TYPE TREE (int32 array) used for generic
//    value skipping, and (b) a PROGRAM: one op per top-level record field
//    (skip / numeric column / string column / feature bag / metadataMap).
//  * Feature (name, term) -> column-id lookup is an open-addressing hash
//    table (MurmurHash64A, linear probing) built by Python from the IndexMap
//    via ph_hash_keys — both sides share this file's hash implementation.
//  * String columns (uid, entity-id tags) are DICTIONARY-ENCODED: per-column
//    string->code maps persist across the whole stream, so Python only ever
//    materializes the unique values.
//  * All reads are bounds-checked; malformed input returns a negative error
//    code (never UB) which Python raises as SchemaError.
//
// ABI: plain C, driven via ctypes. All pointers passed into ph_create are
// copied; nothing is retained.

#include <charconv>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cmath>
#include <memory>
#include <string>
#include <vector>

namespace {

// ---- type-tree kinds (must match photon_tpu/io/streaming.py) ----
enum Kind : int32_t {
  K_NULL = 0, K_BOOL = 1, K_INT = 2, K_LONG = 3, K_FLOAT = 4, K_DOUBLE = 5,
  K_BYTES = 6, K_STRING = 7, K_FIXED = 8, K_ENUM = 9, K_ARRAY = 10,
  K_MAP = 11, K_RECORD = 12, K_UNION = 13,
};

// ---- program opcodes ----
enum Op : int32_t {
  OP_SKIP = 0,   // [op, ttree_off]
  OP_NUM = 1,    // [op, ttree_off, dst_col, only_if_unset]
  OP_STR = 2,    // [op, ttree_off, str_col, null_to_empty]
  OP_BAG = 3,    // [op, ttree_off, name_fpos, term_fpos, value_fpos, fast,
                 //  n_shards, shard_id * n_shards]  (one bag can feed several
                 //  feature shards, each through its own index table; fast=1
                 //  marks the exact NameTermValueAvro layout
                 //  [name: string, term: [null, string], value: double] which
                 //  takes a straight-line parse)
  OP_META = 4,   // [op, ttree_off, ntags, (tag_str_col, tag_name_id) * ntags]
};

enum Err : int64_t {
  E_TRUNCATED = -1, E_BADVARINT = -2, E_BADUNION = -3, E_BADTYPE = -4,
  E_TAGMISSING = -5, E_DEPTH = -6, E_NOMEM = -7,
};

struct Reader {
  const uint8_t* p;
  int64_t n;
  int64_t pos = 0;
  bool fail = false;
  int64_t err = 0;

  bool need(int64_t k) {
    if (pos + k > n) { fail = true; err = E_TRUNCATED; return false; }
    return true;
  }
  int64_t varint() {  // zigzag long
    uint64_t acc = 0;
    int shift = 0;
    while (true) {
      if (!need(1)) return 0;
      uint8_t b = p[pos++];
      acc |= (uint64_t)(b & 0x7F) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
      if (shift > 63) { fail = true; err = E_BADVARINT; return 0; }
    }
    return (int64_t)(acc >> 1) ^ -(int64_t)(acc & 1);
  }
  double f32() {
    if (!need(4)) return 0;
    float v; std::memcpy(&v, p + pos, 4); pos += 4; return (double)v;
  }
  double f64() {
    if (!need(8)) return 0;
    double v; std::memcpy(&v, p + pos, 8); pos += 8; return v;
  }
  // Returns (ptr, len) of a length-prefixed byte region (string/bytes).
  const uint8_t* lenprefixed(int64_t* len) {
    int64_t k = varint();
    if (fail || k < 0 || !need(k)) { fail = true; if (!err) err = E_TRUNCATED; *len = 0; return nullptr; }
    const uint8_t* r = p + pos;
    pos += k;
    *len = k;
    return r;
  }
};

// MurmurHash64A — 8 bytes per round (FNV-1a was the bottleneck of the bag
// hot loop at ~10 cycles/byte). The Python-side tables are built through
// ph_hash_keys, so both sides always share this exact function.
uint64_t hash64(const uint8_t* key, int64_t len) {
  const uint64_t m = 0xc6a4a7935bd1e995ULL;
  const int r = 47;
  uint64_t h = 0x8445d61a4e774912ULL ^ ((uint64_t)len * m);
  const uint8_t* p = key;
  const uint8_t* end = p + (len & ~7LL);
  while (p != end) {
    uint64_t k;
    std::memcpy(&k, p, 8);
    p += 8;
    k *= m; k ^= k >> r; k *= m;
    h ^= k; h *= m;
  }
  int tail = len & 7;
  if (tail) {
    uint64_t k = 0;
    std::memcpy(&k, p, tail);
    h ^= k; h *= m;
  }
  h ^= h >> r; h *= m; h ^= h >> r;
  return h;
}

constexpr uint8_t KEY_DELIM = 0x01;  // feature_key's name\x01term delimiter


// Alloc-free interning dictionary: open addressing keyed by the shared
// hash64, values appended to one heap; collisions verified against the heap
// bytes. The probe array holds ONLY the 8-byte hashes (payloads ride in a
// parallel array): at 10^6+ keys the table outgrows cache, and a repeat
// intern — the overwhelming case at 10^9 lookups over 10^6 uniques — then
// costs one miss on an 8B slot instead of one on a 24B slot (measured 1.24x
// end-to-end on the config-5 1M-feature index scan: 568s -> 459s over 31GB).
struct StrDict {
  struct Payload { int64_t off; int32_t len; int32_t code; };
  std::vector<uint64_t> hashes;
  std::vector<Payload> payloads;
  std::string heap;
  std::vector<int64_t> offsets{0};  // len = n_unique + 1
  size_t n = 0;

  StrDict() : hashes(1024), payloads(1024) {}

  void grow() {
    std::vector<uint64_t> oldh;
    std::vector<Payload> oldp;
    oldh.swap(hashes);
    oldp.swap(payloads);
    hashes.assign(oldh.size() * 2, 0);
    payloads.assign(oldp.size() * 2, Payload{0, 0, 0});
    uint64_t mask = hashes.size() - 1;
    for (size_t j = 0; j < oldh.size(); j++) {
      if (oldh[j] == 0) continue;
      uint64_t i = oldh[j] & mask;
      while (hashes[i] != 0) i = (i + 1) & mask;
      hashes[i] = oldh[j];
      payloads[i] = oldp[j];
    }
  }

  int32_t intern(const char* s, int64_t len) {
    if (2 * (n + 1) > hashes.size()) grow();
    uint64_t h = hash64((const uint8_t*)s, len);
    if (h == 0) h = 1;
    uint64_t mask = hashes.size() - 1;
    uint64_t i = h & mask;
    while (true) {
      uint64_t hv = hashes[i];
      if (hv == 0) {
        hashes[i] = h;
        payloads[i] = Payload{(int64_t)heap.size(), (int32_t)len,
                              (int32_t)n};
        n++;
        heap.append(s, (size_t)len);
        offsets.push_back((int64_t)heap.size());
        return payloads[i].code;
      }
      if (hv == h) {
        const Payload& p = payloads[i];
        if (p.len == len &&
            std::memcmp(heap.data() + p.off, s, (size_t)len) == 0)
          return p.code;
      }
      i = (i + 1) & mask;
    }
  }
};

struct ShardOut {
  // Feature hash table: split hash/value arrays. At bench-scale tables
  // (<=2^18 features) either layout is cache-resident; at config-5 scale
  // (10^6 features, 2M slots) the 16 MB hash-only probe array stays far
  // closer to cache than 32 MB of interleaved slots, and the 4-byte value
  // is touched only on a hit.
  std::vector<uint64_t> table_h;
  std::vector<int32_t> table_v;
  uint64_t mask = 0;
  // Per-chunk triples, emitted in row-major order.
  std::vector<int32_t> rows;
  std::vector<int32_t> idx;
  std::vector<double> val;
  // Parsed-but-unprobed features for the CURRENT container block (SoA).
  // Probing is deferred to a per-block flush whose software-pipelined
  // prefetch gives every table lookup a controlled ~16-probe lead: the
  // measured ablation on the ingest bench is 73 ns/entry probing inline,
  // 45 ns with per-row batching, and the block flush beats both because
  // the prefetch distance no longer depends on the row's bag length.
  std::vector<uint64_t> pend_h;
  std::vector<int32_t> pend_row;
  std::vector<double> pend_val;
  size_t pend_mark = 0;  // pend size at current record start (error rollback)
  // Index-build ("collect") mode: no table; every decoded feature key
  // (name\x01term) interns here in first-seen order, no triples emitted.
  bool collect = false;
  StrDict keys;
};

struct State {
  std::vector<int32_t> ttree;
  std::vector<int32_t> ops;          // flattened program
  std::vector<int32_t> op_starts;    // offset of each op in `ops`
  int32_t n_num = 0, n_str = 0;
  std::vector<double> null_defaults; // per numeric column
  std::vector<std::string> tag_names;
  std::vector<ShardOut> shards;
  std::vector<StrDict> dicts;        // per string column
  // chunk buffers
  std::vector<std::vector<double>> num_cols;
  std::vector<std::vector<int32_t>> str_codes;  // -1 = unset
  int64_t n_rows = 0;
  // scratch (per record)
  std::vector<double> cur_num;
  std::vector<int32_t> cur_str;
  std::vector<uint8_t> keybuf;       // scratch for collect-mode key assembly
  char fmtbuf[64];
};

// THE one key-layout definition (name\x01term) shared by the probe hash
// (stack or keybuf destination) and collect-mode interning, so the bytes
// the tables were built from and the bytes probed can never drift.
inline int64_t assemble_feature_key(uint8_t* dst, const uint8_t* name,
                                    int64_t nlen, const uint8_t* term,
                                    int64_t tlen) {
  std::memcpy(dst, name, (size_t)nlen);
  dst[nlen] = KEY_DELIM;
  if (tlen) std::memcpy(dst + nlen + 1, term, (size_t)tlen);
  return nlen + 1 + tlen;
}

// Assemble into st.keybuf (reused across calls — no per-call allocation
// once warm): the heap destination for collect-mode interning and
// over-long keys.
int64_t build_feature_key(State& st, const uint8_t* name, int64_t nlen,
                          const uint8_t* term, int64_t tlen) {
  st.keybuf.resize((size_t)(nlen + 1 + tlen));
  return assemble_feature_key(st.keybuf.data(), name, nlen, term, tlen);
}

// Returns 0 never (0 is the probe table's empty sentinel).
uint64_t hash_feature_key(State& st, const uint8_t* name, int64_t nlen,
                          const uint8_t* term, int64_t tlen) {
  const int64_t len = nlen + 1 + tlen;
  if (len <= 56) {
    // Hot case (feature keys are short): concatenate on the stack — no
    // vector resize branch, no heap indirection, and the compiler keeps
    // the buffer in registers/L1 for the immediately-following hash.
    uint8_t buf[56];
    assemble_feature_key(buf, name, nlen, term, tlen);
    uint64_t h = hash64(buf, len);
    return h == 0 ? 1 : h;
  }
  build_feature_key(st, name, nlen, term, tlen);
  uint64_t h = hash64(st.keybuf.data(), len);
  return h == 0 ? 1 : h;
}

void collect_feature(State& st, const int32_t* op, int32_t n_sh,
                     const uint8_t* name, int64_t nlen,
                     const uint8_t* term, int64_t tlen) {
  int64_t klen = build_feature_key(st, name, nlen, term, tlen);
  for (int32_t si = 0; si < n_sh; si++) {
    ShardOut& sh = st.shards[op[7 + si]];
    if (sh.collect)
      sh.keys.intern((const char*)st.keybuf.data(), klen);
  }
}

// ---- generic skip driven by the type tree ----
bool skip_value(const State& st, Reader& r, int32_t o, int depth) {
  if (depth > 64) { r.fail = true; r.err = E_DEPTH; return false; }
  const int32_t* t = st.ttree.data();
  switch (t[o]) {
    case K_NULL: return true;
    case K_BOOL: if (!r.need(1)) return false; r.pos += 1; return true;
    case K_INT: case K_LONG: case K_ENUM: r.varint(); return !r.fail;
    case K_FLOAT: if (!r.need(4)) return false; r.pos += 4; return true;
    case K_DOUBLE: if (!r.need(8)) return false; r.pos += 8; return true;
    case K_BYTES: case K_STRING: {
      int64_t len; r.lenprefixed(&len); return !r.fail;
    }
    case K_FIXED: {
      int64_t k = t[o + 1];
      if (!r.need(k)) return false; r.pos += k; return true;
    }
    case K_ARRAY: case K_MAP: {
      bool is_map = t[o] == K_MAP;
      int32_t child = t[o + 1];
      while (true) {
        int64_t cnt = r.varint();
        if (r.fail) return false;
        if (cnt == 0) return true;
        if (cnt < 0) {  // block with byte size: skip wholesale
          int64_t bytes = r.varint();
          if (r.fail || bytes < 0 || !r.need(bytes)) { r.fail = true; if (!r.err) r.err = E_TRUNCATED; return false; }
          r.pos += bytes;
          continue;
        }
        for (int64_t i = 0; i < cnt; i++) {
          if (is_map) { int64_t len; r.lenprefixed(&len); if (r.fail) return false; }
          if (!skip_value(st, r, child, depth + 1)) return false;
        }
      }
    }
    case K_RECORD: {
      int32_t nf = t[o + 1];
      for (int32_t i = 0; i < nf; i++)
        if (!skip_value(st, r, t[o + 2 + i], depth + 1)) return false;
      return true;
    }
    case K_UNION: {
      int64_t br = r.varint();
      if (r.fail) return false;
      if (br < 0 || br >= t[o + 1]) { r.fail = true; r.err = E_BADUNION; return false; }
      return skip_value(st, r, t[o + 2 + br], depth + 1);
    }
    default: r.fail = true; r.err = E_BADTYPE; return false;
  }
}

// Walk through unions at runtime to a concrete node; returns -1 on error.
int32_t resolve_node(const State& st, Reader& r, int32_t o) {
  const int32_t* t = st.ttree.data();
  int guard = 0;
  while (t[o] == K_UNION) {
    int64_t br = r.varint();
    if (r.fail) return -1;
    if (br < 0 || br >= t[o + 1]) { r.fail = true; r.err = E_BADUNION; return -1; }
    o = t[o + 2 + br];
    if (++guard > 16) { r.fail = true; r.err = E_DEPTH; return -1; }
  }
  return o;
}

// Read a concrete-node numeric value as double. has_value=false for null.
bool read_numeric(const State& st, Reader& r, int32_t o, double* out, bool* has_value) {
  const int32_t* t = st.ttree.data();
  *has_value = true;
  switch (t[o]) {
    case K_NULL: *has_value = false; return true;
    case K_BOOL: if (!r.need(1)) return false; *out = r.p[r.pos++] ? 1.0 : 0.0; return true;
    case K_INT: case K_LONG: *out = (double)r.varint(); return !r.fail;
    case K_FLOAT: *out = r.f32(); return !r.fail;
    case K_DOUBLE: *out = r.f64(); return !r.fail;
    default: r.fail = true; r.err = E_BADTYPE; return false;
  }
}

// Read a concrete node as a string (for uid / tags / metadata values).
// Numerics are stringified like Python str(): longs as decimal, doubles with
// %.17g plus a ".0" suffix when integral-looking. null -> has_value=false.
bool read_stringish(State& st, Reader& r, int32_t o, const char** s, int64_t* len, bool* has_value) {
  const int32_t* t = st.ttree.data();
  *has_value = true;
  switch (t[o]) {
    case K_NULL: *has_value = false; return true;
    case K_STRING: case K_BYTES: {
      const uint8_t* p = r.lenprefixed(len);
      if (r.fail) return false;
      *s = (const char*)p;
      return true;
    }
    case K_INT: case K_LONG: {
      int64_t v = r.varint();
      if (r.fail) return false;
      *len = std::snprintf(st.fmtbuf, sizeof st.fmtbuf, "%lld", (long long)v);
      *s = st.fmtbuf;
      return true;
    }
    case K_FLOAT: case K_DOUBLE: {
      double v = t[o] == K_FLOAT ? r.f32() : r.f64();
      if (r.fail) return false;
      // Shortest round-trip repr (std::to_chars), matching Python's str():
      // str(0.1) == "0.1", not "%.17g"'s "0.10000000000000001".
      int n;
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
      auto res = std::to_chars(st.fmtbuf, st.fmtbuf + sizeof st.fmtbuf - 2, v);
      n = (int)(res.ptr - st.fmtbuf);
#else
      // libstdc++ < 11 has no floating-point to_chars: emit the shortest
      // %g repr that round-trips (tries rising precision, like repr()).
      // snprintf and strtod share LC_NUMERIC, so the round-trip check is
      // locale-consistent; the separator then normalizes to '.' so a
      // host process that setlocale()d can't leak "3,14" into output.
      n = 0;
      for (int prec = 15; prec <= 17; prec++) {
        n = std::snprintf(st.fmtbuf, sizeof st.fmtbuf - 2, "%.*g", prec, v);
        char* endp = nullptr;
        double back = std::strtod(st.fmtbuf, &endp);
        if (endp == st.fmtbuf + n && back == v) break;  // NaN: runs to 17
      }
      for (int i = 0; i < n; i++)
        if (st.fmtbuf[i] == ',') st.fmtbuf[i] = '.';
#endif
      // str(3.0) == "3.0": add .0 when the repr has no '.', 'e', or specials.
      bool plain = true;
      for (int i = 0; i < n; i++) {
        char c = st.fmtbuf[i];
        if (c == '.' || c == 'e' || c == 'E' || c == 'n' || c == 'i') plain = false;
      }
      if (plain && n + 2 < (int)sizeof st.fmtbuf) {
        st.fmtbuf[n] = '.'; st.fmtbuf[n + 1] = '0'; n += 2;
      }
      *len = n; *s = st.fmtbuf;
      return true;
    }
    case K_BOOL: {
      if (!r.need(1)) return false;
      bool b = r.p[r.pos++];
      *len = std::snprintf(st.fmtbuf, sizeof st.fmtbuf, b ? "True" : "False");
      *s = st.fmtbuf;
      return true;
    }
    case K_ENUM: { r.varint(); if (r.fail) return false; *has_value = false; return true; }
    default: r.fail = true; r.err = E_BADTYPE; return false;
  }
}

int32_t probe(const ShardOut& sh, uint64_t h) {
  if (sh.mask == 0) return -1;
  uint64_t i = h & sh.mask;
  while (true) {
    uint64_t hv = sh.table_h[i];
    if (hv == h) return sh.table_v[i];
    if (hv == 0) return -1;  // empty sentinel (hash 0 excluded at build)
    i = (i + 1) & sh.mask;
  }
}

// Probe + emit every pending feature of the block, software-pipelined:
// prefetch the table lines PD probes ahead so the (L2/L3-resident at real
// feature counts) random lookups overlap instead of serializing.
void flush_pending(State& st) {
  constexpr size_t PD = 16;
  for (ShardOut& sh : st.shards) {
    const size_t n = sh.pend_h.size();
    if (n == 0) continue;
    for (size_t i = 0; i < n; i++) {
      if (sh.mask && i + PD < n) {
        const uint64_t hp = sh.pend_h[i + PD];
        __builtin_prefetch(&sh.table_h[hp & sh.mask], 0, 1);
        __builtin_prefetch(&sh.table_v[hp & sh.mask], 0, 1);
      }
      const int32_t col = probe(sh, sh.pend_h[i]);
      if (col >= 0) {
        sh.rows.push_back(sh.pend_row[i]);
        sh.idx.push_back(col);
        sh.val.push_back(sh.pend_val[i]);
      }
    }
    sh.pend_h.clear();
    sh.pend_row.clear();
    sh.pend_val.clear();
  }
}

bool decode_record(State& st, Reader& r) {
  const int32_t* t = st.ttree.data();
  std::fill(st.cur_num.begin(), st.cur_num.end(), NAN);
  std::fill(st.cur_str.begin(), st.cur_str.end(), -1);

  for (size_t oi = 0; oi < st.op_starts.size(); oi++) {
    const int32_t* op = st.ops.data() + st.op_starts[oi];
    switch (op[0]) {
      case OP_SKIP: {
        if (!skip_value(st, r, op[1], 0)) return false;
        break;
      }
      case OP_NUM: {
        int32_t o = resolve_node(st, r, op[1]);
        if (o < 0) return false;
        double v; bool has;
        if (!read_numeric(st, r, o, &v, &has)) return false;
        if (has && !(op[3] && !std::isnan(st.cur_num[op[2]])))
          st.cur_num[op[2]] = v;
        break;
      }
      case OP_STR: {
        int32_t o = resolve_node(st, r, op[1]);
        if (o < 0) return false;
        const char* s = ""; int64_t len = 0; bool has;
        if (!read_stringish(st, r, o, &s, &len, &has)) return false;
        if (!has && op[3]) { s = ""; len = 0; has = true; }  // null -> ""
        // Unconditional write: a non-null top-level field always wins over a
        // metadataMap entry regardless of schema field order (OP_META is
        // fill-if-unset; this op overwrites).
        if (has)
          st.cur_str[op[2]] = st.dicts[op[2]].intern(s, len);
        break;
      }
      case OP_BAG: {
        int32_t o = resolve_node(st, r, op[1]);  // null union -> no bag
        if (o < 0) return false;
        if (t[o] == K_NULL) break;
        if (t[o] != K_ARRAY) { r.fail = true; r.err = E_BADTYPE; return false; }
        int32_t rec_o = t[o + 1];
        if (t[rec_o] != K_RECORD) { r.fail = true; r.err = E_BADTYPE; return false; }
        int32_t nf = t[rec_o + 1];
        bool fast = op[5];
        int32_t n_sh = op[6];
        bool any_coll = false, any_probe = false;
        for (int32_t si = 0; si < n_sh; si++) {
          if (st.shards[op[7 + si]].collect) any_coll = true;
          else any_probe = true;
        }
        while (true) {
          int64_t cnt = r.varint();
          if (r.fail) return false;
          if (cnt == 0) break;
          if (cnt < 0) { r.varint(); cnt = -cnt; if (r.fail) return false; }
          if (fast) {
            // Exact NameTermValueAvro layout: straight-line parse. Probing
            // is deferred to flush_pending's block-granular pipeline (see
            // its comment) — this loop only hashes and queues.
            for (int64_t item = 0; item < cnt; item++) {
              int64_t nlen; const uint8_t* np_ = r.lenprefixed(&nlen);
              if (r.fail) return false;
              int64_t br = r.varint();
              if (r.fail) return false;
              const uint8_t* tp = nullptr; int64_t tlen = 0;
              if (br == 1) {
                tp = r.lenprefixed(&tlen);
                if (r.fail) return false;
              } else if (br != 0) { r.fail = true; r.err = E_BADUNION; return false; }
              double v = r.f64();
              if (r.fail) return false;
              if (any_coll)
                collect_feature(st, op, n_sh, np_, nlen, tp, tlen);
              if (any_probe) {  // pure-collect ops skip hash/probe entirely
                uint64_t h = hash_feature_key(st, np_, nlen, tp, tlen);
                for (int32_t si = 0; si < n_sh; si++) {
                  ShardOut& sh = st.shards[op[7 + si]];
                  if (sh.collect) continue;
                  sh.pend_h.push_back(h);
                  sh.pend_row.push_back((int32_t)st.n_rows);
                  sh.pend_val.push_back(v);
                }
              }
            }
          } else {
            for (int64_t item = 0; item < cnt; item++) {
              const char* name = nullptr; int64_t name_len = 0;
              const char* term = nullptr; int64_t term_len = 0;
              double fval = 0; bool have_val = false;
              for (int32_t f = 0; f < nf; f++) {
                int32_t fo = t[rec_o + 2 + f];
                if (f == op[2] || f == op[3]) {  // name / term
                  int32_t c = resolve_node(st, r, fo);
                  if (c < 0) return false;
                  const char* s = nullptr; int64_t len = 0; bool has;
                  if (!read_stringish(st, r, c, &s, &len, &has)) return false;
                  // name/term point into the payload (strings only there);
                  // stringified numerics would alias fmtbuf — treat absent.
                  if (has && s != st.fmtbuf) {
                    if (f == op[2]) { name = s; name_len = len; }
                    else { term = s; term_len = len; }
                  }
                } else if (f == op[4]) {  // value
                  int32_t c = resolve_node(st, r, fo);
                  if (c < 0) return false;
                  if (!read_numeric(st, r, c, &fval, &have_val)) return false;
                } else {
                  if (!skip_value(st, r, fo, 0)) return false;
                }
              }
              if (name == nullptr) continue;
              // Index build sees every named feature — including ones with
              // a null value, which emit no triple but ARE indexed (parity
              // with the per-record scan).
              if (any_coll)
                collect_feature(st, op, n_sh, (const uint8_t*)name, name_len,
                                (const uint8_t*)(term != nullptr ? term : ""),
                                term != nullptr ? term_len : 0);
              if (!have_val || !any_probe) continue;
              uint64_t h = hash_feature_key(
                  st, (const uint8_t*)name, name_len,
                  (const uint8_t*)(term != nullptr ? term : ""),
                  term != nullptr ? term_len : 0);
              for (int32_t si = 0; si < n_sh; si++) {
                ShardOut& sh = st.shards[op[7 + si]];
                if (sh.collect) continue;
                sh.pend_h.push_back(h);
                sh.pend_row.push_back((int32_t)st.n_rows);
                sh.pend_val.push_back(fval);
              }
            }
          }
        }
        // Probing is deferred to flush_pending (block granularity).
        break;
      }
      case OP_META: {
        int32_t o = resolve_node(st, r, op[1]);
        if (o < 0) return false;
        if (t[o] == K_NULL) break;
        if (t[o] != K_MAP) { r.fail = true; r.err = E_BADTYPE; return false; }
        int32_t val_o = t[o + 1];
        int32_t ntags = op[2];
        while (true) {
          int64_t cnt = r.varint();
          if (r.fail) return false;
          if (cnt == 0) break;
          if (cnt < 0) { r.varint(); cnt = -cnt; if (r.fail) return false; }
          for (int64_t item = 0; item < cnt; item++) {
            int64_t klen; const uint8_t* k = r.lenprefixed(&klen);
            if (r.fail) return false;
            int32_t hit_col = -1;
            for (int32_t tg = 0; tg < ntags; tg++) {
              const std::string& nm = st.tag_names[op[3 + 2 * tg + 1]];
              if ((int64_t)nm.size() == klen && std::memcmp(nm.data(), k, klen) == 0) {
                hit_col = op[3 + 2 * tg];
                break;
              }
            }
            if (hit_col >= 0 && st.cur_str[hit_col] < 0) {
              int32_t c = resolve_node(st, r, val_o);
              if (c < 0) return false;
              const char* s = ""; int64_t len = 0; bool has;
              if (!read_stringish(st, r, c, &s, &len, &has)) return false;
              if (has) st.cur_str[hit_col] = st.dicts[hit_col].intern(s, len);
            } else {
              if (!skip_value(st, r, val_o, 0)) return false;
            }
          }
        }
        break;
      }
      default: r.fail = true; r.err = E_BADTYPE; return false;
    }
  }

  for (int32_t c = 0; c < st.n_num; c++) {
    double v = st.cur_num[c];
    st.num_cols[c].push_back(std::isnan(v) ? st.null_defaults[c] : v);
  }
  for (int32_t c = 0; c < st.n_str; c++)
    st.str_codes[c].push_back(st.cur_str[c]);
  st.n_rows++;
  return true;
}

}  // namespace

extern "C" {

void ph_hash_keys(const uint8_t* blob, const int64_t* offs, int64_t n, uint64_t* out) {
  for (int64_t i = 0; i < n; i++) {
    uint64_t h = hash64(blob + offs[i], offs[i + 1] - offs[i]);
    out[i] = h == 0 ? 1 : h;
  }
}

// try/catch at EVERY allocating ABI entry: a std::bad_alloc (host under
// memory pressure — e.g. a co-located 60 GB training run) thrown through
// the extern "C" / ctypes boundary is undefined behavior that in practice
// reaches std::terminate -> abort -> a "Fatal Python error: Aborted" that
// kills the whole interpreter. Allocation failure must surface as a
// catchable Python exception (nullptr / E_NOMEM), not a crashed process.
void* ph_create(
    const int32_t* ttree, int64_t ttree_len,
    const int32_t* ops, int64_t ops_len,
    const int64_t* op_starts, int64_t n_ops,
    int32_t n_num, const double* null_defaults,
    int32_t n_str,
    const uint8_t* tag_blob, const int64_t* tag_offs, int64_t n_tag_names,
    int32_t n_shards, const uint64_t** table_hashes, const int32_t** table_vals,
    const int64_t* table_sizes) try {
  std::unique_ptr<State> owned(new State());
  State* st = owned.get();
  st->ttree.assign(ttree, ttree + ttree_len);
  st->ops.assign(ops, ops + ops_len);
  st->op_starts.assign(op_starts, op_starts + n_ops);
  st->n_num = n_num;
  st->null_defaults.assign(null_defaults, null_defaults + n_num);
  st->n_str = n_str;
  for (int64_t i = 0; i < n_tag_names; i++)
    st->tag_names.emplace_back((const char*)tag_blob + tag_offs[i],
                               (size_t)(tag_offs[i + 1] - tag_offs[i]));
  st->shards.resize(n_shards);
  for (int32_t s = 0; s < n_shards; s++) {
    ShardOut& sh = st->shards[s];
    if (table_sizes[s] < 0) {  // collect (index-build) mode: no table
      sh.collect = true;
      continue;
    }
    sh.table_h.assign(table_hashes[s], table_hashes[s] + table_sizes[s]);
    sh.table_v.assign(table_vals[s], table_vals[s] + table_sizes[s]);
    sh.mask = table_sizes[s] ? (uint64_t)(table_sizes[s] - 1) : 0;
  }
  st->dicts.resize(n_str);
  st->num_cols.resize(n_num);
  st->str_codes.resize(n_str);
  st->cur_num.resize(n_num);
  st->cur_str.resize(n_str);
  return owned.release();
} catch (...) {
  return nullptr;  // caller raises MemoryError("ph_create failed")
}

void ph_destroy(void* p) { delete (State*)p; }

// Decode `count` records from an (already-inflated) block payload.
// Returns rows decoded so far in this chunk, or a negative error code.
int64_t ph_decode_block(void* p, const uint8_t* payload, int64_t size, int64_t count) try {
  State& st = *(State*)p;
  Reader r{payload, size};
  for (int64_t i = 0; i < count; i++) {
    for (ShardOut& sh : st.shards) sh.pend_mark = sh.pend_h.size();
    if (!decode_record(st, r)) {
      // Roll the failed record's partially-queued features back BEFORE the
      // flush: they carry row id == n_rows, which is never incremented for
      // the failed record — emitting them would alias the next decoded
      // record's row (and can index past a caller's (n, k) ELL arrays).
      // Completed rows' features stay valid.
      for (ShardOut& sh : st.shards) {
        sh.pend_h.resize(sh.pend_mark);
        sh.pend_row.resize(sh.pend_mark);
        sh.pend_val.resize(sh.pend_mark);
      }
      flush_pending(st);
      return r.err ? r.err : E_TRUNCATED;
    }
  }
  flush_pending(st);
  if (r.pos != r.n) return E_TRUNCATED;  // trailing garbage = framing bug
  return st.n_rows;
} catch (...) {
  // Almost certainly bad_alloc from a buffer growth mid-decode; the chunk
  // state is now incoherent, so the caller must treat this decoder as
  // dead (the raised error aborts the stream — correct: rows were lost).
  return E_NOMEM;
}

int64_t ph_chunk_rows(void* p) { return ((State*)p)->n_rows; }

// Scatter row-major (row, col, value) triples into preinitialized padded ELL
// arrays ([n_rows, k]; iarr prefilled with the ghost column, varr with 0).
// `rows` must be nondecreasing — exactly the order ph_get_shard_triples
// emits — so each entry's slot is a running position within its row, no
// per-row counts or index arithmetic arrays needed (replaces the numpy
// fancy-index scatter that was ~26% of ingest time).
void ph_ell_scatter_f32(const int32_t* rows, const int32_t* idx,
                        const double* val, int64_t nnz, int64_t k,
                        int64_t base, int32_t* iarr, float* varr) {
  int64_t pos = 0;
  int32_t cur = -1;
  for (int64_t e = 0; e < nnz; e++) {
    int32_t r = rows[e];
    if (r != cur) { cur = r; pos = base; }
    int64_t o = (int64_t)r * k + pos++;
    iarr[o] = idx[e];
    varr[o] = (float)val[e];
  }
}

void ph_ell_scatter_f64(const int32_t* rows, const int32_t* idx,
                        const double* val, int64_t nnz, int64_t k,
                        int64_t base, int32_t* iarr, double* varr) {
  int64_t pos = 0;
  int32_t cur = -1;
  for (int64_t e = 0; e < nnz; e++) {
    int32_t r = rows[e];
    if (r != cur) { cur = r; pos = base; }
    int64_t o = (int64_t)r * k + pos++;
    iarr[o] = idx[e];
    varr[o] = val[e];
  }
}

void ph_get_num_col(void* p, int32_t col, double* out) {
  State& st = *(State*)p;
  std::memcpy(out, st.num_cols[col].data(), st.num_cols[col].size() * 8);
}

void ph_get_str_codes(void* p, int32_t col, int32_t* out) {
  State& st = *(State*)p;
  std::memcpy(out, st.str_codes[col].data(), st.str_codes[col].size() * 4);
}

int64_t ph_shard_nnz(void* p, int32_t shard) {
  return (int64_t)((State*)p)->shards[shard].rows.size();
}

void ph_get_shard_triples(void* p, int32_t shard, int32_t* rows, int32_t* idx, double* val) {
  ShardOut& sh = ((State*)p)->shards[shard];
  std::memcpy(rows, sh.rows.data(), sh.rows.size() * 4);
  std::memcpy(idx, sh.idx.data(), sh.idx.size() * 4);
  std::memcpy(val, sh.val.data(), sh.val.size() * 8);
}

// Direct ELL assembly from the internal row-major triples: ONE pass writes
// entries AND ghost padding straight into the caller's (n_rows, k) arrays.
// Replaces the take-triples -> numpy-bincount -> full/zeros-fill -> scatter
// pipeline on the Python side (three extra O(nnz)+O(n_rows*k) passes and
// ~20 B/entry of copies) with a single native walk.
int64_t ph_shard_max_run(void* p, int32_t shard) {
  // rows is row-major ordered, so the per-row count = longest run.
  ShardOut& sh = ((State*)p)->shards[shard];
  int64_t best = 0, cur = 0;
  int32_t prev = -1;
  for (int32_t r : sh.rows) {
    if (r == prev) {
      cur++;
    } else {
      prev = r;
      cur = 1;
    }
    if (cur > best) best = cur;
  }
  return best;
}

}  // extern "C" — a template cannot carry C linkage; reopened below.

template <typename T>
static void ell_direct(const ShardOut& sh, int64_t n_rows, int64_t k,
                       int64_t icol, int64_t pad_col, int32_t* iarr,
                       T* varr) {
  const int64_t base = icol >= 0 ? 1 : 0;
  const int64_t nnz = (int64_t)sh.rows.size();
  int64_t t = 0;
  for (int64_t r = 0; r < n_rows; r++) {
    int32_t* ip = iarr + r * k;
    T* vp = varr + r * k;
    int64_t c = 0;
    if (base) {
      ip[0] = (int32_t)icol;
      vp[0] = (T)1.0;
      c = 1;
    }
    // Bounded by c < k: callers derive k from ph_shard_max_run, but that
    // invariant crosses a ctypes boundary — a mismatched k must truncate
    // the row, never write past the caller's (n, k) slot. The scan still
    // consumes the whole run so row alignment survives truncation.
    for (; t < nnz && sh.rows[t] == (int32_t)r; t++) {
      if (c < k) {
        ip[c] = sh.idx[t];
        vp[c] = (T)sh.val[t];
        c++;
      }
    }
    for (; c < k; c++) {
      ip[c] = (int32_t)pad_col;
      vp[c] = (T)0.0;
    }
  }
}

extern "C" void ph_shard_ell_f32(void* p, int32_t shard, int64_t n_rows,
                                 int64_t k, int64_t icol, int64_t pad_col,
                                 int32_t* iarr, float* varr) {
  ell_direct(((State*)p)->shards[shard], n_rows, k, icol, pad_col, iarr, varr);
}

extern "C" void ph_shard_ell_f64(void* p, int32_t shard, int64_t n_rows,
                                 int64_t k, int64_t icol, int64_t pad_col,
                                 int32_t* iarr, double* varr) {
  ell_direct(((State*)p)->shards[shard], n_rows, k, icol, pad_col, iarr, varr);
}

extern "C" {  // remaining exports continue with C linkage

// Dictionary snapshots for one string column. The *_range forms fetch only
// entries [start, size) so per-chunk snapshots cost O(new entries), not
// O(all entries) — dictionaries grow monotonically across the stream.
static int64_t dict_size(const StrDict& d) {
  return (int64_t)d.offsets.size() - 1;
}
static int64_t dict_heap_bytes_from(const StrDict& d, int64_t start) {
  return (int64_t)d.heap.size() - d.offsets[start];
}
static void dict_range(const StrDict& d, int64_t start, uint8_t* heap,
                       int64_t* offsets) {
  int64_t base = d.offsets[start];
  int64_t n = (int64_t)d.offsets.size() - 1 - start;
  std::memcpy(heap, d.heap.data() + base, d.heap.size() - base);
  for (int64_t i = 0; i <= n; i++) offsets[i] = d.offsets[start + i] - base;
}

int64_t ph_dict_size(void* p, int32_t col) {
  return dict_size(((State*)p)->dicts[col]);
}
int64_t ph_dict_heap_bytes_from(void* p, int32_t col, int64_t start) {
  return dict_heap_bytes_from(((State*)p)->dicts[col], start);
}
void ph_get_dict_range(void* p, int32_t col, int64_t start, uint8_t* heap,
                       int64_t* offsets) {
  dict_range(((State*)p)->dicts[col], start, heap, offsets);
}

// Collected feature-key dictionaries for collect-mode shards (same range
// protocol as the string-column dicts; keys are name\x01term bytes in
// first-seen order, persisting across chunk resets).
int64_t ph_shard_dict_size(void* p, int32_t shard) {
  return dict_size(((State*)p)->shards[shard].keys);
}
int64_t ph_shard_dict_heap_bytes_from(void* p, int32_t shard, int64_t start) {
  return dict_heap_bytes_from(((State*)p)->shards[shard].keys, start);
}
void ph_shard_dict_range(void* p, int32_t shard, int64_t start, uint8_t* heap,
                         int64_t* offsets) {
  dict_range(((State*)p)->shards[shard].keys, start, heap, offsets);
}

// Clear per-chunk row buffers; dictionaries persist across chunks.
void ph_reset_chunk(void* p) {
  State& st = *(State*)p;
  st.n_rows = 0;
  for (auto& c : st.num_cols) c.clear();
  for (auto& c : st.str_codes) c.clear();
  for (auto& sh : st.shards) {
    sh.rows.clear(); sh.idx.clear(); sh.val.clear();
    sh.pend_h.clear(); sh.pend_row.clear(); sh.pend_val.clear();
  }
}

}  // extern "C"
