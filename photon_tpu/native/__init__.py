"""Native (C++) components, compiled on demand with the system toolchain.

The only native component is the Avro block decoder (``avro_block.cc``) used
by :mod:`photon_tpu.io.streaming`. It is compiled once per source change with
``g++ -O3 -shared`` into this directory and loaded via ctypes; if no compiler
is available (or ``PHOTON_TPU_NO_NATIVE=1``), callers fall back to the pure
Python codec (``photon_tpu.io.avro``) — slower, identical semantics.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "avro_block.cc")


def _isa_tag() -> str:
    """Short tag of this host's vector ISA, so a -march=native build cached
    in a checkout shared over a network filesystem is never dlopen'd by a
    host with a different instruction set (SIGILL). crc32, not md5: FIPS
    hosts raise on md5, and this is a cache key, not cryptography."""
    import platform
    import zlib

    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    return f"{zlib.crc32(line.encode()) & 0xFFFFFFFF:08x}"
    except OSError:
        pass
    return platform.machine() or "unknown"


_SO = os.path.join(_HERE, f"_avro_block.{_isa_tag()}.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_failed = False


def _compile() -> bool:
    cmd = [
        # -march=native is safe here: the .so is compiled on demand on the
        # same host that runs it (never shipped), and the hash/parse inner
        # loops gain measurably from host vector ISA.
        "g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
        "-o", _SO + ".tmp", _SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
    except (OSError, subprocess.SubprocessError):
        return False
    os.replace(_SO + ".tmp", _SO)
    return True


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    i64, i32, u64, f64, u8 = (
        ctypes.c_int64, ctypes.c_int32, ctypes.c_uint64, ctypes.c_double,
        ctypes.c_uint8,
    )
    P = ctypes.POINTER
    lib.ph_hash_keys.argtypes = [P(u8), P(i64), i64, P(u64)]
    lib.ph_hash_keys.restype = None
    lib.ph_create.argtypes = [
        P(i32), i64, P(i32), i64, P(i64), i64,
        i32, P(f64), i32,
        P(u8), P(i64), i64,
        i32, P(P(u64)), P(P(i32)), P(i64),
    ]
    lib.ph_create.restype = ctypes.c_void_p
    lib.ph_destroy.argtypes = [ctypes.c_void_p]
    lib.ph_decode_block.argtypes = [ctypes.c_void_p, P(u8), i64, i64]
    lib.ph_decode_block.restype = i64
    lib.ph_chunk_rows.argtypes = [ctypes.c_void_p]
    lib.ph_chunk_rows.restype = i64
    lib.ph_get_num_col.argtypes = [ctypes.c_void_p, i32, P(f64)]
    lib.ph_get_str_codes.argtypes = [ctypes.c_void_p, i32, P(i32)]
    lib.ph_shard_nnz.argtypes = [ctypes.c_void_p, i32]
    lib.ph_shard_nnz.restype = i64
    lib.ph_get_shard_triples.argtypes = [ctypes.c_void_p, i32, P(i32), P(i32), P(f64)]
    lib.ph_dict_size.argtypes = [ctypes.c_void_p, i32]
    lib.ph_dict_size.restype = i64
    lib.ph_dict_heap_bytes_from.argtypes = [ctypes.c_void_p, i32, i64]
    lib.ph_dict_heap_bytes_from.restype = i64
    lib.ph_get_dict_range.argtypes = [ctypes.c_void_p, i32, i64, P(u8), P(i64)]
    lib.ph_shard_dict_size.argtypes = [ctypes.c_void_p, i32]
    lib.ph_shard_dict_size.restype = i64
    lib.ph_shard_dict_heap_bytes_from.argtypes = [ctypes.c_void_p, i32, i64]
    lib.ph_shard_dict_heap_bytes_from.restype = i64
    lib.ph_shard_dict_range.argtypes = [ctypes.c_void_p, i32, i64, P(u8), P(i64)]
    lib.ph_reset_chunk.argtypes = [ctypes.c_void_p]
    f32 = ctypes.c_float
    lib.ph_ell_scatter_f32.argtypes = [
        P(i32), P(i32), P(f64), i64, i64, i64, P(i32), P(f32)
    ]
    lib.ph_ell_scatter_f32.restype = None
    lib.ph_ell_scatter_f64.argtypes = [
        P(i32), P(i32), P(f64), i64, i64, i64, P(i32), P(f64)
    ]
    lib.ph_ell_scatter_f64.restype = None
    lib.ph_shard_max_run.argtypes = [ctypes.c_void_p, i32]
    lib.ph_shard_max_run.restype = i64
    lib.ph_shard_ell_f32.argtypes = [
        ctypes.c_void_p, i32, i64, i64, i64, i64, P(i32), P(f32)
    ]
    lib.ph_shard_ell_f32.restype = None
    lib.ph_shard_ell_f64.argtypes = [
        ctypes.c_void_p, i32, i64, i64, i64, i64, P(i32), P(f64)
    ]
    lib.ph_shard_ell_f64.restype = None
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The compiled decoder library, or None if native is unavailable."""
    global _lib, _failed
    if _lib is not None:
        return _lib
    if _failed or os.environ.get("PHOTON_TPU_NO_NATIVE") == "1":
        return None
    with _lock:
        if _lib is not None or _failed:
            return _lib
        try:
            stale = (
                not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            )
            if stale and not _compile():
                _failed = True
                return None
            try:
                _lib = _bind(ctypes.CDLL(_SO))
            except AttributeError:
                # A cached .so that predates newly-added symbols (mtime
                # preserved by tar/rsync, or equal mtimes): rebuild once
                # instead of crashing every ingest call.
                if not _compile():
                    _failed = True
                    return None
                _lib = _bind(ctypes.CDLL(_SO))
        except (OSError, AttributeError):
            _failed = True
            return None
    return _lib
