"""Core type aliases and task enumeration.

Parity: reference ⟦photon-api/.../Types.scala⟧ and ⟦TaskType.scala⟧ (paths
unverified — reference mount empty; see SURVEY.md provenance warning).
"""
from __future__ import annotations

import enum

# jax.default_backend() names that mean "a real accelerator serves this
# process" (the axon tunnel registers as "axon"). ONE source of truth for
# every consumer — bench artifact stamping/diversion, the autopilot's
# completeness gate, and the drivers' device-budget auto-routing — so the
# allowlist cannot silently diverge between writer and reader.
REAL_ACCELERATOR_BACKENDS = ("tpu", "axon")
# TEST-ONLY escape hatch for the fake-window automation rehearsal
# (scripts/fake_window_rehearsal.py): lets the CPU backend masquerade as a
# recovery window so the whole window→autopilot→bench→race chain can be
# exercised end-to-end without a chip. Leakage containment: BOTH flags must
# be set (the rehearsal sets both; a stray single export does nothing),
# every artifact stamps the LIVE backend ("cpu"), and bench.py's
# contamination diversion for the real BENCH_DETAILS.json checks the
# hard-coded tuple, not this widened one.
_env = __import__("os").environ
if (_env.get("PHOTON_ACCEPT_CPU_AS_REAL") == "1"
        and _env.get("PHOTON_AUTOPILOT_FAKE") == "1"):
    REAL_ACCELERATOR_BACKENDS = REAL_ACCELERATOR_BACKENDS + ("cpu",)
del _env

# Type aliases mirroring the reference's Types.scala
CoordinateId = str
REId = str          # random-effect entity id (e.g. a userId value)
REType = str        # random-effect type (e.g. "userId" — the column name)
FeatureShardId = str
UniqueSampleId = int


class TaskType(enum.Enum):
    """Training objective family.

    Parity: reference ⟦photon-api/.../TaskType.scala⟧ — LOGISTIC_REGRESSION,
    LINEAR_REGRESSION, POISSON_REGRESSION, SMOOTHED_HINGE_LOSS_LINEAR_SVM.
    """

    LOGISTIC_REGRESSION = "LOGISTIC_REGRESSION"
    LINEAR_REGRESSION = "LINEAR_REGRESSION"
    POISSON_REGRESSION = "POISSON_REGRESSION"
    SMOOTHED_HINGE_LOSS_LINEAR_SVM = "SMOOTHED_HINGE_LOSS_LINEAR_SVM"

    @classmethod
    def parse(cls, s: str) -> "TaskType":
        key = s.strip().upper()
        aliases = {
            "LOGISTIC": cls.LOGISTIC_REGRESSION,
            "LINEAR": cls.LINEAR_REGRESSION,
            "POISSON": cls.POISSON_REGRESSION,
            "SVM": cls.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
            "SMOOTHED_HINGE": cls.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
        }
        if key in aliases:
            return aliases[key]
        return cls(key)
