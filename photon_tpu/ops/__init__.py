"""Numerical ops: pointwise losses and fast sparse feature ops."""
from photon_tpu.ops.losses import (  # noqa: F401
    LogisticLoss,
    PointwiseLoss,
    PoissonLoss,
    SmoothedHingeLoss,
    SquaredLoss,
    get_loss,
    loss_for_task,
)
