"""Pointwise GLM losses as pure functions of the margin.

Each loss exposes the same contract as the reference's ``PointwiseLossFunction``
⟦photon-lib/.../function/glm/*LossFunction.scala⟧ (unverified path — see
SURVEY.md): given the margin z = wᵀx (+ offset) and the label y it returns

  * ``loss(z, y)``   — the per-example loss value,
  * ``d1(z, y)``     — ∂loss/∂z  (the reference's ``DzLoss``),
  * ``d2(z, y)``     — ∂²loss/∂z² (the reference's ``DzzLoss``).

TPU-first design notes: these are scalar-free, shape-polymorphic jnp functions;
they broadcast over whole batches so XLA fuses them into the surrounding
matmul/segment-sum. The logistic and smoothed-hinge losses use overflow-safe
softplus/piecewise forms; the Poisson loss is exp(z) by definition and
overflows for z ≳ 88 in float32 (≳ 709 in float64) — same bound as the
reference's Breeze implementation. Labels follow the reference conventions:
binary {0, 1} for logistic and smoothed-hinge, reals for linear, counts ≥ 0
for Poisson.
"""
from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class PointwiseLoss:
    """A pointwise loss ℓ(z, y) with first and second margin-derivatives."""

    name: str
    loss: Callable[[Array, Array], Array]
    d1: Callable[[Array, Array], Array]
    d2: Callable[[Array, Array], Array]
    # The inverse link (mean function) used by the corresponding GLM when
    # turning a score into a prediction — reference ``computeMean``.
    mean: Callable[[Array], Array]

    def loss_and_d1(self, z: Array, y: Array) -> tuple[Array, Array]:
        return self.loss(z, y), self.d1(z, y)


# --- Logistic (binary cross-entropy on the logit) -----------------------------
# Reference ⟦LogisticLossFunction.scala⟧: y ∈ {0,1},
#   ℓ(z, y) = log(1 + e^z) − y·z ;  ∂ℓ/∂z = σ(z) − y ;  ∂²ℓ/∂z² = σ(z)(1 − σ(z)).

def _logistic_loss(z: Array, y: Array) -> Array:
    return jax.nn.softplus(z) - y * z


def _logistic_d1(z: Array, y: Array) -> Array:
    return jax.nn.sigmoid(z) - y


def _logistic_d2(z: Array, y: Array) -> Array:
    s = jax.nn.sigmoid(z)
    return s * (1.0 - s)


LogisticLoss = PointwiseLoss(
    name="logistic",
    loss=_logistic_loss,
    d1=_logistic_d1,
    d2=_logistic_d2,
    mean=jax.nn.sigmoid,
)


# --- Squared loss -------------------------------------------------------------
# Reference ⟦SquaredLossFunction.scala⟧: ℓ(z, y) = ½(z − y)².

def _squared_loss(z: Array, y: Array) -> Array:
    d = z - y
    return 0.5 * d * d


SquaredLoss = PointwiseLoss(
    name="squared",
    loss=_squared_loss,
    d1=lambda z, y: z - y,
    d2=lambda z, y: jnp.ones_like(z),
    mean=lambda z: z,
)


# --- Poisson loss (negative log-likelihood up to a constant) ------------------
# Reference ⟦PoissonLossFunction.scala⟧: ℓ(z, y) = e^z − y·z.

def _poisson_loss(z: Array, y: Array) -> Array:
    return jnp.exp(z) - y * z


PoissonLoss = PointwiseLoss(
    name="poisson",
    loss=_poisson_loss,
    d1=lambda z, y: jnp.exp(z) - y,
    d2=lambda z, y: jnp.exp(z),
    mean=jnp.exp,
)


# --- Smoothed hinge (Rennie & Srebro 2005) ------------------------------------
# Reference ⟦SmoothedHingeLossFunction.scala⟧: y ∈ {0,1} mapped to s = 2y − 1,
# t = s·z:
#   ℓ = ½ − t          if t ≤ 0
#   ℓ = ½(1 − t)²      if 0 < t < 1
#   ℓ = 0              if t ≥ 1
# Only once-differentiable; d2 is the a.e. second derivative (1 on 0<t<1),
# matching the reference's use of it in Hessian-vector products.

def _hinge_t(z: Array, y: Array) -> Array:
    s = 2.0 * y - 1.0
    return s * z


def _smoothed_hinge_loss(z: Array, y: Array) -> Array:
    t = _hinge_t(z, y)
    return jnp.where(t <= 0.0, 0.5 - t, jnp.where(t < 1.0, 0.5 * (1.0 - t) ** 2, 0.0))


def _smoothed_hinge_d1(z: Array, y: Array) -> Array:
    s = 2.0 * y - 1.0
    t = s * z
    dt = jnp.where(t <= 0.0, -1.0, jnp.where(t < 1.0, t - 1.0, 0.0))
    return s * dt


def _smoothed_hinge_d2(z: Array, y: Array) -> Array:
    t = _hinge_t(z, y)
    return jnp.where((t > 0.0) & (t < 1.0), 1.0, 0.0)


SmoothedHingeLoss = PointwiseLoss(
    name="smoothed_hinge",
    loss=_smoothed_hinge_loss,
    d1=_smoothed_hinge_d1,
    d2=_smoothed_hinge_d2,
    # The SVM "mean" is the raw score (the reference scores by margin sign).
    mean=lambda z: z,
)


_BY_NAME = {
    "logistic": LogisticLoss,
    "squared": SquaredLoss,
    "poisson": PoissonLoss,
    "smoothed_hinge": SmoothedHingeLoss,
}


def loss_for_task(task) -> PointwiseLoss:
    """Map a TaskType to its pointwise loss (reference: GLM task dispatch)."""
    from photon_tpu.types import TaskType

    return {
        TaskType.LOGISTIC_REGRESSION: LogisticLoss,
        TaskType.LINEAR_REGRESSION: SquaredLoss,
        TaskType.POISSON_REGRESSION: PoissonLoss,
        TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: SmoothedHingeLoss,
    }[task]


def get_loss(name: str) -> PointwiseLoss:
    return _BY_NAME[name]
