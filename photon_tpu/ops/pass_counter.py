"""Instrumented data-pass counting for feature-matrix ops.

VERDICT/ADVICE (round 2) flagged that bench.py's ``data_passes`` was computed
from a formula (``2*iters + iters//8 + 2``), not measured. This module makes
the claim self-verifying: every ``matvec`` / ``rmatvec`` / ``sq_rmatvec`` on a
feature container calls :func:`record`, and inside a :func:`counting` context
that embeds a ``jax.debug.callback`` in the traced program, so each *runtime
execution* (including executions inside ``lax.while_loop`` bodies) bumps a
host-side counter.

Counting is trace-time gated: outside the context, ``record`` is a no-op and
nothing is embedded, so the hot path carries zero overhead. To count an
already-jitted function, re-jit it inside the context (a fresh ``jax.jit``
wrapper forces a retrace with the callbacks embedded) and run it once —
untimed, since host callbacks serialize the device stream.

One "data pass" = one touch of all N·K feature entries, i.e. one matvec OR
one rmatvec (the convention bench.py documents).
"""
from __future__ import annotations

import contextlib
from typing import Iterator

import jax

_counts: dict[str, int] = {"matvec": 0, "rmatvec": 0, "sq_rmatvec": 0}
_enabled: bool = False


def _bump(kind: str) -> None:
    # Re-checked at call time: a program traced inside a counting() context
    # keeps its embedded callbacks for the life of its jit cache entry, and
    # those must not mutate counts (or be mistaken for live sessions) after
    # the context exits. (The stale callbacks still cost a host round trip —
    # don't reuse jit wrappers traced under counting() for timing.)
    if _enabled:
        _counts[kind] += 1


def record(kind: str) -> None:
    """Mark one data pass of the given kind at the current trace point."""
    if _enabled:
        jax.debug.callback(lambda k=kind: _bump(k))


@contextlib.contextmanager
def counting() -> Iterator[dict[str, int]]:
    """Enable pass counting; yields the live counter dict.

    Flushes outstanding device callbacks (``jax.effects_barrier``) before
    returning control, so the dict is complete when the block exits.
    """
    global _enabled
    for k in _counts:
        _counts[k] = 0
    _enabled = True
    try:
        yield _counts
    finally:
        jax.effects_barrier()
        _enabled = False


def total_passes() -> int:
    return sum(_counts.values())
