"""MXU-friendly sparse matvec/rmatvec paths for TPU.

Why this exists: XLA's generic ``gather``/``scatter-add`` lowerings on TPU run
near one element per scalar-core cycle, so the ELL hot ops of a sparse GLM pass
(`SparseFeatures.matvec`/`rmatvec`, SURVEY.md §7 hard-part #2) execute ~100×
off the HBM roofline. Measured on a v5e (2^19 rows × 32 nnz over 2^18
features): plain gather ≈ 150 ms, ``segment_sum`` scatter ≈ 118 ms per pass.

This module replaces both with formulations XLA compiles to vector/MXU code:

* ``matvec`` (and the gather side of ``rmatvec``): **row-slice gather +
  lane-select**.  The coefficient vector is viewed as ``[D/128, 128]``; each
  entry fetches its 128-wide row slice (``w2[idx >> 7]`` — a contiguous-slice
  gather XLA vectorizes) and selects its lane with a fused
  ``where(lo == iota)`` reduction.  Measured ≈ 55 ms vs 150 ms.

* ``rmatvec`` reduction: **column-sorted one-hot matmul**.  Entries are
  pre-sorted (host-side, once — indices are static data) by column and grouped
  into rows of a ``[B, Q]`` table whose columns all fall in one aligned
  128-column range.  The scatter-add then becomes
  ``einsum("bql,bq->bl", onehot(col & 127), contrib)`` — an MXU contraction
  with the one-hot fused from an int8 compare, never materialized — followed
  by a tiny sorted segment-sum over ranges.  Measured ≈ 11 ms vs 118 ms for
  the scatter itself.

The plan arrays are built once per dataset on the host (NumPy) and ride along
as an optional pytree on ``SparseFeatures``; all ops stay pure/jittable.
Ghost-padding entries (column id == dim) are mapped to a zero row with value
0, so no masking is needed in the hot loop.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

LANE = 128


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class FastSparseAux:
    """Static auxiliary layouts for the fast paths.

    Row-major digit split (for matvec's row-slice gather):
      ``hi[N, K]`` int16/int32 — column id >> 7 (ghost entries point at the
      zero row appended to the coefficient table; int16 when the block count
      fits, halving that index stream's HBM traffic); ``lo[N, K]`` int8 —
      column & 127.

    Column-sorted table (for rmatvec's one-hot reduce): ``B`` rows of capacity
    ``Q``; every slot in row b carries an entry whose column lies in the
    128-aligned range ``cs_range[b]``. ``cs_rhi``/``cs_rlo`` split the entry's
    ROW id for the dz gather; ``cs_clo`` is its lane within the range;
    ``cs_val`` is the feature value (0 in padding slots).
    """

    hi: Array        # [N, K] int16 or int32 (see _digit_dtype)
    lo: Array        # [N, K] int8
    cs_rhi: Array    # [B, Q] int16 or int32
    cs_rlo: Array    # [B, Q] int8
    cs_clo: Array    # [B, Q] int8
    cs_val: Array    # [B, Q] float32
    cs_range: Array  # [B] int32 (sorted; == n_ranges for padding rows)
    n_ranges: int = dataclasses.field(metadata=dict(static=True))
    n_row_blocks: int = dataclasses.field(metadata=dict(static=True))


def _digit_dtype(n_blocks: int):
    """Narrowest int dtype for a >>7 digit stream with ``n_blocks`` valid
    block ids PLUS the ghost/zero block. The digit arrays are pure HBM
    traffic in the hot loop, so int16 (feature spaces <= 128*32767 ≈ 4.19M,
    row spaces likewise) halves their share of the stream; beyond that the
    layout transparently stays int32."""
    return np.int16 if n_blocks + 1 <= np.iinfo(np.int16).max else np.int32


def build_fast_aux(
    idx: np.ndarray, val: np.ndarray, dim: int, q_capacity: int = 2048
) -> FastSparseAux:
    """Host-side construction of both static layouts from ELL arrays.

    ``idx``/``val`` are the ``SparseFeatures`` arrays ([N, K], ghost column ==
    ``dim`` with value 0). ``q_capacity`` bounds the column-table row width; a
    popular column range simply occupies several table rows (so skewed or
    dense columns — e.g. the intercept — need no special casing).
    """
    idx = np.asarray(idx)
    val = np.asarray(val)
    n, k = idx.shape
    n_row_blocks = -(-n // LANE)
    n_col_blocks = -(-dim // LANE)

    # Row-major digit split; ghost entries -> appended zero row of w table.
    hi = (idx >> 7).astype(_digit_dtype(n_col_blocks))
    lo = (idx & 127).astype(np.int8)
    ghost = idx >= dim
    hi[ghost] = n_col_blocks
    lo[ghost] = 0

    # Column-sorted table.
    flat_col = idx.ravel()
    keep = flat_col < dim
    cols = flat_col[keep].astype(np.int64)
    rows = np.repeat(np.arange(n, dtype=np.int64), k)[keep]
    vals = val.ravel()[keep]
    order = np.argsort(cols, kind="stable")
    cols, rows, vals = cols[order], rows[order], vals[order]

    rng_of = (cols >> 7).astype(np.int64)
    counts = np.bincount(rng_of, minlength=n_col_blocks)
    rows_per_range = np.maximum(1, -(-counts // q_capacity))
    b_total = int(rows_per_range.sum())
    b_pad = -(-b_total // 8) * 8

    cs_rhi = np.zeros((b_pad, q_capacity), _digit_dtype(n_row_blocks))
    cs_rlo = np.zeros((b_pad, q_capacity), np.int8)
    cs_clo = np.zeros((b_pad, q_capacity), np.int8)
    cs_val = np.zeros((b_pad, q_capacity), np.float32)
    cs_range = np.full((b_pad,), n_col_blocks, np.int32)

    starts = np.concatenate([[0], np.cumsum(counts)])
    b = 0
    for r in range(n_col_blocks):
        lo_e, hi_e = int(starts[r]), int(starts[r + 1])
        for off in range(lo_e, max(hi_e, lo_e + 1), q_capacity):
            end = min(off + q_capacity, hi_e)
            m = end - off
            if m > 0:
                sl = slice(off, end)
                cs_rhi[b, :m] = (rows[sl] >> 7).astype(cs_rhi.dtype)
                cs_rlo[b, :m] = (rows[sl] & 127).astype(np.int8)
                cs_clo[b, :m] = (cols[sl] & 127).astype(np.int8)
                cs_val[b, :m] = vals[sl]
            cs_range[b] = r
            b += 1

    return FastSparseAux(
        hi=jnp.asarray(hi),
        lo=jnp.asarray(lo),
        cs_rhi=jnp.asarray(cs_rhi),
        cs_rlo=jnp.asarray(cs_rlo),
        cs_clo=jnp.asarray(cs_clo),
        cs_val=jnp.asarray(cs_val),
        cs_range=jnp.asarray(cs_range),
        n_ranges=n_col_blocks,
        n_row_blocks=n_row_blocks,
    )


def _lane_iota() -> Array:
    return jax.lax.broadcasted_iota(jnp.int8, (1, 1, LANE), 2)


def matvec_fast(aux: FastSparseAux, val: Array, w: Array, dim: int) -> Array:
    """z[i] = Σ_k val[i,k] · w[idx[i,k]] via row-slice gather + lane select."""
    nblk = -(-dim // LANE)
    w2 = jnp.pad(w, (0, nblk * LANE - dim)).reshape(nblk, LANE)
    w2 = jnp.concatenate([w2, jnp.zeros((1, LANE), w.dtype)])  # ghost row
    rows = w2[aux.hi]                                  # [N, K, 128]
    sel = jnp.where(aux.lo[..., None] == _lane_iota(), rows, 0.0)
    # Narrow-stored values (bfloat16 via with_value_dtype) upcast on load:
    # the accumulation stays in w's precision, only the HBM stream shrinks.
    valf = val.astype(jnp.promote_types(val.dtype, w.dtype))
    return jnp.sum(jnp.sum(sel, axis=-1) * valf, axis=-1)


def rmatvec_fast(
    aux: FastSparseAux, dz: Array, dim: int, square_vals: bool = False
) -> Array:
    """g[c] = Σ_{entries of column c} val · dz[row] — scatter-free.

    dz is gathered by row-slice + lane select (same trick as matvec), the
    per-column reduction is a fused one-hot MXU contraction per 128-column
    range, and ranges assemble with one small sorted segment-sum.
    """
    n = dz.shape[0]
    nb = aux.n_row_blocks
    dz2 = jnp.pad(dz, (0, nb * LANE - n)).reshape(nb, LANE)
    rows = dz2[aux.cs_rhi]                             # [B, Q, 128]
    iota = _lane_iota()
    dz_at = jnp.sum(jnp.where(aux.cs_rlo[..., None] == iota, rows, 0.0), axis=-1)
    # Upcast BEFORE squaring: bfloat16-stored values must square in the
    # accumulation precision, not in 8 mantissa bits.
    csv = aux.cs_val.astype(jnp.promote_types(aux.cs_val.dtype, dz.dtype))
    v = csv * csv if square_vals else csv
    contrib = dz_at * v                                # [B, Q]
    oh = jnp.where(aux.cs_clo[..., None] == iota, 1.0, 0.0)
    out_b = jnp.einsum(
        "bql,bq->bl", oh, contrib, preferred_element_type=jnp.float32
    )                                                  # [B, 128]
    out_r = jax.ops.segment_sum(
        out_b, aux.cs_range, num_segments=aux.n_ranges + 1,
        indices_are_sorted=True,
    )[: aux.n_ranges]
    return out_r.reshape(-1)[:dim]


# Note: no custom_vjp wrapper is needed — every optimizer-facing path
# (GLMObjective.value_and_grad / hessian_vector / hessian_diagonal) is
# hand-fused and calls matvec/rmatvec explicitly, so autodiff never
# differentiates through these functions.
