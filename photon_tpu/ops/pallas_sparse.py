"""Pallas TPU kernels for the ELL sparse hot ops (matvec / rmatvec).

Why: the XLA fast paths in :mod:`photon_tpu.ops.fast_sparse` still run ~200x
off the HBM roofline (BENCH_DETAILS.json ``fraction_of_roofline`` ~0.005 on
v5e) because their gathers materialize a 128-wide row slice per entry —
8.6 GB of traffic for a 200 MB dataset. These kernels cut the blow-up by
keeping every intermediate in VMEM and doing the per-entry lookup with the
TPU's hardware ``dynamic_gather`` (Mosaic lowers a same-shape
``jnp.take_along_axis(table, idx, axis=0)`` to one vector gather).

Design (SURVEY.md §7 hard-part #2, VERDICT round-2 ask #2):

* Sparsity is STATIC per dataset, so ALL routing is precomputed on host.
  Entries are packed into slot tables of shape ``[S, 128]``:

  - ``rmatvec`` (g = Aᵀdz): slots grouped by 128-wide COLUMN range; within a
    group a slot sits at lane ``row & 127``, so the dz lookup is exactly the
    hardware gather ``dz2[rhi[s, l], l]``. The per-group reduce over columns
    is a fused one-hot MXU contraction per 8-sublane chunk (chunks never
    cross groups), finished by one tiny sorted ``segment_sum`` outside the
    kernel.
  - ``matvec`` (z = Aw): the exact mirror — slots grouped by 128-row RANGE,
    lane ``col & 127`` so the coefficient lookup is ``w2[chi[s, l], l]``,
    one-hot reduce over ``row & 127``.

* Ghost/padding slots carry value 0 and index 0 — they contribute nothing
  and need no masking in the hot loop.

Layouts ride on ``SparseFeatures.pallas`` (see ``with_pallas_path``); the
kernels are f32-only and fall back to the XLA path off-TPU (tests run them
in Pallas interpret mode on CPU).
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

Array = jax.Array

LANE = 128
CHUNK = 8              # sublanes per one-hot MXU chunk; groups pad to this
TABLE_SUBLANES = {
    "rmatvec": 4096,   # dz table [4096, 128] -> up to 512K rows per chunk
    "matvec": 2048,    # w table [2048, 128] -> up to 256K features
}


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class _OpTables:
    """Slot tables for one direction. All are [S, 128] with S a multiple of
    the block sublane count; ``chunk_group`` is [S / CHUNK] sorted group ids
    (ghost group == n_groups)."""

    hi: Array           # int32 — table-sublane index fed to the hw gather
    lo: Array           # int32 — one-hot key (col&127 / row&127)
    val: Array          # f32 — feature value (0 in padding slots)
    chunk_group: Array  # int32 [S/CHUNK]
    n_groups: int = dataclasses.field(metadata=dict(static=True))


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PallasSparseAux:
    """Static Pallas layouts for both ops of one dataset."""

    rmat: _OpTables
    mat: _OpTables
    n_rows: int = dataclasses.field(metadata=dict(static=True))
    dim: int = dataclasses.field(metadata=dict(static=True))

    @staticmethod
    def supports(n_rows: int, dim: int) -> bool:
        return (
            n_rows <= TABLE_SUBLANES["rmatvec"] * LANE
            and dim <= TABLE_SUBLANES["matvec"] * LANE
        )


def _pack_tables(
    group: np.ndarray,     # per entry: reduce-group id (sorted not required)
    lane: np.ndarray,      # per entry: slot lane (gather alignment)
    hi: np.ndarray,        # per entry: table sublane for the hw gather
    lo: np.ndarray,        # per entry: one-hot key within the group
    val: np.ndarray,
    n_groups: int,
    block_sublanes: int,
) -> _OpTables:
    """Pack entries into lane-aligned slot tables, greedily stacking each
    (group, lane) run into sublanes; groups pad to CHUNK sublanes, the whole
    table pads to a multiple of ``block_sublanes``."""
    order = np.lexsort((lane, group))
    group, lane, hi, lo, val = (a[order] for a in (group, lane, hi, lo, val))
    # rank of each entry within its (group, lane) run = its sublane offset
    gl = group.astype(np.int64) * LANE + lane
    new_run = np.concatenate([[True], gl[1:] != gl[:-1]])
    run_start = np.maximum.accumulate(np.where(new_run, np.arange(len(gl)), 0))
    sub_in_run = np.arange(len(gl)) - run_start
    # sublanes needed per group = max run length in that group
    need = np.zeros(n_groups, np.int64)
    np.maximum.at(need, group, sub_in_run + 1)
    need = -(-need // CHUNK) * CHUNK                     # pad to CHUNK
    g_off = np.zeros(n_groups + 1, np.int64)
    np.cumsum(need, out=g_off[1:])
    total = int(-(-g_off[-1] // block_sublanes) * block_sublanes)

    t_hi = np.zeros((total, LANE), np.int32)
    t_lo = np.zeros((total, LANE), np.int32)
    t_val = np.zeros((total, LANE), np.float32)
    srow = g_off[group] + sub_in_run
    t_hi[srow, lane] = hi
    t_lo[srow, lane] = lo
    t_val[srow, lane] = val

    cg = np.full(total // CHUNK, n_groups, np.int32)     # ghost group at end
    used = np.repeat(np.arange(n_groups, dtype=np.int32), need // CHUNK)
    cg[: len(used)] = used
    return _OpTables(
        hi=jnp.asarray(t_hi), lo=jnp.asarray(t_lo), val=jnp.asarray(t_val),
        chunk_group=jnp.asarray(cg), n_groups=n_groups,
    )


def build_pallas_aux(idx: np.ndarray, val: np.ndarray, dim: int) -> PallasSparseAux:
    """Host-side construction of both directions' tables from ELL arrays
    (``idx[N, K]`` with ghost column == ``dim``, value 0)."""
    idx = np.asarray(idx)
    val = np.asarray(val, np.float32)
    n, k = idx.shape
    if not PallasSparseAux.supports(n, dim):
        raise ValueError(
            f"dataset ({n} rows, {dim} features) exceeds the single-chunk "
            f"Pallas table sizes ({TABLE_SUBLANES['rmatvec'] * LANE} rows, "
            f"{TABLE_SUBLANES['matvec'] * LANE} features)"
        )
    flat = idx.ravel().astype(np.int64)
    keep = flat < dim
    col = flat[keep]
    row = np.repeat(np.arange(n, dtype=np.int64), k)[keep]
    v = val.ravel()[keep]

    n_col_groups = -(-dim // LANE)
    n_row_groups = -(-n // LANE)
    rmat = _pack_tables(
        group=(col >> 7), lane=(row & 127).astype(np.int64),
        hi=(row >> 7).astype(np.int64), lo=(col & 127).astype(np.int64),
        val=v, n_groups=n_col_groups,
        block_sublanes=TABLE_SUBLANES["rmatvec"],
    )
    mat = _pack_tables(
        group=(row >> 7), lane=(col & 127).astype(np.int64),
        hi=(col >> 7).astype(np.int64), lo=(row & 127).astype(np.int64),
        val=v, n_groups=n_row_groups,
        block_sublanes=TABLE_SUBLANES["matvec"],
    )
    return PallasSparseAux(rmat=rmat, mat=mat, n_rows=n, dim=dim)


# ---------------------------------------------------------------- kernels


def _gather_onehot_kernel(table_ref, hi_ref, lo_ref, val_ref, out_ref,
                          *, square_vals: bool):
    """One slot block: hw-gather the table, multiply by values, one-hot
    MXU-reduce each 8-sublane chunk to a 128-vector partial."""
    nb = hi_ref.shape[0]
    gathered = jnp.take_along_axis(
        table_ref[:], hi_ref[:], axis=0, mode="fill", fill_value=0.0
    )
    v = val_ref[:]
    if square_vals:
        v = v * v
    contrib = gathered * v                               # [nb, 128]
    lo = lo_ref[:]

    def chunk(i, _):
        c = lax.dynamic_slice_in_dim(contrib, i * CHUNK, CHUNK, 0)
        keys = lax.dynamic_slice_in_dim(lo, i * CHUNK, CHUNK, 0)
        oh = (
            keys.reshape(CHUNK * LANE, 1)
            == lax.broadcasted_iota(jnp.int32, (CHUNK * LANE, LANE), 1)
        )
        out_ref[i, :] = jnp.dot(
            c.reshape(1, CHUNK * LANE), oh.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )[0]
        return 0

    lax.fori_loop(0, nb // CHUNK, chunk, 0)


def _run_op(tables: _OpTables, vec2: Array, block_sublanes: int,
            square_vals: bool, interpret: bool) -> Array:
    """Shared driver: grid over slot blocks, then the tiny sorted
    segment-sum of chunk partials by group. Returns [n_groups, 128]."""
    total = tables.hi.shape[0]
    n_blocks = total // block_sublanes
    partials = pl.pallas_call(
        functools.partial(_gather_onehot_kernel, square_vals=square_vals),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((block_sublanes, LANE), lambda i: (0, 0)),
            pl.BlockSpec((block_sublanes, LANE), lambda i: (i, 0)),
            pl.BlockSpec((block_sublanes, LANE), lambda i: (i, 0)),
            pl.BlockSpec((block_sublanes, LANE), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_sublanes // CHUNK, LANE),
                               lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((total // CHUNK, LANE), jnp.float32),
        interpret=interpret,
    )(vec2, tables.hi, tables.lo, tables.val)
    return jax.ops.segment_sum(
        partials, tables.chunk_group, num_segments=tables.n_groups + 1,
        indices_are_sorted=True,
    )[: tables.n_groups]


def rmatvec_pallas(
    aux: PallasSparseAux, dz: Array, square_vals: bool = False,
    interpret: bool = False,
) -> Array:
    """g[c] = Σ entries val·dz[row] (val² with ``square_vals``)."""
    nb = TABLE_SUBLANES["rmatvec"]
    dz2 = jnp.pad(dz.astype(jnp.float32), (0, nb * LANE - aux.n_rows))
    out = _run_op(aux.rmat, dz2.reshape(nb, LANE), nb, square_vals, interpret)
    return out.reshape(-1)[: aux.dim]


def matvec_pallas(
    aux: PallasSparseAux, w: Array, interpret: bool = False
) -> Array:
    """z[r] = Σ entries val·w[col]."""
    nb = TABLE_SUBLANES["matvec"]
    w2 = jnp.pad(w.astype(jnp.float32), (0, nb * LANE - aux.dim))
    out = _run_op(aux.mat, w2.reshape(nb, LANE), nb, False, interpret)
    return out.reshape(-1)[: aux.n_rows]
