"""Pallas TPU kernels for the ELL sparse hot ops (matvec / rmatvec).

Why: the XLA fast paths in :mod:`photon_tpu.ops.fast_sparse` still run ~200x
off the HBM roofline (BENCH_DETAILS.json ``fraction_of_roofline`` ~0.005 on
v5e) because their gathers materialize a 128-wide row slice per entry —
8.6 GB of traffic for a 200 MB dataset. These kernels cut the blow-up by
keeping every intermediate in VMEM and doing the per-entry lookup with the
TPU's hardware ``dynamic_gather`` (Mosaic lowers a same-shape
``jnp.take_along_axis(table, idx, axis=0)`` to one vector gather).

Design (SURVEY.md §7 hard-part #2, VERDICT round-2 ask #2):

* Sparsity is STATIC per dataset, so ALL routing is precomputed on host.
  Entries are packed into slot tables of shape ``[S, 128]``:

  - ``rmatvec`` (g = Aᵀdz): slots grouped by 128-wide COLUMN range; within a
    group a slot sits at lane ``row & 127``, so the dz lookup is exactly the
    hardware gather ``dz2[rhi[s, l], l]``. The per-group reduce over columns
    is a fused one-hot MXU contraction per 8-sublane chunk (chunks never
    cross groups), finished by one tiny sorted ``segment_sum`` outside the
    kernel.
  - ``matvec`` (z = Aw): the exact mirror — slots grouped by 128-row RANGE,
    lane ``col & 127`` so the coefficient lookup is ``w2[chi[s, l], l]``,
    one-hot reduce over ``row & 127``.

* Ghost/padding slots carry value 0 and index 0 — they contribute nothing
  and need no masking in the hot loop.

* Datasets larger than one VMEM-resident lookup table (512K rows for the dz
  table, 256K features for the w table) are CHUNKED: entries are split by
  row range (rmatvec) / column range (matvec), each chunk packs its own slot
  tables indexed against its slice of the lookup vector, and the op sums the
  per-chunk group partials — same kernels, one ``pallas_call`` per chunk.
  A ``max_table_bytes`` budget bounds total table memory (group padding is
  per-chunk, so extreme row-chunking of a very wide dataset can inflate it);
  over budget, construction raises and ``with_pallas_path`` falls back to
  the XLA fast path.

Layouts ride on ``SparseFeatures.pallas`` (see ``with_pallas_path``); the
kernels are f32-only and fall back to the XLA path off-TPU (tests run them
in Pallas interpret mode on CPU).
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

Array = jax.Array

LANE = 128
CHUNK = 8              # sublanes per one-hot MXU chunk; groups pad to this
TABLE_SUBLANES = {
    "rmatvec": 4096,   # dz table [4096, 128] -> up to 512K rows per chunk
    "matvec": 2048,    # w table [2048, 128] -> up to 256K features
}


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class _OpTables:
    """Slot tables for one direction. All are [S, 128] with S a multiple of
    the block sublane count; ``chunk_group`` is [S / CHUNK] sorted group ids
    (ghost group == n_groups)."""

    hi: Array           # int32 — table-sublane index fed to the hw gather
    lo: Array           # int32 — one-hot key (col&127 / row&127)
    val: Array          # f32 — feature value (0 in padding slots)
    chunk_group: Array  # int32 [S/CHUNK]
    n_groups: int = dataclasses.field(metadata=dict(static=True))


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PallasSparseAux:
    """Static Pallas layouts for both ops of one dataset.

    ``rmat``/``mat`` hold one table set per non-empty chunk (row chunks of
    512K for rmatvec, column chunks of 256K for matvec); ``rmat_chunks`` /
    ``mat_chunks`` are the matching chunk indices into the dz / w vector
    (static — chunk boundaries are compile-time slices)."""

    rmat: tuple
    mat: tuple
    rmat_chunks: tuple = dataclasses.field(metadata=dict(static=True))
    mat_chunks: tuple = dataclasses.field(metadata=dict(static=True))
    n_rows: int = dataclasses.field(metadata=dict(static=True))
    dim: int = dataclasses.field(metadata=dict(static=True))


def _pack_tables(
    group: np.ndarray,     # per entry: reduce-group id (sorted not required)
    lane: np.ndarray,      # per entry: slot lane (gather alignment)
    hi: np.ndarray,        # per entry: table sublane for the hw gather
    lo: np.ndarray,        # per entry: one-hot key within the group
    val: np.ndarray,
    n_groups: int,
    block_sublanes: int,
) -> _OpTables:
    """Pack entries into lane-aligned slot tables, greedily stacking each
    (group, lane) run into sublanes; groups pad to CHUNK sublanes, the whole
    table pads to a multiple of ``block_sublanes``."""
    order = np.lexsort((lane, group))
    group, lane, hi, lo, val = (a[order] for a in (group, lane, hi, lo, val))
    # rank of each entry within its (group, lane) run = its sublane offset
    gl = group.astype(np.int64) * LANE + lane
    new_run = np.concatenate([[True], gl[1:] != gl[:-1]])
    run_start = np.maximum.accumulate(np.where(new_run, np.arange(len(gl)), 0))
    sub_in_run = np.arange(len(gl)) - run_start
    # sublanes needed per group = max run length in that group
    need = np.zeros(n_groups, np.int64)
    np.maximum.at(need, group, sub_in_run + 1)
    need = -(-need // CHUNK) * CHUNK                     # pad to CHUNK
    g_off = np.zeros(n_groups + 1, np.int64)
    np.cumsum(need, out=g_off[1:])
    total = int(-(-g_off[-1] // block_sublanes) * block_sublanes)

    t_hi = np.zeros((total, LANE), np.int32)
    t_lo = np.zeros((total, LANE), np.int32)
    t_val = np.zeros((total, LANE), np.float32)
    srow = g_off[group] + sub_in_run
    t_hi[srow, lane] = hi
    t_lo[srow, lane] = lo
    t_val[srow, lane] = val

    cg = np.full(total // CHUNK, n_groups, np.int32)     # ghost group at end
    used = np.repeat(np.arange(n_groups, dtype=np.int32), need // CHUNK)
    cg[: len(used)] = used
    # numpy for now: the caller budget-checks total bytes across all chunks
    # BEFORE anything is uploaded to device memory.
    return _OpTables(hi=t_hi, lo=t_lo, val=t_val, chunk_group=cg,
                     n_groups=n_groups)


def _np_bytes(t: _OpTables) -> int:
    return t.hi.nbytes + t.lo.nbytes + t.val.nbytes + t.chunk_group.nbytes


def _to_device(t: _OpTables) -> _OpTables:
    return _OpTables(
        hi=jnp.asarray(t.hi), lo=jnp.asarray(t.lo), val=jnp.asarray(t.val),
        chunk_group=jnp.asarray(t.chunk_group), n_groups=t.n_groups,
    )


def _chunked_tables(
    split_key: np.ndarray,      # per entry: chunk index (row or col chunk)
    chunk_elems: int,           # rows/cols covered by one chunk
    group: np.ndarray,
    lane: np.ndarray,
    hi_global: np.ndarray,      # hi before localizing to the chunk's slice
    lo: np.ndarray,
    val: np.ndarray,
    n_groups: int,
    block_sublanes: int,
) -> tuple[list, list]:
    """Pack one table set per non-empty chunk; ``hi`` is localized to the
    chunk's slice of the lookup vector (its table sublane index)."""
    # One stable sort partitions all entries into contiguous chunk runs
    # (each entry gathered once) instead of a full rescan per chunk.
    order = np.argsort(split_key, kind="stable")
    sk = split_key[order]
    uniq, starts = np.unique(sk, return_index=True)
    bounds = np.append(starts, len(sk))
    tables, chunks = [], []
    for c, lo_i, hi_i in zip(uniq, bounds[:-1], bounds[1:]):
        sl = order[lo_i:hi_i]
        tables.append(_pack_tables(
            group=group[sl], lane=lane[sl],
            hi=hi_global[sl] - int(c) * (chunk_elems // LANE), lo=lo[sl],
            val=val[sl], n_groups=n_groups, block_sublanes=block_sublanes,
        ))
        chunks.append(int(c))
    return tables, chunks


def build_pallas_aux(
    idx: np.ndarray, val: np.ndarray, dim: int,
    max_table_bytes: int = 2 << 30,
) -> PallasSparseAux:
    """Host-side construction of both directions' tables from ELL arrays
    (``idx[N, K]`` with ghost column == ``dim``, value 0). Datasets beyond
    one chunk (512K rows / 256K features) split into per-chunk tables;
    raises ``ValueError`` if the packed tables would exceed
    ``max_table_bytes`` (callers fall back to the XLA fast path)."""
    idx = np.asarray(idx)
    val = np.asarray(val, np.float32)
    n, k = idx.shape
    # Cheap lower bound BEFORE any packing: each real entry occupies one
    # 12-byte slot (hi+lo+val) in each direction's tables, so a dataset that
    # cannot fit is rejected in O(1) instead of after two full lexsorts and
    # multi-GB transient allocations.
    if 24 * n * k > max_table_bytes * 4:  # k includes ghost padding; x4 slack
        if 24 * int(np.count_nonzero(idx < dim)) > max_table_bytes:
            raise ValueError(
                f"Pallas slot tables need >= 24 bytes/entry x ~{n * k} "
                f"entries (> {max_table_bytes / 1e9:.2f} GB budget); "
                "falling back to the XLA fast path"
            )
    flat = idx.ravel().astype(np.int64)
    keep = flat < dim
    col = flat[keep]
    row = np.repeat(np.arange(n, dtype=np.int64), k)[keep]
    v = val.ravel()[keep]

    n_col_groups = -(-dim // LANE)
    n_row_groups = -(-n // LANE)
    row_chunk_elems = TABLE_SUBLANES["rmatvec"] * LANE
    col_chunk_elems = TABLE_SUBLANES["matvec"] * LANE

    rmat, rmat_chunks = _chunked_tables(
        split_key=row // row_chunk_elems, chunk_elems=row_chunk_elems,
        group=(col >> 7), lane=(row & 127).astype(np.int64),
        hi_global=(row >> 7).astype(np.int64), lo=(col & 127).astype(np.int64),
        val=v, n_groups=n_col_groups,
        block_sublanes=TABLE_SUBLANES["rmatvec"],
    )
    mat, mat_chunks = _chunked_tables(
        split_key=col // col_chunk_elems, chunk_elems=col_chunk_elems,
        group=(row >> 7), lane=(col & 127).astype(np.int64),
        hi_global=(col >> 7).astype(np.int64), lo=(row & 127).astype(np.int64),
        val=v, n_groups=n_row_groups,
        block_sublanes=TABLE_SUBLANES["matvec"],
    )
    total_bytes = sum(_np_bytes(t) for t in rmat + mat)
    if total_bytes > max_table_bytes:
        raise ValueError(
            f"Pallas slot tables would take {total_bytes / 1e9:.2f} GB "
            f"(> {max_table_bytes / 1e9:.2f} GB budget) for {n} rows x "
            f"{dim} features; falling back to the XLA fast path"
        )
    return PallasSparseAux(
        rmat=tuple(_to_device(t) for t in rmat),
        mat=tuple(_to_device(t) for t in mat),
        rmat_chunks=tuple(rmat_chunks), mat_chunks=tuple(mat_chunks),
        n_rows=n, dim=dim,
    )


# ---------------------------------------------------------------- kernels


def _gather_onehot_kernel(table_ref, hi_ref, lo_ref, val_ref, out_ref,
                          *, square_vals: bool):
    """One slot block: hw-gather the table, multiply by values, one-hot
    MXU-reduce each 8-sublane chunk to a 128-vector partial."""
    nb = hi_ref.shape[0]
    gathered = jnp.take_along_axis(
        table_ref[:], hi_ref[:], axis=0, mode="fill", fill_value=0.0
    )
    v = val_ref[:]
    if square_vals:
        v = v * v
    contrib = gathered * v                               # [nb, 128]
    lo = lo_ref[:]

    def chunk(i, _):
        c = lax.dynamic_slice_in_dim(contrib, i * CHUNK, CHUNK, 0)
        keys = lax.dynamic_slice_in_dim(lo, i * CHUNK, CHUNK, 0)
        oh = (
            keys.reshape(CHUNK * LANE, 1)
            == lax.broadcasted_iota(jnp.int32, (CHUNK * LANE, LANE), 1)
        )
        out_ref[i, :] = jnp.dot(
            c.reshape(1, CHUNK * LANE), oh.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )[0]
        return 0

    lax.fori_loop(0, nb // CHUNK, chunk, 0)


def _run_op(tables: _OpTables, vec2: Array, block_sublanes: int,
            square_vals: bool, interpret: bool) -> Array:
    """Shared driver: grid over slot blocks, then the tiny sorted
    segment-sum of chunk partials by group. Returns [n_groups, 128]."""
    total = tables.hi.shape[0]
    n_blocks = total // block_sublanes
    partials = pl.pallas_call(
        functools.partial(_gather_onehot_kernel, square_vals=square_vals),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((block_sublanes, LANE), lambda i: (0, 0)),
            pl.BlockSpec((block_sublanes, LANE), lambda i: (i, 0)),
            pl.BlockSpec((block_sublanes, LANE), lambda i: (i, 0)),
            pl.BlockSpec((block_sublanes, LANE), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_sublanes // CHUNK, LANE),
                               lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((total // CHUNK, LANE), jnp.float32),
        interpret=interpret,
    )(vec2, tables.hi, tables.lo, tables.val)
    return jax.ops.segment_sum(
        partials, tables.chunk_group, num_segments=tables.n_groups + 1,
        indices_are_sorted=True,
    )[: tables.n_groups]


def _chunk_slice(vec: Array, chunk: int, chunk_elems: int, nb: int) -> Array:
    """The chunk's slice of the lookup vector, zero-padded to a full
    [nb, 128] table (static bounds — chunk indices are compile-time)."""
    lo = chunk * chunk_elems
    size = min(chunk_elems, vec.shape[0] - lo)
    piece = jax.lax.slice_in_dim(vec, lo, lo + size, axis=0)
    return jnp.pad(piece, (0, chunk_elems - size)).reshape(nb, LANE)


def rmatvec_pallas(
    aux: PallasSparseAux, dz: Array, square_vals: bool = False,
    interpret: bool = False,
) -> Array:
    """g[c] = Σ entries val·dz[row] (val² with ``square_vals``); per-chunk
    group partials sum across row chunks."""
    nb = TABLE_SUBLANES["rmatvec"]
    dzf = dz.astype(jnp.float32)
    out = None
    for tables, chunk in zip(aux.rmat, aux.rmat_chunks):
        dz2 = _chunk_slice(dzf, chunk, nb * LANE, nb)
        part = _run_op(tables, dz2, nb, square_vals, interpret)
        out = part if out is None else out + part
    if out is None:  # dataset with zero real entries
        return jnp.zeros((aux.dim,), jnp.float32)
    return out.reshape(-1)[: aux.dim]


def matvec_pallas(
    aux: PallasSparseAux, w: Array, interpret: bool = False
) -> Array:
    """z[r] = Σ entries val·w[col]; per-chunk row partials sum across
    column chunks."""
    nb = TABLE_SUBLANES["matvec"]
    wf = w.astype(jnp.float32)
    out = None
    for tables, chunk in zip(aux.mat, aux.mat_chunks):
        w2 = _chunk_slice(wf, chunk, nb * LANE, nb)
        part = _run_op(tables, w2, nb, False, interpret)
        out = part if out is None else out + part
    if out is None:  # dataset with zero real entries
        return jnp.zeros((aux.n_rows,), jnp.float32)
    return out.reshape(-1)[: aux.n_rows]
