"""Model substrate: Coefficients pytree, GLM per-task models, GAME models."""
from photon_tpu.models.coefficients import Coefficients  # noqa: F401
