"""Model coefficients as a JAX pytree.

Parity: reference ⟦photon-lib/.../model/Coefficients.scala⟧ — a Breeze vector of
means plus optional per-coefficient variances. Here it is a frozen dataclass
registered as a pytree so it flows through jit/vmap/shard_map and can be
sharded over a feature axis (SURVEY.md §2.6 P3).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Coefficients:
    """means[D] (+ optional variances[D]) for one generalized linear model."""

    means: Array
    variances: Optional[Array] = None

    @property
    def dim(self) -> int:
        return self.means.shape[-1]

    @staticmethod
    def zeros(dim: int, dtype=jnp.float32, with_variances: bool = False) -> "Coefficients":
        v = jnp.zeros((dim,), dtype) if with_variances else None
        return Coefficients(means=jnp.zeros((dim,), dtype), variances=v)

    def norm2(self) -> Array:
        return jnp.sqrt(jnp.sum(self.means * self.means))
