"""Generalized linear models: coefficients + task-specific link.

Parity: reference ⟦photon-lib/.../model/GeneralizedLinearModel.scala⟧ and the
per-task subclasses ⟦LogisticRegressionModel, LinearRegressionModel,
PoissonRegressionModel, SmoothedHingeLossLinearSVMModel⟧. Here one frozen
pytree dataclass with a static ``task`` field replaces the subclass hierarchy —
the task dispatches the mean function, and the whole model flows through
jit/vmap (a [E, D] stack of means IS a batch of E models, which is how
random-effect model collections are stored).
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from photon_tpu.data.batch import Features
from photon_tpu.models.coefficients import Coefficients
from photon_tpu.ops.losses import loss_for_task
from photon_tpu.types import TaskType

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GeneralizedLinearModel:
    """means (+variances) with a task type; scoring is pure."""

    coefficients: Coefficients
    task: TaskType = dataclasses.field(metadata=dict(static=True))

    @property
    def dim(self) -> int:
        return self.coefficients.dim

    def compute_score(self, features: Features, offsets: Array | None = None) -> Array:
        """Raw linear score xᵀβ (+ offset) — reference ``computeScore``."""
        z = features.matvec(self.coefficients.means)
        if offsets is not None:
            z = z + offsets
        return z

    def compute_mean(self, features: Features, offsets: Array | None = None) -> Array:
        """Score through the inverse link — reference ``computeMeanFunction``."""
        return loss_for_task(self.task).mean(self.compute_score(features, offsets))

    @staticmethod
    def zeros(dim: int, task: TaskType, dtype=jnp.float32) -> "GeneralizedLinearModel":
        return GeneralizedLinearModel(Coefficients.zeros(dim, dtype), task)
