"""The staleness- and pressure-aware routing front door.

:class:`RouterServer` fronts N serving replicas: a thin stdlib HTTP
process (no model, no JAX — it boots in milliseconds and never competes
with replicas for the accelerator) that

* **health-checks** every replica on a cadence (``GET /healthz``),
  reading the status, the degraded-reason list, and the replication
  block's seq watermark;
* **weights** ``/score`` traffic by staleness: a replica's weight is
  ``1 / (1 + staleness_penalty * seq_lag)`` against the freshest
  watermark in the pool, so a converged replica takes proportionally
  more traffic than one still replaying its backlog;
* **drains** replicas reporting ``degraded`` (open breakers, memory
  pressure — docs/robustness.md) or an unhealthy/unreachable state:
  weight 0 while the condition holds, traffic restored automatically by
  the next clean health check. When EVERY replica is degraded the router
  serves through them anyway (a degraded answer beats no answer);
* **retries** idempotent reads: a connect failure (or a 503 shed) on one
  replica re-dispatches the same request to the next-best replica,
  bounded by ``retries`` — a killed replica costs its in-flight requests
  one retry, not an error;
* **forwards** ``X-Photon-Trace-Id`` (minting one when absent), so a
  routed request renders as router → replica one flow in the merged
  fleet timeline.

Routes: ``POST /score`` (balanced), ``GET /healthz`` (the router's view
of the pool; 503 when no replica is reachable), ``GET /metrics`` (JSON,
``?format=prom`` for text exposition).
"""
from __future__ import annotations

import http.client
import json
import math
import random
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

from photon_tpu.obs import (
    MetricsRegistry,
    REGISTRY as GLOBAL_REGISTRY,
    new_trace_id,
    trace_context,
    trace_span,
)

_CONNECT_ERRORS = (ConnectionError, TimeoutError, OSError)


class _ReplicaState:
    """The router's last-known view of one replica."""

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        self.reachable = False
        self.status = "unknown"          # ok | degraded | unhealthy | ...
        self.degraded: list = []
        self.seq_watermark: Optional[int] = None
        self.lag: Optional[int] = None
        self.model_version: Optional[int] = None
        self.last_check_ts: Optional[float] = None
        self.consecutive_failures = 0
        # Keep-alive probe connection, owned by the health thread only.
        self.conn: Optional[http.client.HTTPConnection] = None

    def snapshot(self) -> dict:
        return {
            "url": self.url,
            "reachable": self.reachable,
            "status": self.status,
            "degraded": list(self.degraded),
            "seq_watermark": self.seq_watermark,
            "lag": self.lag,
            "model_version": self.model_version,
            "last_check_ts": self.last_check_ts,
            "consecutive_failures": self.consecutive_failures,
        }


class RouterServer:
    """Health-checked, staleness-weighted ``/score`` fan-in (module doc)."""

    def __init__(
        self,
        replicas: Sequence[str],
        host: str = "127.0.0.1",
        port: int = 0,
        health_interval_s: float = 1.0,
        health_timeout_s: float = 2.0,
        staleness_penalty: float = 0.25,
        retries: int = 1,
        timeout_s: float = 30.0,
        logger=None,
        seed: Optional[int] = None,
    ):
        if not replicas:
            raise ValueError("router needs >= 1 replica URL")
        self.logger = logger
        self.health_interval_s = float(health_interval_s)
        self.health_timeout_s = float(health_timeout_s)
        self.staleness_penalty = float(staleness_penalty)
        self.retries = int(retries)
        self.timeout_s = float(timeout_s)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._replicas = [_ReplicaState(u) for u in replicas]
        self._started_at = time.time()
        self.metrics = MetricsRegistry()
        self._requests_c = self.metrics.counter(
            "router_requests_total", "routed /score requests by outcome")
        self._upstream_c = self.metrics.counter(
            "router_upstream_requests_total",
            "requests dispatched to each replica")
        self._retries_c = self.metrics.counter(
            "router_retries_total",
            "idempotent reads re-dispatched to another replica")
        self._upstream_err_c = self.metrics.counter(
            "router_upstream_errors_total",
            "connect failures / sheds per replica")
        self._health_conn_c = self.metrics.counter(
            "router_health_probes_total",
            "health probes by transport (reused keep-alive vs new TCP)")
        self._latency = self.metrics.histogram(
            "router_request_latency_seconds",
            "end-to-end routed /score latency (successes)")
        # Per-dispatch upstream latency labeled by outcome, so a retry
        # storm (ok collapsing into retry) and a shed flood are
        # distinguishable in ONE Prometheus scrape: ok = 200 on the first
        # attempt, retry = 200 after re-dispatch, shed = upstream 503
        # re-dispatched, error = connect failure or non-200 relay.
        self._upstream_latency = self.metrics.histogram(
            "router_upstream_latency_seconds",
            "per-dispatch upstream latency by outcome "
            "(ok/retry/shed/error)")
        for outcome in ("ok", "retry", "shed", "error"):
            # Registered empty at startup: a warm-up scrape reads four
            # zero-count series, never "metric missing".
            self._upstream_latency.child(outcome=outcome)
        self.metrics.gauge_fn(
            "router_healthy_replicas",
            lambda: sum(1 for r in self._routable()),
            "replicas currently eligible for traffic")
        self.metrics.gauge_fn(
            "router_known_replicas", lambda: len(self._replicas),
            "replicas configured on this router")
        # Per-replica drain state as a LABELED gauge (1 = receiving no
        # traffic: unreachable, unhealthy, or degraded-drained), so the
        # control plane and the fleet report read drain posture from one
        # registry scrape instead of a /healthz fan-out.
        self._drained_g = self.metrics.gauge(
            "router_drained_replicas",
            "1 when the labeled replica is excluded from routing")
        # Startup registration (docs/observability.md §"Gauge warm-up"):
        # every configured replica starts DRAINED (1) until its first
        # clean health sweep proves otherwise — a scrape during warm-up
        # reads the honest posture, never "metric missing".
        for r in self._replicas:
            self._drained_g.set(1.0, replica=r.url)
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                if router.logger is not None:
                    router.logger.debug("router http: " + fmt, *args)

            def _reply(self, code: int, payload, headers=()) -> None:
                body = payload if isinstance(payload, bytes) \
                    else json.dumps(payload).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path, _, query = self.path.partition("?")
                if path == "/healthz":
                    snap = router.health_snapshot()
                    self._reply(
                        200 if snap["status"] != "unhealthy" else 503, snap)
                elif path == "/metrics":
                    if "prom" in query:
                        body = router.metrics.to_prometheus(
                            extra=GLOBAL_REGISTRY).encode("utf-8")
                        self.send_response(200)
                        self.send_header(
                            "Content-Type",
                            "text/plain; version=0.0.4; charset=utf-8")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    else:
                        self._reply(200, router.metrics_snapshot())
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path != "/score":
                    n = int(self.headers.get("Content-Length") or 0)
                    if n:
                        self.rfile.read(n)
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b"{}"
                tid = self.headers.get("X-Photon-Trace-Id") or new_trace_id()
                timing = (self.headers.get("X-Photon-Timing")
                          or "").lower() in ("1", "true", "yes", "on")
                with trace_context(tid), \
                        trace_span("router.request", cat="router") as sp:
                    code, payload, hdrs = router.route_score(
                        body, tid, sp, timing=timing)
                self._reply(code, payload, headers=hdrs)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self._loop_started = False
        self._serve_thread: Optional[threading.Thread] = None
        self._health_stop = threading.Event()
        self._health_thread = threading.Thread(
            target=self._health_loop, name="photon-router-health",
            daemon=True)
        self._health_thread.start()

    # --------------------------------------------------------------- health

    @property
    def address(self) -> tuple:
        return self.httpd.server_address[:2]

    def _health_loop(self) -> None:
        self.check_replicas()
        while not self._health_stop.wait(self.health_interval_s):
            self.check_replicas()

    def check_replicas(self) -> None:
        """One health sweep (also callable synchronously from tests).
        Never raises: the health thread runs for the router's whole
        life, and a single replica answering garbage must not freeze the
        pool view forever."""
        for r in self._replicas:
            try:
                self._check_one(r)
            except Exception as e:  # noqa: BLE001 - keep the loop alive
                if self.logger is not None:
                    self.logger.warning(
                        "health check of %s failed unexpectedly: %s: %s",
                        r.url, type(e).__name__, e)
                with self._lock:
                    r.status = "unhealthy"
                    r.consecutive_failures += 1
                    r.last_check_ts = time.time()
        # Stamp drain posture once per sweep (not per request): the gauge
        # answers "who is out of rotation RIGHT NOW" at sweep granularity,
        # which is exactly the granularity the pool view updates at.
        with self._lock:
            states = [(r.url, r.reachable and r.status == "ok"
                       and not r.degraded) for r in self._replicas]
        for url, routable in states:
            self._drained_g.set(0.0 if routable else 1.0, replica=url)

    def _health_fetch(self, r: _ReplicaState) -> tuple:
        """``GET /healthz`` over the replica's cached keep-alive
        connection; returns ``(status_code, body_bytes)``.

        The sweep probes every replica every ``health_interval_s`` for
        the router's whole life — a fresh TCP handshake per probe is
        pure per-sweep overhead that, on a busy box, competes with
        scoring traffic for accept cycles and keeps the
        ``router_upstream_latency_seconds`` floor higher than it needs
        to be. The connection lives on the replica state; concurrent
        sweeps hand it off atomically. A REUSED socket that fails
        mid-probe gets one fresh-connection retry (the upstream may have
        idle-closed it between sweeps) before the failure counts; a
        fresh socket failing is a real connect failure and raises.
        """
        last_exc: Optional[BaseException] = None
        for _ in range(2):
            with self._lock:
                # Atomic take: tests drive check_replicas() concurrently
                # with the health thread's initial sweep, and two probes
                # sharing one socket would interleave their frames.
                conn, r.conn = r.conn, None
            reused = conn is not None
            if conn is None:
                u = urllib.parse.urlsplit(r.url)
                conn = http.client.HTTPConnection(
                    u.hostname, u.port, timeout=self.health_timeout_s)
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                raw = resp.read()  # drain fully or the next probe desyncs
            except _CONNECT_ERRORS + (http.client.HTTPException,) as e:
                conn.close()
                last_exc = e
                if reused:
                    continue  # retry once on a fresh socket
                raise
            if resp.will_close:
                conn.close()
            else:
                with self._lock:
                    if r.conn is None:
                        r.conn = conn
                    else:      # a concurrent probe already parked one
                        conn.close()
            self._health_conn_c.inc(
                1, transport="reused" if reused else "new")
            return resp.status, raw
        raise last_exc  # fresh-socket retry also failed

    def _check_one(self, r: _ReplicaState) -> None:
        try:
            code, raw = self._health_fetch(r)
        except _CONNECT_ERRORS + (http.client.HTTPException,
                                  urllib.error.URLError):
            with self._lock:
                r.reachable = False
                r.status = "unreachable"
                r.consecutive_failures += 1
                r.last_check_ts = time.time()
            return
        # Parse OUTSIDE the fetch try: a 200 carrying a non-JSON body (a
        # proxy error page, a half-written reply) or malformed fields must
        # degrade THIS replica, not kill the health thread.
        try:
            payload = json.loads(raw)
            if not isinstance(payload, dict):
                raise ValueError(f"healthz body is {type(payload).__name__}")
            status = payload.get("status") or \
                ("ok" if code == 200 else "unhealthy")
            degraded = list(payload.get("degraded") or ())
            rep = payload.get("replication") or {}
            watermark = (int(rep["seq_watermark"])
                         if rep.get("seq_watermark") is not None else None)
            lag = int(rep.get("lag") or 0) if watermark is not None else None
            fresh = payload.get("freshness") or {}
            version = (int(fresh["model_version"])
                       if fresh.get("model_version") is not None else None)
        except (ValueError, TypeError, AttributeError) as e:
            if self.logger is not None:
                self.logger.warning(
                    "unparseable /healthz from %s (HTTP %d): %s",
                    r.url, code, e)
            with self._lock:
                r.reachable = True        # it answered — just uselessly
                r.status = "unhealthy"    # drained until it answers sanely
                r.consecutive_failures += 1
                r.last_check_ts = time.time()
            return
        with self._lock:
            r.reachable = True
            r.consecutive_failures = 0
            r.last_check_ts = time.time()
            r.status = status
            r.degraded = degraded
            if watermark is not None:
                r.seq_watermark = watermark
                r.lag = lag
            if version is not None:
                r.model_version = version

    # -------------------------------------------------------------- routing

    def _routable(self) -> list:
        """Replicas eligible for traffic: reachable, healthy, undrained."""
        with self._lock:
            pool = list(self._replicas)
        return [r for r in pool
                if r.reachable and r.status == "ok" and not r.degraded]

    def _weights(self, exclude=()) -> list:
        """(replica, weight) pairs for one pick. Staleness-weighted over
        the routable pool; when that pool is empty, degrade to ANY
        reachable non-unhealthy replica at uniform weight (a stale or
        pressured answer beats refusing everyone)."""
        pool = [r for r in self._routable() if r not in exclude]
        if not pool:
            with self._lock:
                pool = [r for r in self._replicas
                        if r.reachable and r.status != "unhealthy"
                        and r not in exclude]
            return [(r, 1.0) for r in pool]
        marks = [r.seq_watermark for r in pool
                 if r.seq_watermark is not None]
        head = max(marks) if marks else None
        out = []
        for r in pool:
            if head is None or r.seq_watermark is None:
                w = 1.0
            else:
                w = 1.0 / (1.0 + self.staleness_penalty
                           * max(0, head - r.seq_watermark))
            out.append((r, w))
        return out

    def _pick(self, exclude=()):
        weighted = self._weights(exclude=exclude)
        if not weighted:
            return None
        total = sum(w for _, w in weighted)
        x = self._rng.uniform(0.0, total)
        for r, w in weighted:
            x -= w
            if x <= 0:
                return r
        return weighted[-1][0]

    def route_score(self, body: bytes, trace_id: str, span,
                    timing: bool = False) -> tuple:
        """Dispatch one /score read; returns (code, payload-bytes, hdrs).
        Connect failures and 503 sheds retry on the NEXT-best replica
        (scores are idempotent reads) up to ``retries`` times. With
        ``timing`` the X-Photon-Timing opt-in is forwarded upstream and
        the router hop is prepended to the replica's stage breakdown."""
        t0 = time.perf_counter()
        tried: list = []
        last_err: Optional[str] = None
        for attempt in range(self.retries + 1):
            r = self._pick(exclude=tried)
            if r is None:
                break
            if attempt:
                self._retries_c.inc()
            tried.append(r)
            self._upstream_c.inc(1, replica=r.url)
            a0 = time.perf_counter()
            try:
                headers = {"Content-Type": "application/json",
                           "X-Photon-Trace-Id": trace_id}
                if timing:
                    headers["X-Photon-Timing"] = "1"
                req = urllib.request.Request(
                    r.url + "/score", data=body, method="POST",
                    headers=headers)
                with urllib.request.urlopen(
                        req, timeout=self.timeout_s) as resp:
                    payload = resp.read()
                    code = resp.status
                    upstream_timing = resp.headers.get("X-Photon-Timing")
            except urllib.error.HTTPError as e:
                payload = e.read()
                code = e.code
                upstream_timing = e.headers.get("X-Photon-Timing")
                if code == 503 and attempt < self.retries:
                    # A shed (queue full, memory pressure, draining):
                    # idempotent read, another replica may have room.
                    self._upstream_latency.observe(
                        time.perf_counter() - a0, outcome="shed")
                    self._upstream_err_c.inc(1, replica=r.url,
                                             kind="shed")
                    last_err = f"{r.url} shed (503)"
                    continue
            except _CONNECT_ERRORS + (urllib.error.URLError,) as e:
                # Connect failure: mark it down NOW (don't wait for the
                # health sweep) and retry elsewhere.
                self._upstream_latency.observe(
                    time.perf_counter() - a0, outcome="error")
                self._upstream_err_c.inc(1, replica=r.url, kind="connect")
                with self._lock:
                    r.reachable = False
                    r.status = "unreachable"
                    r.consecutive_failures += 1
                last_err = f"{r.url}: {type(e).__name__}: {e}"
                span.set(retried=True)
                continue
            # Success or a non-retryable client/server answer: relay it.
            upstream_s = time.perf_counter() - a0
            outcome = "ok" if code == 200 else f"http_{code}"
            self._upstream_latency.observe(
                upstream_s,
                outcome=("ok" if code == 200 and not attempt else
                         "retry" if code == 200 else "error"))
            self._requests_c.inc(1, outcome=outcome)
            total = time.perf_counter() - t0
            if code == 200:
                self._latency.histogram.observe(total)
            span.set(status=code, replica=r.url, attempts=attempt + 1)
            hdrs = ()
            if timing:
                # router hop = everything spent in front of the replica
                # (pick, failed attempts, proxying) — total minus the
                # answering attempt's upstream wall time.
                hop = max(0.0, total - upstream_s)
                breakdown = f"router;dur={(hop * 1e3):.3f}"
                if upstream_timing:
                    breakdown += ", " + upstream_timing
                hdrs = (("X-Photon-Timing", breakdown),)
            return code, payload, hdrs
        self._requests_c.inc(1, outcome="no_replica")
        span.set(status=503, attempts=len(tried))
        return 503, {
            "error": "no replica available"
                     + (f" (last: {last_err})" if last_err else ""),
        }, (("Retry-After", self._retry_after_hint()),)

    def _retry_after_hint(self) -> str:
        """Retry-After for pool exhaustion, derived from the HEALTHIEST
        replica's probe schedule instead of a fixed constant: the pool
        view can only improve at that replica's next health sweep, so the
        honest hint is the time until ``last_check_ts +
        health_interval_s`` — a client told "1" against a 30 s sweep would
        hammer a door that cannot open yet. Clamped to >= 1 s (ceil)."""
        now = time.time()
        with self._lock:
            checked = [r for r in self._replicas
                       if r.last_check_ts is not None]
            if not checked:
                return str(max(1, math.ceil(self.health_interval_s)))
            best = min(checked,
                       key=lambda r: (r.consecutive_failures,
                                      -(r.last_check_ts or 0.0)))
            eta = (best.last_check_ts + self.health_interval_s) - now
        return str(max(1, math.ceil(eta)))

    # ------------------------------------------------------------ snapshots

    def health_snapshot(self) -> dict:
        with self._lock:
            reps = [r.snapshot() for r in self._replicas]
        routable = sum(1 for r in self._routable())
        reachable = sum(1 for r in reps if r["reachable"])
        status = "ok" if routable else (
            "degraded" if reachable else "unhealthy")
        marks = [r["seq_watermark"] for r in reps
                 if r["seq_watermark"] is not None]
        return {
            "status": status,
            "routable": routable,
            "reachable": reachable,
            "replicas": reps,
            "head_seq_watermark": max(marks) if marks else None,
            "uptime_s": round(time.time() - self._started_at, 1),
        }

    def metrics_snapshot(self) -> dict:
        return {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "latency": self._latency.histogram.snapshot(),
            "metrics": self.metrics.snapshot(),
            "health": self.health_snapshot(),
        }

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._loop_started = True
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever,
            name="photon-router-http", daemon=True)
        self._serve_thread.start()

    def serve_forever(self) -> None:
        self._loop_started = True
        self.httpd.serve_forever()

    def shutdown(self) -> None:
        self._health_stop.set()
        if self._loop_started:
            self.httpd.shutdown()
        self.httpd.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=5.0)
        self._health_thread.join(timeout=5.0)
        for r in self._replicas:  # drop cached keep-alive probe sockets
            if r.conn is not None:
                r.conn.close()
                r.conn = None
