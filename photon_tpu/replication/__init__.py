"""Replicated serving tier (docs/serving.md §"Replication").

Upstream photon-ml stops at offline batch scoring (``GameScoringDriver``
writes scored Avro — PAPER.md §0); our serving path so far is ONE
``ThreadingHTTPServer`` box fed point-to-point by the online trainer's
``POST /admin/patch``. This package goes horizontal:

* **log** — the durable delta log: the online trainer's publisher writes
  each :class:`~photon_tpu.online.delta.ModelDelta` ONCE as an append-only,
  seq-numbered JSONL record (same whole-line O_APPEND contract as the
  event log), and N replicas tail it independently. Torn-tail-safe reader,
  atomic per-replica cursors, snapshot markers for the catch-up path.
* **tailer** — :class:`ReplicaTailer`: a serving replica's consume loop.
  Applies each log record exactly once through the existing
  ``ModelRegistry.apply_delta`` path (dense-seq cursor proves it), exposes
  its seq watermark + lag for ``/healthz``, and when its lag exceeds the
  catch-up threshold swaps to the latest full-snapshot marker through the
  registry's ``prepare_standby``/``swap`` machinery instead of replaying
  the whole backlog.
* **router** — :class:`RouterServer`: the staleness- and pressure-aware
  front door. Health-checks replicas, weights ``/score`` traffic by seq
  lag, drains replicas reporting ``degraded`` or memory pressure, retries
  idempotent reads on a second replica on connect failure, and forwards
  ``X-Photon-Trace-Id`` so a routed request renders as one cross-process
  flow in the merged fleet timeline.

Deployment shape: ``cli/online_training_driver --delta-log`` produces,
``cli/serving_driver --delta-log`` replicas consume, and
``cli/router_driver`` fronts them; ``scripts/replica_smoke.py`` drills the
whole topology (kill/rejoin, exactly-once audit, zero routed errors).
"""
from photon_tpu.replication.log import (
    DeltaLogError,
    DeltaLogPublisher,
    DeltaLogRecord,
    DeltaLogWriter,
    FanoutPublisher,
    ReplicaCursor,
    iter_log,
    log_next_seq,
    pending_records,
)
from photon_tpu.replication.router import RouterServer
from photon_tpu.replication.tailer import ReplicaTailer

__all__ = [
    "DeltaLogError",
    "DeltaLogPublisher",
    "DeltaLogRecord",
    "DeltaLogWriter",
    "FanoutPublisher",
    "ReplicaCursor",
    "ReplicaTailer",
    "RouterServer",
    "iter_log",
    "log_next_seq",
    "pending_records",
]
